"""ElasticStepFunction: the fused train step that survives membership
changes.

The one-program :class:`~mxnet_tpu.step.stepfn.StepFunction` compiles
the gradient exchange *into* the jit (identity or in-mesh psum) — a
shape that cannot abort mid-collective when a peer dies. The elastic
variant splits the step at exactly the exchange boundary:

- **grad program** — forward + backward, compiled once per input
  signature. Its trace is *world-size independent*: membership changes
  never touch it.
- **host exchange** — the flat-bucket allreduce through the elastic
  kvstore, generation-fenced: a :class:`MembershipChanged` aborts the
  step's exchange, the session rebuilds (barrier + bucket relayout +
  batch/LR rescale), and the SAME gradients are re-exchanged under the
  new generation — forward/backward is never recomputed for a bump.
- **update program** — the fused multi-tensor optimizer over the
  reduced gradients, donated buffers. The ``1/world`` normalization of
  the summed exchange rides ``rescale_grad``, a *structural* scalar of
  ``Optimizer.fused_signature()`` — so a world-size change re-keys
  **exactly this one program** (the acceptance budget: one re-key per
  generation bump, zero steady-state recompiles after the rebuild; a
  rejoin back to a previously-seen world size is a cache HIT and
  re-keys nothing).

The trainer keeps owning optimizer state (checkpoints, TrainGuard and
``save_states`` see post-update values), and the step boundary is also
the membership boundary: heartbeats go out here, generation bumps are
observed here, and the group leader publishes join state here.

With ``MXGUARD=1`` the split point gains the integrity vote
(mxnet_tpu/guard/): the grad program emits fingerprint taps, workers
exchange them through a generation-fenced round BEFORE the bucket
allreduce, and a corrupt replica is classified by deterministic
re-execution — transient faults retry in place, persistent ones
quarantine through the same leave/membership-bump machinery
(docs/resilience.md, integrity section).
"""
from __future__ import annotations

import time
from typing import Dict

import jax

from ..base import MXNetError
from ..ndarray.ndarray import _wrap
from ..obs import propagate as _obs_prop
from ..step.stepfn import StepFunction, _raw
from .. import trace as _trace
from .membership import MembershipChanged

__all__ = ["ElasticStepFunction"]


class ElasticStepFunction(StepFunction):
    def __init__(self, net, loss_fn=None, trainer=None, **kwargs):
        if kwargs.get("psum_axis") is not None:
            raise MXNetError(
                "ElasticStepFunction owns the gradient exchange; "
                "psum_axis= does not compose with it")
        if trainer is None or getattr(trainer, "_elastic", None) is None:
            raise MXNetError(
                "ElasticStepFunction needs a trainer with an elastic "
                "session (create the Trainer with an ElasticKVStore, "
                "or call session.attach(trainer) first)")
        super().__init__(net, loss_fn, trainer=trainer, **kwargs)
        self._session = trainer._elastic
        if not trainer._kv_initialized:
            trainer._init_kvstore()
        kv = trainer._kvstore
        if kv is None or not getattr(kv, "supports_flat_allreduce",
                                     False):
            raise MXNetError(
                "ElasticStepFunction needs a flat-allreduce-capable "
                f"kvstore; got {type(kv).__name__}")
        self._kv = kv
        self._grad_cache: Dict = {}
        self._buckets = None  # (GradientBuckets, layout signature)
        self._nstep = 0

    # ------------------------------------------------------------------
    # program caches
    # ------------------------------------------------------------------
    def _grad_key(self, inputs, guard=False):
        return (tuple((tuple(v.shape), str(v.dtype)) for v in inputs),
                self._param_dtypes(), self._opt_level, bool(guard)) \
            + self._shard_key()

    def _update_key(self):
        # rescale_grad (inside fused_signature) carries 1/world — THE
        # re-key on a world-size change; generation itself is absent,
        # so returning to a previously-seen world size is a cache hit
        return (self._param_dtypes(), self._opt_level,
                self._optimizer.fused_signature()) + self._shard_key()

    def _grad_fn(self, inputs, guard=False):
        key = self._grad_key(inputs, guard)
        fn = self._grad_cache.get(key)
        if fn is None:
            self._record_miss(inputs)
            # params NOT donated: the update program still needs the
            # pre-step weights — which is also what makes the mxguard
            # deterministic re-execution safe (guard/voting.py)
            fn = jax.jit(self._build_grads(taps=guard))
            self._grad_cache[key] = fn
        return fn

    def _update_fn(self):
        key = self._update_key()
        fn = self._cache.get(key)
        if fn is None:
            from ..telemetry import metrics as _metrics
            from ..telemetry import recompile as _recompile
            _metrics.counter(
                "fused_step_cache_misses_total",
                "fused-step signature-cache misses (compiles)").inc()
            sig = {"inputs": [], "world": int(self._session.world),
                   "rescale": float(self._optimizer.rescale_grad),
                   "phase": "update"}
            _recompile.record_recompile(
                f"ElasticStepFunction:{self._name}", sig,
                kind="fused_step")
            trainable = self._trainable
            indices = self._indices

            def pure_update(tvals, svals, grads, lrs, wds):
                # the barrier pins the exchange/update boundary for
                # the same bitwise-contraction reason as the fused
                # one-program step
                grads = jax.lax.optimization_barrier(grads)
                return self._optimizer.fused_apply(
                    indices, [tvals[n] for n in trainable],
                    [grads[n] for n in trainable], svals, lrs, wds)

            fn = jax.jit(pure_update,
                         donate_argnums=(0, 1) if self._donate else ())
            self._cache[key] = fn
            self._last = (fn, key)
        return fn

    # ------------------------------------------------------------------
    # the host-side bucketed exchange
    # ------------------------------------------------------------------
    def _grad_buckets(self):
        """Bucket layout for the CURRENT world (rebuilt on a bump:
        the session's generation is part of the signature through
        world_size — step/buckets.GradientBuckets)."""
        from ..step.buckets import GradientBuckets
        items = []
        for i, n in zip(self._indices, self._trainable):
            p = self._param_objs[n]
            v = p.data() if hasattr(p, "data") else p
            items.append((i, tuple(v.shape), str(v.dtype),
                          v.size * v.dtype.itemsize))
        sig = (tuple(items), self._session.world)
        if self._buckets is None or self._buckets[1] != sig:
            self._buckets = (GradientBuckets(
                items, world_size=self._session.world), sig)
        return self._buckets[0]

    def _exchange_once(self, grads_by_name):
        """One attempt: flatten → fenced allreduce per bucket →
        scatter. Raises MembershipChanged whole (no partial effect:
        reduced segments only replace the local grads after EVERY
        bucket of the generation succeeded)."""
        name_of = dict(zip(self._indices, self._trainable))
        grads_by_idx = {i: grads_by_name[name_of[i]]
                        for i in self._indices}
        buckets = self._grad_buckets()
        reduced_parts = []
        for bid, bucket in enumerate(buckets.buckets):
            flat = buckets.flatten(bucket, grads_by_idx)
            out = self._kv.allreduce_flat(f"__estep_b{bid}",
                                          _wrap(flat))
            reduced_parts.append((bucket, out._data))
        reduced = {}
        for bucket, flat in reduced_parts:
            for i, seg in buckets.unflatten(bucket, flat).items():
                reduced[name_of[i]] = seg
        return reduced

    def _exchange(self, grads):
        """In-jit hook disabled: the elastic exchange is host-side."""
        return grads

    def _set_rescale(self, batch_size):
        # summed exchange + 1/(local batch x world) = the global-batch
        # mean — the update math of an uninterrupted run at this world
        self._optimizer.rescale_grad = \
            self._scale / (batch_size * max(1, self._session.world))

    # ------------------------------------------------------------------
    # mxguard: the pre-averaging fingerprint vote (guard/voting.py)
    # ------------------------------------------------------------------
    def _guard_grads(self, grads_fn, pvals, inputs, rng):
        """One gradient computation with the taps: run the grad
        program, evaluate the sdc drill sites (the injection models
        the hardware — it fires per attempt, so re-executions see a
        persistent fault again and a one-shot ``@K`` clause clears),
        and return (grads, extras, loss, host fingerprint matrix) with
        any corrupted row recomputed host-side so the reported
        fingerprint describes the bytes this worker contributes."""
        import numpy as onp
        from ..guard.voting import apply_sdc, sdc_token
        grads, extras, loss, fps = grads_fn(pvals, inputs, rng)
        fps_host = onp.asarray(fps, dtype=onp.float32)
        token = sdc_token(self._session.worker_id, self._nstep,
                          self._session.world)
        if token is not None:
            from .. import config
            grads, name, row = apply_sdc(
                grads, self._trainable, token, self._nstep,
                seed=int(config.get("MXRESIL_SEED")))
            fps_host = fps_host.copy()
            fps_host[1 + self._trainable.index(name)] = row
        return grads, extras, loss, fps_host

    def _guard_vote(self, grads_fn, pvals, inputs, rng, grads,
                    fps_host):
        """Rounds A/B of the pre-exchange fingerprint vote (module
        docstring of guard/voting.py). Returns possibly-replaced
        (grads, fps) on a transient verdict; raises
        :class:`GuardQuarantined` / :class:`GuardCorruption` on a
        persistent one; a :class:`MembershipChanged` fence propagates
        to the caller's rebuild loop like any other fenced round."""
        import numpy as onp
        from .. import config
        from ..guard.fingerprint import vote
        from ..guard.voting import (GuardCorruption, GuardQuarantined,
                                    contribution, table_of)
        from ..telemetry import metrics as _metrics
        session = self._session
        me = session.worker_id
        step = self._nstep
        n_grads = len(self._trainable)

        if session.world <= 1:
            # solo: no peers to vote with — self-check on non-finite
            # GRADIENT fingerprints (a non-finite loss is divergence
            # territory — TrainGuard's rollback, not quarantine),
            # classify by re-execution
            if float(fps_host[1:1 + n_grads, 2].sum()) <= 0:
                return grads, fps_host
            _metrics.counter(
                "mxguard_suspect_verdicts_total",
                "fingerprint verdicts naming a suspect replica").inc()
            grads2, _, _, fps2 = self._guard_grads(
                grads_fn, pvals, inputs, rng)
            if onp.array_equal(fps_host, fps2, equal_nan=True):
                self.guard_events.append(
                    {"step": step, "kind": "persistent",
                     "suspect": me, "reasons": ["nonfinite"]})
                _metrics.counter(
                    "mxguard_hard_fails_total",
                    "solo runs hard-failed on persistent "
                    "corruption").inc()
                raise GuardCorruption(step, ["nonfinite"])
            self.guard_events.append(
                {"step": step, "kind": "transient", "suspect": me,
                 "reasons": ["nonfinite"]})
            _metrics.counter(
                "mxguard_transient_total",
                "transient corruption healed by re-execution").inc()
            return grads2, fps2

        workers = session.view.workers
        rank = session.rank
        world = session.world
        tol = float(config.get("MXGUARD_VOTE_TOL"))
        # the exchanged table carries params digest + gradient rows;
        # the trailing LOCAL loss row stays home (losses legitimately
        # differ per worker — they would only add vote noise)
        voted = fps_host[:1 + n_grads]
        table = table_of(session.allreduce(
            "__guard_fp", contribution(voted, rank, world)), world)
        _metrics.counter(
            "mxguard_votes_total",
            "cross-replica fingerprint vote rounds").inc()
        verdict = vote(table, workers, tol=tol)
        if verdict.clean:
            return grads, fps_host
        if verdict.global_anomaly:
            # every replica agrees the gradients are bad: divergence,
            # not silent corruption — TrainGuard's jurisdiction
            self.guard_events.append(
                {"step": step, "kind": "global-anomaly",
                 "suspect": None, "reasons": ["all-replicas"]})
            return grads, fps_host
        _metrics.counter("mxguard_suspect_verdicts_total",
                         "fingerprint verdicts naming a suspect "
                         "replica").inc()
        suspects = verdict.suspects
        _log_reasons = sorted(
            {r for rs in suspects.values() for r in rs})
        self.guard_events.append(
            {"step": step, "kind": "suspect",
             "suspect": sorted(suspects),
             "reasons": _log_reasons})
        # round B: suspects re-execute on the same inputs, everyone
        # re-contributes — the SAME deterministic verdict again tells
        # every worker how the step ends
        if me in suspects:
            with _trace.span("guard.reexec", "guard", step=step,
                             suspect=me):
                grads, _, _, fps_host = self._guard_grads(
                    grads_fn, pvals, inputs, rng)
        table2 = table_of(session.allreduce(
            "__guard_fp2",
            contribution(fps_host[:1 + n_grads], rank, world)), world)
        verdict2 = vote(table2, workers, tol=tol)
        if me in verdict2.suspects:
            # reproduced under re-execution: persistent. Quarantine —
            # leave (the membership bump survivors fence on) and raise
            _metrics.counter(
                "mxguard_quarantines_total",
                "replicas quarantined for persistent corruption").inc()
            self.guard_events.append(
                {"step": step, "kind": "persistent", "suspect": me,
                 "reasons": verdict2.suspects[me]})
            # coordinated capture BEFORE leaving: the post-mortem needs
            # every live rank's recorder, not just the quarantined one
            if hasattr(session, "request_pod_dump"):
                session.request_pod_dump(f"guard-quarantine-{me}")
            session.leave()
            raise GuardQuarantined(me, step, verdict2.suspects[me])
        if me in suspects:
            _metrics.counter(
                "mxguard_transient_total",
                "transient corruption healed by re-execution").inc()
            self.guard_events.append(
                {"step": step, "kind": "transient", "suspect": me,
                 "reasons": suspects[me]})
        return grads, fps_host

    # ------------------------------------------------------------------
    # the step
    # ------------------------------------------------------------------
    def step(self, x, *labels, batch_size=None, rng_raw=None):
        from ..telemetry import metrics as _metrics
        from .. import telemetry as _telemetry
        t0 = time.perf_counter()
        session = self._session
        # derived pod identity (mxobs): every rank computes the SAME
        # pod.step trace id from (group uid, generation, step) captured
        # at entry — lockstep ranks agree, so the per-rank step trees
        # stitch into one trace under `mxprof trace --dir`. None when
        # MXOBS/MXTRACE is off or the session has no pod uid yet.
        gen0, step0 = session.generation, self._nstep
        pod_ctx = _obs_prop.pod_step_context(
            getattr(session, "pod_uid", None), gen0, step0)
        t_root0 = time.perf_counter_ns()
        # the per-step trace root, keyed by (generation, step) — the
        # cross-subsystem correlation key: heartbeat/rebuild, grad
        # dispatch, guard vote, bucket exchange and update all
        # decompose as children of this one span
        with _trace.under(pod_ctx), \
             _trace.span("train.step", "train", step=self._nstep,
                         generation=session.generation,
                         world=session.world, fn=self._name,
                         kind=type(self).__name__) as _st:
            # the step boundary IS the membership boundary
            with _trace.span("elastic.heartbeat", "elastic",
                             step=self._nstep) as _hb:
                changed = session.heartbeat(self._nstep)
                _hb.set(generation_changed=changed)
            if changed:
                session.rebuild()
                _st.set(generation=session.generation,
                        world=session.world)
            inputs = tuple(_raw(a) for a in (x,) + labels)
            self._prepare(inputs)
            if batch_size is None:
                batch_size = int(inputs[0].shape[0]) \
                    if inputs[0].ndim else 1
            self._set_rescale(batch_size)
            guard = self._guard_enabled()

            with _trace.span("step.prep", "train"):
                grads_fn = self._grad_fn(inputs, guard)
                lrs, wds = self._hyper()
                pvals, svals = self._gather()
                from .. import random as _random
                import jax.numpy as jnp
                rng = jnp.asarray(rng_raw) if rng_raw is not None \
                    else jax.random.key_data(_random.next_key())
            fps_host = None
            with _trace.span("step.grads", "train", guard=guard,
                             batch=batch_size):
                if guard:
                    grads, extras, loss, fps_host = self._guard_grads(
                        grads_fn, pvals, inputs, rng)
                else:
                    grads, extras, loss = grads_fn(pvals, inputs, rng)

            t1 = time.perf_counter()
            while True:
                try:
                    if guard:
                        # the pre-averaging vote: a corrupt replica is
                        # caught BEFORE its gradients enter the
                        # allreduce
                        with _trace.span("guard.vote", "guard",
                                         step=self._nstep,
                                         world=session.world):
                            grads, fps_host = self._guard_vote(
                                grads_fn, pvals, inputs, rng, grads,
                                fps_host)
                    with _trace.span(
                            "step.exchange", "elastic",
                            generation=session.generation,
                            world=session.world) as _ex:
                        reduced = self._exchange_once(grads)
                        # bucket count from the layout _exchange_once
                        # just memoized — rebuilding the O(n_params)
                        # signature for a span attribute would tax
                        # every step, traced or not
                        if self._buckets is not None:
                            _ex.set(buckets=len(
                                self._buckets[0].buckets))
                    break
                except MembershipChanged:
                    # fenced mid-exchange: rebuild with the survivors
                    # and re-exchange the SAME gradients under the new
                    # generation — forward/backward is not recomputed
                    session.rebuild()
                    self._set_rescale(batch_size)
                    _st.set(generation=session.generation,
                            world=session.world, rebuilt=True)
            t2 = time.perf_counter()

            with _trace.span("step.update", "train"):
                update_fn = self._update_fn()
                tvals = {n: pvals[n] for n in self._trainable}
                new_w, new_s = update_fn(tvals, svals, reduced, lrs,
                                         wds)
                new_params = dict(zip(self._trainable, new_w))
                new_params.update(extras)
                self._writeback(new_params, new_s)
            if guard:
                flagged = any(e["step"] == self._nstep
                              for e in self.guard_events)
                self._guard_note(fps_host, loss, inputs, rng,
                                 good=not flagged, strict=False)
            t3 = time.perf_counter()

        if pod_ctx is not None and session.is_leader:
            # exactly one rank records the shared pod.step root the
            # other ranks' step trees already parent under (leadership
            # read AFTER the step: a mid-step rebuild may have moved it)
            _obs_prop.emit_pod_root(
                session.pod_uid, gen0, step0, t_root0,
                time.perf_counter_ns(), world=session.world)
        self._nstep += 1
        session.note_step(batch_size)
        _metrics.histogram(
            "mxelastic_exchange_seconds",
            "elastic bucketed gradient-exchange latency (including "
            "any rebuild absorbed mid-step)").observe(t2 - t1)
        _metrics.histogram(
            "fused_step_dispatch_seconds",
            "fused-step compiled-call dispatch (async; excludes "
            "device wait)").observe((t1 - t0) + (t3 - t2))
        _telemetry.record_step(batch_size, time.perf_counter() - t0)
        return _wrap(loss)

    __call__ = step

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------
    def guard_state(self) -> Dict[str, object]:
        state = super().guard_state()
        state["exchanges_gradients"] = True
        state["kvstore"] = type(self._kv).__name__
        state["world"] = int(self._session.world)
        return state

    def program_counts(self) -> Dict[str, int]:
        """Per-instance compiled-program census — the drill's re-key
        budget check reads this (grad programs never re-key on a
        membership change; update programs re-key once per NEW world
        size)."""
        return {"grad": len(self._grad_cache),
                "update": len(self._cache),
                "total": len(self._grad_cache) + len(self._cache)}
