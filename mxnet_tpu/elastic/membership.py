"""Membership generations: the model behind elastic training.

A training group is a set of worker ids plus a **monotone generation
number**; every membership change — a worker joining, leaving
gracefully, or being declared lost on missed heartbeats — bumps the
generation exactly once. Data-plane exchanges are tagged with the
generation they were issued under, so a change *fences* every in-flight
collective with a typed :class:`MembershipChanged` instead of letting
survivors wedge on a peer that will never push (the dist_sync failure
mode ROADMAP 5(a) names; ref: ps-lite has no analog — the reference's
answer was "restart the job").

:class:`MembershipTracker` is the pure bookkeeping core: no sockets, no
threads, an injectable clock — tier-1 tests drive whole leave/rejoin
histories with fake workers and a fake clock (tests/test_elastic.py).
The blocking coordination built on top (reduce rounds, the rebuild
barrier, join state-sync) lives in
:class:`~mxnet_tpu.elastic.coordinator.ElasticCoordinator`; the socket
transport rides the kvstore server (kvstore_server.KVServer).
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..base import MXNetError, get_logger
from ..resil.policy import RetryableError
from ..san.runtime import make_rlock

__all__ = ["MembershipChanged", "WorkerEvicted", "GroupFailed",
           "ElasticTimeout", "MembershipView", "MembershipTracker"]

_log = get_logger("mxnet_tpu.elastic")


class MembershipChanged(RetryableError):
    """The group's membership generation moved while a collective was
    in flight. Typed and retryable *by contract*: the failed exchange
    had no partial effect (contributions of a dead generation are
    discarded whole), so the caller re-enters the rebuild barrier and
    re-issues the exchange under the new generation. The elastic call
    sites configure their :class:`~mxnet_tpu.resil.policy.RetryPolicy`
    with ``no_retry=(MembershipChanged,)`` — blind retry under a stale
    generation can never succeed; the REBUILD is the retry."""

    def __init__(self, message: str, generation: Optional[int] = None):
        super().__init__(message)
        self.generation = generation  # the new generation, when known


class WorkerEvicted(MXNetError):
    """This worker was declared lost (missed heartbeats) and removed
    from the group — but it is actually alive (a long GC pause, a
    network partition that healed). NOT retryable under the old
    identity: the worker must re-enter through the join protocol."""


class GroupFailed(MXNetError):
    """The group shrank below MXELASTIC_MIN_WORLD (or was explicitly
    failed): elastic adaptation is out of room and the job hard-fails
    so the cluster manager restarts it from checkpoint.

    Constructing one freezes the crash flight recorder (every raise
    site is terminal for the job, so the dump is the last readable
    timeline the operator gets — trace/recorder.py)."""

    def __init__(self, *args):
        super().__init__(*args)
        from ..trace import crash_dump
        crash_dump("group_failed",
                   site=str(args[0])[:120] if args else None)


class ElasticTimeout(RetryableError):
    """A blocking elastic operation (reduce wait, rebuild barrier,
    join admission) exceeded its deadline without a membership verdict
    either way — the control plane itself looks stuck."""


class MembershipView:
    """An immutable snapshot of the group at one generation."""

    __slots__ = ("generation", "workers", "devices")

    def __init__(self, generation: int, workers: Sequence[str],
                 devices: Optional[Dict[str, Tuple[int, ...]]] = None):
        self.generation = int(generation)
        self.workers: Tuple[str, ...] = tuple(sorted(workers))
        self.devices: Dict[str, Tuple[int, ...]] = {
            w: tuple(d) for w, d in (devices or {}).items()
            if w in self.workers}

    @property
    def world_size(self) -> int:
        return len(self.workers)

    @property
    def leader(self) -> Optional[str]:
        """Deterministic leader: the lexicographically first member
        (stable across workers with no election round)."""
        return self.workers[0] if self.workers else None

    def rank_of(self, worker_id: str) -> int:
        return self.workers.index(worker_id)

    def device_ids(self) -> Tuple[int, ...]:
        """All device ids owned by current members, sorted — the input
        to live ShardPlan re-inference."""
        out = set()
        for ids in self.devices.values():
            out.update(ids)
        return tuple(sorted(out))

    def describe(self) -> Dict[str, object]:
        return {"generation": self.generation,
                "workers": list(self.workers),
                "world_size": self.world_size,
                "devices": {w: list(d) for w, d in self.devices.items()}}

    def __repr__(self):
        return (f"<MembershipView gen={self.generation} "
                f"world={self.world_size} workers={self.workers}>")


class _Member:
    __slots__ = ("worker_id", "devices", "last_beat", "joined_gen",
                 "last_step")

    def __init__(self, worker_id, devices, now, gen):
        self.worker_id = worker_id
        self.devices = tuple(devices or ())
        self.last_beat = now
        self.joined_gen = gen
        self.last_step = None


class MembershipTracker:
    """Heartbeat ledger + generation counter (see module docstring).

    Thread-safe; every mutation that changes the member set bumps the
    generation exactly once (``admit`` batches several joins into one
    bump so a multi-worker restart does not trigger a rebuild per
    worker). ``check()`` applies the missed-heartbeat policy: a member
    silent for more than ``heartbeat_interval_s * miss_limit`` seconds
    is declared lost. The clock is injectable — deterministic drills,
    no flaky sleeps."""

    def __init__(self, heartbeat_interval_s: Optional[float] = None,
                 miss_limit: Optional[int] = None,
                 min_world: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic):
        from .. import config
        if heartbeat_interval_s is None:
            heartbeat_interval_s = float(config.get("MXELASTIC_HEARTBEAT_S"))
        if miss_limit is None:
            miss_limit = int(config.get("MXELASTIC_MISS_LIMIT"))
        if min_world is None:
            min_world = int(config.get("MXELASTIC_MIN_WORLD"))
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self.miss_limit = int(miss_limit)
        self.min_world = int(min_world)
        self._clock = clock
        self._lock = make_rlock("elastic.membership")
        self._members: Dict[str, _Member] = {}
        self._generation = 0
        self._failed: Optional[str] = None
        from ..telemetry import metrics as _metrics
        self._g_gen = _metrics.gauge(
            "mxelastic_generation", "current membership generation")
        self._g_world = _metrics.gauge(
            "mxelastic_world_size", "current elastic world size")
        self._m_lost = _metrics.counter(
            "mxelastic_lost_workers_total",
            "workers declared lost on missed heartbeats")
        self._m_leaves = _metrics.counter(
            "mxelastic_leaves_total", "graceful worker departures")
        self._m_joins = _metrics.counter(
            "mxelastic_joins_total", "workers admitted into the group")

    # -- inspection --------------------------------------------------------
    @property
    def lost_after_s(self) -> float:
        """Heartbeat age that converts into a worker-lost verdict."""
        return self.heartbeat_interval_s * self.miss_limit

    def view(self) -> MembershipView:
        with self._lock:
            return MembershipView(
                self._generation, list(self._members),
                {w: m.devices for w, m in self._members.items()})

    @property
    def generation(self) -> int:
        with self._lock:
            return self._generation

    def heartbeat_ages(self) -> Dict[str, float]:
        now = self._clock()
        with self._lock:
            return {w: now - m.last_beat
                    for w, m in self._members.items()}

    def check_failed(self):
        with self._lock:
            if self._failed is not None:
                raise GroupFailed(self._failed)

    # -- mutation ----------------------------------------------------------
    def _bump(self):
        # under self._lock
        self._generation += 1
        self._g_gen.set(self._generation)
        self._g_world.set(len(self._members))

    def admit(self, worker_ids: Sequence[str],
              devices: Optional[Dict[str, Sequence[int]]] = None
              ) -> MembershipView:
        """Add workers (one generation bump for the whole batch)."""
        now = self._clock()
        with self._lock:
            self.check_failed()
            changed = False
            for wid in worker_ids:
                if wid in self._members:
                    continue
                self._members[wid] = _Member(
                    wid, (devices or {}).get(wid, ()), now,
                    self._generation + 1)
                self._m_joins.inc()
                changed = True
            if changed:
                self._bump()
            return self.view()

    def join(self, worker_id: str,
             devices: Sequence[int] = ()) -> MembershipView:
        return self.admit([worker_id], {worker_id: tuple(devices)})

    def _check_min_world(self, lost: bool):
        """Arm the hard-fail after a shrink. Under self._lock.
        min-world applies to SHRINKS only — a forming group passes
        through small sizes legitimately, and a clean drain to zero
        (every worker leaving deliberately) is shutdown, not
        failure; a LOST-verdict shrink to zero does fail."""
        n = len(self._members)
        below = n < self.min_world if lost else 0 < n < self.min_world
        if below and self._failed is None:
            self._failed = (
                f"elastic group shrank to {n} worker(s) — below "
                f"MXELASTIC_MIN_WORLD={self.min_world}; hard-failing "
                "so the job restarts from checkpoint instead of "
                "limping")
            _log.error("%s", self._failed)

    def _remove(self, worker_id: str, lost: bool) -> bool:
        # under self._lock
        if worker_id not in self._members:
            return False
        del self._members[worker_id]
        self._bump()
        self._check_min_world(lost)
        return True

    def bump(self, why: str = "") -> MembershipView:
        """Explicit external generation bump (same member set). The
        coordinator-restart path uses it after :meth:`restore`: the new
        generation fences every exchange issued under the old process,
        so survivors re-enter through the ordinary rebuild barrier."""
        with self._lock:
            self.check_failed()
            self._bump()
            if why:
                _log.info("generation bumped to %d (%s)",
                          self._generation, why)
            return self.view()

    def restore(self, generation: int, workers: Sequence[str],
                devices: Optional[Dict[str, Sequence[int]]] = None
                ) -> MembershipView:
        """Reinstate a journaled group into a FRESH tracker (the
        coordinator-restart path, elastic/coordinator.py): members get
        the recorded devices and a fresh heartbeat stamp — the restart
        window must not count against their budget; a member that
        really died with the old coordinator simply never beats again
        and the normal missed-heartbeat policy removes it. Does NOT
        bump — the caller bumps once after restore so survivors fence
        with MembershipChanged instead of resuming a generation whose
        in-flight rounds died with the old process."""
        now = self._clock()
        with self._lock:
            self.check_failed()
            self._generation = int(generation)
            self._members = {
                str(w): _Member(str(w), (devices or {}).get(w, ()),
                                now, self._generation)
                for w in workers}
            self._g_gen.set(self._generation)
            self._g_world.set(len(self._members))
            return self.view()

    def leave(self, worker_id: str) -> MembershipView:
        """Graceful departure (preemption notice): bump immediately."""
        with self._lock:
            if self._remove(worker_id, lost=False):
                self._m_leaves.inc()
                _log.info("worker %r left the group (generation %d, "
                          "world %d)", worker_id, self._generation,
                          len(self._members))
            return self.view()

    def mark_lost(self, worker_id: str) -> MembershipView:
        """Apply a worker-lost verdict (watchdog or explicit)."""
        with self._lock:
            if self._remove(worker_id, lost=True):
                self._m_lost.inc()
                _log.warning(
                    "worker %r declared LOST (generation %d, world %d)",
                    worker_id, self._generation, len(self._members))
            return self.view()

    def heartbeat(self, worker_id: str,
                  step: Optional[int] = None) -> MembershipView:
        """Record a beat; raises :class:`WorkerEvicted` for a worker
        that was already removed (it must rejoin, not resume)."""
        now = self._clock()
        with self._lock:
            self.check_failed()
            m = self._members.get(worker_id)
            if m is None:
                raise WorkerEvicted(
                    f"worker {worker_id!r} is not a member of "
                    f"generation {self._generation} — it was declared "
                    "lost or never joined; re-enter via the join "
                    "protocol (docs/resilience.md elastic runbook)")
            m.last_beat = now
            if step is not None:
                m.last_step = int(step)
            return self.view()

    def check(self) -> List[str]:
        """The missed-heartbeat policy: declare silent members lost.
        Returns the worker ids removed (one bump covers them all)."""
        now = self._clock()
        threshold = self.lost_after_s
        with self._lock:
            lost = [w for w, m in self._members.items()
                    if now - m.last_beat > threshold]
            for w in lost:
                age = now - self._members[w].last_beat
                del self._members[w]
                self._m_lost.inc()
                _log.warning(
                    "worker %r silent for %.2fs (> %d x %.2fs "
                    "heartbeat budget) — declared lost", w, age,
                    self.miss_limit, self.heartbeat_interval_s)
            if lost:
                self._bump()
                self._check_min_world(lost=True)
        return lost
