"""ElasticCoordinator: the rank-0 control plane of elastic training.

One object owns everything the group must agree on:

- the :class:`~mxnet_tpu.elastic.membership.MembershipTracker`
  (heartbeats → generations);
- **generation-checked reduce rounds** — the synchronous bucketed
  allreduce workers ride (`ElasticKVStore.allreduce_flat`). Every
  contribution is tagged (generation, round, key); a round completes
  when every member of its generation contributed, and the sum is
  folded in *sorted worker order* so the result is bit-identical
  regardless of arrival order. If the generation moves while anyone is
  waiting, the round dies whole and every waiter gets a typed
  :class:`MembershipChanged` — the silent-wedge killer this subsystem
  exists for;
- the **rebuild barrier** — after a bump, survivors (and admitted
  joiners) meet here before the first exchange of the new generation.
  A further membership change while the barrier is forming simply
  re-forms it at the newer generation (the leave-during-rebuild case);
- **join state-sync** — a (re)starting worker announces itself; the
  group leader observes the pending join at its next step boundary and
  publishes the live weights + optimizer state; the joiner is admitted
  in the same move and pulls state *from the group*, never from a
  checkpoint file.

Every blocking wait ticks: it re-checks the deadline, runs the
missed-heartbeat policy (`tracker.check()`), and counts the waiter's
own tick as a heartbeat — a worker blocked inside the protocol is
alive by definition; the workers the policy must catch are the ones
that stopped calling. The clock is injectable end to end, so tier-1
tests drive kill/rejoin histories with a fake clock and fake workers
(no sockets, no sleeps-for-correctness).

Transport: in-process workers (the drill harness, tier-1 tests) share
this object directly; multi-process workers reach it through the
``elastic.*`` command family of :class:`~mxnet_tpu.kvstore_server.
KVServer`, which embeds one coordinator next to the async parameter
store.
"""
from __future__ import annotations

import os
import random
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as onp

from ..base import MXNetError, get_logger
from ..obs import propagate as _obs
from ..san.runtime import make_condition
from .membership import (ElasticTimeout, MembershipChanged,
                         MembershipTracker, MembershipView, WorkerEvicted)

__all__ = ["ElasticCoordinator"]

_log = get_logger("mxnet_tpu.elastic")

# default wait tick: coarse enough to stay off the lock, fine enough
# that a missed-heartbeat verdict lands within one tick of its deadline
_TICK_S = 0.02


class _Round:
    __slots__ = ("expected", "parts", "result", "taken")

    def __init__(self, expected):
        self.expected = frozenset(expected)
        self.parts: Dict[str, onp.ndarray] = {}
        self.result: Optional[onp.ndarray] = None
        self.taken = set()


class _Join:
    __slots__ = ("devices", "admitted_gen", "state", "meta")

    def __init__(self, devices):
        self.devices = tuple(devices or ())
        self.admitted_gen: Optional[int] = None
        self.state = None
        self.meta: Dict[str, object] = {}


class ElasticCoordinator:
    """See module docstring. All public methods are thread-safe."""

    def __init__(self, tracker: Optional[MembershipTracker] = None,
                 timeout_s: Optional[float] = None,
                 tick_s: float = _TICK_S,
                 clock: Callable[[], float] = None,
                 journal_dir: Optional[str] = None):
        clock = clock or time.monotonic
        self.tracker = tracker or MembershipTracker(clock=clock)
        self._clock = self.tracker._clock
        if timeout_s is None:
            from ..base import get_env
            timeout_s = float(get_env("MXNET_KVSTORE_BARRIER_TIMEOUT",
                                      300.0))
        self.timeout_s = float(timeout_s)
        self.tick_s = float(tick_s)
        self._cv = make_condition("elastic.coordinator.cv")
        self._rounds: Dict[Tuple[int, int, str], _Round] = {}
        self._barrier_arrived: Dict[int, set] = {}
        self._barrier_done: set = set()
        self._pending: Dict[str, _Join] = {}
        from ..telemetry import metrics as _metrics
        self._m_aborts = _metrics.counter(
            "mxelastic_aborted_rounds_total",
            "reduce rounds fenced by a membership change")
        self._m_rebuilds = _metrics.counter(
            "mxelastic_rebuild_barriers_total",
            "rebuild barriers completed")
        # -- mxobs: pod identity + collector + coordinated dumps ------
        # the group uid seeds every rank's derived pod.step trace id
        # (obs.propagate) — per-coordinator-instance, distributed over
        # the heartbeat flags so no extra round trip exists to race
        self.uid = f"{random.SystemRandom().getrandbits(32):08x}"
        self._obs_collector = None
        self._dump_epoch = 0
        self._dump_reason = ""
        self._dump_mono = 0.0
        # -- mxfleet: serving-worker directory (fleet.controller) -----
        # worker_id -> {role, address, meta, beat (coordinator-clock
        # mono)}. Deliberately NOT part of the training membership
        # tracker: engine workers register here without joining the
        # allreduce group, and a stale entry only costs the controller
        # one dead-dial (the Router breaker already sheds it).
        self._fleet: Dict[str, Dict[str, object]] = {}
        self._fleet_notes: Dict[str, object] = {}
        # -- control-plane journal (coordinator hardening, mxpod) -----
        # One JSON line per generation bump; a restarted rank-0 replays
        # the newest entry so the group RE-FORMS (members restored,
        # generation bumped once more) instead of orphaning every
        # worker behind a fresh empty tracker.
        if journal_dir is None:
            from .. import config
            journal_dir = str(config.get("MXPOD_JOURNAL_DIR") or "")
        self._journal_path = (
            os.path.join(journal_dir, "membership.jsonl")
            if journal_dir else None)
        self._journaled_gen: Optional[int] = None
        self.restored = False
        if self._journal_path:
            os.makedirs(journal_dir, exist_ok=True)
            last = self._read_journal_tail()
            if last is not None:
                view = self.tracker.restore(
                    int(last["generation"]), last.get("workers") or [],
                    {w: tuple(d) for w, d in
                     (last.get("devices") or {}).items()})
                self.tracker.bump("coordinator restarted: journal "
                                  "replayed")
                self.restored = True
                _metrics.counter(
                    "mxpod_coordinator_restores_total",
                    "coordinator restarts that re-formed the group "
                    "from the membership journal").inc()
                _log.warning(
                    "coordinator restart: journal %s replayed — "
                    "generation %d, %d member(s) %s restored and "
                    "bumped to %d so survivors fence and rebuild",
                    self._journal_path, view.generation,
                    view.world_size, list(view.workers),
                    self.tracker.generation)
            with self._cv:
                self._journal_sync(reason="restart" if self.restored
                                   else "open")

    # ------------------------------------------------------------------
    # the control-plane journal
    # ------------------------------------------------------------------
    def _read_journal_tail(self) -> Optional[Dict[str, object]]:
        import json
        if not self._journal_path or \
                not os.path.exists(self._journal_path):
            return None
        last = None
        try:
            with open(self._journal_path) as f:
                for ln in f:
                    ln = ln.strip()
                    if not ln:
                        continue
                    try:
                        last = json.loads(ln)
                    except ValueError:
                        # a torn tail line (crash mid-append) is
                        # expected — the previous entry still stands
                        continue
        except OSError as e:
            _log.warning("membership journal unreadable (%s): %s — "
                         "starting empty", self._journal_path, e)
            return None
        return last

    def _journal_sync(self, reason: Optional[str] = None):
        """Append the current view if its generation is not journaled
        yet. Under _cv (every mutation notify path funnels through
        here); append+flush+fsync so the entry survives a SIGKILL'd
        coordinator — the exact crash the replay exists for."""
        if not self._journal_path:
            return
        view = self.tracker.view()
        if view.generation == self._journaled_gen and reason is None:
            return
        import json
        entry = {"generation": view.generation,
                 "workers": list(view.workers),
                 "devices": {w: list(d)
                             for w, d in view.devices.items()},
                 "ts": time.time()}
        if reason:
            entry["reason"] = reason
        try:
            with open(self._journal_path, "a") as f:
                f.write(json.dumps(entry) + "\n")
                f.flush()
                os.fsync(f.fileno())
            self._journaled_gen = view.generation
        except OSError as e:  # a full disk must not kill the group
            _log.warning("membership journal append failed: %s", e)

    # ------------------------------------------------------------------
    # internals
    # ------------------------------------------------------------------
    def _poll(self):
        """Run the missed-heartbeat policy; on any verdict, wake every
        waiter so fenced rounds/barriers abort promptly. Under _cv."""
        lost = self.tracker.check()
        if lost:
            self._gc(self.tracker.generation)
            self._journal_sync()
            for w in lost:
                self._obs_retire(w)
            self._trigger_dump_locked(
                "host-lost-" + "-".join(str(w) for w in sorted(lost)))
            self._cv.notify_all()
        return lost

    # ------------------------------------------------------------------
    # mxobs: coordinated capture + the collector channel
    # ------------------------------------------------------------------
    def _trigger_dump_locked(self, reason: str) -> int:
        """Advance the pod dump epoch (under _cv) so every worker's
        DumpFollower freezes its recorder at the next beat, and freeze
        THIS process's recorder off-thread (rank 0 is a live rank too;
        file IO must not stall the control plane). Deduped: the same
        reason within the recorder's rate window advances nothing."""
        if not _obs.enabled():
            return self._dump_epoch
        now = time.monotonic()
        if reason == self._dump_reason and \
                now - self._dump_mono < 10.0:
            return self._dump_epoch
        self._dump_epoch += 1
        self._dump_reason = str(reason)[:120]
        self._dump_mono = now

        def _local():
            from ..trace import crash_dump
            crash_dump(f"pod-dump-{reason}", site="elastic.coordinator",
                       extra={"dump_epoch": self._dump_epoch})

        threading.Thread(target=_local, name="mxobs-dump",
                         daemon=True).start()
        _log.warning("pod dump epoch %d: %s — broadcasting dump-all "
                     "over the heartbeat channel", self._dump_epoch,
                     reason)
        return self._dump_epoch

    def request_dump(self, reason: str = "requested") -> int:
        """The rank-0 dump trigger (tentpole 3): watchdog verdicts,
        GroupFailed/quarantine at the leader boundary, or an operator
        (``obs_request_dump`` over the control plane) land here; the
        returned epoch rides every heartbeat until all live ranks have
        dumped into the shared MXTRACE_DUMP_DIR."""
        with self._cv:
            epoch = self._trigger_dump_locked(reason)
            self._cv.notify_all()
            return epoch

    def obs_collector(self, create: bool = True):
        """The pod metrics collector (obs.collector.MetricsCollector),
        created lazily on first use when MXOBS is on."""
        with self._cv:
            if self._obs_collector is None and create \
                    and _obs._obs_on():
                from ..obs.collector import MetricsCollector
                self._obs_collector = MetricsCollector("pod")
            return self._obs_collector

    def obs_push(self, worker_id: str, rank=None, snap=None) -> None:
        """Collector channel (tentpole 2): one host's mergeable
        metrics snapshot, pushed by its heartbeat pump every
        MXOBS_PUSH_INTERVAL_S."""
        col = self.obs_collector()
        if col is not None:
            if rank is None:
                view = self.view()
                rank = view.rank_of(worker_id) \
                    if worker_id in view.workers else -1
            col.push(worker_id, rank, snap)

    def obs_merged(self) -> Optional[Dict[str, object]]:
        """The pod-merged snapshot (None before any push / MXOBS=0)."""
        col = self.obs_collector(create=False)
        return col.merged() if col is not None else None

    # -- mxfleet: serving-worker directory -------------------------
    # The fleet control plane's source of truth for "which engine
    # hosts exist, what role each plays, and where to dial them".
    # Same discipline as the obs channel: quick ops under _cv, no
    # blocking waits, survives independently of the training
    # membership tracker.

    def fleet_register(self, worker_id: str, role: str, address: str,
                       meta=None) -> Dict[str, object]:
        """An engine worker announces itself (role: 'decode' |
        'prefill'). Idempotent — a re-register after a worker restart
        just refreshes the entry."""
        with self._cv:
            self._fleet[str(worker_id)] = {
                "role": str(role), "address": str(address),
                "meta": dict(meta or {}),
                "beat": float(self._clock()),
            }
            self._cv.notify_all()
            return {"uid": self.uid, "workers": len(self._fleet)}

    def fleet_heartbeat(self, worker_id: str,
                        depth=None) -> bool:
        """Refresh a directory entry's liveness (and optionally its
        advertised queue depth). Returns False when the worker is not
        registered — the signal to re-register after a coordinator
        restart (the directory is NOT journaled; serving workers are
        expected to outlive it and re-announce)."""
        with self._cv:
            ent = self._fleet.get(str(worker_id))
            if ent is None:
                return False
            ent["beat"] = float(self._clock())
            if depth is not None:
                ent["meta"]["depth"] = int(depth)
            return True

    def fleet_leave(self, worker_id: str) -> None:
        """Graceful directory exit (SIGTERM drain path)."""
        with self._cv:
            self._fleet.pop(str(worker_id), None)
            self._cv.notify_all()

    def fleet_view(self) -> Dict[str, object]:
        """Snapshot of the directory: entries plus each one's beat
        age on the COORDINATOR clock (callers must not compare beats
        against their own clock across hosts)."""
        with self._cv:
            now = float(self._clock())
            workers = {}
            for wid, ent in self._fleet.items():
                d = dict(ent)
                d["meta"] = dict(ent["meta"])
                d["age_s"] = max(0.0, now - float(ent["beat"]))
                workers[wid] = d
            return {"uid": self.uid, "workers": workers,
                    "notes": dict(self._fleet_notes)}

    def fleet_note(self, key: str, value) -> None:
        """Controller-published breadcrumbs (last autoscale decision,
        controller liveness) for fleet_view consumers —
        tools/diagnose.py's mxfleet section reads these."""
        with self._cv:
            self._fleet_notes[str(key)] = value

    def _obs_retire(self, worker_id: str) -> None:
        """Host left the membership plane: drop its snapshot and
        unregister its per-rank gauges (the metriclint leak class)."""
        if self._obs_collector is not None:
            self._obs_collector.retire(worker_id)

    def _gc(self, current_gen: int):
        """Drop rounds/barriers of dead generations. Under _cv. A
        round whose result was never fully collected dies with its
        generation — contributions are discarded WHOLE, which is what
        makes MembershipChanged safe to recover from."""
        for key in [k for k in self._rounds if k[0] < current_gen]:
            r = self._rounds.pop(key)
            if r.result is None:
                self._m_aborts.inc()
        for gen in [g for g in self._barrier_arrived
                    if g < current_gen - 4]:
            self._barrier_arrived.pop(gen, None)
            self._barrier_done.discard(gen)

    def _deadline_check(self, deadline: float, what: str):
        """Callers enforce the deadline AFTER re-checking their fence
        condition each tick, so a membership verdict always wins over
        a simultaneous timeout. Under _cv."""
        if self._clock() >= deadline:
            raise ElasticTimeout(
                f"elastic {what} timed out after {self.timeout_s:.1f}s "
                f"at generation {self.tracker.generation} — control "
                "plane stuck (raise MXNET_KVSTORE_BARRIER_TIMEOUT or "
                "check the coordinator host)")

    def _beat_and_poll(self, worker_id: Optional[str]):
        """Loop-top step of every blocking wait: beat for the waiter
        FIRST (a waiter blocked inside the protocol is alive by
        definition — only workers that stopped calling accrue
        heartbeat age), then run the missed-heartbeat policy so a
        verdict is visible to the caller's fence check before its
        deadline check. Under _cv."""
        if worker_id is not None:
            self.tracker.heartbeat(worker_id)
        self._poll()

    def _wait_tick(self, worker_id: Optional[str]):
        """Block for one tick, releasing _cv so peers can contribute
        (fake-clock tests keep the real cv wait — the injectable clock
        governs VERDICTS and deadlines, not the tick cadence)."""
        self._cv.wait(self.tick_s)

    def _barrier_mark(self, worker_id: str, view) -> None:
        """Record that ``worker_id`` adopted ``view``'s generation.
        Called from the rebuild barrier AND from every reduce
        contribution: a worker exchanging under generation g has
        trivially agreed to g's view, so a peer waiting at the g
        barrier must not wait for it to show up separately (the
        barrier-vs-exchange deadlock a mid-training register would
        otherwise cause). Under _cv."""
        gen = view.generation
        arrived = self._barrier_arrived.setdefault(gen, set())
        arrived.add(worker_id)
        if arrived >= set(view.workers) and \
                gen not in self._barrier_done:
            self._barrier_done.add(gen)
            self._m_rebuilds.inc()
            self._cv.notify_all()

    # ------------------------------------------------------------------
    # membership plane
    # ------------------------------------------------------------------
    def register(self, worker_id: str,
                 devices: Sequence[int] = ()) -> MembershipView:
        """Initial join (before training starts): immediate admit."""
        with self._cv:
            view = self.tracker.join(worker_id, devices)
            self._gc(view.generation)
            self._journal_sync()
            self._cv.notify_all()
            return view

    def heartbeat(self, worker_id: str, step: Optional[int] = None
                  ) -> Tuple[MembershipView, Dict[str, object]]:
        """Record a step-boundary beat. Returns the current view plus
        control flags — ``pending_join`` tells the leader someone is
        waiting to be admitted (publish state at THIS boundary)."""
        with self._cv:
            view = self.tracker.heartbeat(worker_id, step=step)
            self._poll()
            view = self.tracker.view()
            flags: Dict[str, object] = {"pending_join": any(
                j.admitted_gen is None for j in self._pending.values())}
            if _obs.enabled():
                # the obs sidecar rides the beat every worker already
                # sends: pod_uid seeds the derived pod.step trace id,
                # dump_epoch broadcasts coordinated capture (flags stay
                # tiny when nothing is happening)
                flags["pod_uid"] = self.uid
                if self._dump_epoch:
                    flags["dump_epoch"] = self._dump_epoch
                    flags["dump_reason"] = self._dump_reason
            return view, flags

    def leave(self, worker_id: str) -> MembershipView:
        """Graceful departure (preemption): bump NOW so survivors fence
        at their next exchange instead of waiting out the heartbeat
        budget."""
        with self._cv:
            view = self.tracker.leave(worker_id)
            self._gc(view.generation)
            self._journal_sync()
            self._obs_retire(worker_id)
            self._cv.notify_all()
            return view

    def mark_lost(self, worker_id: str) -> MembershipView:
        """Explicit worker-lost verdict (the watchdog action path)."""
        with self._cv:
            view = self.tracker.mark_lost(worker_id)
            self._gc(view.generation)
            self._journal_sync()
            self._obs_retire(worker_id)
            self._trigger_dump_locked(f"mark-lost-{worker_id}")
            self._cv.notify_all()
            return view

    def view(self) -> MembershipView:
        with self._cv:
            return self.tracker.view()

    # ------------------------------------------------------------------
    # data plane: generation-checked reduce
    # ------------------------------------------------------------------
    def allreduce(self, worker_id: str, generation: int, round_id: int,
                  key: str, value, timeout_s: Optional[float] = None
                  ) -> onp.ndarray:
        """Contribute ``value`` to round (generation, round_id, key)
        and block until every member of that generation contributed;
        returns the SUM (sorted-worker fold — deterministic). Raises
        :class:`MembershipChanged` the moment the generation moves."""
        value = onp.asarray(value)
        deadline = self._clock() + (timeout_s if timeout_s is not None
                                    else self.timeout_s)
        rkey = (int(generation), int(round_id), str(key))
        with self._cv:
            self.tracker.check_failed()
            view = self.tracker.view()
            if generation != view.generation:
                raise MembershipChanged(
                    f"exchange issued under generation {generation} but "
                    f"the group is at {view.generation} — rebuild and "
                    "re-issue", view.generation)
            if worker_id not in view.workers:
                raise WorkerEvicted(
                    f"worker {worker_id!r} is not a member of "
                    f"generation {view.generation}")
            r = self._rounds.get(rkey)
            if r is None:
                r = self._rounds[rkey] = _Round(view.workers)
            self._barrier_mark(worker_id, view)
            if worker_id not in r.parts:
                if r.parts:
                    first = next(iter(r.parts.values()))
                    if value.shape != first.shape or \
                            value.dtype != first.dtype:
                        raise MXNetError(
                            f"elastic allreduce {key!r} round "
                            f"{round_id}: worker {worker_id!r} "
                            f"contributed {value.dtype}{value.shape} "
                            f"against {first.dtype}{first.shape} — "
                            "workers out of lockstep")
                r.parts[worker_id] = value
                if frozenset(r.parts) >= r.expected:
                    # deterministic fold: sorted worker order, never
                    # arrival order — drills replay bit-for-bit
                    acc = None
                    for w in sorted(r.parts):
                        acc = r.parts[w] if acc is None \
                            else acc + r.parts[w]
                    r.result = acc
                    self._cv.notify_all()
            while r.result is None:
                self._beat_and_poll(worker_id)
                cur = self.tracker.generation
                if cur != generation:
                    raise MembershipChanged(
                        f"membership changed (generation {generation} "
                        f"-> {cur}) while exchange {key!r} round "
                        f"{round_id} was in flight — "
                        f"{len(r.parts)}/{len(r.expected)} "
                        "contributions arrived; rebuild and re-issue",
                        cur)
                self._deadline_check(deadline, f"allreduce({key!r})")
                self._wait_tick(worker_id)
            out = r.result
            r.taken.add(worker_id)
            if r.taken >= r.expected:
                self._rounds.pop(rkey, None)  # fully collected
            return out

    # ------------------------------------------------------------------
    # rebuild barrier
    # ------------------------------------------------------------------
    def rebuild_barrier(self, worker_id: str,
                        timeout_s: Optional[float] = None
                        ) -> MembershipView:
        """Meet the rest of the CURRENT generation before the first
        exchange after a bump. If membership changes while the barrier
        forms, it silently re-forms at the newer generation — callers
        get the FINAL agreed view."""
        deadline = self._clock() + (timeout_s if timeout_s is not None
                                    else self.timeout_s)
        with self._cv:
            while True:
                self.tracker.check_failed()
                view = self.tracker.view()
                if worker_id not in view.workers:
                    raise WorkerEvicted(
                        f"worker {worker_id!r} is not a member of "
                        f"generation {view.generation}")
                gen = view.generation
                self._barrier_mark(worker_id, view)
                while gen not in self._barrier_done and \
                        gen == self.tracker.generation:
                    self._beat_and_poll(worker_id)
                    if gen in self._barrier_done or \
                            gen != self.tracker.generation:
                        break
                    self._deadline_check(deadline, "rebuild barrier")
                    self._wait_tick(worker_id)
                if gen in self._barrier_done and \
                        gen == self.tracker.generation:
                    return self.tracker.view()
                # generation moved while we waited: re-form

    # ------------------------------------------------------------------
    # join / state sync
    # ------------------------------------------------------------------
    def announce_join(self, worker_id: str,
                      devices: Sequence[int] = ()) -> None:
        """A (re)starting worker asks to enter. It becomes a member at
        the generation bumped by the leader's admission, with the
        group's live state — never a checkpoint file."""
        with self._cv:
            self.tracker.check_failed()
            if worker_id not in self._pending:
                self._pending[worker_id] = _Join(devices)
                _log.info("worker %r announced (pending join)",
                          worker_id)
            self._cv.notify_all()

    def admit_joiners(self, leader_id: str, state,
                      meta: Optional[Dict[str, object]] = None
                      ) -> MembershipView:
        """Leader publishes the live training state at a step boundary
        and admits EVERY pending joiner in one generation bump."""
        with self._cv:
            pending = {w: j for w, j in self._pending.items()
                       if j.admitted_gen is None}
            if not pending:
                return self.tracker.view()
            view = self.tracker.admit(
                list(pending), {w: j.devices
                                for w, j in pending.items()})
            for w, j in pending.items():
                j.admitted_gen = view.generation
                j.state = state
                j.meta = dict(meta or {})
            self._gc(view.generation)
            self._journal_sync()
            self._cv.notify_all()
            _log.info("leader %r admitted %s at generation %d",
                      leader_id, sorted(pending), view.generation)
            return view

    def wait_admitted(self, worker_id: str,
                      timeout_s: Optional[float] = None
                      ) -> Tuple[MembershipView, object,
                                 Dict[str, object]]:
        """Block until a leader admits this worker; returns the view
        plus the published (state, meta) to install."""
        deadline = self._clock() + (timeout_s if timeout_s is not None
                                    else self.timeout_s)
        with self._cv:
            while True:
                self.tracker.check_failed()
                j = self._pending.get(worker_id)
                if j is None:
                    raise MXNetError(
                        f"worker {worker_id!r} never announced a join")
                if j.admitted_gen is not None:
                    state, meta = j.state, j.meta
                    del self._pending[worker_id]
                    return self.tracker.view(), state, meta
                self._poll()
                self._deadline_check(deadline, "join admission")
                # not a member yet: no heartbeat identity to tick with
                self._wait_tick(None)

    # ------------------------------------------------------------------
    # watchdog wiring (resil/watchdog.py on_verdict registry)
    # ------------------------------------------------------------------
    def watchdog_probe(self) -> List:
        """Extra Watchdog probe: one ``worker_lost`` finding per member
        over the heartbeat budget. Report-only by itself — pair with
        :meth:`watchdog_action` (Watchdog.on_verdict) to turn verdicts
        into generation bumps."""
        from ..passes import Finding
        out = []
        threshold = self.tracker.lost_after_s
        for wid, age in sorted(self.tracker.heartbeat_ages().items()):
            if age > threshold:
                out.append(Finding(
                    "watchdog", "worker_lost", f"elastic.{wid}",
                    "error",
                    f"worker {wid!r} silent for {age:.2f}s (budget "
                    f"{threshold:.2f}s = MXELASTIC_HEARTBEAT_S x "
                    "MXELASTIC_MISS_LIMIT) — candidate for a "
                    "membership bump"))
        return out

    def watchdog_action(self, finding) -> None:
        """``Watchdog.on_verdict`` handler: apply a ``worker_lost``
        finding as a membership bump. Opt-in — the watchdog default
        stays report-only."""
        if getattr(finding, "check", None) != "worker_lost":
            return
        obj = getattr(finding, "obj", "")
        if obj.startswith("elastic."):
            self.mark_lost(obj[len("elastic."):])

    def attach_watchdog(self, watchdog, act: bool = False,
                        hosts: bool = True):
        """Register the probe (and, when ``act=True``, the verdict
        action) on a :class:`~mxnet_tpu.resil.watchdog.Watchdog`.
        ``hosts=True`` (default) additionally wires the pod host-scope
        liveness probe (resil.watchdog.host_liveness_probe): per-rank
        last-beat age gauges plus a ``host_lost`` finding that names
        the rank and last generation and freezes the crash flight
        recorder on the verdict."""
        watchdog.add_probe(self.watchdog_probe)
        if hosts:
            from ..resil.watchdog import host_liveness_probe
            watchdog.add_probe(host_liveness_probe(self))
        col = self.obs_collector()
        if col is not None:
            # stall/host-loss verdicts should read FLEET state, not
            # just local counters: the staleness probe fires before
            # the heartbeat budget turns a wedged pump into a loss
            from ..obs.collector import fleet_probe
            watchdog.add_probe(fleet_probe(col))
            watchdog.on_verdict(self._obs_verdict_dump)
        if act:
            watchdog.on_verdict(self.watchdog_action)
        return watchdog

    def _obs_verdict_dump(self, finding) -> None:
        """Error-severity watchdog verdicts trigger a coordinated pod
        dump — the post-mortem directory then holds every live rank's
        recorder, not just the rank the verdict named."""
        if getattr(finding, "severity", "") == "error":
            self.request_dump(
                f"watchdog-{getattr(finding, 'check', 'verdict')}")

    # ------------------------------------------------------------------
    def describe(self) -> Dict[str, object]:
        with self._cv:
            view = self.tracker.view()
            return {"view": view.describe(),
                    "open_rounds": len(self._rounds),
                    "pending_joins": sorted(
                        w for w, j in self._pending.items()
                        if j.admitted_gen is None),
                    "heartbeat_ages": {
                        w: round(a, 3) for w, a in
                        self.tracker.heartbeat_ages().items()},
                    "lost_after_s": self.tracker.lost_after_s,
                    "journal": self._journal_path,
                    "restored": self.restored,
                    "obs": {
                        "uid": self.uid,
                        "dump_epoch": self._dump_epoch,
                        "dump_reason": self._dump_reason,
                        "collector": (
                            self._obs_collector.describe()
                            if self._obs_collector is not None
                            else None)}}
