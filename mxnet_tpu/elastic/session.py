"""ElasticSession: one worker's view of the membership protocol.

The session owns everything generation-scoped on the worker side: the
current :class:`MembershipView`, the per-generation round counter the
reduce rounds are tagged with, the effective-batch / LR-schedule
accounting that keeps the loss trajectory within the declared tolerance
of an uninterrupted run, and the state snapshot/install helpers the
join protocol uses so a rejoiner syncs **from the group**, not from a
checkpoint file.

Lifecycle::

    session = ElasticSession(group, "w0", trainer=trainer)   # register
    ...
    changed = session.heartbeat(step)        # every step boundary
    if changed:
        session.rebuild()                    # barrier + trainer re-plan

    # a (re)started worker instead:
    session = ElasticSession.join(group, "w3", trainer=trainer)
    # -> announced, admitted at the next boundary, live state installed

``group`` is anything with the :class:`~mxnet_tpu.elastic.coordinator.
ElasticCoordinator` worker surface — the coordinator itself in-process,
or the kvstore-server transport (`elastic.kvstore.RemoteGroup`) across
processes.
"""
from __future__ import annotations

import io
import pickle
import time
from typing import Dict, Optional, Sequence

import numpy as onp

from ..base import MXNetError, get_logger
from .membership import MembershipChanged, MembershipView

__all__ = ["ElasticSession"]

_log = get_logger("mxnet_tpu.elastic")


class _ElasticSchedule:
    """LR-scheduler proxy installed by :meth:`ElasticSession.attach`:
    schedulers see the session's *virtual* update count — steps scaled
    by ``world / reference_world`` — so after a shrink the schedule
    advances at the rate of samples actually consumed and the decay
    landmarks stay aligned with the uninterrupted run."""

    def __init__(self, inner, session: "ElasticSession"):
        self.inner = inner
        self.session = session

    def __call__(self, num_update):
        return self.inner(self.session.schedule_updates())

    def __getattr__(self, name):  # base_lr etc. pass through
        return getattr(self.inner, name)


class ElasticSession:
    def __init__(self, group, worker_id: str, trainer=None,
                 devices: Sequence[int] = (), register: bool = True,
                 clock=time.monotonic):
        self.group = group
        self.worker_id = str(worker_id)
        self.devices = tuple(devices)
        self._clock = clock
        self._round = 0
        self._samples = 0.0
        self._virtual_updates = 0.0
        self._ref_world: Optional[int] = None
        self._base_lr: Optional[float] = None
        self._trainer = None
        self._pump = None
        self._pump_stop = None
        self._pending_state = None  # join-before-trainer snapshot
        # -- mxobs sidecar state (absorbed from heartbeat flags) ------
        self._pod_uid: Optional[str] = None
        self._dump_follower = None
        self._last_push = 0.0
        self.start_meta: Dict[str, object] = {}
        self.view: Optional[MembershipView] = None
        if register:
            self.view = group.register(self.worker_id, self.devices)
        if trainer is not None:
            self.attach(trainer)

    # ------------------------------------------------------------------
    # identity
    # ------------------------------------------------------------------
    @property
    def generation(self) -> int:
        return self.view.generation if self.view else 0

    @property
    def world(self) -> int:
        return self.view.world_size if self.view else 1

    @property
    def rank(self) -> int:
        return self.view.rank_of(self.worker_id) if self.view else 0

    @property
    def is_leader(self) -> bool:
        return self.view is not None and \
            self.view.leader == self.worker_id

    @property
    def ref_world(self) -> int:
        """The reference world size schedule accounting is anchored to
        (the world when training started)."""
        return self._ref_world or self.world or 1

    # ------------------------------------------------------------------
    # trainer wiring
    # ------------------------------------------------------------------
    def attach(self, trainer) -> "ElasticSession":
        """Bind a gluon ``Trainer``: the trainer absorbs generation
        bumps inside ``step()`` with zero user code (docs/resilience.md
        elastic section)."""
        self._trainer = trainer
        trainer._elastic = self
        opt = trainer._optimizer
        self._base_lr = float(getattr(opt, "lr", 0.0) or 0.0)
        if self._ref_world is None:
            self._ref_world = self.world
        sched = getattr(opt, "lr_scheduler", None)
        if sched is not None and not isinstance(sched, _ElasticSchedule):
            opt.lr_scheduler = _ElasticSchedule(sched, self)
        pending = getattr(self, "_pending_state", None)
        if pending is not None:  # a join that ran before the trainer
            self._pending_state = None
            self.install_state(*pending)
        return self

    def refresh(self) -> MembershipView:
        """Adopt the group's current view without acting on it (no
        leader duties, no rebuild) — drivers call this after forming
        the initial group so every session starts at the same
        generation."""
        view, flags = self.group.heartbeat(self.worker_id)
        self.view = view
        self._absorb_flags(flags)
        return view

    # ------------------------------------------------------------------
    # the heartbeat pump
    # ------------------------------------------------------------------
    def start_heartbeat_pump(self, interval_s: Optional[float] = None):
        """Liveness side channel: a daemon thread beating at half the
        heartbeat interval, so compiles/rebuilds/IO pauses on the
        training thread never read as death. The pump carries NO
        protocol duties (no leader publish, no rebuild) — those belong
        to the step boundary; a worker killed by the drill stops its
        pump too, which is exactly what lets survivors detect it."""
        import threading
        if self._pump is not None:
            return self
        if interval_s is None:
            from .. import config
            interval_s = float(config.get("MXELASTIC_HEARTBEAT_S")) / 2.0
        stop = threading.Event()

        def pump():
            while not stop.wait(interval_s):
                try:
                    _view, flags = self.group.heartbeat(self.worker_id)
                    # the obs sidecar rides the liveness beat: absorb
                    # dump-epoch broadcasts and push the mergeable
                    # metrics snapshot on cadence — no extra thread,
                    # no extra connection
                    self._absorb_flags(flags)
                except Exception:
                    return  # evicted / group gone: the boundary will see

        self._pump_stop = stop
        self._pump = threading.Thread(
            target=pump, name=f"mxelastic-hb-{self.worker_id}",
            daemon=True)
        self._pump.start()
        return self

    def stop_heartbeat_pump(self):
        if self._pump is None:
            return
        self._pump_stop.set()
        self._pump.join(timeout=2.0)
        self._pump = None
        self._pump_stop = None

    # ------------------------------------------------------------------
    # the step boundary
    # ------------------------------------------------------------------
    def heartbeat(self, step: Optional[int] = None) -> bool:
        """Step-boundary beat. Leaders publish live state for pending
        joiners HERE (the consistent point: every parameter reflects
        the same completed step). Returns True when the generation
        moved — the caller must :meth:`rebuild` before the next
        exchange."""
        view, flags = self.group.heartbeat(self.worker_id, step=step)
        self._absorb_flags(flags)
        if flags.get("pending_join") and view.leader == self.worker_id:
            state, meta = self.snapshot_state(step=step)
            view = self.group.admit_joiners(self.worker_id, state, meta)
        changed = self.view is None or \
            view.generation != self.view.generation
        if changed:
            _log.info("worker %r observed generation %s -> %d at step "
                      "boundary", self.worker_id,
                      self.view.generation if self.view else None,
                      view.generation)
        return changed

    # ------------------------------------------------------------------
    # the mxobs sidecar (pod identity, coordinated dumps, metrics push)
    # ------------------------------------------------------------------
    def _absorb_flags(self, flags) -> None:
        """Process the obs sidecar riding every heartbeat's control
        flags: remember the group uid (seeds the derived pod.step trace
        id), follow dump-epoch broadcasts (coordinated flight capture),
        and push this host's mergeable metrics snapshot to the rank-0
        collector every MXOBS_PUSH_INTERVAL_S. Never raises; one cached
        flag read when MXOBS=0."""
        if not isinstance(flags, dict):
            return
        uid = flags.get("pod_uid")
        if uid:
            self._pod_uid = str(uid)
        from ..obs import propagate as _prop
        if not _prop._obs_on():
            return
        try:
            if self._dump_follower is None:
                from ..obs.capture import DumpFollower
                self._dump_follower = DumpFollower()
            self._dump_follower.observe(flags)
            now = time.monotonic()
            from .. import config
            if now - self._last_push >= \
                    float(config.get("MXOBS_PUSH_INTERVAL_S")):
                push = getattr(self.group, "obs_push", None)
                if push is not None:
                    self._last_push = now
                    from ..telemetry.metrics import mergeable_snapshot
                    push(self.worker_id, self.rank,
                         mergeable_snapshot())
        except Exception:  # noqa: BLE001 — telemetry never kills a beat
            pass

    @property
    def pod_uid(self) -> Optional[str]:
        """The coordinator's group uid (None until the first heartbeat
        with MXOBS+MXTRACE on, or in non-obs runs)."""
        return self._pod_uid

    def push_metrics(self) -> bool:
        """Force one immediate snapshot push (tests / shutdown flush;
        the pump handles cadence)."""
        push = getattr(self.group, "obs_push", None)
        if push is None:
            return False
        from ..telemetry.metrics import mergeable_snapshot
        self._last_push = time.monotonic()
        push(self.worker_id, self.rank, mergeable_snapshot())
        return True

    def request_pod_dump(self, reason: str = "requested"):
        """Ask rank 0 to broadcast dump-all (leaders call this on
        GroupFailed / quarantine; operators via mxprof). Returns the
        new dump epoch, or None when the group has no obs surface."""
        fn = getattr(self.group, "obs_request_dump", None) or \
            getattr(self.group, "request_dump", None)
        if fn is None:
            return None
        try:
            return fn(reason)
        except Exception:  # noqa: BLE001 — best-effort on a dying path
            return None

    def next_round(self) -> int:
        r = self._round
        self._round += 1
        return r

    def allreduce(self, key: str, value) -> onp.ndarray:
        """One generation-tagged contribution (raises
        :class:`MembershipChanged` when fenced)."""
        return self.group.allreduce(self.worker_id, self.generation,
                                    self.next_round(), key, value)

    def rebuild(self) -> MembershipView:
        """The rebuild barrier: agree on the new view with every
        member, reset the round numbering, and re-plan the trainer
        (bucket layout, shard plan, batch/LR accounting). Loops
        internally if membership changes again mid-barrier."""
        old = self.view
        t0 = self._clock()
        from .. import trace as _trace
        with _trace.span("elastic.rebuild", "elastic",
                         worker=self.worker_id,
                         from_generation=old.generation if old
                         else None) as _rb:
            view = self.group.rebuild_barrier(self.worker_id)
            self.view = view
            self._round = 0
            _rb.set(generation=view.generation,
                    world=view.world_size)
            from ..telemetry import metrics as _metrics
            _metrics.counter(
                "mxelastic_rebuilds_total",
                "generation rebuilds completed by this worker").inc()
            _metrics.histogram(
                "mxelastic_rebuild_seconds",
                "rebuild-barrier latency (bump observed -> new view "
                "agreed)").observe(self._clock() - t0)
            if self._trainer is not None:
                self._trainer._on_membership_change(old, view)
        _log.info("worker %r rebuilt: generation %d, world %d",
                  self.worker_id, view.generation, view.world_size)
        return view

    def note_step(self, batch_size: int):
        """Effective-batch accounting: one step consumed
        ``batch_size x world`` samples; the virtual update counter
        advances by ``world / ref_world`` so LR schedules track samples
        rather than wall steps across world-size changes."""
        if self._ref_world is None:
            self._ref_world = self.world
        self._samples += float(batch_size) * self.world
        self._virtual_updates += self.world / float(self.ref_world)

    def schedule_updates(self) -> int:
        return int(round(self._virtual_updates))

    @property
    def samples_seen(self) -> float:
        return self._samples

    def leave(self):
        """Graceful departure (the preempt path): bump immediately so
        survivors fence at the next exchange instead of burning the
        heartbeat budget."""
        self.group.leave(self.worker_id)

    # ------------------------------------------------------------------
    # join / state sync
    # ------------------------------------------------------------------
    def snapshot_state(self, step: Optional[int] = None):
        """Serialize the live trainer state for a joiner: parameters
        as host arrays in trainer order (POSITIONAL — gluon name
        counters differ between worker instances of the same model)
        plus the pickled updater-state blob (the format
        ``Trainer.save_states`` writes)."""
        tr = self._trainer
        if tr is None:
            return None, {"step": step}
        params = [(p.name, p.data().asnumpy()) for p in tr._params]
        try:
            opt_state = tr._updaters[0].get_states(dump_optimizer=True)
        except Exception:
            opt_state = None
        meta = {"step": step, "samples": self._samples,
                "virtual_updates": self._virtual_updates,
                "ref_world": self.ref_world,
                "base_lr": self._base_lr}
        return {"params": params, "opt_state": opt_state}, meta

    def install_state(self, state, meta: Dict[str, object]):
        """Install a leader-published snapshot into the attached
        trainer: the joiner starts from the group's LIVE weights and
        optimizer state — never a checkpoint file. Parameters map by
        trainer position (same model structure), validated by shape."""
        tr = self._trainer
        if tr is None or state is None:
            return
        entries = list(state.get("params") or [])
        if len(entries) != len(tr._params):
            raise MXNetError(
                f"elastic join: group state has {len(entries)} "
                f"parameters, this worker's model has "
                f"{len(tr._params)} — model mismatch between joiner "
                "and group")
        from ..ndarray.ndarray import array as nd_array
        for p, (name, arr) in zip(tr._params, entries):
            if p._data is not None and \
                    tuple(arr.shape) != tuple(p.data().shape):
                raise MXNetError(
                    f"elastic join: parameter {p.name!r} expects "
                    f"shape {tuple(p.data().shape)}, group published "
                    f"{name!r} with {tuple(arr.shape)} — model "
                    "mismatch between joiner and group")
            # set_data finishes a DEFERRED init from the published
            # shape — a freshly-built joiner model need never run a
            # forward before entering the group
            p.set_data(nd_array(arr))
        blob = state.get("opt_state")
        if blob is not None:
            try:
                for updater in tr._updaters:
                    updater.set_states(blob)
                    updater.optimizer = tr._updaters[0].optimizer
                tr._optimizer = tr._updaters[0].optimizer
                tr._optimizer.param_dict = {
                    i: p for i, p in enumerate(tr._params)}
            except Exception as e:
                _log.warning("elastic join: optimizer state not "
                             "installed (%s); joiner starts with fresh "
                             "state", e)
        self._samples = float(meta.get("samples") or 0.0)
        self._virtual_updates = float(meta.get("virtual_updates")
                                      or 0.0)
        if meta.get("ref_world"):
            self._ref_world = int(meta["ref_world"])
        if meta.get("base_lr") is not None:
            self._base_lr = float(meta["base_lr"])

    @classmethod
    def join(cls, group, worker_id: str, trainer=None,
             devices: Sequence[int] = (), timeout_s: Optional[float]
             = None) -> "ElasticSession":
        """The rejoin protocol: announce, wait for a leader to admit
        us with the group's live state, install it, and meet the group
        at the rebuild barrier. Returns a session already inside the
        new generation."""
        self = cls(group, worker_id, trainer=None, devices=devices,
                   register=False)
        if trainer is not None:
            self.attach(trainer)
        group.announce_join(self.worker_id, self.devices)
        view, state, meta = group.wait_admitted(self.worker_id,
                                                timeout_s=timeout_s)
        self.view = view
        self.start_meta = dict(meta or {})
        if self._trainer is not None:
            self.install_state(state, meta)
        else:
            # trainer built after the join (the kvstore-first order):
            # attach() installs this pending snapshot
            self._pending_state = (state, dict(meta or {}))
        # keep beating while the joiner compiles its step programs —
        # survivors are already waiting on its first contribution
        self.start_heartbeat_pump()
        # meet the survivors before the first exchange; membership may
        # move again mid-barrier — rebuild() loops until agreed
        self.rebuild()
        from ..telemetry import metrics as _metrics
        _metrics.counter(
            "mxelastic_rejoins_total",
            "workers that rejoined via group state sync").inc()
        return self

    def __repr__(self):
        return (f"<ElasticSession {self.worker_id!r} gen="
                f"{self.generation} world={self.world}"
                f"{' leader' if self.is_leader else ''}>")
