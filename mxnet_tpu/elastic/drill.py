"""Deterministic in-process elastic drills.

``run_elastic_drill`` stands up N worker threads sharing one
:class:`ElasticCoordinator` — each with its own model replica, gluon
``Trainer`` over an :class:`ElasticKVStore`, and split-phase
:class:`ElasticStepFunction` — trains a small regression MLP in
lockstep, kills (or preempts) one worker at a scripted step via the
``MXRESIL_FAULT_PLAN`` thread-mode actions, optionally rejoins a fresh
worker through the group state-sync, and reports:

- per-phase (full group / shrunk / rejoined) median step rates and the
  aggregate-throughput ratios;
- recovery time (kill → first completed post-rebuild step) and the
  number of steps the survivors had in flight when fenced;
- the re-key budget: per surviving worker, exactly ONE new update
  program per NEW world size, grad programs untouched, and zero
  further compiles in the steady state after a rebuild;
- final mean loss (for the loss-trajectory contract against an
  uninterrupted baseline, ``MXELASTIC_LOSS_TOL``).

Faults are scripted, never timed: ``elastic.worker.<rank>:K=kill``
fires at step K of that worker exactly. Shared by
``tools/mxresil.py elastic``, ``bench.py --elastic`` and the tier-1
integration test.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import numpy as onp

from ..base import get_logger
from .coordinator import ElasticCoordinator
from .membership import GroupFailed, MembershipTracker, WorkerEvicted

__all__ = ["run_elastic_drill", "run_pod_drill"]


def run_pod_drill(*args, **kwargs):
    """The subprocess N-HOST harness: same drill contract, but every
    worker is a real host process over the socket-transport exchange
    (SIGKILL'able, coordinator-restartable). Implementation lives in
    :mod:`mxnet_tpu.pod.drill`; re-exported here because the two
    harnesses are the two rungs of one ladder — threads prove the
    protocol, processes prove the pod."""
    from ..pod.drill import run_pod_drill as _impl
    return _impl(*args, **kwargs)

_log = get_logger("mxnet_tpu.elastic")


def _make_data(seed: int, in_dim: int, out_dim: int):
    """The fixed regression task: y = tanh(x W) with a seeded W —
    every worker/batch draws from it deterministically."""
    rng = onp.random.RandomState(seed)
    w = rng.uniform(-1, 1, size=(in_dim, out_dim)).astype("float32")

    def batch(worker_seed: int, step: int, batch_size: int):
        r = onp.random.RandomState(
            (seed * 1000003 + worker_seed * 9973 + step) % (2 ** 31))
        x = r.uniform(-1, 1, size=(batch_size, in_dim)).astype("float32")
        y = onp.tanh(x @ w).astype("float32")
        return x, y

    return batch


class _DrillWorker:
    def __init__(self, rank: int, group, cfg: dict, join: bool = False):
        import mxnet_tpu as mx
        from mxnet_tpu import gluon
        from .kvstore import ElasticKVStore

        self.rank = rank
        self.wid = f"w{rank}"
        self.cfg = cfg
        self.join = join
        self.steps: List[Dict] = []  # {step, t, loss, world, gen}
        self.death: Optional[str] = None
        self.error: Optional[BaseException] = None
        self.recovered_at: Optional[float] = None
        self.rekeys: List[Dict] = []
        self.thread: Optional[threading.Thread] = None

        # identical initial weights on every ORIGINAL worker: re-seed
        # the global stream before each net's initialize (a rejoiner's
        # init is irrelevant — it installs the group's live state)
        mx.random.seed(cfg["seed"])
        onp.random.seed(cfg["seed"])
        net = gluon.nn.HybridSequential()
        with net.name_scope():
            net.add(gluon.nn.Dense(cfg["hidden"], activation="relu",
                                   flatten=False))
            net.add(gluon.nn.Dense(cfg["out_dim"], flatten=False))
        net.initialize()
        self.net = net
        self.loss_fn = gluon.loss.L2Loss()
        if join:
            # announce → admitted with the group's live state →
            # rebuild barrier; blocks until a leader's step boundary
            # (the join path starts its heartbeat pump itself)
            self.kv = ElasticKVStore(group=group, worker_id=self.wid,
                                     join=True)
        else:
            self.kv = ElasticKVStore(group=group, worker_id=self.wid)
            # beat from the moment of registration: trainer/step
            # construction and the first compile must not read as death
            self.kv.session.start_heartbeat_pump(
                cfg["hb_interval"] / 2.0)
        self.trainer = gluon.Trainer(
            net.collect_params(), "sgd",
            {"learning_rate": cfg["lr"]}, kvstore=self.kv,
            update_on_kvstore=False)
        self.fused = self.trainer.fuse_step(net, self.loss_fn)
        self.session = self.kv.session
        self.start_step = int(self.session.start_meta.get("step") or 0) \
            if join else 0

    def programs(self):
        return self.fused.program_counts()

    def worlds(self):
        """Distinct world sizes this worker completed steps at — the
        re-key budget is exactly one UPDATE program per entry (and one
        grad program total)."""
        return sorted({r["world"] for r in self.steps})

    def run(self):
        from ..guard.voting import GuardQuarantined
        from ..resil import faultplan
        from ..resil.faultplan import WorkerKilled, WorkerPreempted
        from mxnet_tpu.ndarray.ndarray import array as nd_array
        cfg = self.cfg
        data = cfg["data"]
        self.session.start_heartbeat_pump(cfg["hb_interval"] / 2.0)
        try:
            for step in range(self.start_step, cfg["steps"]):
                t0 = time.perf_counter()
                try:
                    faultplan.inject(f"elastic.worker.{self.rank}",
                                     step=step, thread_mode=True)
                    x, y = data(self.rank, step, cfg["batch"])
                    loss = self.fused.step(nd_array(x), nd_array(y))
                    lval = float(onp.mean(loss.asnumpy()))
                except WorkerKilled:
                    # hard death: no leave, no pump — survivors must
                    # detect this through missed heartbeats alone
                    self.death = "killed"
                    self.session.stop_heartbeat_pump()
                    return
                except WorkerPreempted:
                    self.death = "preempted"
                    self.session.leave()
                    self.session.stop_heartbeat_pump()
                    return
                except GuardQuarantined:
                    # the fingerprint vote named this worker and the
                    # corruption reproduced under re-execution: the
                    # step already left the group (the membership bump
                    # survivors fence on) — just stop driving it
                    self.death = "quarantined"
                    self.session.stop_heartbeat_pump()
                    return
                self.steps.append({
                    "step": step, "t": time.perf_counter() - t0,
                    "loss": lval, "world": self.session.world,
                    "gen": self.session.generation,
                    "wall": time.perf_counter()})
            self.session.leave()  # clean exit: don't burn the budget
        except (GroupFailed, WorkerEvicted) as e:
            self.death = type(e).__name__
            self.error = e
        except BaseException as e:  # pragma: no cover - surfaced up
            self.error = e
        finally:
            self.session.stop_heartbeat_pump()

    def start(self):
        self.thread = threading.Thread(
            target=self.run, name=f"mxelastic-drill-{self.wid}",
            daemon=True)
        self.thread.start()
        return self


def _median(vals):
    vals = sorted(vals)
    return vals[len(vals) // 2] if vals else None


def _phase_rate(workers, lo_gen, hi_gen, batch):
    """Aggregate samples/sec for steps whose generation g satisfies
    lo_gen <= g < hi_gen (None = unbounded), from the median per-step
    time x contributing world size."""
    times, worlds = [], []
    for w in workers:
        for rec in w.steps:
            if (lo_gen is None or rec["gen"] >= lo_gen) and \
                    (hi_gen is None or rec["gen"] < hi_gen):
                times.append(rec["t"])
                worlds.append(rec["world"])
    med = _median(times)
    if med is None or med <= 0:
        return None, 0
    world = max(worlds) if worlds else 0
    return world * batch / med, len(times)


def run_elastic_drill(n_workers: int = 3, steps: int = 40,
                      kill_step: Optional[int] = None,
                      kill_rank: int = 1, action: str = "kill",
                      rejoin: bool = False,
                      rejoin_after_steps: int = 6, batch: int = 8,
                      in_dim: int = 16, hidden: int = 32,
                      out_dim: int = 4, lr: float = 0.05,
                      seed: int = 0, hb_interval: float = 0.1,
                      miss_limit: int = 3, min_world: int = 1,
                      timeout_s: float = 120.0,
                      fault_plan: Optional[str] = None,
                      guard: bool = False) -> Dict[str, object]:
    """One scripted drill (see module docstring); returns the report
    dict. ``kill_step=None`` runs the uninterrupted baseline.

    ``action="sdc"`` (or ``"sdc:scale"``) is the mxguard
    silent-corruption drill: instead of dying, the selected worker's
    gradients are corrupted by one element from ``kill_step`` onward
    (the ``guard.sdc.<worker_id>`` site, persistent ``:K+`` selector), the
    fingerprint vote catches it pre-averaging, and the worker is
    QUARANTINED through the same membership-bump machinery a kill
    exercises — the report gains a ``guard`` section (detection step,
    attribution, per-worker verdicts). MXGUARD taps are forced on for
    every worker of an sdc drill (or via ``guard=True`` with any
    action); ``fault_plan`` overrides the drill-owned plan entirely
    (custom-selector drills, e.g. a transient ``@K`` sdc clause)."""
    from mxnet_tpu import config
    from ..resil import faultplan

    sdc = action.startswith("sdc")
    saved_plan = config.get("MXRESIL_FAULT_PLAN")
    config.set_flag("MXELASTIC_HEARTBEAT_S", hb_interval)
    config.set_flag("MXELASTIC_MISS_LIMIT", miss_limit)
    config.set_flag("MXELASTIC_MIN_WORLD", min_world)
    if sdc or guard:
        mode = action.split(":", 1)[1] if ":" in action else "bitflip"
        config.set_flag("MXGUARD", True)
    if fault_plan is not None:
        config.set_flag("MXRESIL_FAULT_PLAN", fault_plan)
    elif kill_step is not None:
        config.set_flag(
            "MXRESIL_FAULT_PLAN",
            f"guard.sdc.w{kill_rank}:{kill_step}+=sdc:{mode}" if sdc
            else f"elastic.worker.{kill_rank}:{kill_step}={action}")
    else:
        config.set_flag("MXRESIL_FAULT_PLAN", "")
    faultplan.reset()
    try:
        return _run(n_workers, steps, kill_step, kill_rank, action,
                    rejoin, rejoin_after_steps, batch, in_dim, hidden,
                    out_dim, lr, seed, hb_interval, miss_limit,
                    min_world, timeout_s)
    finally:
        # restore a caller's programmatic plan override; with none,
        # drop ours so the env/default value resolves again (the
        # restore-then-unset form would discard the caller's override
        # — same bug class fixed in guard/replay.py)
        if saved_plan:
            config.set_flag("MXRESIL_FAULT_PLAN", saved_plan)
        else:
            config.unset_flag("MXRESIL_FAULT_PLAN")
        faultplan.reset()
        for f in ("MXELASTIC_HEARTBEAT_S", "MXELASTIC_MISS_LIMIT",
                  "MXELASTIC_MIN_WORLD"):
            config.unset_flag(f)
        if sdc or guard:
            config.unset_flag("MXGUARD")


def _run(n_workers, steps, kill_step, kill_rank, action, rejoin,
         rejoin_after_steps, batch, in_dim, hidden, out_dim, lr, seed,
         hb_interval, miss_limit, min_world, timeout_s):
    tracker = MembershipTracker(heartbeat_interval_s=hb_interval,
                                miss_limit=miss_limit,
                                min_world=min_world)
    co = ElasticCoordinator(tracker=tracker, timeout_s=timeout_s,
                            tick_s=min(0.02, hb_interval / 4.0))
    cfg = dict(steps=steps, batch=batch, lr=lr, seed=seed,
               hidden=hidden, out_dim=out_dim, hb_interval=hb_interval,
               data=_make_data(seed, in_dim, out_dim))

    t_start = time.perf_counter()
    workers = [_DrillWorker(r, co, cfg) for r in range(n_workers)]
    # one agreed starting view before anyone steps (registration churn
    # is not what this drill measures)
    for w in workers:
        w.session.refresh()
    gen0 = co.view().generation

    for w in workers:
        w.start()

    report: Dict[str, object] = {
        "workers": n_workers, "steps": steps, "kill_step": kill_step,
        "action": action if kill_step is not None else None,
        "rejoin": bool(rejoin and kill_step is not None),
        "batch": batch, "gen0": gen0}
    joiner = None
    t_kill = None
    gen_after_kill = None

    if kill_step is not None:
        # wait for the membership verdict (scripted step, measured
        # recovery — the only timing here is the detection itself)
        deadline = time.time() + timeout_s

        def _check_errors(ws):
            for w in ws:
                if w.error is not None:
                    raise w.error

        while co.view().generation == gen0:
            if time.time() > deadline:
                raise RuntimeError("drill: kill was never detected")
            _check_errors(workers)
            time.sleep(hb_interval / 4.0)
        t_kill = time.perf_counter()
        gen_after_kill = co.view().generation
        survivors = [w for w in workers if w.rank != kill_rank]
        # first completed step at the post-kill generation = recovered
        while not any(any(r["gen"] >= gen_after_kill for r in w.steps)
                      for w in survivors):
            if time.time() > deadline:
                raise RuntimeError("drill: survivors never recovered")
            _check_errors(survivors)
            time.sleep(hb_interval / 4.0)
        t_rec = time.perf_counter()
        report["recovery_s"] = round(t_rec - t_kill, 4)
        report["world_after_kill"] = co.view().world_size

        if rejoin:
            # let the shrunk group reach steady state first (the
            # post-shrink throughput phase needs real steps, not the
            # one that paid the update-program re-key)
            def shrunk_steps():
                return max((sum(1 for r in w.steps
                                if r["gen"] >= gen_after_kill)
                            for w in survivors), default=0)
            while shrunk_steps() < rejoin_after_steps:
                if time.time() > deadline:
                    raise RuntimeError(
                        "drill: shrunk phase never reached "
                        f"{rejoin_after_steps} steps")
                _check_errors(survivors)
                time.sleep(hb_interval / 4.0)
            joiner = _DrillWorker(n_workers, co, cfg, join=True)
            joiner.start()

    for w in workers:
        w.thread.join(timeout=timeout_s)
    if joiner is not None:
        joiner.thread.join(timeout=timeout_s)
    wall = time.perf_counter() - t_start

    live = [w for w in workers + ([joiner] if joiner else [])
            if w.thread is not None]
    for w in live:
        if w.thread.is_alive():
            raise RuntimeError(f"drill: worker {w.wid} wedged "
                               f"(report so far: {report})")
        if w.error is not None:
            raise w.error

    # ---- phases by generation: [gen0, kill) / [kill, rejoin) / rest
    all_workers = workers + ([joiner] if joiner else [])
    if kill_step is not None:
        rate_full, n_full = _phase_rate(workers, None, gen_after_kill,
                                        batch)
        gen_rejoin = None
        if joiner is not None and joiner.steps:
            gen_rejoin = min(r["gen"] for r in joiner.steps)
        rate_shrunk, n_shrunk = _phase_rate(
            all_workers, gen_after_kill, gen_rejoin, batch)
        report["rate_full_samples_per_s"] = \
            round(rate_full, 2) if rate_full else None
        report["rate_shrunk_samples_per_s"] = \
            round(rate_shrunk, 2) if rate_shrunk else None
        report["shrink_throughput_ratio"] = (
            round(rate_shrunk / rate_full, 4)
            if rate_full and rate_shrunk else None)
        if gen_rejoin is not None:
            rate_re, n_re = _phase_rate(all_workers, gen_rejoin, None,
                                        batch)
            report["rate_rejoined_samples_per_s"] = \
                round(rate_re, 2) if rate_re else None
            report["rejoin_gen"] = gen_rejoin
        # the re-key budget, deterministic absolute counts: ONE grad
        # program per worker, ONE update program per distinct world
        # size it trained at, nothing else — any excess is a
        # steady-state recompile after a rebuild
        report["rekeys"] = {
            w.wid: {"grad": w.programs()["grad"],
                    "update": w.programs()["update"],
                    "worlds": w.worlds()}
            for w in all_workers if w.rank != kill_rank}
        report["recompiles_after_rebuild"] = sum(
            max(0, w.programs()["grad"] - 1)
            + max(0, w.programs()["update"] - len(w.worlds()))
            for w in all_workers if w.rank != kill_rank)
    else:
        rate, n = _phase_rate(workers, None, None, batch)
        report["rate_full_samples_per_s"] = round(rate, 2) if rate \
            else None

    # final loss: mean of each final member's last recorded loss
    finals = [w.steps[-1]["loss"] for w in all_workers
              if w.steps and w.death is None]
    report["final_loss"] = round(float(onp.mean(finals)), 6) if finals \
        else None
    report["final_view"] = co.view().describe()
    report["wall_s"] = round(wall, 3)
    report["per_worker"] = {
        w.wid: {"steps": len(w.steps), "death": w.death,
                "programs": w.programs(),
                "start_step": w.start_step}
        for w in all_workers}

    # mxguard verdict summary (sdc drills): who was suspected, when,
    # and whether the quarantine landed through a membership bump
    events = {w.wid: list(w.fused.guard_events) for w in all_workers
              if w.fused.guard_events}
    if events:
        suspect_steps = [e["step"] for evs in events.values()
                         for e in evs if e["kind"] == "suspect"]
        suspects = [s for evs in events.values() for e in evs
                    if e["kind"] in ("suspect", "persistent")
                    for s in (e["suspect"] if isinstance(
                        e["suspect"], list) else [e["suspect"]])]
        quarantined = [w.wid for w in all_workers
                       if w.death == "quarantined"]
        report["guard"] = {
            "detected_step": min(suspect_steps) if suspect_steps
            else None,
            "suspects": sorted(set(suspects)),
            "quarantined": quarantined,
            "events": events}
    return report
