"""ElasticKVStore: the synchronous data-parallel store that survives
membership changes.

``dist_sync`` maps the exchange onto collectives that wait forever on a
dead peer; ``dist_async`` survives deaths but gives up synchronous
semantics. This store keeps the synchronous contract — one flat-bucket
allreduce per exchange, every live worker contributes — while fencing
every round with the membership generation: a worker that dies mid-step
turns the survivors' blocking wait into a typed
:class:`~mxnet_tpu.elastic.membership.MembershipChanged` (absorbed by
the gluon ``Trainer`` / ``ElasticStepFunction`` rebuild path) instead
of a silent wedge.

Two transports behind one ``group`` duck type:

- in-process: pass the :class:`~mxnet_tpu.elastic.coordinator.
  ElasticCoordinator` directly (the drill harness, tier-1 tests);
- multi-process: :class:`RemoteGroup` speaks the ``elastic.*`` command
  family of the rank-0 kvstore server (`kvstore_server.KVServer`) over
  the same framed-pickle wire as ``dist_async`` — the server relays
  typed membership errors so the worker-side rebuild logic is
  transport-blind.

``supports_flat_allreduce = True`` and ``elastic_abort =
"generation"`` — the contract ``passes/elasticlint.py`` audits: any
store claiming the flat-allreduce fast path must say how a blocked
exchange aborts when a peer dies.
"""
from __future__ import annotations

import os
from typing import Optional, Sequence

import jax.numpy as jnp

from ..base import MXNetError, get_logger
from ..kvstore import KVStoreBase
from ..ndarray.ndarray import NDArray, _wrap
from ..obs import propagate as _obs_prop
from .membership import MembershipChanged
from .session import ElasticSession

__all__ = ["ElasticKVStore", "RemoteGroup"]

_log = get_logger("mxnet_tpu.elastic")


class RemoteGroup:
    """Worker-side proxy for a coordinator living inside the rank-0
    kvstore server. Mirrors the ElasticCoordinator worker surface 1:1;
    each call is one framed request (kvstore_server.KVClient), and the
    server relays :class:`MembershipChanged` / eviction as typed
    replies so rebuild logic cannot tell the transports apart."""

    def __init__(self, address: Optional[str] = None,
                 client=None, retries: int = 50):
        from .. import kvstore_server as srv
        if client is not None:
            self._client = client
        else:
            if address is None:
                address = srv.server_address()
            if address is None:
                raise MXNetError(
                    "elastic RemoteGroup needs a server address: launch "
                    "via tools/launch.py (exports MX_KV_SERVER) or set "
                    "MX_KV_SERVER=host:port")
            self._client = srv.KVClient(address, retries=retries)

    def _req(self, op, **payload):
        wire = _obs_prop.wire_context()
        if wire is not None:
            # carried trace context (mxobs): the rank-0 server runs
            # this op under OUR span, so fenced rounds and barriers
            # stitch into the calling rank's trace. One dict compare
            # when MXOBS/MXTRACE is off — never a recompile.
            payload["_trace"] = wire
        return self._client.request("elastic", op, payload)

    def register(self, worker_id, devices=()):
        return self._req("register", worker_id=worker_id,
                         devices=tuple(devices))

    def heartbeat(self, worker_id, step=None):
        return self._req("heartbeat", worker_id=worker_id, step=step)

    def leave(self, worker_id):
        return self._req("leave", worker_id=worker_id)

    def mark_lost(self, worker_id):
        return self._req("mark_lost", worker_id=worker_id)

    def view(self):
        return self._req("view")

    def allreduce(self, worker_id, generation, round_id, key, value,
                  timeout_s=None):
        return self._req("allreduce", worker_id=worker_id,
                         generation=int(generation),
                         round_id=int(round_id), key=str(key),
                         value=value, timeout_s=timeout_s)

    def rebuild_barrier(self, worker_id, timeout_s=None):
        return self._req("rebuild_barrier", worker_id=worker_id,
                         timeout_s=timeout_s)

    def announce_join(self, worker_id, devices=()):
        return self._req("announce_join", worker_id=worker_id,
                         devices=tuple(devices))

    def wait_admitted(self, worker_id, timeout_s=None):
        return self._req("wait_admitted", worker_id=worker_id,
                         timeout_s=timeout_s)

    def admit_joiners(self, leader_id, state, meta=None):
        return self._req("admit_joiners", leader_id=leader_id,
                         state=state, meta=meta)

    def describe(self):
        return self._req("describe")

    # -- mxobs sidecar ops --------------------------------------------
    def obs_push(self, worker_id, rank=None, snap=None):
        return self._req("obs_push", worker_id=worker_id, rank=rank,
                         snap=snap)

    def obs_merged(self):
        return self._req("obs_merged")

    def obs_request_dump(self, reason="requested"):
        return self._req("obs_request_dump", reason=str(reason))

    # -- mxfleet serving-worker directory ops --------------------------
    def fleet_register(self, worker_id, role, address, meta=None):
        return self._req("fleet_register", worker_id=worker_id,
                         role=role, address=address, meta=meta)

    def fleet_heartbeat(self, worker_id, depth=None):
        return self._req("fleet_heartbeat", worker_id=worker_id,
                         depth=depth)

    def fleet_leave(self, worker_id):
        return self._req("fleet_leave", worker_id=worker_id)

    def fleet_view(self):
        return self._req("fleet_view")

    def fleet_note(self, key, value=None):
        return self._req("fleet_note", key=key, value=value)

    def close(self):
        self._client.close()


class ElasticKVStore(KVStoreBase):
    """'elastic' kvstore (see module docstring).

    The dense exchange rides :meth:`allreduce_flat` (the gluon Trainer
    bucketed path); the per-key push/pull fallback reduces through the
    same generation-checked rounds via ``_global_reduce``. Reductions
    return the SUM over the current members — callers fold the
    ``1/world`` normalization into ``rescale_grad``, which is exactly
    the structural scalar whose change re-keys the fused step once per
    world-size change (docs/resilience.md).
    """

    supports_flat_allreduce = True
    # elasticlint contract: how a blocked exchange aborts when a peer
    # dies — "generation" means every round is fenced by the membership
    # generation and raises the typed MembershipChanged
    elastic_abort = "generation"
    # guardlint contract: the mxguard fingerprint vote rides a fenced
    # round BEFORE the bucket allreduce (ElasticStepFunction pairs the
    # taps with this store's generation-checked rounds)
    guard_tap = "pre-exchange"
    # podlint contract (passes/elasticlint.PodScopeAudit): this store's
    # exchange crosses HOST PROCESSES, so membership must be able to
    # tell a dead host from a slow one — "control-socket" names the
    # liveness channel (per-host beats to the rank-0 coordinator, the
    # heartbeat pump + every blocked protocol wait). A pod-scope store
    # without a heartbeat channel turns every host loss into a
    # full-budget hang; without generation fencing, into a wedge.
    pod_scope = True
    heartbeat_channel = "control-socket"

    def __init__(self, group=None, worker_id: Optional[str] = None,
                 devices: Sequence[int] = (), join: bool = False,
                 trainer=None):
        super().__init__()
        self._type = "elastic"
        if group is None:
            group = RemoteGroup()
        if worker_id is None:
            from ..base import worker_rank
            worker_id = os.environ.get("MX_WORKER_ID",
                                       f"w{worker_rank()}")
        self.group = group
        if join:
            self.session = ElasticSession.join(
                group, worker_id, trainer=trainer, devices=devices)
        else:
            self.session = ElasticSession(
                group, worker_id, trainer=trainer, devices=devices)
        # transient transport faults retry; a membership fence must NOT
        # be retried blind — the REBUILD is the retry (session.rebuild)
        from ..resil.policy import RetryPolicy
        self._policy = RetryPolicy(name="elastic.allreduce",
                                   no_retry=(MembershipChanged,))

    # -- identity ---------------------------------------------------------
    @property
    def rank(self) -> int:
        return self.session.rank

    @property
    def num_workers(self) -> int:
        return self.session.world

    # -- data plane -------------------------------------------------------
    def _reduce_round(self, key: str, data):
        """One generation-checked round under the retry policy, with
        the kvstore.push fault-injection site evaluated per attempt
        (drills exercise the REAL recovery path)."""
        from ..resil import faultplan

        def attempt():
            faultplan.inject("kvstore.push")
            return self.session.allreduce(key, data)

        return self._policy.call(attempt)

    def allreduce_flat(self, key, value: NDArray) -> NDArray:
        from ..kvstore import _kv_timer
        with _kv_timer("kvstore_bucket_seconds"):
            import numpy as onp
            reduced = self._reduce_round(key, onp.asarray(value._data))
            return _wrap(jnp.asarray(reduced).astype(value._data.dtype))

    def _global_reduce(self, key, val: NDArray) -> NDArray:
        # the per-key push/pull fallback (sparse leftovers) crosses
        # workers through the same fenced rounds
        import numpy as onp
        reduced = self._reduce_round(f"__key_{key}",
                                     onp.asarray(val._data))
        return _wrap(jnp.asarray(reduced).astype(val._data.dtype))

    def barrier(self):
        """A plain barrier is a zero-payload reduce round: completes
        when every current member arrives, fences on membership
        change like everything else."""
        import numpy as onp
        self._reduce_round("__barrier__", onp.zeros((), onp.float32))

    def close(self):
        self.session.stop_heartbeat_pump()
        close = getattr(self.group, "close", None)
        if close is not None:
            close()
