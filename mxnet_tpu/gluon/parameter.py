"""Gluon Parameter / ParameterDict.

ref: python/mxnet/gluon/parameter.py (1,029 LoC) — Parameter with deferred
initialization, grad_req, per-context copies; ParameterDict with prefix
scoping. TPU-native: one jax buffer per parameter (replication/sharding is
the mesh's job under pjit, not a per-GPU copy list — SURVEY.md §2.4).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as onp

from .. import initializer as init_mod
from ..base import MXNetError
from ..context import Context, cpu, current_context
from ..ndarray.ndarray import NDArray, zeros as nd_zeros, array as nd_array

__all__ = ["Parameter", "Constant", "ParameterDict", "DeferredInitializationError"]


class DeferredInitializationError(MXNetError):
    """ref: parameter.py DeferredInitializationError."""


class Parameter:
    def __init__(self, name, grad_req="write", shape=None, dtype="float32",
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype="default", grad_stype="default"):
        self._var = None
        self._data: Optional[NDArray] = None
        self._grad: Optional[NDArray] = None
        self._deferred_init = ()
        self.name = name
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self.grad_req = grad_req if differentiable else "null"
        self._differentiable = differentiable
        self._stype = stype
        self._grad_stype = grad_stype
        self._trainer = None
        # one-shot callbacks fired right after a deferred init resolves
        # (e.g. horovod_compat.broadcast_parameters syncing a param whose
        # shape was unknown at broadcast time)
        self._post_init_hooks = []

    def __repr__(self):
        return f"Parameter {self.name} (shape={self.shape}, dtype={self.dtype})"

    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if self._shape is None:
            self._shape = tuple(new_shape) if new_shape else None
            return
        # allow filling in unknown (0) dims
        assert len(self._shape) == len(new_shape) and all(
            s == 0 or s == n for s, n in zip(self._shape, new_shape)), \
            f"Expected shape {self._shape} is incompatible with given " \
            f"shape {new_shape} for Parameter {self.name}"
        self._shape = tuple(new_shape)

    # ------------------------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        """ref: parameter.py initialize — supports deferred init when the
        shape is not yet known (filled by the first forward)."""
        default_init = default_init or init_mod.Uniform()
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = current_context()
        if isinstance(ctx, Context):
            ctx = [ctx]
        if self._shape is None or any(s == 0 for s in self._shape):
            if self.allow_deferred_init:
                self._deferred_init = (init, ctx, default_init)
                return
            raise MXNetError(
                f"Cannot initialize Parameter {self.name} because it has "
                f"invalid shape: {self._shape}.")
        self._finish_init(init, ctx, default_init)

    def _finish_init(self, init, ctx, default_init):
        self._deferred_init = ()
        data = nd_zeros(self._shape, ctx[0] if ctx else None,
                        dtype=onp.dtype(self.dtype).name
                        if not isinstance(self.dtype, str) else self.dtype)
        initializer = init or self.init or default_init
        if isinstance(initializer, str):
            initializer = init_mod.create(initializer)
        initializer(init_mod.InitDesc(self.name), data)
        self._data = data
        if self.grad_req != "null":
            self._grad = nd_zeros(self._shape, ctx[0] if ctx else None,
                                  dtype=str(data.dtype))
            from .. import autograd as ag
            ag.mark_variables([self._data], [self._grad], [self.grad_req])
            # the data NDArray itself carries the grad buffer
            self._data._grad = self._grad
            self._data._grad_req = self.grad_req
        # fire here (not in _finish_deferred_init) so hooks run however
        # the init resolves — first forward OR a later initialize()
        # with the shape filled in / force_reinit
        hooks, self._post_init_hooks = self._post_init_hooks, []
        for hook in hooks:
            hook(self)

    def _finish_deferred_init(self):
        if not self._deferred_init:
            return
        init, ctx, default_init = self._deferred_init
        if self._shape is None or any(s == 0 for s in self._shape):
            raise DeferredInitializationError(
                f"Parameter {self.name} has unknown shape")
        self._finish_init(init, ctx, default_init)

    def _check_initialized(self):
        if self._data is not None:
            return
        if self._deferred_init:
            raise DeferredInitializationError(
                f"Parameter {self.name} has not been initialized yet because "
                f"initialization was deferred. Actual initialization happens "
                f"during the first forward pass.")
        raise MXNetError(
            f"Parameter {self.name} has not been initialized. You should "
            f"initialize parameters with Block.initialize().")

    # ------------------------------------------------------------------
    def data(self, ctx=None) -> NDArray:
        self._check_initialized()
        return self._data

    def list_data(self):
        return [self.data()]

    def grad(self, ctx=None) -> NDArray:
        self._check_initialized()
        if self._grad is None:
            raise MXNetError(
                f"Cannot get gradient array for Parameter {self.name} "
                f"because grad_req='null'")
        return self._grad

    def list_grad(self):
        return [self.grad()]

    def list_ctx(self):
        self._check_initialized()
        return [self._data.ctx]

    def set_data(self, data):
        self.shape = data.shape
        if self._data is None:
            if not self._deferred_init:
                raise MXNetError(
                    f"Parameter {self.name} has not been initialized")
            self._finish_deferred_init()
        dt = self._data._data.dtype
        self._data._rebind(
            data._data.astype(dt) if isinstance(data, NDArray)
            else nd_array(data)._data.astype(dt))

    def zero_grad(self):
        if self._grad is not None:
            import jax.numpy as jnp
            self._grad._rebind(jnp.zeros_like(self._grad._data))

    def reset_ctx(self, ctx):
        pass

    def cast(self, dtype):
        from ..ndarray.ndarray import _canon_dtype
        self.dtype = dtype
        dt = _canon_dtype(dtype) if isinstance(dtype, str) else dtype
        if self._data is not None:
            self._data._rebind(self._data._data.astype(dt))
            if self._grad is not None:
                self._grad._rebind(self._grad._data.astype(dt))

    def var(self):
        """Symbol placeholder for SymbolBlock interop."""
        if self._var is None:
            from ..symbol.symbol import Variable
            self._var = Variable(self.name, shape=self.shape,
                                 dtype=self.dtype)
        return self._var

    @property
    def stype(self):
        return self._stype


class Constant(Parameter):
    """ref: parameter.py Constant — non-trainable parameter with fixed
    value."""

    def __init__(self, name, value):
        if not isinstance(value, NDArray):
            value = nd_array(value)
        self.value = value

        class _InitConst(init_mod.Initializer):
            def _init_weight(self, _, arr):
                arr._rebind(value._data.astype(arr._data.dtype))

        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=str(value.dtype), init=_InitConst())


class ParameterDict:
    """ref: parameter.py ParameterDict."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params: Dict[str, Parameter] = {}
        self._shared = shared

    @property
    def prefix(self):
        return self._prefix

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def __iter__(self):
        return iter(self._params)

    def __getitem__(self, key):
        return self._params[key]

    def __contains__(self, key):
        return key in self._params

    def __len__(self):
        return len(self._params)

    def __repr__(self):
        s = "\n".join(repr(p) for p in self._params.values())
        return f"ParameterDict ({self._prefix})\n{s}"

    def get(self, name, **kwargs) -> Parameter:
        """ref: parameter.py ParameterDict.get — create-or-retrieve."""
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            param = Parameter(name, **kwargs)
            self._params[name] = param
        else:
            for k, v in kwargs.items():
                if hasattr(param, k) and getattr(param, k) is not None:
                    existing = getattr(param, k)
                    if k == "shape" and v is not None and existing is not None:
                        param.shape = v
                        continue
                else:
                    setattr(param, k, v)
        return param

    def get_constant(self, name, value=None) -> Constant:
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            if value is None:
                raise KeyError(name)
            param = Constant(name, value)
            self._params[name] = param
        return param

    def _get_impl(self, name):
        if name in self._params:
            return self._params[name]
        if self._shared is not None and name in self._shared._params:
            self._params[name] = self._shared._params[name]
            return self._params[name]
        return None

    def update(self, other):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise ValueError(f"Cannot update self with other because they"
                                 f" have different Parameters with the same "
                                 f"name {k}")
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        for _, v in self.items():
            v.initialize(None, ctx, init or init_mod.Uniform(),
                         force_reinit=force_reinit)

    def zero_grad(self):
        for v in self.values():
            v.zero_grad()

    def reset_ctx(self, ctx):
        pass

    def setattr(self, name, value):
        for v in self.values():
            setattr(v, name, value)

    def save(self, filename, strip_prefix=""):
        from ..ndarray import ndarray as nd_mod
        arg_dict = {}
        for param in self.values():
            weight = param.data()
            if not param.name.startswith(strip_prefix):
                raise ValueError(f"Prefix '{strip_prefix}' is to be stripped "
                                 f"but Parameter's name '{param.name}' does "
                                 f"not start with it")
            arg_dict[param.name[len(strip_prefix):]] = weight
        nd_mod.save(filename, arg_dict)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        from ..ndarray import ndarray as nd_mod
        loaded = nd_mod.load(filename)
        arg_dict = {restore_prefix + k: v for k, v in loaded.items()}
        if not allow_missing:
            for name in self.keys():
                assert name in arg_dict, \
                    f"Parameter {name} is missing in file {filename}"
        for name in arg_dict:
            if name not in self._params:
                assert ignore_extra, \
                    f"Parameter {name} loaded from file {filename} is not " \
                    f"present in ParameterDict"
                continue
            self[name].set_data(arg_dict[name])
