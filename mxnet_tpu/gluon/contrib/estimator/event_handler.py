"""Estimator event handlers
(ref: python/mxnet/gluon/contrib/estimator/event_handler.py — the
TrainBegin/.../BatchEnd mixin protocol and the stock handlers:
StoppingHandler, MetricHandler, ValidationHandler, LoggingHandler,
CheckpointHandler, EarlyStoppingHandler)."""
import logging
import os
import time

__all__ = ["TrainBegin", "TrainEnd", "EpochBegin", "EpochEnd",
           "BatchBegin", "BatchEnd", "StoppingHandler", "MetricHandler",
           "ValidationHandler", "LoggingHandler", "CheckpointHandler",
           "EarlyStoppingHandler"]


class TrainBegin:
    def train_begin(self, estimator, *args, **kwargs):
        pass


class TrainEnd:
    def train_end(self, estimator, *args, **kwargs):
        pass


class EpochBegin:
    def epoch_begin(self, estimator, *args, **kwargs):
        pass


class EpochEnd:
    def epoch_end(self, estimator, *args, **kwargs):
        """Return False to stop training."""
        pass


class BatchBegin:
    def batch_begin(self, estimator, *args, **kwargs):
        pass


class BatchEnd:
    def batch_end(self, estimator, *args, **kwargs):
        """Return False to stop training."""
        pass


class StoppingHandler(TrainBegin, BatchEnd, EpochEnd):
    """Stop on max_epoch/max_batch (ref: event_handler.py
    StoppingHandler)."""

    def __init__(self, max_epoch=None, max_batch=None):
        self.max_epoch = max_epoch
        self.max_batch = max_batch
        self.current_batch = 0
        self.current_epoch = 0
        self.stop_training = False

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.max_batch and self.current_batch >= self.max_batch:
            self.stop_training = True
        return not self.stop_training

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.max_epoch and self.current_epoch >= self.max_epoch:
            self.stop_training = True
        return not self.stop_training


class MetricHandler(EpochBegin, BatchEnd):
    """Reset metrics per epoch, update per batch (ref: event_handler.py
    MetricHandler)."""

    def __init__(self, train_metrics):
        self.train_metrics = train_metrics or []

    def epoch_begin(self, estimator, *args, **kwargs):
        for metric in self.train_metrics:
            metric.reset()

    def batch_end(self, estimator, *args, **kwargs):
        from .... import metric as metric_mod
        pred = kwargs.get("pred")
        label = kwargs.get("label")
        loss = kwargs.get("loss")
        for metric in self.train_metrics:
            if isinstance(metric, metric_mod.Loss):
                # the running-loss display metric consumes the loss
                # value; name-matching would misroute real metrics whose
                # names merely contain 'loss' (e.g. nll-loss)
                metric.update(0, loss)
            else:
                metric.update(label, pred)


class ValidationHandler(TrainBegin, BatchEnd, EpochEnd):
    """Run validation on an interval (ref: event_handler.py
    ValidationHandler)."""

    def __init__(self, val_data, eval_fn, epoch_period=1,
                 batch_period=None):
        self.val_data = val_data
        self.eval_fn = eval_fn
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.current_batch = 0
        self.current_epoch = 0

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and \
                self.current_batch % self.batch_period == 0:
            self.eval_fn(val_data=self.val_data)

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and \
                self.current_epoch % self.epoch_period == 0:
            self.eval_fn(val_data=self.val_data)


class LoggingHandler(TrainBegin, TrainEnd, EpochBegin, EpochEnd,
                     BatchBegin, BatchEnd):
    """Periodic progress logging (ref: event_handler.py
    LoggingHandler)."""

    def __init__(self, log_interval="epoch", metrics=None):
        self.log_interval = log_interval
        self.metrics = metrics or []
        self.logger = logging.getLogger("mxnet_tpu.estimator")
        self.batch_index = 0
        self.current_epoch = 0
        self.processed_samples = 0

    def train_begin(self, estimator, *args, **kwargs):
        self.train_start = time.time()
        self.logger.info("Training begin")

    def train_end(self, estimator, *args, **kwargs):
        t = time.time() - self.train_start
        self.logger.info("Train finished using %.3fs", t)

    def epoch_begin(self, estimator, *args, **kwargs):
        self.epoch_start = time.time()
        self.batch_index = 0
        self.processed_samples = 0

    def epoch_end(self, estimator, *args, **kwargs):
        t = time.time() - self.epoch_start
        msg = f"[Epoch {self.current_epoch}] finished in {t:.3f}s: "
        for metric in self.metrics:
            name, value = metric.get()
            msg += f"{name}: {value:.4f} "
        self.logger.info(msg)
        self.current_epoch += 1

    def batch_end(self, estimator, *args, **kwargs):
        if isinstance(self.log_interval, int):
            batch = kwargs.get("batch")
            if batch is not None and hasattr(batch, "data"):
                self.processed_samples += batch.data[0].shape[0]
            self.batch_index += 1
            if self.batch_index % self.log_interval == 0:
                msg = (f"[Epoch {self.current_epoch}] "
                       f"batch {self.batch_index}: ")
                for metric in self.metrics:
                    name, value = metric.get()
                    msg += f"{name}: {value:.4f} "
                self.logger.info(msg)


class CheckpointHandler(TrainBegin, BatchEnd, EpochEnd):
    """Save params (+trainer states) periodically and track the best
    model by a monitored metric (ref: event_handler.py
    CheckpointHandler)."""

    def __init__(self, model_dir, model_prefix="model", monitor=None,
                 mode="min", epoch_period=1, max_checkpoints=5):
        self.model_dir = model_dir
        self.model_prefix = model_prefix
        self.monitor = monitor
        self.mode = mode
        self.epoch_period = epoch_period
        self.max_checkpoints = max_checkpoints
        self.current_epoch = 0
        self.best = float("inf") if mode == "min" else -float("inf")
        self.saved = []

    def train_begin(self, estimator, *args, **kwargs):
        os.makedirs(self.model_dir, exist_ok=True)

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.current_epoch % self.epoch_period:
            return
        path = os.path.join(
            self.model_dir,
            f"{self.model_prefix}-epoch{self.current_epoch}.params")
        estimator.net.save_parameters(path)
        self.saved.append(path)
        while len(self.saved) > self.max_checkpoints:
            old = self.saved.pop(0)
            try:
                os.unlink(old)
            except OSError:
                pass
        if self.monitor is not None:
            name, value = self.monitor.get()
            better = value < self.best if self.mode == "min" \
                else value > self.best
            if better:
                self.best = value
                estimator.net.save_parameters(os.path.join(
                    self.model_dir, f"{self.model_prefix}-best.params"))


class EarlyStoppingHandler(TrainBegin, EpochEnd):
    """Stop when the monitored metric stops improving
    (ref: event_handler.py EarlyStoppingHandler)."""

    def __init__(self, monitor, mode="min", patience=3, min_delta=0.0):
        self.monitor = monitor
        self.mode = mode
        self.patience = patience
        self.min_delta = min_delta
        self.wait = 0
        self.best = float("inf") if mode == "min" else -float("inf")
        self.stopped_epoch = None
        self.current_epoch = 0

    def train_begin(self, estimator, *args, **kwargs):
        self.wait = 0
        self.current_epoch = 0
        self.best = float("inf") if self.mode == "min" else -float("inf")

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        name, value = self.monitor.get()
        improved = (value < self.best - self.min_delta
                    if self.mode == "min"
                    else value > self.best + self.min_delta)
        if improved:
            self.best = value
            self.wait = 0
            return True
        self.wait += 1
        if self.wait >= self.patience:
            self.stopped_epoch = self.current_epoch
            logging.getLogger("mxnet_tpu.estimator").info(
                "Early stopping at epoch %d: %s did not improve for %d "
                "epochs", self.current_epoch, name, self.patience)
            return False
        return True
