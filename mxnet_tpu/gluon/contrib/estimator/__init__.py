"""Gluon Estimator: a batteries-included fit loop
(ref: python/mxnet/gluon/contrib/estimator/)."""
from .estimator import Estimator
from .event_handler import (CheckpointHandler, EarlyStoppingHandler,
                            EpochBegin, EpochEnd, LoggingHandler,
                            MetricHandler, StoppingHandler, TrainBegin,
                            TrainEnd, BatchBegin, BatchEnd,
                            ValidationHandler)

__all__ = ["Estimator", "TrainBegin", "TrainEnd", "EpochBegin", "EpochEnd",
           "BatchBegin", "BatchEnd", "StoppingHandler", "MetricHandler",
           "ValidationHandler", "LoggingHandler", "CheckpointHandler",
           "EarlyStoppingHandler"]
