"""Gluon Estimator — the batteries-included train loop
(ref: python/mxnet/gluon/contrib/estimator/estimator.py: Estimator.fit
drives epochs/batches, dispatches the event-handler protocol, and owns
loss/metrics/trainer wiring)."""
from .... import autograd, metric as metric_mod
from ...trainer import Trainer
from .event_handler import (BatchBegin, BatchEnd, EpochBegin, EpochEnd,
                            LoggingHandler, MetricHandler, StoppingHandler,
                            TrainBegin, TrainEnd)

__all__ = ["Estimator"]


class Estimator:
    """High-level fit/evaluate driver (ref: estimator.py Estimator)."""

    def __init__(self, net, loss, train_metrics=None, val_metrics=None,
                 trainer=None, context=None):
        self.net = net
        self.loss = loss
        if train_metrics is None:
            train_metrics = [metric_mod.Accuracy()]
        elif not isinstance(train_metrics, (list, tuple)):
            train_metrics = [train_metrics]
        self.train_metrics = list(train_metrics)
        if val_metrics is None:
            # SEPARATE instances: evaluate() resets its metrics, and
            # sharing the training ones would wipe the epoch's train
            # stats whenever a ValidationHandler fires mid-fit
            val_metrics = [type(m)() for m in self.train_metrics]
        elif not isinstance(val_metrics, (list, tuple)):
            val_metrics = [val_metrics]
        self.val_metrics = list(val_metrics)
        # a Loss running-mean shown next to the metrics, like the ref
        self.loss_metric = metric_mod.Loss(
            name=f"train_{type(loss).__name__.lower()}_loss")
        self.trainer = trainer or Trainer(
            net.collect_params(), "adam", {"learning_rate": 1e-3})
        self.context = context
        self.stop_training = False

    # -- evaluation --------------------------------------------------------
    def evaluate(self, val_data, val_metrics=None):
        """Run the net over val_data updating val_metrics
        (ref: estimator.py evaluate)."""
        metrics = val_metrics if val_metrics is not None \
            else self.val_metrics
        for metric in metrics:
            metric.reset()
        for batch in val_data:
            data, label = self._unpack(batch)
            pred = self.net(data)
            for metric in metrics:
                metric.update(label, pred)
        return [m.get() for m in metrics]

    def _unpack(self, batch):
        if isinstance(batch, (list, tuple)):
            data, label = batch[0], batch[1]
        else:
            data, label = batch.data[0], batch.label[0]
        if self.context is not None:
            data = data.as_in_context(self.context)
            label = label.as_in_context(self.context)
        return data, label

    def _handlers(self, event_handlers, epochs):
        handlers = list(event_handlers or [])
        # ALWAYS bound by fit(epochs=...) — a caller-supplied
        # StoppingHandler may only stop earlier, never extend past it
        handlers.append(StoppingHandler(max_epoch=epochs))
        if not any(isinstance(h, MetricHandler) for h in handlers):
            handlers.append(MetricHandler(
                [self.loss_metric] + self.train_metrics))
        if not any(isinstance(h, LoggingHandler) for h in handlers):
            handlers.append(LoggingHandler(
                metrics=[self.loss_metric] + self.train_metrics))
        return handlers

    def fit(self, train_data, val_data=None, epochs=1, event_handlers=None,
            batch_size=None):
        """ref: estimator.py fit — the epoch/batch loop with the
        handler protocol around it."""
        handlers = self._handlers(event_handlers, epochs)

        def dispatch(cls, method, **kwargs):
            keep_going = True
            for h in handlers:
                if isinstance(h, cls):
                    out = getattr(h, method)(self, **kwargs)
                    if out is False:
                        keep_going = False
            return keep_going

        self.stop_training = False
        dispatch(TrainBegin, "train_begin")
        for _epoch in range(10 ** 9):  # bounded by StoppingHandler
            if self.stop_training:
                break
            dispatch(EpochBegin, "epoch_begin")
            for batch in train_data:
                dispatch(BatchBegin, "batch_begin", batch=batch)
                data, label = self._unpack(batch)
                with autograd.record():
                    pred = self.net(data)
                    loss = self.loss(pred, label)
                loss.backward()
                n = data.shape[0]
                self.trainer.step(n)
                if not dispatch(BatchEnd, "batch_end", batch=batch,
                                pred=pred, label=label, loss=loss):
                    self.stop_training = True
                    break
            if hasattr(train_data, "reset"):
                train_data.reset()
            if not dispatch(EpochEnd, "epoch_end"):
                self.stop_training = True
        dispatch(TrainEnd, "train_end")
        return self
