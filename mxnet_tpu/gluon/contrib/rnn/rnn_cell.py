"""Experimental recurrent cells
(ref: python/mxnet/gluon/contrib/rnn/rnn_cell.py:20 — LSTMPCell,
VariationalDropoutCell)."""
from ...rnn.rnn_cell import HybridRecurrentCell, ModifierCell

__all__ = ["VariationalDropoutCell", "LSTMPCell"]


class VariationalDropoutCell(ModifierCell):
    """Variational (a.k.a. locked) dropout: ONE dropout mask per
    sequence, shared across all time steps, applied to inputs / states /
    outputs (ref: contrib/rnn/rnn_cell.py VariationalDropoutCell,
    Gal & Ghahramani 2016 semantics)."""

    def __init__(self, base_cell, drop_inputs=0., drop_states=0.,
                 drop_outputs=0.):
        super().__init__(base_cell)
        self.drop_inputs = drop_inputs
        self.drop_states = drop_states
        self.drop_outputs = drop_outputs
        self._input_mask = None
        self._state_mask = None
        self._output_mask = None

    def reset(self):
        super().reset()
        self._input_mask = None
        self._state_mask = None
        self._output_mask = None

    def _alias(self):
        return "vardrop"

    def _mask(self, F, cached_name, p, like):
        """Sample a keep/drop mask once (first step) and reuse it."""
        mask = getattr(self, cached_name)
        if mask is None:
            mask = F.Dropout(F.ones_like(like), p=p)
            setattr(self, cached_name, mask)
        return mask

    def hybrid_forward(self, F, inputs, states):
        from .... import autograd
        training = autograd.is_training()
        if training and self.drop_inputs:
            inputs = inputs * self._mask(F, "_input_mask",
                                         self.drop_inputs, inputs)
        if training and self.drop_states:
            mask = self._mask(F, "_state_mask", self.drop_states, states[0])
            states = [states[0] * mask] + list(states[1:])
        output, next_states = self.base_cell(inputs, states)
        if training and self.drop_outputs:
            output = output * self._mask(F, "_output_mask",
                                         self.drop_outputs, output)
        return output, next_states

    def __repr__(self):
        return (f"VariationalDropoutCell(in={self.drop_inputs}, "
                f"state={self.drop_states}, out={self.drop_outputs})")


class LSTMPCell(HybridRecurrentCell):
    """LSTM with a hidden-state projection (ref: contrib/rnn/rnn_cell.py
    LSTMPCell; Sak et al. 2014). The recurrent state is the PROJECTED
    vector r (size projection_size); the cell state keeps hidden_size:

        gates from [x, r];  c' = f*c + i*g;  h = o*tanh(c');  r' = W_r h
    """

    def __init__(self, hidden_size, projection_size,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 h2r_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._projection_size = projection_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(4 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(4 * hidden_size, projection_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.h2r_weight = self.params.get(
            "h2r_weight", shape=(projection_size, hidden_size),
            init=h2r_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(4 * hidden_size,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(4 * hidden_size,),
            init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._projection_size),
                 "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "lstmp"

    def _infer_param_shapes(self, x, *args):
        self.i2h_weight.shape = (4 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       h2r_weight, i2h_bias, h2h_bias):
        r, c = states
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(r, h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        sliced = F.SliceChannel(gates, num_outputs=4, axis=1)
        in_gate = F.sigmoid(sliced[0])
        forget_gate = F.sigmoid(sliced[1])
        in_transform = F.tanh(sliced[2])
        out_gate = F.sigmoid(sliced[3])
        next_c = forget_gate * c + in_gate * in_transform
        hidden = out_gate * F.tanh(next_c)
        next_r = F.FullyConnected(hidden, h2r_weight, no_bias=True,
                                  num_hidden=self._projection_size)
        return next_r, [next_r, next_c]
