"""Convolutional recurrent cells
(ref: python/mxnet/gluon/contrib/rnn/conv_rnn_cell.py:21 —
Conv{1,2,3}D{RNN,LSTM,GRU}Cell; Shi et al. 2015 ConvLSTM). The dense
i2h/h2h projections of the plain cells become convolutions over the
spatial dims; states carry (C, *spatial) feature maps."""
from ...rnn.rnn_cell import HybridRecurrentCell

__all__ = ["Conv1DRNNCell", "Conv2DRNNCell", "Conv3DRNNCell",
           "Conv1DLSTMCell", "Conv2DLSTMCell", "Conv3DLSTMCell",
           "Conv1DGRUCell", "Conv2DGRUCell", "Conv3DGRUCell"]


def _tup(v, n):
    return (v,) * n if isinstance(v, int) else tuple(v)


class _BaseConvRNNCell(HybridRecurrentCell):
    """Shared conv-cell machinery (ref: conv_rnn_cell.py
    _BaseConvRNNCell): input_shape is (C, *spatial) channels-first."""

    _n_gates = 1

    def __init__(self, input_shape, hidden_channels, i2h_kernel,
                 h2h_kernel, i2h_pad=0, i2h_dilate=1, h2h_dilate=1,
                 conv_layout="NCHW", activation="tanh", prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        dims = len(conv_layout) - 2
        self._dims = dims
        self._input_shape = tuple(input_shape)
        self._hidden_channels = hidden_channels
        self._activation = activation
        self._i2h_kernel = _tup(i2h_kernel, dims)
        self._h2h_kernel = _tup(h2h_kernel, dims)
        for k in self._h2h_kernel:
            assert k % 2 == 1, \
                "h2h kernel must be odd to preserve the state shape " \
                f"(got {self._h2h_kernel})"
        self._i2h_pad = _tup(i2h_pad, dims)
        self._i2h_dilate = _tup(i2h_dilate, dims)
        self._h2h_dilate = _tup(h2h_dilate, dims)
        # SAME-padding for h2h so state spatial dims are stable
        self._h2h_pad = tuple(d * (k - 1) // 2 for k, d in
                              zip(self._h2h_kernel, self._h2h_dilate))
        in_c = input_shape[0]
        ng = self._n_gates
        self._state_shape = self._compute_state_shape()
        self.i2h_weight = self.params.get(
            "i2h_weight",
            shape=(ng * hidden_channels, in_c) + self._i2h_kernel,
            allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight",
            shape=(ng * hidden_channels, hidden_channels) +
                  self._h2h_kernel,
            allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(ng * hidden_channels,), init="zeros",
            allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(ng * hidden_channels,), init="zeros",
            allow_deferred_init=True)

    def _compute_state_shape(self):
        spatial = self._input_shape[1:]
        out = []
        for s, k, p, d in zip(spatial, self._i2h_kernel, self._i2h_pad,
                              self._i2h_dilate):
            out.append((s + 2 * p - d * (k - 1) - 1) + 1)
        return (self._hidden_channels,) + tuple(out)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size,) + self._state_shape,
                 "__layout__": "NC" + "DHW"[-self._dims:]}]

    def _conv(self, F, x, weight, bias, kernel, pad, dilate):
        ng = self._n_gates
        return F.Convolution(
            x, weight, bias, kernel=kernel, pad=pad, dilate=dilate,
            stride=(1,) * self._dims,
            num_filter=ng * self._hidden_channels)

    def _gates(self, F, inputs, states, p):
        # p: the param values injected into hybrid_forward (kwargs named
        # by parameter) — NOT .data(), which would bypass the traced
        # values under functional_call/jit
        i2h = self._conv(F, inputs, p["i2h_weight"], p["i2h_bias"],
                         self._i2h_kernel, self._i2h_pad,
                         self._i2h_dilate)
        h2h = self._conv(F, states[0], p["h2h_weight"], p["h2h_bias"],
                         self._h2h_kernel, self._h2h_pad,
                         self._h2h_dilate)
        return i2h, h2h

    def _act(self, F, x):
        return F.Activation(x, act_type=self._activation)


class _ConvRNNCell(_BaseConvRNNCell):
    _n_gates = 1

    def _alias(self):
        return "conv_rnn"

    def hybrid_forward(self, F, inputs, states, **params):
        i2h, h2h = self._gates(F, inputs, states, params)
        out = self._act(F, i2h + h2h)
        return out, [out]


class _ConvLSTMCell(_BaseConvRNNCell):
    _n_gates = 4

    def state_info(self, batch_size=0):
        info = super().state_info(batch_size)
        return info + [dict(info[0])]

    def _alias(self):
        return "conv_lstm"

    def hybrid_forward(self, F, inputs, states, **params):
        i2h, h2h = self._gates(F, inputs, states, params)
        gates = i2h + h2h
        sliced = F.SliceChannel(gates, num_outputs=4, axis=1)
        i = F.sigmoid(sliced[0])
        f = F.sigmoid(sliced[1])
        g = self._act(F, sliced[2])
        o = F.sigmoid(sliced[3])
        next_c = f * states[1] + i * g
        next_h = o * self._act(F, next_c)
        return next_h, [next_h, next_c]


class _ConvGRUCell(_BaseConvRNNCell):
    _n_gates = 3

    def _alias(self):
        return "conv_gru"

    def hybrid_forward(self, F, inputs, states, **params):
        i2h, h2h = self._gates(F, inputs, states, params)
        i2h_s = F.SliceChannel(i2h, num_outputs=3, axis=1)
        h2h_s = F.SliceChannel(h2h, num_outputs=3, axis=1)
        reset = F.sigmoid(i2h_s[0] + h2h_s[0])
        update = F.sigmoid(i2h_s[1] + h2h_s[1])
        cand = self._act(F, i2h_s[2] + reset * h2h_s[2])
        next_h = (1.0 - update) * cand + update * states[0]
        return next_h, [next_h]


def _make(cell_base, dims, name):
    layout = {1: "NCW", 2: "NCHW", 3: "NCDHW"}[dims]

    class _Cell(cell_base):
        __doc__ = (f"ref: contrib/rnn/conv_rnn_cell.py {name} "
                   f"(layout {layout}).")

        def __init__(self, input_shape, hidden_channels, i2h_kernel,
                     h2h_kernel, i2h_pad=0, i2h_dilate=1, h2h_dilate=1,
                     activation="tanh", prefix=None, params=None,
                     conv_layout=layout):
            super().__init__(input_shape, hidden_channels, i2h_kernel,
                             h2h_kernel, i2h_pad=i2h_pad,
                             i2h_dilate=i2h_dilate, h2h_dilate=h2h_dilate,
                             conv_layout=conv_layout,
                             activation=activation, prefix=prefix,
                             params=params)

    _Cell.__name__ = name
    _Cell.__qualname__ = name
    return _Cell


Conv1DRNNCell = _make(_ConvRNNCell, 1, "Conv1DRNNCell")
Conv2DRNNCell = _make(_ConvRNNCell, 2, "Conv2DRNNCell")
Conv3DRNNCell = _make(_ConvRNNCell, 3, "Conv3DRNNCell")
Conv1DLSTMCell = _make(_ConvLSTMCell, 1, "Conv1DLSTMCell")
Conv2DLSTMCell = _make(_ConvLSTMCell, 2, "Conv2DLSTMCell")
Conv3DLSTMCell = _make(_ConvLSTMCell, 3, "Conv3DLSTMCell")
Conv1DGRUCell = _make(_ConvGRUCell, 1, "Conv1DGRUCell")
Conv2DGRUCell = _make(_ConvGRUCell, 2, "Conv2DGRUCell")
Conv3DGRUCell = _make(_ConvGRUCell, 3, "Conv3DGRUCell")
