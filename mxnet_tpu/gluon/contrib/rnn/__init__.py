"""Experimental gluon RNN cells
(ref: python/mxnet/gluon/contrib/rnn/)."""
from .conv_rnn_cell import (Conv1DGRUCell, Conv1DLSTMCell, Conv1DRNNCell,
                            Conv2DGRUCell, Conv2DLSTMCell, Conv2DRNNCell,
                            Conv3DGRUCell, Conv3DLSTMCell, Conv3DRNNCell)
from .rnn_cell import LSTMPCell, VariationalDropoutCell

__all__ = ["Conv1DRNNCell", "Conv2DRNNCell", "Conv3DRNNCell",
           "Conv1DLSTMCell", "Conv2DLSTMCell", "Conv3DLSTMCell",
           "Conv1DGRUCell", "Conv2DGRUCell", "Conv3DGRUCell",
           "VariationalDropoutCell", "LSTMPCell"]
