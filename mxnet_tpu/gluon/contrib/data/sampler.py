"""ref: python/mxnet/gluon/contrib/data/sampler.py:21 IntervalSampler."""
from ...data.sampler import Sampler

__all__ = ["IntervalSampler"]


class IntervalSampler(Sampler):
    """Samples i, i+interval, i+2*interval, ... for each start i —
    the strided-epoch ordering used by truncated-BPTT language-model
    training (ref: contrib/data/sampler.py)."""

    def __init__(self, length, interval, rollover=True):
        assert interval <= length, \
            f"interval {interval} must not be larger than length {length}"
        self._length = length
        self._interval = interval
        self._rollover = rollover

    def __iter__(self):
        for i in range(self._interval if self._rollover else 1):
            for j in range(i, self._length, self._interval):
                yield j

    def __len__(self):
        if self._rollover:
            return self._length
        # without rollover only the first stride's indices are yielded
        return (self._length + self._interval - 1) // self._interval
