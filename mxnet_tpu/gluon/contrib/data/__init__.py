"""Experimental gluon datasets/samplers
(ref: python/mxnet/gluon/contrib/data/)."""
from .sampler import IntervalSampler

__all__ = ["IntervalSampler"]
