"""Experimental gluon layers
(ref: python/mxnet/gluon/contrib/nn/basic_layers.py:22-30 — Concurrent,
HybridConcurrent, Identity, SparseEmbedding, SyncBatchNorm,
PixelShuffle1D/2D/3D)."""
from ...block import Block, HybridBlock
from ...nn import BatchNorm, Embedding, HybridSequential, Sequential

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SparseEmbedding",
           "SyncBatchNorm", "PixelShuffle1D", "PixelShuffle2D",
           "PixelShuffle3D"]


class Concurrent(Sequential):
    """Runs children on the same input, concatenates outputs on `axis`
    (ref: basic_layers.py Concurrent)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        from .... import nd as F
        return F.concat(*[block(x) for block in self._children.values()],
                        dim=self.axis)


class HybridConcurrent(HybridSequential):
    """Hybridizable Concurrent (ref: basic_layers.py HybridConcurrent).

    Overrides forward (not hybrid_forward): this codebase's
    HybridSequential dispatches children through its own forward, which
    would otherwise CHAIN the branches instead of fanning out."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        from .... import nd as F
        return F.concat(*[block(x) for block in self._children.values()],
                        dim=self.axis)

    def hybrid_forward(self, F, x):
        return F.concat(*[block(x) for block in self._children.values()],
                        dim=self.axis)


class Identity(HybridBlock):
    """Pass-through (ref: basic_layers.py Identity) — useful inside
    Concurrent to keep the input as one of the concatenated branches."""

    def hybrid_forward(self, F, x):
        return x


class SparseEmbedding(Block):
    """Embedding with row_sparse gradients (ref: basic_layers.py
    SparseEmbedding). On TPU the lookup itself is the dense MXU-friendly
    gather; sparse_grad marks the weight for row-sparse update math in
    the sparse optimizer path (optimizer.py sparse updates)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(**kwargs)
        self._embedding = Embedding(input_dim, output_dim, dtype=dtype,
                                    weight_initializer=weight_initializer,
                                    sparse_grad=True)
        self._kwargs = {"input_dim": input_dim, "output_dim": output_dim}

    @property
    def weight(self):
        return self._embedding.weight

    def forward(self, x):
        return self._embedding(x)

    def __repr__(self):
        return "SparseEmbedding({input_dim} -> {output_dim})".format(
            **self._kwargs)


class SyncBatchNorm(BatchNorm):
    """Cross-device BatchNorm (ref: basic_layers.py SyncBatchNorm over
    src/operator/contrib/sync_batch_norm.cc). Under pjit the batch axis
    is GLOBAL — statistics reduce over all devices by construction — so
    plain BatchNorm already has sync semantics on TPU; this subclass
    keeps the explicit name/num_devices API."""

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, **kwargs):
        super().__init__(in_channels=in_channels, momentum=momentum,
                         epsilon=epsilon, **kwargs)
        self._num_devices = num_devices


def _pixel_shuffle(F, x, factors, ndim):
    """Rearrange (N, C*prod(f), *S) -> (N, C, *S*f) — the reference's
    depth-to-space (basic_layers.py PixelShuffle*D reshape/transpose
    chains), expressed as one reshape + transpose + reshape."""
    fshape = tuple(factors)
    N, C = x.shape[0], x.shape[1]
    spatial = x.shape[2:]
    C_out = C
    for f in fshape:
        C_out //= f
    # (N, C_out, f1..fn, s1..sn)
    x = x.reshape((N, C_out) + fshape + tuple(spatial))
    # interleave: (N, C_out, s1, f1, s2, f2, ...)
    perm = [0, 1]
    for i in range(ndim):
        perm += [2 + ndim + i, 2 + i]
    x = x.transpose(tuple(perm))
    out_spatial = tuple(s * f for s, f in zip(spatial, fshape))
    return x.reshape((N, C_out) + out_spatial)


class PixelShuffle1D(HybridBlock):
    """ref: basic_layers.py PixelShuffle1D."""

    def __init__(self, factor, **kwargs):
        super().__init__(**kwargs)
        self._factor = (int(factor),)

    def hybrid_forward(self, F, x):
        return _pixel_shuffle(F, x, self._factor, 1)

    def __repr__(self):
        return f"PixelShuffle1D({self._factor[0]})"


class PixelShuffle2D(HybridBlock):
    """ref: basic_layers.py PixelShuffle2D."""

    def __init__(self, factor, **kwargs):
        super().__init__(**kwargs)
        if isinstance(factor, int):
            factor = (factor, factor)
        self._factor = tuple(int(f) for f in factor)

    def hybrid_forward(self, F, x):
        return _pixel_shuffle(F, x, self._factor, 2)

    def __repr__(self):
        return f"PixelShuffle2D({self._factor})"


class PixelShuffle3D(HybridBlock):
    """ref: basic_layers.py PixelShuffle3D."""

    def __init__(self, factor, **kwargs):
        super().__init__(**kwargs)
        if isinstance(factor, int):
            factor = (factor, factor, factor)
        self._factor = tuple(int(f) for f in factor)

    def hybrid_forward(self, F, x):
        return _pixel_shuffle(F, x, self._factor, 3)

    def __repr__(self):
        return f"PixelShuffle3D({self._factor})"
