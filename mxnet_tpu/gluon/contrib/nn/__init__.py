"""Experimental gluon layers (ref: python/mxnet/gluon/contrib/nn/)."""
from .basic_layers import (Concurrent, HybridConcurrent, Identity,
                           PixelShuffle1D, PixelShuffle2D, PixelShuffle3D,
                           SparseEmbedding, SyncBatchNorm)

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SparseEmbedding",
           "SyncBatchNorm", "PixelShuffle1D", "PixelShuffle2D",
           "PixelShuffle3D"]
