"""Deformable convolution layer
(ref: python/mxnet/gluon/contrib/cnn/conv_layers.py:22
DeformableConvolution — an offset-predicting conv feeding
_contrib_DeformableConvolution, src/operator/contrib/
deformable_convolution.cc)."""
from ...block import HybridBlock
from ...nn import Conv2D

__all__ = ["DeformableConvolution"]


class DeformableConvolution(HybridBlock):
    """2-D deformable convolution (Dai et al. 2017): a regular conv
    predicts per-tap sampling offsets, then the deformable kernel
    bilinear-samples the input at those offsets before the MXU matmul
    (ops/extra_ops.py deformable_convolution)."""

    def __init__(self, channels, kernel_size=(3, 3), strides=(1, 1),
                 padding=(1, 1), dilation=(1, 1), groups=1,
                 num_deformable_group=1, use_bias=True, in_channels=0,
                 activation=None, weight_initializer=None,
                 bias_initializer="zeros",
                 offset_weight_initializer="zeros",
                 offset_bias_initializer="zeros", offset_use_bias=True,
                 **kwargs):
        super().__init__(**kwargs)
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size, kernel_size)
        if isinstance(strides, int):
            strides = (strides, strides)
        if isinstance(padding, int):
            padding = (padding, padding)
        if isinstance(dilation, int):
            dilation = (dilation, dilation)
        self._channels = channels
        self._kernel = tuple(kernel_size)
        self._stride = tuple(strides)
        self._pad = tuple(padding)
        self._dilate = tuple(dilation)
        self._groups = groups
        self._ndg = num_deformable_group
        self._use_bias = use_bias
        self._activation = activation
        kh, kw = self._kernel
        with self.name_scope():
            # offset conv: 2 offsets (dy, dx) per deformable group per tap
            # (zero-init so the layer starts as a plain conv — the
            # reference's recommended init)
            self.offset = Conv2D(
                2 * num_deformable_group * kh * kw, kernel_size,
                strides=strides, padding=padding, dilation=dilation,
                use_bias=offset_use_bias,
                weight_initializer=offset_weight_initializer,
                bias_initializer=offset_bias_initializer,
                prefix="offset_")
            self.weight = self.params.get(
                "weight", shape=(channels, in_channels // groups, kh, kw),
                init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(channels,), init=bias_initializer,
                    allow_deferred_init=True)

    def _infer_param_shapes(self, x, *args):
        kh, kw = self._kernel
        self.weight.shape = (self._channels, x.shape[1] // self._groups,
                             kh, kw)

    def hybrid_forward(self, F, x, weight, bias=None):
        offset = self.offset(x)
        args = [x, offset, weight] + ([bias] if bias is not None else [])
        out = F.contrib.DeformableConvolution(
            *args, kernel=self._kernel, stride=self._stride,
            pad=self._pad, dilate=self._dilate,
            num_filter=self._channels, num_group=self._groups,
            num_deformable_group=self._ndg,
            no_bias=bias is None)
        if self._activation:
            out = F.Activation(out, act_type=self._activation)
        return out
