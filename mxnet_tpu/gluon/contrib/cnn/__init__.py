"""Experimental conv layers (ref: python/mxnet/gluon/contrib/cnn/)."""
from .conv_layers import DeformableConvolution

__all__ = ["DeformableConvolution"]
