"""Experimental gluon components
(ref: python/mxnet/gluon/contrib/__init__.py — nn, rnn, cnn, data,
estimator)."""
from . import cnn, data, estimator, nn, rnn

__all__ = ["nn", "rnn", "cnn", "data", "estimator"]
