"""Vision datasets (ref: python/mxnet/gluon/data/vision/datasets.py).

No network egress in this environment: datasets read from local files under
`root`; synthetic fallback available for tests via `synthetic=True`.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct

import numpy as onp

from ....base import MXNetError, data_dir
from ....ndarray.ndarray import array
from ..dataset import ArrayDataset, Dataset, RecordFileDataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageRecordDataset", "ImageFolderDataset"]


class _DownloadedDataset(Dataset):
    def __init__(self, root, transform):
        self._transform = transform
        self._data = None
        self._label = None
        root = os.path.expanduser(root)
        self._root = root
        if not os.path.isdir(root):
            os.makedirs(root, exist_ok=True)
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(array(self._data[idx]), self._label[idx])
        return array(self._data[idx]), self._label[idx]

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


class MNIST(_DownloadedDataset):
    """ref: datasets.py MNIST — idx-ubyte files in `root`."""

    _train_files = ("train-images-idx3-ubyte", "train-labels-idx1-ubyte")
    _test_files = ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte")

    def __init__(self, root=os.path.join(data_dir(), "datasets", "mnist"),
                 train=True, transform=None, synthetic=False,
                 synthetic_size=1024):
        self._train = train
        self._synthetic = synthetic
        self._synthetic_size = synthetic_size
        super().__init__(root, transform)

    def _read_idx(self, path):
        for cand in (path, path + ".gz"):
            if os.path.exists(cand):
                opener = gzip.open if cand.endswith(".gz") else open
                with opener(cand, "rb") as f:
                    magic = struct.unpack(">I", f.read(4))[0]
                    ndim = magic & 0xFF
                    dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
                    return onp.frombuffer(f.read(), dtype=onp.uint8) \
                        .reshape(dims)
        return None

    def _get_data(self):
        files = self._train_files if self._train else self._test_files
        imgs = self._read_idx(os.path.join(self._root, files[0]))
        labels = self._read_idx(os.path.join(self._root, files[1]))
        if imgs is None or labels is None:
            if not self._synthetic:
                raise MXNetError(
                    f"MNIST files not found under {self._root} (no network "
                    f"egress; place idx-ubyte files there, or pass "
                    f"synthetic=True for a deterministic synthetic set)")
            rng = onp.random.RandomState(42 if self._train else 43)
            n = self._synthetic_size
            labels = rng.randint(0, 10, size=n).astype(onp.int32)
            imgs = onp.zeros((n, 28, 28), onp.uint8)
            for i, lab in enumerate(labels):
                imgs[i, 2 + lab * 2:6 + lab * 2, 4:24] = 200
                imgs[i] += rng.randint(0, 30, size=(28, 28)).astype(onp.uint8)
        self._data = imgs.reshape(-1, 28, 28, 1)
        self._label = labels.astype(onp.int32)


class FashionMNIST(MNIST):
    def __init__(self, root=os.path.join(data_dir(), "datasets",
                                         "fashion-mnist"), train=True,
                 transform=None, **kwargs):
        super().__init__(root, train, transform, **kwargs)


class CIFAR10(_DownloadedDataset):
    """ref: datasets.py CIFAR10 — python-pickle batches in `root`."""

    def __init__(self, root=os.path.join(data_dir(), "datasets", "cifar10"),
                 train=True, transform=None, synthetic=False,
                 synthetic_size=1024):
        self._train = train
        self._synthetic = synthetic
        self._synthetic_size = synthetic_size
        super().__init__(root, transform)

    def _get_data(self):
        batch_files = [f"data_batch_{i}" for i in range(1, 6)] \
            if self._train else ["test_batch"]
        data, labels = [], []
        found = True
        for fname in batch_files:
            path = os.path.join(self._root, "cifar-10-batches-py", fname)
            if not os.path.exists(path):
                found = False
                break
            with open(path, "rb") as f:
                d = pickle.load(f, encoding="bytes")
            data.append(d[b"data"].reshape(-1, 3, 32, 32))
            labels.extend(d[b"labels"])
        if not found:
            if not self._synthetic:
                raise MXNetError(
                    f"CIFAR10 files not found under {self._root}; pass "
                    f"synthetic=True for tests")
            rng = onp.random.RandomState(7 if self._train else 8)
            n = self._synthetic_size
            labels = rng.randint(0, 10, size=n).tolist()
            raw = rng.randint(0, 255, size=(n, 3, 32, 32)).astype(onp.uint8)
            data = [raw]
        imgs = onp.concatenate(data).transpose(0, 2, 3, 1)
        self._data = imgs
        self._label = onp.asarray(labels, onp.int32)


class CIFAR100(CIFAR10):
    def __init__(self, root=os.path.join(data_dir(), "datasets", "cifar100"),
                 fine_label=False, train=True, transform=None, **kwargs):
        self._fine = fine_label
        super().__init__(root, train, transform, **kwargs)


class ImageRecordDataset(RecordFileDataset):
    """ref: datasets.py ImageRecordDataset."""

    def __init__(self, filename, flag=1, transform=None):
        super().__init__(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        from ....recordio import unpack
        record = super().__getitem__(idx)
        header, img_bytes = unpack(record)
        from ....image import imdecode
        img = imdecode(img_bytes, flag=self._flag)
        label = header.label
        if self._transform is not None:
            return self._transform(img, label)
        return img, label


class ImageFolderDataset(Dataset):
    """ref: datasets.py ImageFolderDataset — root/<class>/<img>."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = [".jpg", ".jpeg", ".png"]
        self._list_images(self._root)

    def _list_images(self, root):
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(root)):
            path = os.path.join(root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                filename = os.path.join(path, filename)
                ext = os.path.splitext(filename)[1]
                if ext.lower() not in self._exts:
                    continue
                self.items.append((filename, label))

    def __getitem__(self, idx):
        from ....image import imread
        img = imread(self.items[idx][0], flag=self._flag)
        label = self.items[idx][1]
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)
