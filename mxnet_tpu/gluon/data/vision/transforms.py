"""Vision transforms (ref: python/mxnet/gluon/data/vision/transforms.py;
kernels in src/operator/image/image_random.cc)."""
from __future__ import annotations

import numpy as onp

from ....ndarray.ndarray import NDArray, array, invoke
from ...block import Block, HybridBlock
from ...nn import Sequential, HybridSequential

import jax.numpy as jnp

__all__ = ["CropResize", "Compose", "Cast", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomResizedCrop", "RandomFlipLeftRight", "RandomFlipTopBottom",
           "RandomBrightness", "RandomContrast", "RandomSaturation",
           "RandomHue", "RandomColorJitter", "RandomLighting"]


class Compose(Sequential):
    """ref: transforms.py Compose."""

    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return x.astype(self._dtype)


class ToTensor(HybridBlock):
    """HWC uint8 [0,255] -> CHW float32 [0,1] (ref: _image_to_tensor)."""

    def hybrid_forward(self, F, x):
        out = x.astype("float32") / 255.0
        if out.ndim == 3:
            return out.transpose((2, 0, 1))
        return out.transpose((0, 3, 1, 2))


class Normalize(HybridBlock):
    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = onp.asarray(mean, onp.float32).reshape(-1, 1, 1)
        self._std = onp.asarray(std, onp.float32).reshape(-1, 1, 1)

    def hybrid_forward(self, F, x):
        mean = array(self._mean)
        std = array(self._std)
        return (x - mean) / std


class Resize(Block):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)

    def forward(self, x):
        import jax
        h, w = self._size[1], self._size[0]
        if x.ndim == 3:
            return invoke(lambda a: jax.image.resize(
                a, (h, w, a.shape[2]), method="linear"), [x])
        return invoke(lambda a: jax.image.resize(
            a, (a.shape[0], h, w, a.shape[3]), method="linear"), [x])


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)

    def forward(self, x):
        w, h = self._size
        H, W = x.shape[-3], x.shape[-2]
        y0, x0 = max((H - h) // 2, 0), max((W - w) // 2, 0)
        return x[..., y0:y0 + h, x0:x0 + w, :]


class CropResize(Block):
    """Crop the fixed (x, y, width, height) window, then optionally
    resize (ref: gluon/data/vision/transforms.py CropResize). Accepts
    (H, W, C) images or (N, H, W, C) batches; out-of-bounds windows
    raise (matching the reference's image.crop validation — silent
    truncation would corrupt pipelines)."""

    def __init__(self, x, y, width, height, size=None, interpolation=1):
        super().__init__()
        self._x, self._y = x, y
        self._w, self._h = width, height
        self._size = (size, size) if isinstance(size, int) else size
        self._interp = interpolation

    _METHODS = {0: "nearest", 1: "linear", 2: "linear", 3: "cubic"}

    def forward(self, img):
        H, W = img.shape[-3], img.shape[-2]
        if self._w <= 0 or self._h <= 0 or self._x < 0 or self._y < 0 \
                or self._x + self._w > W or self._y + self._h > H:
            raise ValueError(
                f"crop window (x={self._x}, y={self._y}, w={self._w}, "
                f"h={self._h}) exceeds image bounds {W}x{H}")
        out = img[..., self._y:self._y + self._h,
                  self._x:self._x + self._w, :]
        if self._size is not None:
            import jax
            import jax.numpy as jnp
            method = self._METHODS.get(self._interp, "linear")
            tw, th = self._size

            def _resize(a):
                target = a.shape[:-3] + (th, tw, a.shape[-1])
                res = jax.image.resize(
                    a.astype(jnp.float32), target, method=method)
                # crop-only path preserves dtype; the resize path must
                # too (int images round-trip, low-precision floats are
                # not silently promoted)
                return res.astype(a.dtype)

            out = invoke(_resize, [out])
        return out


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3.0 / 4.0, 4.0 / 3.0),
                 interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        import math
        H, W = x.shape[-3], x.shape[-2]
        area = H * W
        for _ in range(10):
            target_area = onp.random.uniform(*self._scale) * area
            aspect = math.exp(onp.random.uniform(
                math.log(self._ratio[0]), math.log(self._ratio[1])))
            w = int(round(math.sqrt(target_area * aspect)))
            h = int(round(math.sqrt(target_area / aspect)))
            if w <= W and h <= H:
                x0 = onp.random.randint(0, W - w + 1)
                y0 = onp.random.randint(0, H - h + 1)
                crop = x[..., y0:y0 + h, x0:x0 + w, :]
                return Resize(self._size)(crop)
        return Compose([Resize(self._size)])(x)


class RandomFlipLeftRight(Block):
    def forward(self, x):
        if onp.random.rand() < 0.5:
            return x[..., :, ::-1, :]
        return x


class RandomFlipTopBottom(Block):
    def forward(self, x):
        if onp.random.rand() < 0.5:
            return x[..., ::-1, :, :]
        return x


class _RandomJitter(Block):
    def __init__(self, magnitude):
        super().__init__()
        self._m = magnitude

    def _factor(self):
        return 1.0 + onp.random.uniform(-self._m, self._m)


class RandomBrightness(_RandomJitter):
    def forward(self, x):
        return x * self._factor()


class RandomContrast(_RandomJitter):
    def forward(self, x):
        f = self._factor()
        mean = x.astype("float32").mean()
        return x.astype("float32") * f + mean * (1 - f)


class RandomSaturation(_RandomJitter):
    def forward(self, x):
        f = self._factor()
        coef = array(onp.asarray([0.299, 0.587, 0.114], onp.float32))
        gray = (x.astype("float32") * coef).sum(axis=-1, keepdims=True)
        return x.astype("float32") * f + gray * (1 - f)


class RandomHue(_RandomJitter):
    def forward(self, x):
        # simplified: rotate color channels toward mean by factor
        f = self._factor()
        mean = x.astype("float32").mean(axis=-1, keepdims=True)
        return x.astype("float32") * f + mean * (1 - f)


class RandomColorJitter(Block):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._ts = []
        if brightness:
            self._ts.append(RandomBrightness(brightness))
        if contrast:
            self._ts.append(RandomContrast(contrast))
        if saturation:
            self._ts.append(RandomSaturation(saturation))
        if hue:
            self._ts.append(RandomHue(hue))

    def forward(self, x):
        order = onp.random.permutation(len(self._ts))
        for i in order:
            x = self._ts[i](x)
        return x


class RandomLighting(Block):
    """AlexNet-style PCA lighting noise (ref: transforms.py RandomLighting)."""

    _eigval = onp.asarray([55.46, 4.794, 1.148], onp.float32)
    _eigvec = onp.asarray([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]], onp.float32)

    def __init__(self, alpha):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        alpha = onp.random.normal(0, self._alpha, size=(3,))
        rgb = (self._eigvec * alpha * self._eigval).sum(axis=1)
        return x.astype("float32") + array(rgb.astype(onp.float32))
