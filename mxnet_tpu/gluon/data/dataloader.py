"""Gluon DataLoader.

ref: python/mxnet/gluon/data/dataloader.py — multi-worker loading. The
reference forks worker processes that share NDArrays through
cpu_shared_storage + ForkingPickler (dataloader.py:27-71). On TPU the
device transfer happens once per batch on the host side, so workers here
are a thread pool (decode/augment release the GIL in numpy/cv2) with an
optional process pool; batches land as host numpy and are device_put once.
"""
from __future__ import annotations

import concurrent.futures
import multiprocessing
from typing import Optional

import numpy as onp

from ...ndarray.ndarray import NDArray, array
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """ref: dataloader.py default_batchify_fn."""
    if isinstance(data[0], NDArray):
        from ...ndarray.ndarray import stack
        return stack(data)
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    data = onp.asarray(data)
    return array(data)


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, pin_device_id=0,
                 prefetch=None, thread_pool=True, timeout=120):
        self._dataset = dataset
        self._pin_memory = pin_memory
        self._timeout = timeout

        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size must be specified unless "
                                 "batch_sampler is specified")
            if sampler is None:
                if shuffle:
                    sampler = RandomSampler(len(dataset))
                else:
                    sampler = SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must not be specified if sampler "
                                 "is specified")
            batch_sampler = BatchSampler(
                sampler, batch_size, last_batch or "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError("batch_size, shuffle, sampler and last_batch "
                             "must not be specified if batch_sampler is "
                             "specified.")
        self._batch_sampler = batch_sampler
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, int(prefetch) if prefetch is not None
                             else 2 * self._num_workers)
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._pool = None
        if self._num_workers > 0:
            self._pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=self._num_workers)

    def _load_batch(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])

    def __iter__(self):
        if self._pool is None:
            for batch_idx in self._batch_sampler:
                yield self._load_batch(batch_idx)
            return
        # pipelined: keep `prefetch` batches in flight
        sampler_iter = iter(self._batch_sampler)
        futures = []
        try:
            for _ in range(max(1, self._prefetch)):
                futures.append(self._pool.submit(self._load_batch,
                                                 next(sampler_iter)))
        except StopIteration:
            pass
        while futures:
            fut = futures.pop(0)
            try:
                futures.append(self._pool.submit(self._load_batch,
                                                 next(sampler_iter)))
            except StopIteration:
                pass
            yield fut.result(timeout=self._timeout)

    def __len__(self):
        return len(self._batch_sampler)

    def __del__(self):
        if self._pool is not None:
            self._pool.shutdown(wait=False)
