"""Gluon DataLoader.

ref: python/mxnet/gluon/data/dataloader.py — multi-worker loading. The
reference forks worker processes that share NDArrays through
cpu_shared_storage + ForkingPickler (dataloader.py:27-71). Here workers
are SPAWNED (forking a JAX-initialized parent is unsafe — the runtime
is multithreaded) with the dataset shipped pre-pickled, and finished
batches travel back through POSIX shared memory
(multiprocessing.shared_memory — the cpu_shared storage role): the
worker batchifies into numpy, copies into a shm segment, and the parent
re-wraps without a queue-pickle of the bulk data. The device transfer
(jax.device_put) happens exactly once, in the parent.

Workers run numpy-only code (datasets/transforms should return numpy) —
each child forces the CPU jax backend before the dataset unpickles, so
a worker can never open (or hang on) the accelerator. `thread_pool=True`
selects the in-process thread pool instead (useful when __getitem__
already releases the GIL).
"""
from __future__ import annotations

import concurrent.futures
import multiprocessing as mp
from multiprocessing import shared_memory
from typing import Optional

import numpy as onp

from ...ndarray.ndarray import NDArray, array
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn", "default_mp_batchify_fn"]


def default_batchify_fn(data):
    """ref: dataloader.py default_batchify_fn."""
    if isinstance(data[0], NDArray):
        from ...ndarray.ndarray import stack
        return stack(data)
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    data = onp.asarray(data)
    return array(data)


def default_mp_batchify_fn(data):
    """Worker-process batchify: numpy in, numpy out — no NDArray/XLA in
    the forked child (ref: dataloader.py default_mp_batchify_fn, which
    targets shared-memory ndarrays for the same reason)."""
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_mp_batchify_fn(i) for i in data]
    return onp.asarray(data)


# ---------------------------------------------------------------------------
# shared-memory transport (the cpu_shared_storage + ForkingPickler role)
# ---------------------------------------------------------------------------

def _shm_encode(obj, segments):
    """Replace numpy leaves with shm descriptors; collect segments."""
    if isinstance(obj, onp.ndarray):
        seg = shared_memory.SharedMemory(create=True, size=max(1, obj.nbytes))
        flat = onp.ndarray(obj.shape, dtype=obj.dtype, buffer=seg.buf)
        flat[...] = obj
        segments.append(seg)
        return ("__shm__", seg.name, obj.shape, str(obj.dtype))
    if isinstance(obj, (list, tuple)):
        return type(obj)(_shm_encode(o, segments) for o in obj)
    return obj


def _shm_decode(obj, opened):
    if isinstance(obj, tuple) and len(obj) == 4 and obj[0] == "__shm__":
        _, name, shape, dtype = obj
        seg = shared_memory.SharedMemory(name=name)
        opened.append(seg)
        arr = onp.ndarray(shape, dtype=onp.dtype(dtype),
                          buffer=seg.buf).copy()
        return array(arr)
    if isinstance(obj, (list, tuple)):
        return [_shm_decode(o, opened) for o in obj] \
            if isinstance(obj, list) else \
            tuple(_shm_decode(o, opened) for o in obj)
    return obj


def _worker_entry(dataset_bytes, batchify_bytes, task_q, res_q):
    """Spawn-context child entry. The payloads arrive PICKLED so nothing
    jax-backed materializes before this body forces the CPU backend —
    a worker must never open the accelerator (slow init; over a tunneled
    TPU a wedged transport would hang every worker). Spawn replaces the
    previous fork context: forking a JAX-initialized parent is
    documented-unsafe (os.fork + multithreaded runtime). Like torch's
    spawn-mode DataLoader, user SCRIPTS must guard DataLoader
    construction with `if __name__ == "__main__":` (the child re-imports
    the main module at bootstrap)."""
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    import cloudpickle
    dataset = cloudpickle.loads(dataset_bytes)
    batchify_fn = cloudpickle.loads(batchify_bytes)
    # startup handshake: tells the parent this worker is fully
    # operational, so the (long) spawn+import boot window is not
    # charged against the per-batch timeout
    res_q.put(("__ready__", None, None, None))
    _worker_loop(dataset, batchify_fn, task_q, res_q)


def _worker_loop(dataset, batchify_fn, task_q, res_q):
    """Runs in the worker child: pull (seq, indices), batchify, ship via
    shared memory (ref: dataloader.py worker_loop)."""
    # MXNET_MP_WORKER_NTHREADS caps per-worker decode threads
    # (ref: env_var.md:60 / MXNET_MP_OPENCV_NUM_THREADS)
    try:
        from ...base import get_env
        import cv2
        cv2.setNumThreads(int(get_env("MXNET_MP_WORKER_NTHREADS", 4)))
    except Exception:
        pass
    warned_ndarray = [False]

    def _to_np(x):
        if isinstance(x, NDArray):
            if not warned_ndarray[0]:
                warned_ndarray[0] = True
                import warnings
                warnings.warn(
                    "DataLoader worker received NDArray items from the "
                    "dataset; worker-side XLA arrays live on the "
                    "worker's CPU backend — return numpy from "
                    "__getitem__ for zero-copy shm handoff")
            return x.asnumpy()
        return x

    while True:
        task = task_q.get()
        if task is None:
            return
        epoch, seq, indices = task
        try:
            items = [dataset[i] for i in indices]
            items = [_to_np(i) if not isinstance(i, tuple)
                     else tuple(_to_np(x) for x in i) for i in items]
            batch = batchify_fn(items)
            segments = []
            payload = _shm_encode(batch, segments)
            res_q.put((epoch, seq, payload, None))
            for seg in segments:  # parent owns them now
                seg.close()
                # ownership moved to the parent (which unlinks); without
                # this the child's resource tracker double-counts them
                try:
                    from multiprocessing import resource_tracker
                    resource_tracker.unregister(seg._name, "shared_memory")
                except Exception:
                    pass
        except Exception as e:  # surface the error at the parent
            res_q.put((epoch, seq, None, f"{type(e).__name__}: {e}"))


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, pin_device_id=0,
                 prefetch=None, thread_pool=False, timeout=120):
        self._dataset = dataset
        self._pin_memory = pin_memory
        self._timeout = timeout

        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size must be specified unless "
                                 "batch_sampler is specified")
            if sampler is None:
                if shuffle:
                    sampler = RandomSampler(len(dataset))
                else:
                    sampler = SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must not be specified if sampler "
                                 "is specified")
            batch_sampler = BatchSampler(
                sampler, batch_size, last_batch or "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError("batch_size, shuffle, sampler and last_batch "
                             "must not be specified if batch_sampler is "
                             "specified.")
        self._batch_sampler = batch_sampler
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, int(prefetch) if prefetch is not None
                             else 2 * self._num_workers)
        self._thread_pool = thread_pool
        self._pool = None
        self._workers = []
        self._task_q = self._res_q = None
        self._epoch = 0
        if self._num_workers > 0 and thread_pool:
            self._batchify_fn = batchify_fn or default_batchify_fn
            self._pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=self._num_workers)
        elif self._num_workers > 0:
            # real worker processes (ref: dataloader.py:27-71) — SPAWNED
            # (forking a JAX-initialized parent is unsafe: the runtime
            # is multithreaded), results via shared memory. Dataset and
            # batchify_fn ship pre-pickled so the child can force its
            # CPU backend before anything jax-backed unpickles.
            # cloudpickle, not pickle: datasets/batchify fns defined in
            # local scope (or as lambdas) must keep working under the
            # spawn context the way they did under fork
            import cloudpickle
            self._batchify_fn = batchify_fn or default_mp_batchify_fn
            # spawn, not fork: fork would clone the JAX-initialized
            # (multithreaded) parent — documented-unsafe. Spawn requires
            # the torch-style `if __name__ == "__main__"` guard in user
            # scripts; a missing guard is detected and reported below.
            ctx = mp.get_context("spawn")
            self._task_q = ctx.Queue()
            self._res_q = ctx.Queue()
            dataset_bytes = cloudpickle.dumps(dataset)
            batchify_bytes = cloudpickle.dumps(self._batchify_fn)
            # _worker_entry forces the CPU backend before anything
            # jax-backed unpickles; importing mxnet_tpu itself is
            # backend-free (lazy RNG key), so no env mutation is needed
            # — a global os.environ dance here would race concurrent
            # spawns in other threads
            for _ in range(self._num_workers):
                w = ctx.Process(target=_worker_entry,
                                args=(dataset_bytes, batchify_bytes,
                                      self._task_q, self._res_q),
                                daemon=True)
                w.start()
                self._workers.append(w)
            self._pending_ready = self._num_workers
        else:
            self._batchify_fn = batchify_fn or default_batchify_fn

    def _load_batch(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])

    def __iter__(self):
        if self._workers:
            yield from self._mp_iter()
            return
        if self._pool is None:
            for batch_idx in self._batch_sampler:
                yield self._load_batch(batch_idx)
            return
        # thread pool: keep `prefetch` batches in flight
        sampler_iter = iter(self._batch_sampler)
        futures = []
        try:
            for _ in range(max(1, self._prefetch)):
                futures.append(self._pool.submit(self._load_batch,
                                                 next(sampler_iter)))
        except StopIteration:
            pass
        while futures:
            fut = futures.pop(0)
            try:
                futures.append(self._pool.submit(self._load_batch,
                                                 next(sampler_iter)))
            except StopIteration:
                pass
            yield fut.result(timeout=self._timeout)

    @staticmethod
    def _discard_payload(payload):
        """Free shm segments of a result that will never be consumed
        (stale epoch after an abandoned iteration)."""
        opened = []
        try:
            _shm_decode(payload, opened)
        except Exception:
            pass
        for seg in opened:
            seg.close()
            try:
                seg.unlink()
            except FileNotFoundError:
                pass

    def _mp_iter(self):
        # epoch tag: results of an abandoned/failed earlier iteration
        # still in res_q must not be served as this epoch's batches
        self._epoch += 1
        epoch = self._epoch
        sampler_iter = iter(self._batch_sampler)
        sent = 0
        received = 0
        buffered = {}
        for _ in range(max(1, self._prefetch)):
            try:
                self._task_q.put((epoch, sent, next(sampler_iter)))
                sent += 1
            except StopIteration:
                break
        try:
            while received < sent:
                while received not in buffered:
                    import queue as _queue
                    import time as _time
                    # poll in short slices so dead workers surface
                    # immediately instead of after the full timeout;
                    # worker BOOT (spawn + fresh interpreter + imports)
                    # gets its own generous window, charged only while
                    # workers are alive-but-not-ready
                    booting = self._pending_ready > 0
                    deadline = _time.monotonic() + (
                        max(self._timeout, 600) if booting
                        else self._timeout)
                    while True:
                        try:
                            e, seq, payload, err = self._res_q.get(
                                timeout=min(
                                    5.0, max(0.1, deadline
                                             - _time.monotonic())))
                            break
                        except _queue.Empty:
                            dead = [w.pid for w in self._workers
                                    if not w.is_alive()]
                            if dead and self._pending_ready > 0:
                                raise RuntimeError(
                                    "DataLoader worker process(es) "
                                    f"{dead} died during startup — if "
                                    "this is a script, DataLoader with "
                                    "num_workers>0 must be created "
                                    "under the `if __name__ == "
                                    "'__main__':` guard (spawn start "
                                    "method re-imports the main module)")
                            if dead:
                                # mid-epoch death: the task it held can
                                # never complete — fail NOW, not after
                                # the full timeout
                                raise RuntimeError(
                                    f"DataLoader worker process(es) "
                                    f"{dead} died mid-epoch (killed/"
                                    "OOM?); in-flight batches are lost")
                            if _time.monotonic() >= deadline:
                                raise RuntimeError(
                                    "DataLoader timed out after "
                                    f"{self._timeout}s")
                            continue
                    if e == "__ready__":
                        self._pending_ready -= 1
                        continue
                    if e != epoch:  # stale result, abandoned epoch
                        if payload is not None:
                            self._discard_payload(payload)
                        continue
                    buffered[seq] = (payload, err)
                payload, err = buffered.pop(received)
                received += 1
                try:
                    self._task_q.put((epoch, sent, next(sampler_iter)))
                    sent += 1
                except StopIteration:
                    pass
                if err is not None:
                    raise RuntimeError(f"DataLoader worker failed: {err}")
                opened = []
                try:
                    batch = _shm_decode(payload, opened)
                finally:
                    for seg in opened:
                        seg.close()
                        try:
                            seg.unlink()
                        except FileNotFoundError:
                            pass
                yield batch
        finally:
            # free shm of out-of-order results that will never be served
            # (worker error / abandoned generator)
            for payload, _ in buffered.values():
                if payload is not None:
                    self._discard_payload(payload)

    def __len__(self):
        return len(self._batch_sampler)

    def _shutdown(self):
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None
        if self._workers:
            for _ in self._workers:
                try:
                    self._task_q.put(None)
                except Exception:
                    pass
            for w in self._workers:
                w.join(timeout=2)
                if w.is_alive():
                    w.terminate()
            self._workers = []
            # free any undelivered results' shm segments
            try:
                while True:
                    _, _, payload, _ = self._res_q.get_nowait()
                    if payload is not None:
                        self._discard_payload(payload)
            except Exception:
                pass

    def __del__(self):
        try:
            self._shutdown()
        except Exception:
            pass
