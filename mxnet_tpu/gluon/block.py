"""Gluon Block / HybridBlock.

ref: python/mxnet/gluon/block.py — Block :131, HybridBlock :705 (whose
_build_cache :786 captures the graph into a CachedOp, ref:
src/imperative/cached_op.cc), SymbolBlock :992.

TPU-native hybridize: instead of tracing with Symbol proxies into an NNVM
graph executed by CachedOp's static/dynamic paths, `hybridize()` wraps the
block's forward in jax.jit. The compiled function takes (param values,
input values, rng key) and returns (outputs, mutated-state updates), so:
- static_alloc/static_shape semantics are XLA's default (preallocated
  buffers, shape-specialized executable — ref: cached_op.cc StaticForward);
- randomness stays fresh across calls (key is an argument);
- BatchNorm-style running stats flow out functionally and are written back
  (the aux-state story, ref: batch_norm.cc aux).
Autograd through a hybridized call records ONE tape node whose vjp is the
compiled function's vjp — the analog of CachedOp::Backward (:1128).
"""
from __future__ import annotations

import re
import threading
import warnings
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as onp

from .. import autograd
from .. import random as _random
from ..base import MXNetError
from ..context import current_context
from ..ndarray.ndarray import NDArray, _wrap
from .parameter import Parameter, ParameterDict, DeferredInitializationError

__all__ = ["Block", "HybridBlock", "SymbolBlock", "nn_trace_ctx"]

_naming = threading.local()


def _leak_check_mode() -> str:
    """MXNET_TRACER_CHECK: 'warn' (default) reports hybridize()-time
    tracer leaks as warnings, 'raise' makes them MXNetError, 'off'
    disables the scan."""
    from ..base import get_env
    mode = str(get_env("MXNET_TRACER_CHECK", "warn")).lower()
    return mode if mode in ("off", "warn", "raise") else "warn"


class _BlockScope:
    """ref: block.py _BlockScope — name management."""

    _current = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None
        self._name_scope = None

    @staticmethod
    def create(prefix, params, hint):
        current = getattr(_BlockScope._current, "value", None)
        if current is None:
            if prefix is None:
                if not hasattr(_naming, "counts"):
                    _naming.counts = {}
                count = _naming.counts.get(hint, 0)
                _naming.counts[hint] = count + 1
                prefix = f"{hint}{count}_"
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            current._counter[hint] = count + 1
            prefix = f"{hint}{count}_"
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        if self._block._empty_prefix:
            return self
        self._old_scope = getattr(_BlockScope._current, "value", None)
        _BlockScope._current.value = self
        return self

    def __exit__(self, ptype, value, trace):
        if self._block._empty_prefix:
            return
        _BlockScope._current.value = self._old_scope


# trace context for mutable-state updates under jit (BatchNorm stats)
class _TraceCtx(threading.local):
    def __init__(self):
        self.active = False
        self.aux_updates: List[Tuple[Parameter, Any]] = []


_trace_ctx = _TraceCtx()


class nn_trace_ctx:
    def __enter__(self):
        self._saved = (_trace_ctx.active, _trace_ctx.aux_updates)
        _trace_ctx.active = True
        _trace_ctx.aux_updates = []
        return _trace_ctx

    def __exit__(self, *exc):
        _trace_ctx.active, _trace_ctx.aux_updates = self._saved


def record_aux_update(param: Parameter, new_value: NDArray):
    """Called by layers with mutable aux state (BatchNorm). Under a jit
    trace the update is routed out of the compiled function; eagerly it is
    applied immediately."""
    if _trace_ctx.active:
        _trace_ctx.aux_updates.append((param, new_value._data))
    else:
        param.data()._rebind(new_value._data)


def functional_call(block, pvals: Dict[str, Any], args, training=False,
                    rng_raw=None):
    """Run `block.forward(*args)` as a pure function of parameter values.

    The bridge between the stateful Gluon API and jax transforms: parameter
    buffers are temporarily rebound to the provided (possibly traced)
    values; mutable aux-state writes (BatchNorm stats) are captured and
    returned instead of applied. Used by hybridize (jit), the parallel
    train-step builders (pjit/shard_map), and checkpointing.

    Returns (outputs: tuple of jax values, aux_updates: {param_name: value}).
    """
    from ..ndarray.ndarray import NDArray as _ND, _wrap as _w
    plist = sorted(block._collect_params_with_prefix().items())
    saved = [(p, p._data._data if p._data is not None else None)
             for _, p in plist]
    call_args = [_w(a) if (hasattr(a, "shape") and hasattr(a, "dtype")
                           and not isinstance(a, _ND)) else a
                 for a in args]
    try:
        for (n, p) in plist:
            if p._data is not None and n in pvals:
                p._data._data = pvals[n]
        ctxs = []
        tc_scope = nn_trace_ctx()
        tc = tc_scope.__enter__()
        try:
            if rng_raw is not None:
                rng_scope = _random.trace_rng(
                    jax.random.wrap_key_data(rng_raw))
                rng_scope.__enter__()
            else:
                rng_scope = None
            try:
                with autograd._Scope(False, training):
                    out = block.forward(*call_args)
            finally:
                if rng_scope is not None:
                    rng_scope.__exit__(None, None, None)
            aux = {p.name: v for p, v in tc.aux_updates}
            # map back to prefixed names used in pvals
            name_of = {p.name: n for n, p in plist}
            aux = {name_of.get(k, k): v for k, v in aux.items()}
        finally:
            tc_scope.__exit__(None, None, None)
    finally:
        for p, d in saved:
            if d is not None:
                p._data._data = d
    single = not isinstance(out, (list, tuple))
    outs = [out] if single else list(out)
    return tuple(o._data for o in outs), aux


class Block:
    """ref: block.py:131."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(
            prefix, params, self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") \
            else self._prefix
        self._scope = _BlockScope(self)
        self._children: Dict[str, Block] = {}
        self._reg_params: Dict[str, Parameter] = {}
        self._forward_hooks: List = []
        self._forward_pre_hooks: List = []

    def _alias(self):
        return self.__class__.__name__.lower()

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        return self._scope

    @property
    def params(self):
        return self._params

    def collect_params(self, select=None) -> ParameterDict:
        """ref: block.py collect_params."""
        ret = ParameterDict(self._params.prefix)
        if not select:
            ret.update(self.params)
        else:
            pattern = re.compile(select)
            ret.update({name: value for name, value in self.params.items()
                        if pattern.match(name)})
        for child in self._children.values():
            ret.update(child.collect_params(select=select))
        return ret

    def __setattr__(self, name, value):
        if isinstance(value, Block):
            existing = self.__dict__.get("_children")
            if existing is not None:
                existing[name] = value
        elif isinstance(value, Parameter):
            reg = self.__dict__.get("_reg_params")
            if reg is not None:
                reg[name] = value
        super().__setattr__(name, value)

    def register_child(self, block, name=None):
        self._children[name or str(len(self._children))] = block

    def register_forward_hook(self, hook):
        self._forward_hooks.append(hook)
        return hook

    def register_forward_pre_hook(self, hook):
        self._forward_pre_hooks.append(hook)
        return hook

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        from ..initializer import Uniform
        self.collect_params().initialize(init or Uniform(), ctx, verbose,
                                         force_reinit)

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for _, param in self.params.items():
            param.cast(dtype)

    def __call__(self, *args):
        for hook in self._forward_pre_hooks:
            hook(self, args)
        out = self.forward(*args)
        for hook in self._forward_hooks:
            hook(self, args, out)
        return out

    def forward(self, *args):
        raise NotImplementedError

    def summary(self, *inputs):
        summary_rows = []

        def walk(block, depth):
            n_params = sum(int(onp.prod(p.shape or ()))
                           for p in block._reg_params.values())
            summary_rows.append(("  " * depth + block.name,
                                 block.__class__.__name__, n_params))
            for c in block._children.values():
                walk(c, depth + 1)

        walk(self, 0)
        print(f"{'Layer':<40}{'Type':<24}{'Params':<12}")
        print("-" * 76)
        for name, type_, n in summary_rows:
            print(f"{name:<40}{type_:<24}{n:<12}")

    # -- (de)serialization (ref: block.py:319 save_parameters) -----------
    def save_parameters(self, filename, deduplicate=False):
        params = self._collect_params_with_prefix()
        from ..ndarray import ndarray as nd_mod
        arg_dict = {key: val.data() for key, val in params.items()}
        nd_mod.save(filename, arg_dict)

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False,
                        dtype_source="current"):
        from ..ndarray import ndarray as nd_mod
        loaded = nd_mod.load(filename)
        params = self._collect_params_with_prefix()
        if not allow_missing:
            for name in params:
                assert name in loaded, \
                    f"Parameter '{name}' is missing in file '{filename}'"
        for name in loaded:
            if name not in params:
                assert ignore_extra, \
                    f"Parameter '{name}' loaded from file '{filename}' is " \
                    f"not present in Block"
                continue
            params[name].shape = loaded[name].shape
            if params[name]._data is None and params[name]._deferred_init:
                params[name]._finish_deferred_init()
            elif params[name]._data is None:
                params[name].initialize(ctx=ctx or current_context())
            params[name].set_data(loaded[name])

    def _collect_params_with_prefix(self, prefix=""):
        if prefix:
            prefix += "."
        ret = {prefix + key: val for key, val in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    save_params = save_parameters
    load_params = load_parameters

    def hybridize(self, active=True, **kwargs):
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join(
            f"  ({key}): " + repr(block).replace("\n", "\n  ")
            for key, block in self._children.items())
        return s.format(name=self.__class__.__name__, modstr=modstr)


class HybridBlock(Block):
    """ref: block.py:705."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._cached = {}          # (shapes, dtypes, training) -> jitted fn
        self._flags = {}
        self._partition_if_dynamic = True

    def hybridize(self, active=True, static_alloc=False, static_shape=False,
                  inline_limit=2, forward_bulk_size=None,
                  backward_bulk_size=None, **kwargs):
        """ref: block.py:537 — flags kept for parity; jax.jit implies
        static_alloc/static_shape."""
        self._active = active
        self._flags = dict(static_alloc=static_alloc,
                           static_shape=static_shape)
        self._cached = {}
        super().hybridize(active, **kwargs)

    def infer_shape(self, *args):
        self._infer_attrs("shape", *args)

    def _infer_attrs(self, attr, *args):
        """Run a shape-only trace so deferred params get concrete shapes."""
        params = {k: v for k, v in self._reg_params.items()}
        # deferred params are resolved inside forward via in_shape hooks
        # implemented per-layer (_infer_param_shapes)
        if hasattr(self, "_infer_param_shapes"):
            self._infer_param_shapes(*args)

    def cast(self, dtype):
        super().cast(dtype)
        self._cached = {}

    def __call__(self, *args):
        from ..symbol.symbol import Symbol
        if any(isinstance(a, Symbol) for a in args):
            # symbolic tracing (export): no jit cache, just compose the
            # graph (ref: block.py forward dispatches on input type)
            return self.forward(*args)
        if not self._active:
            return super().__call__(*args)
        return self._call_cached(*args)

    def optimize_for(self, x, *args, backend=None, **kwargs):
        """ref: block.py optimize_for — subgraph backend hook. On TPU the
        'backend' is always XLA via jit."""
        self.hybridize(True)
        return self(x, *args)

    # ------------------------------------------------------------------
    def _flat_params(self) -> List[Tuple[str, Parameter]]:
        out = []
        for name, p in sorted(self._collect_params_with_prefix().items()):
            out.append((name, p))
        return out

    def _call_cached(self, *args):
        """CachedOp analog (ref: cached_op.cc Forward :904)."""
        inputs = [a for a in args if isinstance(a, NDArray)]
        training = autograd.is_training()
        key = (tuple(tuple(i.shape) + (str(i.dtype),) for i in inputs),
               training)
        if self._cached.get(key, False) is None:
            # known dynamic-shape signature: skip the parameter gather
            # entirely and run eagerly
            return super(HybridBlock, self).__call__(*args)
        try:
            plist = self._flat_params()
            pvals = {n: p.data()._data for n, p in plist}
        except DeferredInitializationError:
            # first call resolves deferred shapes eagerly (ref:
            # block.py:786 _build_cache's deferred-infer)
            return super(HybridBlock, self).__call__(*args)
        if key not in self._cached:
            # recompile accounting (telemetry pillar 2): every cache
            # miss of the CachedOp analog is counted and classified
            # ("why did we recompile" — first compile vs shape/dtype/
            # train-flag change) with the triggering signature
            from ..telemetry import recompile as _recompile
            _recompile.record_recompile(
                f"{type(self).__name__}:{self.name}",
                _recompile.signature_of(inputs, training),
                kind="cached_op")
            try:
                self._cached[key] = self._build_jit(args, training)
            except (jax.errors.ConcretizationTypeError,
                    jax.errors.TracerArrayConversionError,
                    jax.errors.TracerBoolConversionError,
                    jax.errors.TracerIntegerConversionError) as e:
                # dynamic-shape op in the graph (boolean_mask & co):
                # XLA needs static shapes, so this graph runs eagerly —
                # the analog of the reference's dynamic-shape executor
                # path that re-infers shapes every call
                # (graph_executor.cc:1421; test_dynamic_shape.py runs
                # boolean_mask under hybridize the same way). The jax
                # message is kept: data-dependent python control flow
                # raises the same error and the user must see which
                # line concretized a tracer.
                self._cached[key] = None
                # point at the user's line when their own Python consumed
                # the tracer (tracercheck pass); an all-internal traceback
                # means a dynamic-shape op, which is the expected case
                from ..passes.tracercheck import explain_concretization
                user_loc = explain_concretization(e)
                cause = (f"data-dependent python control flow at "
                         f"{user_loc} (a bug — hoist it out of forward)"
                         if user_loc else
                         "a dynamic-output-shape op (expected, e.g. "
                         "boolean_mask)")
                warnings.warn(
                    f"{type(self).__name__}: tracing failed; hybridize "
                    "falls back to eager execution for this input "
                    f"signature. Cause: {cause}. Trace error:\n{e}")
                return super(HybridBlock, self).__call__(*args)
        fn = self._cached[key]
        rng = jax.random.key_data(_random.next_key())
        in_vals = [i._data for i in inputs]
        outs_flat, aux_vals = fn(pvals, in_vals, rng)
        # write back mutated aux state (running stats)
        aux_params = self._cached_aux_params
        for p, v in zip(aux_params, aux_vals):
            p.data()._rebind(v)
        if autograd.is_recording():
            tape = autograd.current_tape()
            pnames = [n for n, _ in plist]
            np_ = len(pnames)

            def tape_fn(*arrays, _fn=fn, _rng=rng, _np=np_, _pn=tuple(pnames)):
                pv = dict(zip(_pn, arrays[:_np]))
                o, _ = _fn(pv, list(arrays[_np:]), _rng)
                return tuple(o)

            owners = [p.data() for _, p in plist] + list(inputs)
            in_arrays = [pvals[n] for n in pnames] + in_vals
            tape.record(tape_fn, in_arrays, list(outs_flat), owners)
        outs = [_wrap(o) for o in outs_flat]
        return outs[0] if self._cached_single else outs

    def _build_jit(self, sample_args, training):
        """Trace forward once into a jitted function."""
        block = self
        sample_inputs = [a for a in sample_args if isinstance(a, NDArray)]
        struct = [("nd", None) if isinstance(a, NDArray) else ("raw", a)
                  for a in sample_args]
        aux_params_found: List[Parameter] = []

        def pure_fn(pvals, in_vals, rng_raw):
            # rebind param buffers to traced values for the duration
            plist = block._flat_params()
            saved = [(p, p._data._data if p._data is not None else None)
                     for _, p in plist]
            args_it = iter(in_vals)
            call_args = []
            for kind, raw in struct:
                call_args.append(_wrap(next(args_it)) if kind == "nd" else raw)
            try:
                for (n, p) in plist:
                    if p._data is not None:
                        p._data._data = pvals[n]
                with nn_trace_ctx() as tc, \
                        _random.trace_rng(jax.random.wrap_key_data(rng_raw)), \
                        autograd._Scope(False, training):
                    out = block.forward(*call_args)
                aux_updates = list(tc.aux_updates)
            finally:
                for p, d in saved:
                    if d is not None:
                        p._data._data = d
            single = not isinstance(out, (list, tuple))
            outs = [out] if single else list(out)
            block._cached_single = single
            aux_params_found.clear()
            aux_params_found.extend(p for p, _ in aux_updates)
            return tuple(o._data for o in outs), tuple(
                v for _, v in aux_updates)

        jitted = jax.jit(pure_fn)
        # trigger trace now so _cached_single/_cached_aux_params are set
        rng = jax.random.key_data(_random.next_key())
        plist = self._flat_params()
        pvals = {n: p.data()._data for n, p in plist}
        jitted(pvals, [i._data for i in sample_inputs], rng)
        self._cached_aux_params = list(aux_params_found)
        # hybridize()-time tracer-leak check: a forward that stored an
        # intermediate on self just left a dead tracer behind; report it
        # NOW, naming the attribute, instead of the UnexpectedTracerError
        # jax raises wherever the attribute is next touched
        mode = _leak_check_mode()
        if mode != "off":
            from ..passes.tracercheck import scan_block_for_tracers
            leaks = scan_block_for_tracers(self)
            if leaks:
                msg = "; ".join(f.message for f in leaks[:3])
                if mode == "raise":
                    raise MXNetError(msg)
                warnings.warn(msg)
        return jitted

    def compile_signature(self, input_shapes, dtypes="float32",
                          training=False):
        """AOT compile-by-signature hook (mxserve warmup): populate the
        hybridize jit cache for ONE input signature using zero-filled
        inputs, without real data. ``input_shapes`` is one shape tuple
        or a list of them (full shapes, batch axis included); ``dtypes``
        a matching dtype or list. The compile is recorded by the
        recompile auditor as usual (classified ``first-compile`` during
        warmup) and later real traffic on the signature is a cache hit.

        Requires an active ``hybridize()`` — without it there is no jit
        cache to warm — and resolved parameter shapes (run one forward,
        or let deferred init resolve from the zeros here)."""
        if not self._active:
            raise MXNetError(
                f"{type(self).__name__}.compile_signature: call "
                "hybridize() first — eager blocks have no jit cache to "
                "warm")
        from ..ndarray.ndarray import zeros as nd_zeros
        shapes = [input_shapes] if input_shapes and \
            isinstance(input_shapes[0], int) else list(input_shapes)
        if isinstance(dtypes, str):
            dtypes = [dtypes] * len(shapes)
        args = [nd_zeros(tuple(s), dtype=d)
                for s, d in zip(shapes, dtypes)]
        with autograd._Scope(False, training):
            self(*args)
        return self

    def as_serving_engine(self, input_specs=None, **kwargs):
        """Export-to-engine path: wrap this block in a
        :class:`~mxnet_tpu.serve.engine.ServingEngine` (bucketed,
        batched, warmed inference — docs/serving.md). ``input_specs``
        are per-item shapes (no batch axis); remaining kwargs go to the
        engine (ladder, max_linger_ms, ...)."""
        from ..serve import ServingEngine
        return ServingEngine(self, input_specs=input_specs, **kwargs)

    def forward(self, x, *args):
        """ref: block.py:941 — dispatches hybrid_forward with F=nd for
        NDArray inputs, F=sym for Symbol inputs (the export trace)."""
        from ..symbol.symbol import Symbol
        if isinstance(x, Symbol):
            from .. import symbol as sym_ns
            from ..symbol.symbol import var as sym_var
            params = {name: sym_var(p.name)
                      for name, p in self._reg_params.items()}
            return self.hybrid_forward(sym_ns, x, *args, **params)
        from .. import ndarray as nd_ns
        params = {}
        for name, p in self._reg_params.items():
            try:
                params[name] = p.data()
            except DeferredInitializationError:
                self._deferred_infer_shape(x, *args)
                for p2 in self._reg_params.values():
                    p2._finish_deferred_init()
                params = {name: p.data()
                          for name, p in self._reg_params.items()}
                break
        return self.hybrid_forward(nd_ns, x, *args, **params)

    def _deferred_infer_shape(self, *args):
        if hasattr(self, "_infer_param_shapes"):
            self._infer_param_shapes(*args)
        else:
            raise MXNetError(
                f"Deferred initialization failed for {self.name}: layer "
                f"does not implement shape inference")

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    def export(self, path, epoch=0, remove_amp_cast=True):
        """ref: block.py:907 export — emits symbol JSON + params usable by
        SymbolBlock.imports / Module.load. Aux states (BN running
        stats) are saved under the aux: prefix, as the traced symbol
        classifies them — Module.load splits arg/aux by that prefix."""
        sym = self._trace_symbol()
        sym.save(f"{path}-symbol.json")
        aux_names = set(sym.list_auxiliary_states())
        params = self._collect_params_with_prefix()
        from ..ndarray import ndarray as nd_mod
        arg_dict = {}
        for name, p in params.items():
            kind = "aux" if p.name in aux_names else "arg"
            try:
                arg_dict[f"{kind}:{p.name}"] = p.data()
            except DeferredInitializationError as e:
                raise MXNetError(
                    "export requires resolved parameter shapes; run one "
                    "forward pass before export") from e
        nd_mod.save("%s-%04d.params" % (path, epoch), arg_dict)

    def _trace_symbol(self):
        """Trace hybrid_forward with Symbol proxies (ref: block.py
        _build_cache's symbol trace backing export). Single-"data"-input
        convention, like the reference's deployment flow; parameters
        must be initialized (run one forward first for deferred
        shapes)."""
        from ..symbol.symbol import var as sym_var
        return self.forward(sym_var("data"))


class SymbolBlock(HybridBlock):
    """ref: block.py:992 — wrap a Symbol + params as a Block."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix=None, params=params)
        from ..symbol.symbol import Symbol, Group
        if isinstance(outputs, (list, tuple)):
            outputs = Group(outputs)
        if isinstance(inputs, Symbol):
            inputs = [inputs]
        self._symbol = outputs
        self._input_names = [i.name for i in inputs]
        # graph variables carry their original fully-qualified names;
        # the block prefix must NOT be prepended or imports() misses
        # every parameter when matching loaded arrays by name
        self.params._prefix = ""
        arg_names = outputs.list_arguments()
        aux_names = set(outputs.list_auxiliary_states())
        for name in arg_names:
            if name not in self._input_names:
                self.params.get(name, allow_deferred_init=True)
        for name in outputs.list_auxiliary_states():
            self.params.get(name, allow_deferred_init=True, grad_req="null")

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        """ref: block.py:1025."""
        from ..symbol import symbol as sym_mod
        sym = sym_mod.load(symbol_file)
        if isinstance(input_names, str):
            input_names = [input_names]
        inputs = [sym_mod.Variable(n) for n in input_names]
        ret = SymbolBlock(sym, inputs)
        if param_file is not None:
            from ..model import load_params
            arg_params, aux_params = load_params(
                param_file.rsplit("-", 1)[0],
                int(param_file.rsplit("-", 1)[1].split(".")[0]))
            for name, p in {**arg_params, **aux_params}.items():
                if name in ret.params:
                    ret.params[name].shape = p.shape
                    ret.params[name]._finish_deferred_init() \
                        if ret.params[name]._deferred_init else \
                        ret.params[name].initialize(ctx=ctx)
                    ret.params[name].set_data(p)
        return ret

    def _collect_params_with_prefix(self, prefix=""):
        # SymbolBlock params are registered on the ParameterDict by
        # their graph names, not as _reg_params attributes; expose them
        # so save_parameters/load_parameters (and export) see them
        return {name: p for name, p in self.params.items()}

    def _trace_symbol(self):
        # the stored graph IS the symbol — re-export without re-tracing
        # (tracing through forward would need symbolic substitution)
        return self._symbol

    def forward(self, *args):
        from ..symbol.symbol import Symbol
        if any(isinstance(a, Symbol) for a in args):
            raise MXNetError(
                "composing an imported SymbolBlock into another "
                "symbolic trace is not supported; export from the "
                "original network (the SymbolBlock itself can "
                "export() — it re-emits its stored graph)")
        values = {}
        for name, a in zip(self._input_names, args):
            values[name] = a._data if isinstance(a, NDArray) else a
        for name, p in self.params.items():
            if p._data is None:
                # lazily infer from graph
                from ..symbol.symbol import _infer_all_shapes
                shapes = _infer_all_shapes(
                    self._symbol,
                    {n: tuple(v.shape) for n, v in values.items()})
                if shapes.get(name) is not None:
                    p.shape = shapes[name]
                    if p._deferred_init:
                        p._finish_deferred_init()
                    else:
                        p.initialize()
            values[name] = p.data()._data
        from ..symbol.symbol import eval_graph
        outs, aux = eval_graph(self._symbol, values,
                               autograd.is_training(), None)
        res = [_wrap(o) for o in outs]
        for name, v in aux.items():
            if name in self.params:
                self.params[name].data()._rebind(v)
        return res[0] if len(res) == 1 else res
