"""Gluon Trainer.

ref: python/mxnet/gluon/trainer.py (495 LoC) — optimizer driver over
KVStore: _init_kvstore :169, step :305, allreduce_grads :334, update :366.
On TPU the gradient "allreduce" across local devices is a no-op (one buffer
per param; the multi-chip reduce is a psum inside a pjit'd step — see
parallel/), but the kvstore plumbing and update_on_kvstore semantics are
preserved so distributed workflows match the reference's.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from .. import optimizer as opt_mod
from ..base import MXNetError
from ..kvstore import KVStoreBase, create as kv_create
from ..model import _create_kvstore
from .parameter import Parameter, ParameterDict

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None,
                 update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise ValueError(
                "First argument must be a list or dict of Parameters, "
                f"got {type(params)}.")
        self._params = []
        self._param2idx = {}
        for i, param in enumerate(params):
            if not isinstance(param, Parameter):
                raise ValueError(
                    "First argument must be a list or dict of Parameters, "
                    f"got list of {type(param)}.")
            self._param2idx[param.name] = i
            self._params.append(param)
            param._trainer = self
        self._compression_params = compression_params
        optimizer_params = optimizer_params or {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._init_optimizer(optimizer, optimizer_params)
        self._kvstore_params = {"kvstore": kvstore,
                                "update_on_kvstore": update_on_kvstore}
        self._kv_initialized = False
        self._kvstore = None
        self._update_on_kvstore = None
        self._params_to_init = []
        self._contains_sparse_weight = False
        self._contains_sparse_grad = False
        self._grad_buckets = None  # lazy; see _allreduce_grads
        self._shard_plan = None  # set by fuse_step(shard_plan=...)
        self._elastic = None  # ElasticSession (elastic kvstore attach)

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt_mod.Optimizer):
            assert not optimizer_params, \
                "optimizer_params must be None if optimizer is an " \
                "Optimizer instance"
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt_mod.create(optimizer,
                                             param_dict=param_dict,
                                             **optimizer_params)
        self._updaters = [opt_mod.get_updater(self._optimizer)]

    def _init_kvstore(self):
        """ref: trainer.py:169."""
        config = self._kvstore_params
        kvstore, update_on_kvstore = _create_kvstore(
            config["kvstore"], 1,
            {p.name: p.data() for p in self._params
             if p._data is not None})
        if config["update_on_kvstore"] is not None:
            update_on_kvstore = config["update_on_kvstore"]
        if kvstore is not None:
            if self._compression_params:
                kvstore.set_gradient_compression(self._compression_params)
            if update_on_kvstore:
                kvstore.set_optimizer(self._optimizer)
            if getattr(kvstore, "session", None) is None:
                # elastic stores hold no weights (the exchange is a
                # stateless fenced allreduce; weights live on the
                # workers), so there is nothing to init server-side —
                # and deferred-shape parameters stay deferred
                for i, param in enumerate(self._params):
                    if param.grad_req != "null":
                        kvstore.init(i, param.data())
        self._kvstore = kvstore
        self._update_on_kvstore = update_on_kvstore if kvstore else False
        self._kv_initialized = True
        session = getattr(kvstore, "session", None)
        if session is not None:  # elastic store: bind the membership
            session.attach(self)  # session so step() absorbs bumps

    @property
    def learning_rate(self):
        return self._optimizer.lr_scheduler(self._optimizer.num_update) \
            if self._optimizer.lr_scheduler else self._optimizer.lr

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr) \
            if self._optimizer.lr_scheduler is None else None
        if self._optimizer.lr_scheduler is None:
            self._optimizer.lr = lr

    def step(self, batch_size, ignore_stale_grad=False):
        """ref: trainer.py:305 — allreduce + update.

        The step boundary is the telemetry heartbeat: step count/latency/
        throughput counters update here, a throttled memory sample is
        taken, and one metrics line goes to the MXNET_METRICS_EXPORT
        sink when configured (telemetry.record_step)."""
        import time as _time
        from .. import telemetry as _telemetry
        t0 = _time.perf_counter()
        if not self._kv_initialized:
            self._init_kvstore()
        if self._elastic is not None:
            self._elastic_step(batch_size)
        else:
            self._optimizer.rescale_grad = self._scale / batch_size
            self._allreduce_grads()
        self._update(ignore_stale_grad)
        if self._elastic is not None:
            self._elastic.note_step(batch_size)
        _telemetry.record_step(batch_size, _time.perf_counter() - t0)

    def _elastic_step(self, batch_size):
        """The zero-user-code elastic boundary: heartbeat, observe
        generation bumps, absorb a mid-exchange MembershipChanged by
        rebuilding with the survivors and re-exchanging the SAME
        gradients under the new generation (docs/resilience.md). The
        summed exchange is normalized by 1/(batch x world), i.e. the
        global-batch mean — shrinking the world keeps per-sample
        update math intact."""
        from ..elastic.membership import MembershipChanged
        ses = self._elastic
        if ses.heartbeat():
            ses.rebuild()  # clears buckets, rescales LR, replans
        while True:
            self._optimizer.rescale_grad = \
                self._scale / (batch_size * max(1, ses.world))
            try:
                self._allreduce_grads()
                return
            except MembershipChanged:
                ses.rebuild()

    def _on_membership_change(self, old_view, new_view):
        """Session rebuild hook: relayout the gradient buckets for the
        new world size, rescale the LR (linear-scaling rule, anchored
        at the reference world — MXELASTIC_LR_SCALE), and re-infer the
        shard plan's batch axis from the devices still present (the
        ShardPlan.from_manifest path, live)."""
        from .. import config
        self._grad_buckets = None  # relayout for the new world
        ses = self._elastic
        if ses is not None and config.get("MXELASTIC_LR_SCALE") and \
                ses._base_lr and self._optimizer.lr_scheduler is None:
            self._optimizer.lr = ses._base_lr * \
                new_view.world_size / float(ses.ref_world)
        plan = self._shard_plan
        if plan is not None and new_view is not None and \
                new_view.devices:
            try:
                import jax as _jax
                ids = set(new_view.device_ids())
                devs = [d for d in _jax.devices() if d.id in ids]
                if devs:
                    self._shard_plan = plan.reinfer(devices=devs)
            except Exception as e:  # a bad device map must not stop
                import warnings  # the rebuild — weights stay usable
                warnings.warn(
                    f"elastic rebuild: shard-plan re-inference failed "
                    f"({e}); keeping the previous plan")

    def allreduce_grads(self):
        """ref: trainer.py:334."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore is None:
            return
        if self._update_on_kvstore or \
                not getattr(self._kvstore, "supports_flat_allreduce",
                            False):
            # server-side optimizer (or async PS): the server applies
            # per key — per-param push/pull semantics are the contract
            for i, param in enumerate(self._params):
                if param.grad_req != "null":
                    self._kvstore.push(i, param.list_grad(), priority=-i)
                    if not self._update_on_kvstore:
                        self._kvstore.pull(i, param.list_grad(),
                                           priority=-i)
            return
        self._allreduce_grads_bucketed()

    def _bucketable(self, param):
        """Dense single-buffer gradients coalesce; row_sparse grads and
        multi-device shard lists keep the per-param path."""
        from ..ndarray.sparse import RowSparseNDArray
        grads = param.list_grad()
        return len(grads) == 1 and \
            not isinstance(grads[0], RowSparseNDArray)

    def _allreduce_grads_bucketed(self):
        """DDP-style coalesced exchange (ISSUE 5): O(buckets) kvstore
        round trips instead of O(params) — gradients of like dtype are
        flattened into buckets capped at MXNET_GRAD_BUCKET_BYTES
        (step.buckets), allreduced flat, and scattered back into the
        parameters' grad buffers."""
        from ..ndarray.ndarray import _wrap
        from ..step.buckets import GradientBuckets
        items, leftover = [], []
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            if not self._bucketable(param):
                leftover.append(i)
                continue
            g = param.grad()
            items.append((i, tuple(g.shape), str(g.dtype),
                          g.size * g.dtype.itemsize))
        world = self._elastic.world if self._elastic is not None \
            else getattr(self._kvstore, "num_workers", 1)
        sig = (tuple(items), tuple(leftover), world)
        # (re)build when the layout changes — a Parameter.cast (amp
        # fine-tuning), grad_req flip, or elastic world-size change
        # would otherwise hit a stale assignment (mixed-dtype concat /
        # a layout whose round numbering belonged to a dead generation)
        if self._grad_buckets is None or self._grad_buckets[2] != sig:
            self._grad_buckets = (GradientBuckets(items,
                                                  world_size=world),
                                  leftover, sig)
        buckets, leftover, _ = self._grad_buckets
        grads = {i: self._params[i].grad()._data
                 for b in buckets.buckets for i, _, _ in b.entries}
        # exchange EVERY bucket before rebinding any: an elastic
        # MembershipChanged mid-exchange aborts the whole step's
        # reduce with no partial effect, so the retry after the
        # rebuild re-exchanges the ORIGINAL gradients — a per-bucket
        # rebind would feed already-reduced sums back into the retry
        # and double-count them (same invariant as
        # ElasticStepFunction._exchange_once)
        reduced_parts = []
        for bid, bucket in enumerate(buckets.buckets):
            flat = buckets.flatten(bucket, grads)
            reduced = self._kvstore.allreduce_flat(
                f"__grad_bucket_{bid}", _wrap(flat))
            reduced_parts.append((bucket, reduced._data))
        for bucket, flat in reduced_parts:
            for i, seg in buckets.unflatten(bucket, flat).items():
                self._params[i].grad()._rebind(seg)
        for i in leftover:  # sparse / multi-device: per-param exchange
            self._kvstore.push(i, self._params[i].list_grad(),
                               priority=-i)
            self._kvstore.pull(i, self._params[i].list_grad(),
                               priority=-i)

    def update(self, batch_size, ignore_stale_grad=False):
        """ref: trainer.py:366."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        updater = self._updaters[0]
        if self._kvstore and self._update_on_kvstore:
            for i, param in enumerate(self._params):
                if param.grad_req != "null":
                    self._kvstore.pull(i, param.list_data(), priority=-i)
            return
        live = [(i, param) for i, param in enumerate(self._params)
                if param.grad_req != "null"]
        if len(live) > 1 and updater.aggregate_updates:
            # aggregated multi-tensor update: the list-form Updater
            # chunks by MXNET_OPTIMIZER_AGGREGATION_SIZE and runs one
            # fused kernel call per chunk (optimizer.update_multi);
            # sparse/multi-precision fall back per-param inside it
            updater([i for i, _ in live],
                    [p.grad() for _, p in live],
                    [p.data() for _, p in live])
            return
        for i, param in live:
            updater(i, param.grad(), param.data())

    def fuse_step(self, net, loss_fn=None, shard_plan=None, **kwargs):
        """Compile this trainer's whole step into one donated XLA
        computation (mxnet_tpu.step.StepFunction): ``fused.step(x, y)``
        replaces the record/backward/step(batch) triple with a single
        dispatch, bitwise-equal to the eager loop for optimizers with a
        functional fused_apply. The trainer keeps owning optimizer
        state (save_states/load_states and mxresil checkpoints see the
        post-update values).

        With ``shard_plan=`` (a :class:`mxnet_tpu.shard.ShardPlan`) —
        or ``MXSHARD_AUTO=1`` and more than one local device — the
        step compiles GSPMD-sharded over the plan's named mesh: batch
        sharded on the ``batch`` axis, optimizer state ZeRO-sharded,
        parameters tensor-sharded per the plan's ``param_specs``; the
        same user code, ``P("batch", "model")`` composition included.
        Checkpoints taken through this trainer record the plan in
        their manifest and reshard on restore (docs/sharding.md)."""
        from .. import config
        if config.get("MXTUNE_AUTO"):
            # mxtune auto-apply (docs/tuning.md): the best measured
            # step/opt config for THIS model+device+space, applied via
            # set_flag before the step traces; any key mismatch or
            # validation failure leaves defaults untouched
            from ..tune.apply import consult_train, signature_of
            consult_train(signature_of(net))
        if shard_plan is None:
            import jax as _jax
            if config.get("MXSHARD_AUTO") and len(_jax.devices()) > 1:
                from ..shard import ShardPlan
                shard_plan = ShardPlan.from_env()
        if shard_plan is not None:
            from ..shard import ShardedStepFunction
            self._shard_plan = shard_plan
            return ShardedStepFunction(net, loss_fn, trainer=self,
                                       shard_plan=shard_plan, **kwargs)
        kvs = self._kvstore_params.get("kvstore")
        if not self._kv_initialized and (
                getattr(kvs, "session", None) is not None
                or (isinstance(kvs, str) and "elastic" in kvs)):
            self._init_kvstore()  # an elastic kvstore attaches here
        if self._elastic is not None:
            # elastic membership: the split-phase step whose update
            # program re-keys exactly once per world-size change
            from ..elastic.stepfn import ElasticStepFunction
            self._shard_plan = None
            return ElasticStepFunction(net, loss_fn, trainer=self,
                                       **kwargs)
        from ..step import StepFunction
        self._shard_plan = None  # an unsharded rebuild clears the plan
        return StepFunction(net, loss_fn, trainer=self, **kwargs)

    def save_states(self, fname):
        """ref: trainer.py save_states."""
        assert self._optimizer is not None
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname, dump_optimizer=True)
        else:
            with open(fname, "wb") as fout:
                fout.write(self._updaters[0].get_states(dump_optimizer=True))

    def load_states(self, fname):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
            self._optimizer = self._kvstore._updater.optimizer
        else:
            with open(fname, "rb") as f:
                states = f.read()
            for updater in self._updaters:
                updater.set_states(states)
                updater.optimizer = self._updaters[0].optimizer
            self._optimizer = self._updaters[0].optimizer
        param_dict = {i: param for i, param in enumerate(self._params)}
        self._optimizer.param_dict = param_dict
