"""Gluon RNN cells.

ref: python/mxnet/gluon/rnn/rnn_cell.py (1,092 LoC) — RecurrentCell base
with begin_state/unroll, RNNCell, LSTMCell, GRUCell, SequentialRNNCell,
DropoutCell, ZoneoutCell, ResidualCell, BidirectionalCell.
"""
from __future__ import annotations

from ...base import MXNetError
from ..block import Block, HybridBlock

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "HybridSequentialRNNCell",
           "ModifierCell", "DropoutCell", "ZoneoutCell",
           "ResidualCell", "BidirectionalCell"]


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


class RecurrentCell(Block):
    """ref: rnn_cell.py RecurrentCell."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """ref: rnn_cell.py begin_state."""
        assert not self._modified
        from ...ndarray.ndarray import zeros as nd_zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            shape = list(info["shape"])
            if shape[info.get("batch_axis", 1) if False else 1] == 0 \
                    or shape[1] == 0:
                shape[1] = batch_size
            if func is None:
                states.append(nd_zeros(tuple(shape), **kwargs))
            else:
                states.append(func(
                    name=f"{self._prefix}begin_state_{self._init_counter}",
                    shape=tuple(shape), **kwargs))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """ref: rnn_cell.py unroll — explicit python loop; jit sees it as
        an unrolled graph (lax.scan fused path lives in rnn_layer)."""
        self.reset()
        axis = layout.find("T")
        batch_axis = layout.find("N")
        if isinstance(inputs, (list, tuple)):
            assert len(inputs) == length
            batch_size = inputs[0].shape[batch_axis]
            seq = list(inputs)
        else:
            batch_size = inputs.shape[batch_axis]
            seq = [inputs[(slice(None),) * axis + (i,)]
                   for i in range(length)]
        if begin_state is None:
            begin_state = self.begin_state(batch_size)
        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(seq[i], states)
            outputs.append(output)
        if valid_length is not None:
            from ... import ndarray as F
            outputs = [F.where(
                (valid_length > i).reshape((-1,) + (1,) *
                                           (outputs[i].ndim - 1))
                .broadcast_to(outputs[i].shape),
                outputs[i], F.zeros(outputs[i].shape)) for i in
                range(length)]
        if merge_outputs:
            from ...ndarray.ndarray import stack
            outputs = stack(outputs, axis=axis)
        return outputs, states

    def forward(self, inputs, states):
        self._counter += 1
        return super().forward(inputs, states)

    def _alias(self):
        return "recurrentcell"


class HybridRecurrentCell(RecurrentCell, HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def forward(self, inputs, states):
        self._counter += 1
        return HybridBlock.forward(self, inputs, states)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class RNNCell(HybridRecurrentCell):
    """ref: rnn_cell.py RNNCell — h' = act(W_i x + b_i + W_h h + b_h)."""

    def __init__(self, hidden_size, activation="tanh",
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(hidden_size,), init=i2h_bias_initializer,
            allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(hidden_size,), init=h2h_bias_initializer,
            allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "rnn"

    def _infer_param_shapes(self, x, *args):
        self.i2h_weight.shape = (self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        output = F.Activation(i2h + h2h, act_type=self._activation)
        return output, [output]


class LSTMCell(HybridRecurrentCell):
    """ref: rnn_cell.py LSTMCell (gate order i,f,g,o — cuDNN packing)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None, activation="tanh",
                 recurrent_activation="sigmoid"):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(4 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(4 * hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(4 * hidden_size,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(4 * hidden_size,),
            init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "lstm"

    def _infer_param_shapes(self, x, *args):
        self.i2h_weight.shape = (4 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        slice_gates = F.SliceChannel(gates, num_outputs=4, axis=1)
        in_gate = F.sigmoid(slice_gates[0])
        forget_gate = F.sigmoid(slice_gates[1])
        in_transform = F.tanh(slice_gates[2])
        out_gate = F.sigmoid(slice_gates[3])
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * F.tanh(next_c)
        return next_h, [next_h, next_c]


class GRUCell(HybridRecurrentCell):
    """ref: rnn_cell.py GRUCell (gate order r,z,n)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(3 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(3 * hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(3 * hidden_size,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(3 * hidden_size,),
            init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "gru"

    def _infer_param_shapes(self, x, *args):
        self.i2h_weight.shape = (3 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prev_state_h = states[0]
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=3 * self._hidden_size)
        h2h = F.FullyConnected(prev_state_h, h2h_weight, h2h_bias,
                               num_hidden=3 * self._hidden_size)
        i2h_r, i2h_z, i2h_n = F.SliceChannel(i2h, num_outputs=3, axis=1)
        h2h_r, h2h_z, h2h_n = F.SliceChannel(h2h, num_outputs=3, axis=1)
        reset_gate = F.sigmoid(i2h_r + h2h_r)
        update_gate = F.sigmoid(i2h_z + h2h_z)
        next_h_tmp = F.tanh(i2h_n + reset_gate * h2h_n)
        next_h = (1.0 - update_gate) * next_h_tmp + update_gate * prev_state_h
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    """ref: rnn_cell.py SequentialRNNCell."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(list(self._children.values()), batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        assert not self._modified
        return _cells_begin_state(list(self._children.values()),
                                  batch_size=batch_size, **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.extend(state)
        return inputs, next_states

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return list(self._children.values())[i]


class HybridSequentialRNNCell(SequentialRNNCell):
    """ref: rnn_cell.py HybridSequentialRNNCell — the hybridizable
    stacked-cell container. Stacking logic is identical; under this
    framework both variants trace cleanly through jit (hybridize is a
    whole-graph property), so this subclass exists for API parity."""


class DropoutCell(HybridRecurrentCell):
    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def _alias(self):
        return "dropout"

    def hybrid_forward(self, F, inputs, states):
        if self._rate > 0:
            inputs = F.Dropout(inputs, p=self._rate, axes=self._axes)
        return inputs, states


class ModifierCell(HybridRecurrentCell):
    def __init__(self, base_cell):
        assert not base_cell._modified
        base_cell._modified = True
        super().__init__(prefix=base_cell.prefix + self._alias(),
                         params=None)
        self.base_cell = base_cell

    @property
    def params(self):
        return self.base_cell.params

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(batch_size=batch_size, func=func,
                                           **kwargs)
        self.base_cell._modified = True
        return begin


class ZoneoutCell(ModifierCell):
    """ref: rnn_cell.py ZoneoutCell."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def _alias(self):
        return "zoneout"

    def reset(self):
        super().reset()
        self._prev_output = None

    def hybrid_forward(self, F, inputs, states):
        cell = self.base_cell
        next_output, next_states = cell(inputs, states)
        p_outputs, p_states = self.zoneout_outputs, self.zoneout_states

        from ... import random as _rand

        def mask(p, like):
            return _rand.bernoulli(1 - p, shape=like.shape)

        prev_output = self._prev_output if self._prev_output is not None \
            else F.zeros(next_output.shape)
        output = F.where(mask(p_outputs, next_output), next_output,
                         prev_output) if p_outputs != 0.0 else next_output
        new_states = [F.where(mask(p_states, new_s), new_s, old_s)
                      for new_s, old_s in zip(next_states, states)] \
            if p_states != 0.0 else next_states
        self._prev_output = output
        return output, new_states


class ResidualCell(ModifierCell):
    def __init__(self, base_cell):
        super().__init__(base_cell)

    def _alias(self):
        return "residual"

    def hybrid_forward(self, F, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = output + inputs
        return output, states


class BidirectionalCell(HybridRecurrentCell):
    """ref: rnn_cell.py BidirectionalCell."""

    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix="", params=None)
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")
        self._output_prefix = output_prefix

    def __call__(self, inputs, states):
        raise MXNetError("Bidirectional cannot be stepped. Please use unroll")

    def state_info(self, batch_size=0):
        return _cells_state_info(list(self._children.values()), batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        assert not self._modified
        return _cells_begin_state(list(self._children.values()),
                                  batch_size=batch_size, **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        axis = layout.find("T")
        batch_axis = layout.find("N")
        if isinstance(inputs, (list, tuple)):
            batch_size = inputs[0].shape[batch_axis]
        else:
            batch_size = inputs.shape[batch_axis]
        if begin_state is None:
            begin_state = self.begin_state(batch_size)
        states = begin_state
        l_cell, r_cell = self._children.values()
        n_l = len(l_cell.state_info(batch_size))
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs, begin_state=states[:n_l], layout=layout,
            merge_outputs=False, valid_length=valid_length)
        if isinstance(inputs, (list, tuple)):
            rev_inputs = list(reversed(inputs))
        else:
            from ... import ndarray as F
            rev_inputs = [inputs[(slice(None),) * axis + (i,)]
                          for i in reversed(range(length))]
        r_outputs, r_states = r_cell.unroll(
            length, inputs=rev_inputs, begin_state=states[n_l:],
            layout=layout, merge_outputs=False, valid_length=valid_length)
        r_outputs = list(reversed(r_outputs))
        from ...ndarray.ndarray import concat
        outputs = [concat(l_o, r_o, dim=1)
                   for l_o, r_o in zip(l_outputs, r_outputs)]
        if merge_outputs:
            from ...ndarray.ndarray import stack
            outputs = stack(outputs, axis=axis)
        states = l_states + r_states
        return outputs, states
