"""Gluon fused RNN layers (RNN/LSTM/GRU).

ref: python/mxnet/gluon/rnn/rnn_layer.py (634 LoC) — _RNNLayer over the
fused RNN op (here ops/rnn.py's lax.scan implementation). Parameters are
registered per-layer/direction/gate to match the reference's naming
(l0_i2h_weight, ...) and packed into the flat vector at call time.
"""
from __future__ import annotations

from ...base import MXNetError
from ..block import HybridBlock

__all__ = ["RNN", "LSTM", "GRU"]


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, mode, projection_size=None, **kwargs):
        super().__init__(**kwargs)
        assert layout in ("TNC", "NTC"), \
            f"Invalid layout {layout}; must be one of ['TNC' or 'NTC']"
        self._hidden_size = hidden_size
        self._projection_size = projection_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._gates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]

        ng, ni, nh = self._gates, input_size, hidden_size
        for i in range(num_layers):
            for j in ["l", "r"][:self._dir]:
                self._register_param(f"{j}{i}_i2h_weight",
                                     (ng * nh, ni), i2h_weight_initializer)
                self._register_param(f"{j}{i}_h2h_weight",
                                     (ng * nh, nh), h2h_weight_initializer)
                self._register_param(f"{j}{i}_i2h_bias",
                                     (ng * nh,), i2h_bias_initializer)
                self._register_param(f"{j}{i}_h2h_bias",
                                     (ng * nh,), h2h_bias_initializer)
            ni = nh * self._dir

    def _register_param(self, name, shape, init):
        p = self.params.get(name, shape=shape, init=init,
                            allow_deferred_init=True)
        setattr(self, name, p)
        return p

    def _infer_param_shapes(self, x, *args):
        ni = x.shape[-1]
        ng, nh = self._gates, self._hidden_size
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                getattr(self, f"{j}{i}_i2h_weight").shape = (ng * nh, ni)
            ni = nh * self._dir

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ...ndarray.ndarray import zeros as nd_zeros
        states = []
        for i, info in enumerate(self.state_info(batch_size)):
            if func is None:
                states.append(nd_zeros(info["shape"], **kwargs))
            else:
                info.update(kwargs)
                states.append(func(name=f"{self.prefix}h0_{i}",
                                   **{k: v for k, v in info.items()
                                      if k != "__layout__"}))
        return states

    def _pack_params(self):
        """Flatten per-gate params into the fused layout (all weights then
        all biases — matches ops/rnn.py unpack_rnn_params)."""
        from ...ndarray.ndarray import concat
        ws, bs = [], []
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                ws.append(getattr(self, f"{j}{i}_i2h_weight").data()
                          .reshape((-1,)))
                ws.append(getattr(self, f"{j}{i}_h2h_weight").data()
                          .reshape((-1,)))
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                bs.append(getattr(self, f"{j}{i}_i2h_bias").data())
                bs.append(getattr(self, f"{j}{i}_h2h_bias").data())
        return concat(*(ws + bs), dim=0)

    def __call__(self, inputs, states=None):
        self._resolve_deferred(inputs)
        skip_states = states is None
        if skip_states:
            batch_size = inputs.shape[self._layout.find("N")]
            states = self.begin_state(batch_size)
        if not isinstance(states, (list, tuple)):
            states = [states]
        out, out_states = self._forward_kernel(inputs, states)
        return out if skip_states else (out, out_states)

    def _resolve_deferred(self, x):
        try:
            for p in self._reg_params.values():
                p.data()
        except Exception:
            xx = x if self._layout == "TNC" else x
            self._infer_param_shapes(xx)
            for p in self.collect_params().values():
                if p._deferred_init:
                    p._finish_deferred_init()
                elif p._data is None:
                    p.initialize()

    def _forward_kernel(self, inputs, states):
        from ... import ndarray as F
        if self._layout == "NTC":
            inputs = inputs.swapaxes(0, 1)
        params = self._pack_params()
        rnn_args = [inputs, params] + list(states)
        outputs = F.RNN(*rnn_args, state_size=self._hidden_size,
                        num_layers=self._num_layers, mode=self._mode,
                        bidirectional=self._dir == 2, p=self._dropout,
                        state_outputs=True)
        if self._mode == "lstm":
            out, h_out, c_out = outputs
        else:
            out, h_out = outputs
            c_out = None
        if self._layout == "NTC":
            out = out.swapaxes(0, 1)
        if self._mode == "lstm":
            return out, [h_out, c_out]
        return out, [h_out]

    def __repr__(self):
        return f"{self.__class__.__name__}({self._input_size} -> " \
               f"{self._hidden_size}, {self._layout}, " \
               f"num_layers={self._num_layers})"


class RNN(_RNNLayer):
    """ref: rnn_layer.py RNN."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "rnn_" + activation, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class LSTM(_RNNLayer):
    """ref: rnn_layer.py LSTM."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 projection_size=None, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "lstm", projection_size,
                         **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"},
                {"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class GRU(_RNNLayer):
    """ref: rnn_layer.py GRU."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "gru", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]
