"""Gluon basic layers.

ref: python/mxnet/gluon/nn/basic_layers.py (802 LoC) — Sequential,
HybridSequential, Dense, Dropout, BatchNorm, InstanceNorm, LayerNorm,
GroupNorm, Embedding, Flatten, Lambda, HybridLambda, Activation.
"""
from __future__ import annotations

from typing import Optional

from ... import initializer as init_mod
from ...base import MXNetError
from ..block import Block, HybridBlock, record_aux_update
from ..parameter import Parameter

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "BatchNorm",
           "InstanceNorm", "LayerNorm", "GroupNorm", "Embedding", "Flatten",
           "Lambda", "HybridLambda", "Activation", "LeakyReLU", "PReLU",
           "ELU", "SELU", "Swish", "GELU"]


class Sequential(Block):
    """ref: basic_layers.py Sequential."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __iter__(self):
        return iter(self._children.values())


class HybridSequential(HybridBlock):
    """ref: basic_layers.py HybridSequential."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def hybrid_forward(self, F, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """ref: basic_layers.py Dense → FullyConnected op."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self._units = units
            self._flatten = flatten
            self.weight = self.params.get(
                "weight", shape=(units, in_units), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), dtype=dtype,
                    init=bias_initializer, allow_deferred_init=True)
            else:
                self.bias = None
            if activation is not None:
                self.act = Activation(activation, prefix=activation + "_")
            else:
                self.act = None

    def _infer_param_shapes(self, x, *args):
        import numpy as onp
        in_units = x.shape[-1] if not self._flatten else \
            int(onp.prod(x.shape[1:]))
        self.weight.shape = (self._units, in_units)

    def hybrid_forward(self, F, x, weight, bias=None):
        if bias is None:
            act = F.FullyConnected(x, weight, num_hidden=self._units,
                                   no_bias=True, flatten=self._flatten)
        else:
            act = F.FullyConnected(x, weight, bias, num_hidden=self._units,
                                   flatten=self._flatten)
        if self.act is not None:
            act = self.act(act)
        return act

    def __repr__(self):
        shape = self.weight.shape
        return f"Dense({shape[1] if shape and len(shape) > 1 else None} -> " \
               f"{shape[0] if shape else None}, " \
               f"{'linear' if self.act is None else self.act._act_type})"


class Activation(HybridBlock):
    def __init__(self, activation, **kwargs):
        self._act_type = activation
        super().__init__(**kwargs)

    def _alias(self):
        return self._act_type

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)

    def __repr__(self):
        return f"Activation({self._act_type})"


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        if self._rate > 0:
            return F.Dropout(x, p=self._rate, axes=self._axes)
        return F.identity(x)

    def __repr__(self):
        return f"Dropout(p = {self._rate}, axes={self._axes})"


class BatchNorm(HybridBlock):
    """ref: basic_layers.py BatchNorm — running stats are aux state,
    written back through record_aux_update (jit-safe)."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"axis": axis, "eps": epsilon, "momentum": momentum,
                        "fix_gamma": not scale,
                        "use_global_stats": use_global_stats}
        self._axis = axis
        if in_channels != 0:
            self.in_channels = in_channels
        self.gamma = self.params.get("gamma",
                                     grad_req="write" if scale else "null",
                                     shape=(in_channels,),
                                     init=gamma_initializer,
                                     allow_deferred_init=True)
        self.beta = self.params.get("beta",
                                    grad_req="write" if center else "null",
                                    shape=(in_channels,),
                                    init=beta_initializer,
                                    allow_deferred_init=True)
        self.running_mean = self.params.get(
            "running_mean", grad_req="null", shape=(in_channels,),
            init=running_mean_initializer, allow_deferred_init=True,
            differentiable=False)
        self.running_var = self.params.get(
            "running_var", grad_req="null", shape=(in_channels,),
            init=running_variance_initializer, allow_deferred_init=True,
            differentiable=False)

    def _infer_param_shapes(self, x, *args):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p.shape = (c,)

    def cast(self, dtype):
        if str(dtype) in ("float16", "bfloat16"):
            dtype = "float32"  # BN stats stay fp32 (AMP practice)
        super().cast(dtype)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        res = F.BatchNorm(
            x, gamma, beta, running_mean, running_var, **self._kwargs)
        if not isinstance(res, (tuple, list)):
            # symbolic trace: the graph op exposes one output; the
            # running-stat updates are executor aux-state semantics
            return res
        out, new_mean, new_var = res
        record_aux_update(self.running_mean, new_mean)
        record_aux_update(self.running_var, new_var)
        return out

    def __repr__(self):
        in_channels = self.gamma.shape[0] if self.gamma.shape else None
        return f"BatchNorm(axis={self._axis}, in_channels={in_channels})"


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"eps": epsilon}
        self._axis = axis
        self.gamma = self.params.get("gamma",
                                     grad_req="write" if scale else "null",
                                     shape=(in_channels,),
                                     init=gamma_initializer,
                                     allow_deferred_init=True)
        self.beta = self.params.get("beta",
                                    grad_req="write" if center else "null",
                                    shape=(in_channels,),
                                    init=beta_initializer,
                                    allow_deferred_init=True)

    def _infer_param_shapes(self, x, *args):
        c = x.shape[self._axis]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, **self._kwargs)


class LayerNorm(HybridBlock):
    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._kwargs = {"axis": axis, "eps": epsilon}
        self._axis = axis
        self.gamma = self.params.get("gamma",
                                     grad_req="write" if scale else "null",
                                     shape=(in_channels,),
                                     init=gamma_initializer,
                                     allow_deferred_init=True)
        self.beta = self.params.get("beta",
                                    grad_req="write" if center else "null",
                                    shape=(in_channels,),
                                    init=beta_initializer,
                                    allow_deferred_init=True)

    def _infer_param_shapes(self, x, *args):
        c = x.shape[self._axis]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, **self._kwargs)


class GroupNorm(HybridBlock):
    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._kwargs = {"num_groups": num_groups, "eps": epsilon}
        self.gamma = self.params.get("gamma",
                                     grad_req="write" if scale else "null",
                                     shape=(in_channels,),
                                     init=gamma_initializer,
                                     allow_deferred_init=True)
        self.beta = self.params.get("beta",
                                    grad_req="write" if center else "null",
                                    shape=(in_channels,),
                                    init=beta_initializer,
                                    allow_deferred_init=True)

    def _infer_param_shapes(self, x, *args):
        c = x.shape[1]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.GroupNorm(x, gamma, beta, **self._kwargs)


class Embedding(HybridBlock):
    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"input_dim": input_dim, "output_dim": output_dim,
                        "sparse_grad": sparse_grad}
        self.weight = self.params.get("weight",
                                      shape=(input_dim, output_dim),
                                      init=weight_initializer, dtype=dtype,
                                      allow_deferred_init=True)

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, **self._kwargs)

    def __repr__(self):
        return "Embedding({input_dim} -> {output_dim})".format(**self._kwargs)


class Flatten(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.Flatten(x)

    def __repr__(self):
        return "Flatten"


class Lambda(Block):
    """ref: basic_layers.py Lambda."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as nd_ns
            assert hasattr(nd_ns, function), \
                f"Function name {function} is not found in ndarray"
            self._func_impl = getattr(nd_ns, function)
        else:
            self._func_impl = function

    def forward(self, *args):
        return self._func_impl(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            self._func_name = function
            self._func = None
        else:
            self._func = function
            self._func_name = getattr(function, "__name__", "lambda")

    def hybrid_forward(self, F, x, *args):
        if self._func is not None:
            return self._func(F, x, *args)
        return getattr(F, self._func_name)(x, *args)


class LeakyReLU(HybridBlock):
    def __init__(self, alpha, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)


class PReLU(HybridBlock):
    def __init__(self, alpha_initializer=init_mod.Constant(0.25),
                 in_channels=1, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.alpha = self.params.get("alpha", shape=(in_channels,),
                                         init=alpha_initializer)

    def hybrid_forward(self, F, x, alpha):
        return F.LeakyReLU(x, alpha, act_type="prelu")


class ELU(HybridBlock):
    def __init__(self, alpha=1.0, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="selu")


class GELU(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="gelu")


class Swish(HybridBlock):
    def __init__(self, beta=1.0, **kwargs):
        super().__init__(**kwargs)
        self._beta = beta

    def hybrid_forward(self, F, x):
        return x * F.sigmoid(self._beta * x)
