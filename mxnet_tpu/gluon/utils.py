"""Gluon utilities.

ref: python/mxnet/gluon/utils.py — split_data/split_and_load (the
data-parallel batch scatter), clip_global_norm, check_sha1, download.
"""
from __future__ import annotations

import hashlib
import os
import math

import numpy as onp

from ..base import MXNetError
from ..context import Context, cpu
from ..ndarray.ndarray import NDArray, array

__all__ = ["shape_is_known", "split_data", "split_and_load", "clip_global_norm", "check_sha1",
           "download"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """ref: utils.py split_data."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise ValueError(
            f"data with shape {data.shape} cannot be evenly split into "
            f"{num_slice} slices along axis {batch_axis}.")
    if num_slice == 1:
        return [data]
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        start = i * step
        end = size if i == num_slice - 1 else (i + 1) * step
        idx = [slice(None)] * data.ndim
        idx[batch_axis] = slice(start, end)
        slices.append(data[tuple(idx)])
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """ref: utils.py split_and_load — scatter a batch across devices.
    On a one-mesh TPU program the scatter is a sharding annotation; this
    per-device list form is kept for reference-style training loops."""
    if not isinstance(data, NDArray):
        data = array(data)
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """ref: utils.py clip_global_norm."""
    assert len(arrays) > 0
    total_norm = math.sqrt(sum(
        float((a * a).sum().asscalar()) for a in arrays))
    if check_isfinite and not math.isfinite(total_norm):
        import warnings
        warnings.warn("nan or inf is detected. Clipping results will be "
                      "undefined.", stacklevel=2)
    scale = max_norm / (total_norm + 1e-8)
    if scale < 1.0:
        for arr in arrays:
            arr *= scale
    return total_norm


def check_sha1(filename, sha1_hash):
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None,
             retries=5, verify_ssl=True):
    """ref: utils.py download (no egress in this environment — local
    files/file:// only)."""
    if path is None:
        fname = url.split("/")[-1]
    elif os.path.isdir(path):
        fname = os.path.join(path, url.split("/")[-1])
    else:
        fname = path
    if os.path.exists(fname) and not overwrite and (
            not sha1_hash or check_sha1(fname, sha1_hash)):
        return fname
    if url.startswith("file://"):
        import shutil
        shutil.copyfile(url[7:], fname)
        return fname
    raise MXNetError("network download is unavailable in this environment; "
                     "place the file locally and pass a file:// url")


def shape_is_known(shape):
    """True when every dim of `shape` is concrete (ref: gluon/utils.py
    shape_is_known; unknown is -1 under np semantics, 0 otherwise)."""
    if shape is None:
        return False
    from ..util import is_np_shape
    unknown = -1 if is_np_shape() else 0
    if len(shape) == 0:
        # rank-0: known only under np semantics (ref: utils.py:433)
        return unknown == -1
    for dim in shape:
        if dim == unknown:
            return False
        assert dim > unknown, (
            f"shape dimension must be >= {unknown}, got {dim}")
    return True
