"""Model zoo: predefined vision networks.

ref: python/mxnet/gluon/model_zoo/vision/ (~2.9k LoC) — resnet v1/v2
(resnet.py), vgg(+bn), alexnet, squeezenet, densenet, mobilenet v1/v2.
Pretrained-weight download is unavailable (no egress); architectures are
complete and train from scratch.
"""
from __future__ import annotations

from ...base import MXNetError
from ..block import HybridBlock
from .. import nn

__all__ = ["get_model", "resnet18_v1", "resnet34_v1", "resnet50_v1",
           "resnet101_v1", "resnet152_v1", "resnet18_v2", "resnet34_v2",
           "resnet50_v2", "resnet101_v2", "resnet152_v2", "vgg11", "vgg13",
           "vgg16", "vgg19", "vgg11_bn", "vgg13_bn", "vgg16_bn", "vgg19_bn",
           "alexnet", "squeezenet1_0", "squeezenet1_1", "densenet121",
           "densenet161", "densenet169", "densenet201", "mobilenet1_0",
           "mobilenet0_75", "mobilenet0_5", "mobilenet0_25",
           "mobilenet_v2_1_0", "get_resnet", "get_vgg", "get_mobilenet",
           "ResNetV1", "ResNetV2", "VGG", "AlexNet", "SqueezeNet",
           "DenseNet", "MobileNet", "MobileNetV2", "Inception3",
           "inception_v3"]


# ---------------------------------------------------------------------------
# ResNet (ref: model_zoo/vision/resnet.py)
# ---------------------------------------------------------------------------

def _conv3x3(channels, stride, in_channels):
    return nn.Conv2D(channels, kernel_size=3, strides=stride, padding=1,
                     use_bias=False, in_channels=in_channels)


class BasicBlockV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential(prefix="")
        self.body.add(_conv3x3(channels, stride, in_channels))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(_conv3x3(channels, 1, channels))
        self.body.add(nn.BatchNorm())
        if downsample:
            self.downsample = nn.HybridSequential(prefix="")
            self.downsample.add(nn.Conv2D(channels, kernel_size=1,
                                          strides=stride, use_bias=False,
                                          in_channels=in_channels))
            self.downsample.add(nn.BatchNorm())
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.body(x)
        if self.downsample:
            residual = self.downsample(residual)
        return F.Activation(residual + x, act_type="relu")


class BottleneckV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential(prefix="")
        self.body.add(nn.Conv2D(channels // 4, kernel_size=1, strides=stride))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(_conv3x3(channels // 4, 1, channels // 4))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels, kernel_size=1, strides=1))
        self.body.add(nn.BatchNorm())
        if downsample:
            self.downsample = nn.HybridSequential(prefix="")
            self.downsample.add(nn.Conv2D(channels, kernel_size=1,
                                          strides=stride, use_bias=False,
                                          in_channels=in_channels))
            self.downsample.add(nn.BatchNorm())
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.body(x)
        if self.downsample:
            residual = self.downsample(residual)
        return F.Activation(x + residual, act_type="relu")


class BasicBlockV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.bn1 = nn.BatchNorm()
        self.conv1 = _conv3x3(channels, stride, in_channels)
        self.bn2 = nn.BatchNorm()
        self.conv2 = _conv3x3(channels, 1, channels)
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride, use_bias=False,
                                        in_channels=in_channels)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.bn1(x)
        x = F.Activation(x, act_type="relu")
        if self.downsample:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv2(x)
        return x + residual


class BottleneckV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.bn1 = nn.BatchNorm()
        self.conv1 = nn.Conv2D(channels // 4, kernel_size=1, strides=1,
                               use_bias=False)
        self.bn2 = nn.BatchNorm()
        self.conv2 = _conv3x3(channels // 4, stride, channels // 4)
        self.bn3 = nn.BatchNorm()
        self.conv3 = nn.Conv2D(channels, kernel_size=1, strides=1,
                               use_bias=False)
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride, use_bias=False,
                                        in_channels=in_channels)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.bn1(x)
        x = F.Activation(x, act_type="relu")
        if self.downsample:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv2(x)
        x = self.bn3(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv3(x)
        return x + residual


class ResNetV1(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000, thumbnail=False,
                 **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(channels) - 1
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            if thumbnail:
                self.features.add(_conv3x3(channels[0], 1, 0))
            else:
                self.features.add(nn.Conv2D(channels[0], 7, 2, 3,
                                            use_bias=False))
                self.features.add(nn.BatchNorm())
                self.features.add(nn.Activation("relu"))
                self.features.add(nn.MaxPool2D(3, 2, 1))
            for i, num_layer in enumerate(layers):
                stride = 1 if i == 0 else 2
                self.features.add(self._make_layer(
                    block, num_layer, channels[i + 1], stride, i + 1,
                    in_channels=channels[i]))
            self.features.add(nn.GlobalAvgPool2D())
            self.output = nn.Dense(classes, in_units=channels[-1])

    def _make_layer(self, block, layers, channels, stride, stage_index,
                    in_channels=0):
        layer = nn.HybridSequential(prefix=f"stage{stage_index}_")
        with layer.name_scope():
            layer.add(block(channels, stride, channels != in_channels,
                            in_channels=in_channels, prefix=""))
            for _ in range(layers - 1):
                layer.add(block(channels, 1, False, in_channels=channels,
                                prefix=""))
        return layer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        x = self.output(x)
        return x


class ResNetV2(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000, thumbnail=False,
                 **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(channels) - 1
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.BatchNorm(scale=False, center=False))
            if thumbnail:
                self.features.add(_conv3x3(channels[0], 1, 0))
            else:
                self.features.add(nn.Conv2D(channels[0], 7, 2, 3,
                                            use_bias=False))
                self.features.add(nn.BatchNorm())
                self.features.add(nn.Activation("relu"))
                self.features.add(nn.MaxPool2D(3, 2, 1))
            in_channels = channels[0]
            for i, num_layer in enumerate(layers):
                stride = 1 if i == 0 else 2
                self.features.add(self._make_layer(
                    block, num_layer, channels[i + 1], stride, i + 1,
                    in_channels=in_channels))
                in_channels = channels[i + 1]
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.GlobalAvgPool2D())
            self.features.add(nn.Flatten())
            self.output = nn.Dense(classes, in_units=in_channels)

    _make_layer = ResNetV1._make_layer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        x = self.output(x)
        return x


resnet_spec = {18: ("basic_block", [2, 2, 2, 2], [64, 64, 128, 256, 512]),
               34: ("basic_block", [3, 4, 6, 3], [64, 64, 128, 256, 512]),
               50: ("bottle_neck", [3, 4, 6, 3], [64, 256, 512, 1024, 2048]),
               101: ("bottle_neck", [3, 4, 23, 3], [64, 256, 512, 1024, 2048]),
               152: ("bottle_neck", [3, 8, 36, 3], [64, 256, 512, 1024, 2048])}
resnet_net_versions = [ResNetV1, ResNetV2]
resnet_block_versions = [{"basic_block": BasicBlockV1,
                          "bottle_neck": BottleneckV1},
                         {"basic_block": BasicBlockV2,
                          "bottle_neck": BottleneckV2}]


def get_resnet(version, num_layers, pretrained=False, ctx=None, root=None,
               **kwargs):
    """ref: resnet.py get_resnet."""
    assert num_layers in resnet_spec
    block_type, layers, channels = resnet_spec[num_layers]
    assert 1 <= version <= 2
    resnet_class = resnet_net_versions[version - 1]
    block_class = resnet_block_versions[version - 1][block_type]
    net = resnet_class(block_class, layers, channels, **kwargs)
    if pretrained:
        raise MXNetError("pretrained weights unavailable (no egress); "
                         "load_parameters from a local file instead")
    return net


def resnet18_v1(**kw):
    return get_resnet(1, 18, **kw)


def resnet34_v1(**kw):
    return get_resnet(1, 34, **kw)


def resnet50_v1(**kw):
    return get_resnet(1, 50, **kw)


def resnet101_v1(**kw):
    return get_resnet(1, 101, **kw)


def resnet152_v1(**kw):
    return get_resnet(1, 152, **kw)


def resnet18_v2(**kw):
    return get_resnet(2, 18, **kw)


def resnet34_v2(**kw):
    return get_resnet(2, 34, **kw)


def resnet50_v2(**kw):
    return get_resnet(2, 50, **kw)


def resnet101_v2(**kw):
    return get_resnet(2, 101, **kw)


def resnet152_v2(**kw):
    return get_resnet(2, 152, **kw)


# ---------------------------------------------------------------------------
# VGG (ref: model_zoo/vision/vgg.py)
# ---------------------------------------------------------------------------

class VGG(HybridBlock):
    def __init__(self, layers, filters, classes=1000, batch_norm=False,
                 **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(filters)
        with self.name_scope():
            self.features = self._make_features(layers, filters, batch_norm)
            self.features.add(nn.Dense(4096, activation="relu",
                                       weight_initializer="normal"))
            self.features.add(nn.Dropout(rate=0.5))
            self.features.add(nn.Dense(4096, activation="relu",
                                       weight_initializer="normal"))
            self.features.add(nn.Dropout(rate=0.5))
            self.output = nn.Dense(classes, weight_initializer="normal")

    def _make_features(self, layers, filters, batch_norm):
        featurizer = nn.HybridSequential(prefix="")
        for i, num in enumerate(layers):
            for _ in range(num):
                featurizer.add(nn.Conv2D(filters[i], kernel_size=3,
                                         padding=1))
                if batch_norm:
                    featurizer.add(nn.BatchNorm())
                featurizer.add(nn.Activation("relu"))
            featurizer.add(nn.MaxPool2D(strides=2))
        return featurizer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        x = self.output(x)
        return x


vgg_spec = {11: ([1, 1, 2, 2, 2], [64, 128, 256, 512, 512]),
            13: ([2, 2, 2, 2, 2], [64, 128, 256, 512, 512]),
            16: ([2, 2, 3, 3, 3], [64, 128, 256, 512, 512]),
            19: ([2, 2, 4, 4, 4], [64, 128, 256, 512, 512])}


def get_vgg(num_layers, pretrained=False, **kwargs):
    layers, filters = vgg_spec[num_layers]
    net = VGG(layers, filters, **kwargs)
    if pretrained:
        raise MXNetError("pretrained weights unavailable (no egress)")
    return net


def vgg11(**kw):
    return get_vgg(11, **kw)


def vgg13(**kw):
    return get_vgg(13, **kw)


def vgg16(**kw):
    return get_vgg(16, **kw)


def vgg19(**kw):
    return get_vgg(19, **kw)


def vgg11_bn(**kw):
    return get_vgg(11, batch_norm=True, **kw)


def vgg13_bn(**kw):
    return get_vgg(13, batch_norm=True, **kw)


def vgg16_bn(**kw):
    return get_vgg(16, batch_norm=True, **kw)


def vgg19_bn(**kw):
    return get_vgg(19, batch_norm=True, **kw)


# ---------------------------------------------------------------------------
# AlexNet (ref: model_zoo/vision/alexnet.py)
# ---------------------------------------------------------------------------

class AlexNet(HybridBlock):
    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.Conv2D(64, kernel_size=11, strides=4,
                                        padding=2, activation="relu"))
            self.features.add(nn.MaxPool2D(pool_size=3, strides=2))
            self.features.add(nn.Conv2D(192, kernel_size=5, padding=2,
                                        activation="relu"))
            self.features.add(nn.MaxPool2D(pool_size=3, strides=2))
            self.features.add(nn.Conv2D(384, kernel_size=3, padding=1,
                                        activation="relu"))
            self.features.add(nn.Conv2D(256, kernel_size=3, padding=1,
                                        activation="relu"))
            self.features.add(nn.Conv2D(256, kernel_size=3, padding=1,
                                        activation="relu"))
            self.features.add(nn.MaxPool2D(pool_size=3, strides=2))
            self.features.add(nn.Flatten())
            self.features.add(nn.Dense(4096, activation="relu"))
            self.features.add(nn.Dropout(0.5))
            self.features.add(nn.Dense(4096, activation="relu"))
            self.features.add(nn.Dropout(0.5))
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        x = self.features(x)
        x = self.output(x)
        return x


def alexnet(pretrained=False, **kwargs):
    net = AlexNet(**kwargs)
    if pretrained:
        raise MXNetError("pretrained weights unavailable (no egress)")
    return net


# ---------------------------------------------------------------------------
# SqueezeNet (ref: model_zoo/vision/squeezenet.py)
# ---------------------------------------------------------------------------

def _make_fire(squeeze_channels, expand1x1_channels, expand3x3_channels):
    out = nn.HybridSequential(prefix="")
    out.add(_make_fire_conv(squeeze_channels, 1))
    paths = _FireConcat(expand1x1_channels, expand3x3_channels)
    out.add(paths)
    return out


def _make_fire_conv(channels, kernel_size, padding=0):
    out = nn.HybridSequential(prefix="")
    out.add(nn.Conv2D(channels, kernel_size, padding=padding))
    out.add(nn.Activation("relu"))
    return out


class _FireConcat(HybridBlock):
    def __init__(self, c1, c3, **kwargs):
        super().__init__(**kwargs)
        self.p1 = _make_fire_conv(c1, 1)
        self.p3 = _make_fire_conv(c3, 3, 1)

    def hybrid_forward(self, F, x):
        return F.Concat(self.p1(x), self.p3(x), dim=1)


class SqueezeNet(HybridBlock):
    def __init__(self, version, classes=1000, **kwargs):
        super().__init__(**kwargs)
        assert version in ("1.0", "1.1")
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            if version == "1.0":
                self.features.add(nn.Conv2D(96, kernel_size=7, strides=2))
                self.features.add(nn.Activation("relu"))
                self.features.add(nn.MaxPool2D(pool_size=3, strides=2,
                                               ceil_mode=True))
                self.features.add(_make_fire(16, 64, 64))
                self.features.add(_make_fire(16, 64, 64))
                self.features.add(_make_fire(32, 128, 128))
                self.features.add(nn.MaxPool2D(pool_size=3, strides=2,
                                               ceil_mode=True))
                self.features.add(_make_fire(32, 128, 128))
                self.features.add(_make_fire(48, 192, 192))
                self.features.add(_make_fire(48, 192, 192))
                self.features.add(_make_fire(64, 256, 256))
                self.features.add(nn.MaxPool2D(pool_size=3, strides=2,
                                               ceil_mode=True))
                self.features.add(_make_fire(64, 256, 256))
            else:
                self.features.add(nn.Conv2D(64, kernel_size=3, strides=2))
                self.features.add(nn.Activation("relu"))
                self.features.add(nn.MaxPool2D(pool_size=3, strides=2,
                                               ceil_mode=True))
                self.features.add(_make_fire(16, 64, 64))
                self.features.add(_make_fire(16, 64, 64))
                self.features.add(nn.MaxPool2D(pool_size=3, strides=2,
                                               ceil_mode=True))
                self.features.add(_make_fire(32, 128, 128))
                self.features.add(_make_fire(32, 128, 128))
                self.features.add(nn.MaxPool2D(pool_size=3, strides=2,
                                               ceil_mode=True))
                self.features.add(_make_fire(48, 192, 192))
                self.features.add(_make_fire(48, 192, 192))
                self.features.add(_make_fire(64, 256, 256))
                self.features.add(_make_fire(64, 256, 256))
            self.features.add(nn.Dropout(0.5))
            self.output = nn.HybridSequential(prefix="")
            self.output.add(nn.Conv2D(classes, kernel_size=1))
            self.output.add(nn.Activation("relu"))
            self.output.add(nn.GlobalAvgPool2D())
            self.output.add(nn.Flatten())

    def hybrid_forward(self, F, x):
        x = self.features(x)
        x = self.output(x)
        return x


def squeezenet1_0(**kwargs):
    kwargs.pop("pretrained", None)
    return SqueezeNet("1.0", **kwargs)


def squeezenet1_1(**kwargs):
    kwargs.pop("pretrained", None)
    return SqueezeNet("1.1", **kwargs)


# ---------------------------------------------------------------------------
# DenseNet (ref: model_zoo/vision/densenet.py)
# ---------------------------------------------------------------------------

class _DenseLayerConcat(HybridBlock):
    def __init__(self, growth_rate, bn_size, dropout, **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential(prefix="")
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(bn_size * growth_rate, kernel_size=1,
                                use_bias=False))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(growth_rate, kernel_size=3, padding=1,
                                use_bias=False))
        if dropout:
            self.body.add(nn.Dropout(dropout))

    def hybrid_forward(self, F, x):
        return F.Concat(x, self.body(x), dim=1)


def _make_dense_block(num_layers, bn_size, growth_rate, dropout, stage_index):
    out = nn.HybridSequential(prefix=f"stage{stage_index}_")
    with out.name_scope():
        for _ in range(num_layers):
            out.add(_DenseLayerConcat(growth_rate, bn_size, dropout))
    return out


def _make_transition(num_output_features):
    out = nn.HybridSequential(prefix="")
    out.add(nn.BatchNorm())
    out.add(nn.Activation("relu"))
    out.add(nn.Conv2D(num_output_features, kernel_size=1, use_bias=False))
    out.add(nn.AvgPool2D(pool_size=2, strides=2))
    return out


class DenseNet(HybridBlock):
    def __init__(self, num_init_features, growth_rate, block_config,
                 bn_size=4, dropout=0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.Conv2D(num_init_features, kernel_size=7,
                                        strides=2, padding=3, use_bias=False))
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(pool_size=3, strides=2,
                                           padding=1))
            num_features = num_init_features
            for i, num_layers in enumerate(block_config):
                self.features.add(_make_dense_block(
                    num_layers, bn_size, growth_rate, dropout, i + 1))
                num_features = num_features + num_layers * growth_rate
                if i != len(block_config) - 1:
                    self.features.add(_make_transition(num_features // 2))
                    num_features = num_features // 2
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.AvgPool2D(pool_size=7))
            self.features.add(nn.Flatten())
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        x = self.features(x)
        x = self.output(x)
        return x


densenet_spec = {121: (64, 32, [6, 12, 24, 16]),
                 161: (96, 48, [6, 12, 36, 24]),
                 169: (64, 32, [6, 12, 32, 32]),
                 201: (64, 32, [6, 12, 48, 32])}


def _densenet(num_layers, **kwargs):
    kwargs.pop("pretrained", None)
    num_init_features, growth_rate, block_config = densenet_spec[num_layers]
    return DenseNet(num_init_features, growth_rate, block_config, **kwargs)


def densenet121(**kw):
    return _densenet(121, **kw)


def densenet161(**kw):
    return _densenet(161, **kw)


def densenet169(**kw):
    return _densenet(169, **kw)


def densenet201(**kw):
    return _densenet(201, **kw)


# ---------------------------------------------------------------------------
# MobileNet v1/v2 (ref: model_zoo/vision/mobilenet.py)
# ---------------------------------------------------------------------------

def _add_conv(out, channels=1, kernel=1, stride=1, pad=0, num_group=1,
              active=True, relu6=False):
    out.add(nn.Conv2D(channels, kernel, stride, pad, groups=num_group,
                      use_bias=False))
    out.add(nn.BatchNorm(scale=True))
    if active:
        out.add(_RELU6() if relu6 else nn.Activation("relu"))


class _RELU6(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.clip(x, 0, 6)


def _add_conv_dw(out, dw_channels, channels, stride, relu6=False):
    _add_conv(out, dw_channels, kernel=3, stride=stride, pad=1,
              num_group=dw_channels, relu6=relu6)
    _add_conv(out, channels, relu6=relu6)


class LinearBottleneck(HybridBlock):
    def __init__(self, in_channels, channels, t, stride, **kwargs):
        super().__init__(**kwargs)
        self.use_shortcut = stride == 1 and in_channels == channels
        with self.name_scope():
            self.out = nn.HybridSequential()
            _add_conv(self.out, in_channels * t, relu6=True)
            _add_conv(self.out, in_channels * t, kernel=3, stride=stride,
                      pad=1, num_group=in_channels * t, relu6=True)
            _add_conv(self.out, channels, active=False, relu6=True)

    def hybrid_forward(self, F, x):
        out = self.out(x)
        if self.use_shortcut:
            out = out + x
        return out


class MobileNet(HybridBlock):
    def __init__(self, multiplier=1.0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            _add_conv(self.features, int(32 * multiplier), kernel=3, stride=2,
                      pad=1)
            dw_channels = [int(x * multiplier) for x in
                           [32, 64] + [128] * 2 + [256] * 2 + [512] * 6
                           + [1024]]
            channels = [int(x * multiplier) for x in
                        [64] + [128] * 2 + [256] * 2 + [512] * 6
                        + [1024] * 2]
            strides = [1, 2] * 3 + [1] * 5 + [2, 1]
            for dwc, c, s in zip(dw_channels, channels, strides):
                _add_conv_dw(self.features, dwc, c, s)
            self.features.add(nn.GlobalAvgPool2D())
            self.features.add(nn.Flatten())
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        x = self.features(x)
        x = self.output(x)
        return x


class MobileNetV2(HybridBlock):
    def __init__(self, multiplier=1.0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="features_")
            with self.features.name_scope():
                _add_conv(self.features, int(32 * multiplier), kernel=3,
                          stride=2, pad=1, relu6=True)
                in_channels_group = [int(x * multiplier) for x in
                                     [32] + [16] + [24] * 2 + [32] * 3
                                     + [64] * 4 + [96] * 3 + [160] * 3]
                channels_group = [int(x * multiplier) for x in
                                  [16] + [24] * 2 + [32] * 3 + [64] * 4
                                  + [96] * 3 + [160] * 3 + [320]]
                ts = [1] + [6] * 16
                strides = [1, 2] * 2 + [1, 1, 2] + [1] * 6 + [2] + [1] * 3
                for in_c, c, t, s in zip(in_channels_group, channels_group,
                                         ts, strides):
                    self.features.add(LinearBottleneck(
                        in_channels=in_c, channels=c, t=t, stride=s))
                last_channels = int(1280 * multiplier) \
                    if multiplier > 1.0 else 1280
                _add_conv(self.features, last_channels, relu6=True)
                self.features.add(nn.GlobalAvgPool2D())
            self.output = nn.HybridSequential(prefix="output_")
            with self.output.name_scope():
                self.output.add(nn.Conv2D(classes, 1, use_bias=False,
                                          prefix="pred_"),
                                nn.Flatten())

    def hybrid_forward(self, F, x):
        x = self.features(x)
        x = self.output(x)
        return x


def get_mobilenet(multiplier, pretrained=False, **kwargs):
    net = MobileNet(multiplier, **kwargs)
    if pretrained:
        raise MXNetError("pretrained weights unavailable (no egress)")
    return net


def mobilenet1_0(**kw):
    return get_mobilenet(1.0, **kw)


def mobilenet0_75(**kw):
    return get_mobilenet(0.75, **kw)


def mobilenet0_5(**kw):
    return get_mobilenet(0.5, **kw)


def mobilenet0_25(**kw):
    return get_mobilenet(0.25, **kw)


def mobilenet_v2_1_0(**kw):
    if kw.pop("pretrained", False):
        raise MXNetError("pretrained weights unavailable (no egress)")
    return MobileNetV2(1.0, **kw)


def mobilenet_v2_0_75(**kw):
    if kw.pop("pretrained", False):
        raise MXNetError("pretrained weights unavailable (no egress)")
    return MobileNetV2(0.75, **kw)


def mobilenet_v2_0_5(**kw):
    if kw.pop("pretrained", False):
        raise MXNetError("pretrained weights unavailable (no egress)")
    return MobileNetV2(0.5, **kw)


def mobilenet_v2_0_25(**kw):
    if kw.pop("pretrained", False):
        raise MXNetError("pretrained weights unavailable (no egress)")
    return MobileNetV2(0.25, **kw)


# ---------------------------------------------------------------------------
# Inception V3 (ref: gluon/model_zoo/vision/inception.py — Inception3 /
# inception_v3; Szegedy et al. 2015, 299x299 input)
# ---------------------------------------------------------------------------

def _inc_conv(channels, kernel, stride=1, pad=0):
    out = nn.HybridSequential(prefix="")
    out.add(nn.Conv2D(channels, kernel, strides=stride, padding=pad,
                      use_bias=False),
            nn.BatchNorm(epsilon=0.001),
            nn.Activation("relu"))
    return out


def _inc_branch(use_pool, *conv_settings):
    out = nn.HybridSequential(prefix="")
    if use_pool == "avg":
        out.add(nn.AvgPool2D(pool_size=3, strides=1, padding=1))
    elif use_pool == "max":
        out.add(nn.MaxPool2D(pool_size=3, strides=2))
    for channels, kernel, stride, pad in conv_settings:
        out.add(_inc_conv(channels, kernel, stride, pad))
    return out


def _inc_A(pool_features):
    from ..contrib.nn import HybridConcurrent
    out = HybridConcurrent(axis=1, prefix="")
    out.add(_inc_branch(None, (64, 1, 1, 0)),
            _inc_branch(None, (48, 1, 1, 0), (64, 5, 1, 2)),
            _inc_branch(None, (64, 1, 1, 0), (96, 3, 1, 1),
                        (96, 3, 1, 1)),
            _inc_branch("avg", (pool_features, 1, 1, 0)))
    return out


def _inc_B():
    from ..contrib.nn import HybridConcurrent
    out = HybridConcurrent(axis=1, prefix="")
    out.add(_inc_branch(None, (384, 3, 2, 0)),
            _inc_branch(None, (64, 1, 1, 0), (96, 3, 1, 1),
                        (96, 3, 2, 0)),
            _inc_branch("max"))
    return out


def _inc_C(channels_7x7):
    from ..contrib.nn import HybridConcurrent
    c = channels_7x7
    out = HybridConcurrent(axis=1, prefix="")
    out.add(_inc_branch(None, (192, 1, 1, 0)),
            _inc_branch(None, (c, 1, 1, 0), (c, (1, 7), 1, (0, 3)),
                        (192, (7, 1), 1, (3, 0))),
            _inc_branch(None, (c, 1, 1, 0), (c, (7, 1), 1, (3, 0)),
                        (c, (1, 7), 1, (0, 3)), (c, (7, 1), 1, (3, 0)),
                        (192, (1, 7), 1, (0, 3))),
            _inc_branch("avg", (192, 1, 1, 0)))
    return out


def _inc_D():
    from ..contrib.nn import HybridConcurrent
    out = HybridConcurrent(axis=1, prefix="")
    out.add(_inc_branch(None, (192, 1, 1, 0), (320, 3, 2, 0)),
            _inc_branch(None, (192, 1, 1, 0), (192, (1, 7), 1, (0, 3)),
                        (192, (7, 1), 1, (3, 0)), (192, 3, 2, 0)),
            _inc_branch("max"))
    return out


class _IncESplit(HybridBlock):
    """The E-block's forked 1x3/3x1 pair, concatenated."""

    def __init__(self, pre_settings, **kwargs):
        super().__init__(**kwargs)
        self.pre = nn.HybridSequential(prefix="")
        for channels, kernel, stride, pad in pre_settings:
            self.pre.add(_inc_conv(channels, kernel, stride, pad))
        self.a = _inc_conv(384, (1, 3), 1, (0, 1))
        self.b = _inc_conv(384, (3, 1), 1, (1, 0))

    def hybrid_forward(self, F, x):
        h = self.pre(x)
        return F.Concat(self.a(h), self.b(h), dim=1)


def _inc_E():
    from ..contrib.nn import HybridConcurrent
    out = HybridConcurrent(axis=1, prefix="")
    out.add(_inc_branch(None, (320, 1, 1, 0)),
            _IncESplit([(384, 1, 1, 0)]),
            _IncESplit([(448, 1, 1, 0), (384, 3, 1, 1)]),
            _inc_branch("avg", (192, 1, 1, 0)))
    return out


class Inception3(HybridBlock):
    """ref: inception.py Inception3 (input 3x299x299)."""

    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(_inc_conv(32, 3, 2),
                              _inc_conv(32, 3),
                              _inc_conv(64, 3, pad=1),
                              nn.MaxPool2D(pool_size=3, strides=2),
                              _inc_conv(80, 1),
                              _inc_conv(192, 3),
                              nn.MaxPool2D(pool_size=3, strides=2),
                              _inc_A(32), _inc_A(64), _inc_A(64),
                              _inc_B(),
                              _inc_C(128), _inc_C(160), _inc_C(160),
                              _inc_C(192),
                              _inc_D(),
                              _inc_E(), _inc_E(),
                              nn.AvgPool2D(pool_size=8),
                              nn.Dropout(0.5))
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        x = self.features(x)
        x = self.output(x)
        return x


def inception_v3(pretrained=False, classes=1000, **kwargs):
    """ref: inception.py inception_v3."""
    if pretrained:
        raise MXNetError("pretrained weights unavailable (no egress)")
    return Inception3(classes=classes, **kwargs)


_models = {
    "resnet18_v1": resnet18_v1, "resnet34_v1": resnet34_v1,
    "resnet50_v1": resnet50_v1, "resnet101_v1": resnet101_v1,
    "resnet152_v1": resnet152_v1,
    "resnet18_v2": resnet18_v2, "resnet34_v2": resnet34_v2,
    "resnet50_v2": resnet50_v2, "resnet101_v2": resnet101_v2,
    "resnet152_v2": resnet152_v2,
    "vgg11": vgg11, "vgg13": vgg13, "vgg16": vgg16, "vgg19": vgg19,
    "vgg11_bn": vgg11_bn, "vgg13_bn": vgg13_bn, "vgg16_bn": vgg16_bn,
    "vgg19_bn": vgg19_bn,
    "alexnet": alexnet,
    "squeezenet1.0": squeezenet1_0, "squeezenet1.1": squeezenet1_1,
    "densenet121": densenet121, "densenet161": densenet161,
    "densenet169": densenet169, "densenet201": densenet201,
    "mobilenet1.0": mobilenet1_0, "mobilenet0.75": mobilenet0_75,
    "mobilenet0.5": mobilenet0_5, "mobilenet0.25": mobilenet0_25,
    "mobilenetv2_1.0": mobilenet_v2_1_0,
    "mobilenetv2_0.75": mobilenet_v2_0_75,
    "mobilenetv2_0.5": mobilenet_v2_0_5,
    "mobilenetv2_0.25": mobilenet_v2_0_25,
    "inceptionv3": inception_v3,
}


def get_model(name, **kwargs):
    """ref: model_zoo/__init__.py get_model."""
    name = name.lower()
    if name not in _models:
        raise ValueError(
            f"Model {name} is not supported. Available: {sorted(_models)}")
    return _models[name](**kwargs)
