"""Training-progress callbacks for notebooks (ref:
python/mxnet/notebook/callback.py — PandasLogger, LiveBokehChart/
LiveLearningCurve, args_wrapper).

Dependency-light: metric history is accumulated in plain dicts of
lists (pandas optional for PandasLogger.to_dataframe), and live charts
degrade to text summaries when no plotting backend is present.
"""
from __future__ import annotations

import time

__all__ = ["PandasLogger", "LiveLearningCurve", "LiveTimeSeries",
           "args_wrapper"]


class _MetricHistory:
    def __init__(self):
        self.rows = []  # list of dicts

    def append(self, metrics):
        self.rows.append(dict(metrics))

    def series(self, key):
        return [r[key] for r in self.rows if key in r]


class PandasLogger:
    """Accumulate train/eval metrics per batch/epoch
    (ref: notebook/callback.py PandasLogger). History is kept as plain
    dict rows; .train_df/.eval_df return pandas frames when pandas is
    importable, else the raw row lists."""

    def __init__(self, batch_size, frequent=50):
        self.batch_size = batch_size
        self.frequent = frequent
        self._train = _MetricHistory()
        self._eval = _MetricHistory()
        self._epoch = _MetricHistory()
        self.last_time = time.time()

    def _to_frame(self, hist):
        try:
            import pandas as pd
            return pd.DataFrame(hist.rows)
        except ImportError:
            return hist.rows

    @property
    def train_df(self):
        return self._to_frame(self._train)

    @property
    def eval_df(self):
        return self._to_frame(self._eval)

    @property
    def epoch_df(self):
        return self._to_frame(self._epoch)

    def train_cb(self, param):
        if param.nbatch % self.frequent == 0:
            self._process(param, self._train)

    def eval_cb(self, param):
        self._process(param, self._eval)

    def epoch_cb(self, epoch, *_args):
        now = time.time()
        self._epoch.append({"epoch": epoch,
                            "elapsed": now - self.last_time})
        self.last_time = now

    def _process(self, param, hist):
        row = {"epoch": getattr(param, "epoch", 0),
               "nbatch": getattr(param, "nbatch", 0)}
        if param.eval_metric is not None:
            names, vals = param.eval_metric.get()
            if not isinstance(names, list):
                names, vals = [names], [vals]
            row.update(dict(zip(names, vals)))
        row["elapsed"] = time.time() - self.last_time
        hist.append(row)

    def append_metrics(self, metrics, which="train"):
        {"train": self._train, "eval": self._eval,
         "epoch": self._epoch}[which].append(metrics)


class LiveLearningCurve:
    """Live train/eval metric curve (ref: notebook/callback.py
    LiveLearningCurve). Renders with matplotlib when importable,
    otherwise prints a compact text summary on each update."""

    def __init__(self, metric_name="accuracy", frequent=50):
        self.metric_name = metric_name
        self.frequent = frequent
        self._train_x, self._train_y = [], []
        self._eval_x, self._eval_y = [], []
        self._n = 0

    def train_cb(self, param):
        self._n += 1
        if self._n % self.frequent == 0 and param.eval_metric is not None:
            _, vals = param.eval_metric.get()
            val = vals[0] if isinstance(vals, (list, tuple)) else vals
            self._train_x.append(self._n)
            self._train_y.append(float(val))
            self._update()

    def eval_cb(self, param):
        if param.eval_metric is not None:
            name, val = param.eval_metric.get()
            if isinstance(val, (list, tuple)):
                val = val[0]
            self._eval_x.append(self._n)
            self._eval_y.append(float(val))
            self._update()

    def _update(self):
        try:
            import matplotlib.pyplot as plt
            plt.clf()
            plt.plot(self._train_x, self._train_y, label="train")
            if self._eval_x:
                plt.plot(self._eval_x, self._eval_y, label="eval")
            plt.xlabel("batch")
            plt.ylabel(self.metric_name)
            plt.legend()
            plt.pause(0.001)
        except Exception:
            tail = self._train_y[-1] if self._train_y else None
            etail = self._eval_y[-1] if self._eval_y else None
            print(f"[LiveLearningCurve] batch {self._n}: "
                  f"train {self.metric_name}={tail} "
                  f"eval {self.metric_name}={etail}")


class LiveTimeSeries(LiveLearningCurve):
    """Single time-series variant (ref: notebook/callback.py
    LiveTimeSeries)."""

    def append(self, value):
        self._n += 1
        self._train_x.append(self._n)
        self._train_y.append(float(value))
        self._update()


def args_wrapper(*args):
    """Generate callbacks for Module.fit from logger/chart objects
    (ref: notebook/callback.py:392). Returns a dict of fit kwargs."""
    out = {"batch_end_callback": [], "eval_end_callback": [],
           "epoch_end_callback": []}
    for a in args:
        if hasattr(a, "train_cb"):
            out["batch_end_callback"].append(a.train_cb)
        if hasattr(a, "eval_cb"):
            out["eval_end_callback"].append(a.eval_cb)
        if hasattr(a, "epoch_cb"):
            out["epoch_end_callback"].append(a.epoch_cb)
    return out
