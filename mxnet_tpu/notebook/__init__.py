"""Notebook utilities (ref: python/mxnet/notebook/ — live training-curve
plotting callbacks for Jupyter)."""
from . import callback  # noqa: F401
