"""Span exporters: JSON-lines stream (MXTRACE_EXPORT) + Chrome trace.

Two on-disk forms, one logical schema (the ``Span.to_dict`` fields):

- **JSON-lines** — one span object per line, append-only, written as
  spans finish when ``MXTRACE_EXPORT`` names a path. Writes are
  OS-buffered and flushed every ``_FLUSH_EVERY`` lines / 0.5 s (spans
  can finish under scheduler locks — a per-line flush would put disk
  latency inside the engine's lock hold); every flight-recorder dump
  and ``reset_sink``/process exit flushes the rest, so the spans
  preceding a failure reach disk with the dump. Concatenates across
  runs; what ``tools/mxprof.py trace`` reads natively.
- **Chrome trace** — :func:`write_chrome` renders spans as a
  ``traceEvents`` document (``ph:"X"`` duration events, one track per
  thread, span identity in ``args``) for chrome://tracing / Perfetto.
  Load one back with :func:`load_spans`, which accepts all three
  on-disk shapes (JSONL, Chrome, flight-recorder dump).

Export must never take down the traffic it observes: sink errors are
swallowed, and the sink re-resolves its path when the config
generation moves (tests flip MXTRACE_EXPORT with set_flag).
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

from ..san.runtime import make_lock
from .spans import _cfg

__all__ = ["sink_write", "flush_sink", "reset_sink", "write_chrome",
           "load_spans"]

_SINK_LOCK = make_lock("trace.export.sink")
_SINK = {"gen": -1, "path": "", "fh": None, "pending": 0, "last": 0.0}
# flush cadence: spans can be written from under scheduler locks
# (serve2 _resolve), so a per-line flush would put disk latency inside
# the engine's lock hold. The OS buffer takes the line immediately;
# fsync-grade durability is the flight recorder's job, not the sink's.
_FLUSH_EVERY = 64
_FLUSH_INTERVAL_S = 0.5


def _resolve_sink():
    """(Re)open the MXTRACE_EXPORT file handle when the flag moved."""
    from .. import config
    gen = config.generation()
    if _SINK["gen"] == gen:
        return _SINK["fh"]
    path = str(config.get("MXTRACE_EXPORT") or "")
    if path != _SINK["path"]:
        if _SINK["fh"] is not None:
            try:
                _SINK["fh"].close()
            except OSError:
                pass
            _SINK["fh"] = None
        if path:
            try:
                _SINK["fh"] = open(path, "a")
            except OSError:
                _SINK["fh"] = None
        _SINK["path"] = path
    _SINK["gen"] = gen
    return _SINK["fh"]


def sink_write_span(span) -> None:
    """Hot-path form: pays the dict+json cost only when a sink is
    actually configured."""
    try:
        if _SINK["fh"] is None and \
                _SINK["gen"] == _cfg().generation():
            return
    except Exception:  # noqa: BLE001
        return
    sink_write(span.to_dict() if not isinstance(span, dict) else span)


def sink_write(span_dict: Dict[str, object]) -> None:
    """Append one span line to the MXTRACE_EXPORT sink (no-op without
    one). Never raises — telemetry must not take down serving."""
    try:
        # lock-free fast path for the common no-sink case: dict reads
        # are atomic, and a stale miss only delays the first write one
        # config-generation check
        from .. import config
        if _SINK["fh"] is None and _SINK["gen"] == config.generation():
            return
        with _SINK_LOCK:
            fh = _resolve_sink()
            if fh is None:
                return
            fh.write(json.dumps(span_dict) + "\n")
            _SINK["pending"] += 1
            now = time.monotonic()
            if _SINK["pending"] >= _FLUSH_EVERY \
                    or now - _SINK["last"] >= _FLUSH_INTERVAL_S:
                fh.flush()
                _SINK["pending"] = 0
                _SINK["last"] = now
    except (OSError, ValueError, TypeError):
        pass


def flush_sink() -> None:
    """Force pending buffered lines to disk (flight-recorder dumps
    call this so the export file is consistent with the dump)."""
    try:
        with _SINK_LOCK:
            if _SINK["fh"] is not None and _SINK["pending"]:
                _SINK["fh"].flush()
                _SINK["pending"] = 0
                _SINK["last"] = time.monotonic()
    except (OSError, ValueError):
        pass


def reset_sink() -> None:
    """Flush + close the sink so the next write re-resolves (tests /
    end of run — pending buffered lines land here)."""
    with _SINK_LOCK:
        if _SINK["fh"] is not None:
            try:
                _SINK["fh"].close()  # close() flushes pending lines
            except OSError:
                pass
        _SINK.update(gen=-1, path="", fh=None, pending=0, last=0.0)


def to_chrome_events(spans: List[dict]) -> List[dict]:
    """Span dicts -> chrome-trace ``ph:"X"`` duration events (identity
    rides in args so a chrome dump round-trips through load_spans)."""
    pid = os.getpid()
    events = []
    for s in spans:
        if s.get("dur_us") is None:
            continue
        events.append({
            "name": s["name"], "ph": "X", "cat": s["subsystem"],
            "pid": pid, "tid": s.get("thread", 0),
            "ts": s["ts_us"], "dur": s["dur_us"],
            "args": {"trace_id": s["trace_id"],
                     "span_id": s["span_id"],
                     "parent_id": s.get("parent_id"),
                     "status": s.get("status", "ok"),
                     **(s.get("attrs") or {})},
        })
    return events


def write_chrome(path: str, spans: Optional[List[dict]] = None) -> str:
    """Write a chrome-trace JSON document of ``spans`` (default: the
    drained thread buffers + the flight-recorder rings)."""
    if spans is None:
        from . import recorder as _recorder
        from . import spans as _spans
        spans = _spans.drain() + _recorder.get_recorder().spans()
        seen = set()
        uniq = []
        for s in spans:
            if s["span_id"] in seen:
                continue
            seen.add(s["span_id"])
            uniq.append(s)
        spans = sorted(uniq, key=lambda d: d["ts_us"])
    doc = {"traceEvents": to_chrome_events(spans),
           "displayTimeUnit": "ms"}
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


def _span_from_chrome(e: dict) -> Optional[dict]:
    if e.get("ph") != "X" or "dur" not in e:
        return None
    args = dict(e.get("args") or {})
    trace_id = args.pop("trace_id", None)
    span_id = args.pop("span_id", None)
    if not trace_id or not span_id:
        return None
    return {"trace_id": trace_id, "span_id": span_id,
            "parent_id": args.pop("parent_id", None),
            "name": e.get("name", "?"),
            "subsystem": e.get("cat", "app"),
            "ts_us": e.get("ts", 0.0), "dur_us": e.get("dur", 0.0),
            "thread": e.get("tid", 0),
            "status": args.pop("status", "ok"), "attrs": args}


def load_spans(path: str) -> List[dict]:
    """Read spans back from any supported file shape: span JSON-lines
    (MXTRACE_EXPORT), a chrome-trace document (write_chrome), or a
    flight-recorder dump ({"spans": {subsystem: [...]}})."""
    with open(path) as f:
        text = f.read()
    text = text.strip()
    if not text:
        return []
    spans: List[dict] = []
    try:
        doc = json.loads(text)
    except ValueError:
        doc = None
    if isinstance(doc, dict):
        if "traceEvents" in doc:
            for e in doc["traceEvents"]:
                s = _span_from_chrome(e)
                if s is not None:
                    spans.append(s)
            return sorted(spans, key=lambda d: d["ts_us"])
        if isinstance(doc.get("spans"), dict):
            for ring in doc["spans"].values():
                spans.extend(s for s in ring
                             if isinstance(s, dict)
                             and "span_id" in s)
            return sorted(spans, key=lambda d: d["ts_us"])
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "span_id" in rec \
                and "trace_id" in rec:
            spans.append(rec)
    return sorted(spans, key=lambda d: d["ts_us"])
