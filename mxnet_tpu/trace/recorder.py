"""Crash flight recorder: the last N spans per subsystem, dumped on
failure.

The operational problem (ISSUE 13): when a drill fails — a breaker
trips, a scheduler thread dies, an elastic group hard-fails, mxguard
quarantines a worker, the watchdog declares a stall, or the cluster
manager SIGTERMs the process — the logs say WHAT died but not what the
last five seconds looked like. The recorder keeps one bounded ring of
finished spans per subsystem (``MXTRACE_RECORDER_SPANS`` each) plus
explicit event notes (breaker trips, crash sites), and
:func:`crash_dump` writes the whole picture — rings, events, a metrics
snapshot, the recent recompile records — to one timestamped JSON file
in ``MXTRACE_DUMP_DIR`` that ``tools/mxprof.py trace`` and
``tools/diagnose.py`` read back.

Dump triggers wired across the stack (each calls :func:`crash_dump`):

- :class:`~mxnet_tpu.resil.policy.CircuitBreaker` trip
- :meth:`~mxnet_tpu.serve2.scheduler.DecodeEngine` scheduler crash
  (EngineCrashedError)
- :class:`~mxnet_tpu.elastic.membership.GroupFailed`
- :class:`~mxnet_tpu.guard.voting.GuardQuarantined`
- :class:`~mxnet_tpu.resil.watchdog.Watchdog` stall verdict
- SIGTERM (handler installed lazily from the main thread, chaining any
  existing handler)

Dumps are rate-limited per reason (default 5 s) so a breaker-trip
storm produces one readable file, not a thousand; ``force=True``
bypasses for tests/drills.
"""
from __future__ import annotations

import itertools
import json
import os
import signal
import sys
import tempfile
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from ..san.runtime import make_lock
from .spans import _cfg

__all__ = ["FlightRecorder", "get_recorder", "crash_dump",
           "install_signal_handler"]

_DUMP_SEQ = itertools.count(1)
_MIN_DUMP_INTERVAL_S = 5.0


def _dump_dir() -> str:
    from .. import config
    d = str(config.get("MXTRACE_DUMP_DIR") or "")
    if not d:
        d = os.path.join(tempfile.gettempdir(), "mxtrace")
    os.makedirs(d, exist_ok=True)
    return d


def _pod_rank() -> int:
    """This process's pod rank for the dump filename: hosts sharing
    one MXTRACE_DUMP_DIR (the coordinated-capture layout) must not
    collide on same-second dumps, and the post-mortem reader wants
    files NAMED by rank. MXPOD_RANK wins, launcher env falls back,
    single process is rank 0."""
    try:
        from .. import config
        r = int(config.get("MXPOD_RANK"))
        if r >= 0:
            return r
        from ..base import worker_rank
        return int(worker_rank(0))
    except Exception:  # noqa: BLE001 — naming must never block a dump
        return 0


class FlightRecorder:
    """See module docstring. One process-wide instance
    (:func:`get_recorder`); every method is safe from any thread."""

    def __init__(self):
        self._lock = make_lock("trace.recorder")
        self._rings: Dict[str, deque] = {}
        self._events: deque = deque(maxlen=128)
        self._last_dump: Optional[dict] = None
        self._last_dump_ts: Dict[str, float] = {}
        self._n_dumps = 0
        self._cap_cache = (-1, 256)  # (config generation, cap)

    def _cap(self) -> int:
        config = _cfg()
        gen = config.generation()
        cached = self._cap_cache
        if cached[0] == gen:
            return cached[1]
        cap = max(8, int(config.get("MXTRACE_RECORDER_SPANS")))
        self._cap_cache = (gen, cap)
        return cap

    def add(self, span) -> None:
        """Append one finished span (a Span object or its dict form —
        rings hold either; readers normalize via :meth:`spans`)."""
        sub = getattr(span, "subsystem", None) \
            or span.get("subsystem", "app")
        with self._lock:
            ring = self._rings.get(sub)
            if ring is None or ring.maxlen != self._cap():
                ring = deque(ring or (), maxlen=self._cap())
                self._rings[sub] = ring
            ring.append(span)

    def note(self, subsystem: str, name: str, **attrs) -> None:
        """Record one explicit event (a breaker trip, a crash site) —
        shows up in the dump's ``events`` timeline next to the spans."""
        with self._lock:
            self._events.append({
                "ts": time.time(), "subsystem": subsystem,
                "name": name, "attrs": attrs})

    @staticmethod
    def _as_dict(span) -> dict:
        return span if isinstance(span, dict) else span.to_dict()

    def spans(self, subsystem: Optional[str] = None) -> List[dict]:
        with self._lock:
            if subsystem is not None:
                out = [self._as_dict(s)
                       for s in self._rings.get(subsystem, ())]
                out.sort(key=lambda d: d.get("ts_us", 0))
                return out
            out = []
            for ring in self._rings.values():
                out.extend(self._as_dict(s) for s in ring)
        out.sort(key=lambda d: d.get("ts_us", 0))
        return out

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def describe(self) -> dict:
        with self._lock:
            return {
                "subsystems": {s: len(r)
                               for s, r in sorted(self._rings.items())},
                "events": len(self._events),
                "dumps": self._n_dumps,
                "last_dump": dict(self._last_dump)
                if self._last_dump else None,
            }

    @property
    def last_dump(self) -> Optional[dict]:
        with self._lock:
            return dict(self._last_dump) if self._last_dump else None

    def reset(self) -> None:
        with self._lock:
            self._rings.clear()
            self._events.clear()
            self._last_dump = None
            self._last_dump_ts.clear()

    def dump(self, reason: str, site: Optional[str] = None,
             extra: Optional[dict] = None,
             force: bool = False) -> Optional[str]:
        """Write the dump file; returns its path (None when the
        per-reason rate limit suppressed it). Never raises."""
        try:
            now = time.monotonic()
            with self._lock:
                last = self._last_dump_ts.get(reason)
                if not force and last is not None \
                        and now - last < _MIN_DUMP_INTERVAL_S:
                    return None
                self._last_dump_ts[reason] = now
                rings = {s: [self._as_dict(x) for x in r]
                         for s, r in sorted(self._rings.items())}
                events = list(self._events)
            from ..telemetry import metrics as _metrics
            from ..telemetry import recompile as _recompile
            from . import export as _export
            # land any buffered MXTRACE_EXPORT lines NOW: the spans
            # leading up to a failure are exactly the ones a batched
            # sink would otherwise lose if the process dies next
            _export.flush_sink()
            rank = _pod_rank()
            doc = {
                "reason": reason,
                "site": site,
                "ts": time.time(),
                "iso": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                     time.gmtime()),
                "pid": os.getpid(),
                "rank": rank,
                "spans": rings,
                "events": events,
                "metrics": _metrics.snapshot(),
                "recompiles": _recompile.recompile_report()[-32:],
            }
            if extra:
                doc["extra"] = extra
            tag = "".join(c if c.isalnum() or c in "-_" else "_"
                          for c in reason)[:48]
            fname = (f"mxtrace-flight-{tag}-"
                     f"{time.strftime('%Y%m%d-%H%M%S', time.gmtime())}"
                     f"-r{rank}-p{os.getpid()}-{next(_DUMP_SEQ)}.json")
            path = os.path.join(_dump_dir(), fname)
            with open(path, "w") as f:
                json.dump(doc, f)
            with self._lock:
                self._n_dumps += 1
                self._last_dump = {"reason": reason, "site": site,
                                   "path": path, "ts": doc["ts"]}
            return path
        except Exception:  # noqa: BLE001 — the recorder must never
            # take down the job it is documenting
            return None


_RECORDER = FlightRecorder()


def get_recorder() -> FlightRecorder:
    return _RECORDER


def crash_dump(reason: str, site: Optional[str] = None,
               extra: Optional[dict] = None,
               force: bool = False) -> Optional[str]:
    """The one failure hook: note the event (so the dump's final
    timeline names the failing site) and write the dump. Gated on
    MXTRACE; rate-limited per reason; never raises."""
    try:
        from . import spans as _spans
        if not _spans.enabled():
            return None
        _RECORDER.note("crash", reason, site=site)
        return _RECORDER.dump(reason, site=site, extra=extra,
                              force=force)
    except Exception:  # noqa: BLE001
        return None


_SIGTERM_INSTALLED = [False]


def install_signal_handler() -> bool:
    """Install the SIGTERM dump hook (main thread only; chains any
    existing handler, then restores + re-raises the default so the
    process still terminates). Returns True when installed."""
    if _SIGTERM_INSTALLED[0]:
        return True
    if threading.current_thread() is not threading.main_thread():
        return False
    try:
        prev = signal.getsignal(signal.SIGTERM)

        def _on_sigterm(signum, frame):
            crash_dump("sigterm", force=True)
            if callable(prev) and prev not in (signal.SIG_IGN,
                                               signal.SIG_DFL):
                prev(signum, frame)
                return
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGTERM)

        signal.signal(signal.SIGTERM, _on_sigterm)
        _SIGTERM_INSTALLED[0] = True
        return True
    except (ValueError, OSError):  # non-main thread / exotic platform
        return False
