"""mxtrace: correlated cross-subsystem tracing + crash flight recorder.

The measurement plane ISSUE 13 adds over PR 2's telemetry: one span
model (:mod:`~mxnet_tpu.trace.span`) threaded through BOTH hot paths —

- **serving**: endpoint request → router pick (+breaker state) →
  scheduler admit/tick → prefix-cache lookup → prefill / prefill_ext /
  decode / verify dispatch → reply. Every request decomposes into
  queue / admission / prefill / decode phases
  (``mxtrace_phase_*_seconds`` histograms, p50/p99 in the metrics
  registry) and the HTTP endpoint echoes ``X-MXTrace-Id``;
- **training**: Trainer step → StepFunction dispatch → bucket
  exchange → guard vote/re-execute → elastic heartbeat/rebuild, keyed
  by ``(generation, step)``.

Spans export as JSON-lines (``MXTRACE_EXPORT``) and Chrome-trace JSON
(:func:`~mxnet_tpu.trace.export.write_chrome`); sampling rides
``MXTRACE_SAMPLE``; the bounded flight recorder
(:mod:`~mxnet_tpu.trace.recorder`) dumps the last-N-spans picture on
breaker trips, engine crashes, GroupFailed, guard quarantine, watchdog
stall verdicts and SIGTERM. ``tools/mxprof.py trace`` summarizes a
trace file (critical path, phase self-time, cross-subsystem gaps,
orphan/coverage findings in the shared mxlint schema).

See docs/observability.md for the span taxonomy and the
flight-recorder runbook.
"""
from __future__ import annotations

from . import export  # noqa: F401
from . import recorder  # noqa: F401
from . import spans  # noqa: F401
from .export import load_spans, write_chrome  # noqa: F401
from .recorder import (crash_dump, get_recorder,  # noqa: F401
                       install_signal_handler)
from .spans import (Span, SpanContext, current_context,  # noqa: F401
                    drain, emit, emit_root, enabled, reset, span,
                    under)

__all__ = ["Span", "SpanContext", "span", "emit", "emit_root", "under",
           "enabled", "current_context", "drain", "reset",
           "load_spans", "write_chrome", "crash_dump", "get_recorder",
           "install_signal_handler"]
