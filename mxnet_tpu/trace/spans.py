"""Correlated span model: trace_id/span_id/parent over monotonic clocks.

One process-wide tracer for BOTH hot paths (docs/observability.md):

- a **span** is one timed operation (an endpoint request, a router
  pick, a scheduler admission, a fused-step dispatch, a bucket
  exchange, a guard vote) with a ``trace_id`` shared by everything the
  same logical unit of work touched, a unique ``span_id``, and a
  ``parent_id`` linking it into the tree ``tools/mxprof.py trace``
  reconstructs;
- propagation is **contextvar-based** on one thread (nested ``span()``
  blocks parent automatically) and **explicit** across threads: the
  serving scheduler stores :func:`current_context` on each submitted
  sequence and emits that sequence's phase spans with the stored
  parent (``emit`` / ``under``), so a request's spans land in ONE
  trace even though submit and decode run on different threads;
- clocks are ``time.perf_counter_ns()`` (monotonic — durations and
  orderings are exact within the process); one wall-clock anchor pair
  taken at import converts to absolute time for exports;
- completed spans land in **bounded per-thread buffers** (drained by
  exporters/tests), the flight-recorder rings
  (:mod:`~mxnet_tpu.trace.recorder`), and — when ``MXTRACE_EXPORT``
  names a file — one JSON line per span.

Cost model: tracing is ON by default (``MXTRACE``) because a span is
two clock reads, one small dict and a deque append — the <2% overhead
contract ``bench.py --trace-overhead`` enforces. ``MXTRACE_SAMPLE``
drops whole traces (the decision is made once at the root and
inherited), so high-QPS serving can run at 0.1 sampling and still pay
~nothing on the untraced requests. Nothing here touches jit cache
keys: tracing can never cause a recompile.
"""
from __future__ import annotations

import contextlib
import contextvars
import itertools
import os
import random
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from ..san.runtime import make_lock

__all__ = ["Span", "SpanContext", "enabled", "span", "emit",
           "emit_root", "under", "current_context", "drain", "reset",
           "wall_of_ns"]

# wall-clock anchor: perf_counter_ns <-> epoch seconds, taken once so
# every exported span converts consistently
_ANCHOR_NS = time.perf_counter_ns()
_ANCHOR_WALL = time.time()

_PID = os.getpid()
_IDS = itertools.count(1)
# sampling decisions only — per-root, and a torn read under free
# threading would just skew one sample, so no lock
_RNG = random.Random()

# (config generation, MXTRACE, MXTRACE_SAMPLE) — refreshed when a
# set_flag/unset_flag bumps the config generation; the hot-path check
# is two attribute reads and an int compare
_FLAG_CACHE = (-1, True, 1.0)
_BUF_LOCK = make_lock("trace.spans.buf")
_BUFFERS: Dict[int, deque] = {}   # thread ident -> finished-span deque
_LOCAL = threading.local()


def wall_of_ns(t_ns: int) -> float:
    """Epoch seconds for a perf_counter_ns stamp (export rendering)."""
    return _ANCHOR_WALL + (t_ns - _ANCHOR_NS) / 1e9


# the config module ref is cached after first use: a per-span
# `from .. import config` costs ~1.5us in importlib machinery
_CONFIG = []


def _cfg():
    if not _CONFIG:
        from .. import config
        _CONFIG.append(config)
    return _CONFIG[0]


def _flags():
    global _FLAG_CACHE
    config = _cfg()
    gen = config.generation()
    cached = _FLAG_CACHE
    if cached[0] == gen:
        return cached
    on = bool(config.get("MXTRACE"))
    sample = float(config.get("MXTRACE_SAMPLE"))
    _FLAG_CACHE = (gen, on, sample)
    return _FLAG_CACHE


def enabled() -> bool:
    return _flags()[1]


# trace ids only need process-lifetime uniqueness plus a cross-process
# discriminator (the pid) — a counter beats a locked RNG on the hot
# path; one random session prefix keeps ids distinct across restarts
# sharing an export file
_TIDS = itertools.count(1)
_SESSION = f"{random.SystemRandom().getrandbits(24):06x}"


def _new_trace_id() -> str:
    return f"{_SESSION}{_PID:x}t{next(_TIDS)}"


def _new_span_id() -> str:
    return f"{_PID:x}.{next(_IDS)}"


class SpanContext:
    """The propagated identity of an in-flight span: enough to parent
    a child from another thread. ``sampled=False`` contexts still
    propagate (children inherit the drop decision)."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: str, span_id: str, sampled: bool):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled

    def __repr__(self):
        return (f"SpanContext({self.trace_id}, {self.span_id}, "
                f"sampled={self.sampled})")


_CURRENT = contextvars.ContextVar("mxtrace_ctx", default=None)


class Span:
    """One finished-or-open span. Mutate attributes via :meth:`set`;
    the dict form (:meth:`to_dict`) is the export/recorder unit."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name",
                 "subsystem", "t0_ns", "t1_ns", "attrs", "thread",
                 "status", "sampled")

    def __init__(self, name: str, subsystem: str, trace_id: str,
                 span_id: str, parent_id: Optional[str],
                 t0_ns: Optional[int] = None, sampled: bool = True):
        self.name = name
        self.subsystem = subsystem
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.t0_ns = time.perf_counter_ns() if t0_ns is None else t0_ns
        self.t1_ns = None
        self.attrs: Dict[str, object] = {}
        self.thread = threading.get_ident()
        self.status = "ok"
        self.sampled = sampled

    def set(self, **attrs) -> "Span":
        """Attach typed attributes (JSON-serializable values)."""
        self.attrs.update(attrs)
        return self

    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id, self.sampled)

    @property
    def duration_s(self) -> Optional[float]:
        if self.t1_ns is None:
            return None
        return (self.t1_ns - self.t0_ns) / 1e9

    def to_dict(self) -> Dict[str, object]:
        # no rounding here: this runs on the hot path for every
        # finished span; exporters own presentation precision
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "subsystem": self.subsystem,
            "ts_us": self.t0_ns / 1e3,
            "dur_us": ((self.t1_ns - self.t0_ns) / 1e3
                       if self.t1_ns is not None else None),
            "wall": wall_of_ns(self.t0_ns),
            "thread": self.thread,
            "status": self.status,
            "attrs": self.attrs,
        }

    def __repr__(self):
        dur = self.duration_s
        return (f"<Span {self.name} [{self.subsystem}] "
                f"{self.trace_id}/{self.span_id}"
                + (f" {dur * 1e3:.3f}ms" if dur is not None else "")
                + (f" {self.status}" if self.status != "ok" else "")
                + ">")


class _NullSpan:
    """Shared no-op span: returned when tracing is off (or a trace is
    unsampled) so call sites never branch on enablement."""

    __slots__ = ()
    trace_id = ""
    span_id = ""
    parent_id = None
    sampled = False
    status = "ok"

    def set(self, **attrs):
        return self

    def context(self):
        return None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


def _buffer() -> deque:
    buf = getattr(_LOCAL, "buf", None)
    if buf is None:
        config = _cfg()
        cap = max(16, int(config.get("MXTRACE_BUFFER_SPANS")))
        buf = deque(maxlen=cap)
        _LOCAL.buf = buf
        ident = threading.get_ident()
        with _BUF_LOCK:
            _BUFFERS[ident] = buf
            if len(_BUFFERS) > 128:
                # sweep buffers of dead threads (HTTP handler threads
                # come and go; their spans already reached the
                # recorder/export sink)
                live = {t.ident for t in threading.enumerate()}
                for dead in [i for i in _BUFFERS if i not in live]:
                    _BUFFERS.pop(dead, None)
    return buf


# resolved once at first record: per-span `from . import ...` lookups
# are measurable on the hot path
_SINKS = []


def _record(sp: Span):
    # buffers and the recorder hold Span OBJECTS (finished, never
    # mutated again); dict conversion is deferred to drain()/dump()
    # readers, off the hot path. Only an active MXTRACE_EXPORT sink
    # pays the dict+json cost per span.
    _buffer().append(sp)
    if not _SINKS:
        from . import export as _export
        from . import recorder as _recorder
        _SINKS.append((_recorder.get_recorder().add,
                       _export.sink_write_span,
                       _recorder._SIGTERM_INSTALLED,
                       _recorder.install_signal_handler))
    add, sink, sig_installed, sig_install = _SINKS[0]
    if not sig_installed[0]:
        # the documented SIGTERM dump trigger self-wires with the
        # first traced work; retried until a MAIN-thread span records
        # (signal handlers can only install there)
        sig_install()
    add(sp)
    sink(sp)


def drain() -> List[dict]:
    """Collect and clear every thread's finished-span buffer (tests,
    ad-hoc exporters). The flight-recorder rings are untouched.

    Pop-based on purpose: other threads keep APPENDING to their own
    deques without this lock (deque append/popleft are atomic), so
    iterating a live deque would raise 'mutated during iteration' —
    popleft-until-empty is safe against concurrent appends."""
    out: List[dict] = []
    with _BUF_LOCK:
        bufs = list(_BUFFERS.values())
    for buf in bufs:
        while True:
            try:
                out.append(buf.popleft().to_dict())
            except IndexError:
                break
    out.sort(key=lambda d: d["ts_us"])
    return out


def reset():
    """Clear buffers, the flight recorder, and dump rate limits
    (tests)."""
    with _BUF_LOCK:
        for buf in _BUFFERS.values():
            buf.clear()
    from . import recorder as _recorder
    _recorder.get_recorder().reset()
    from . import export as _export
    _export.reset_sink()


class _SpanCm:
    """The ``with span(...)`` context manager: opens a child of the
    ambient context (or a new sampled-or-not root), publishes itself
    as the ambient context, and records on exit — error status and
    exception type attached when the block raised."""

    __slots__ = ("span", "_token")

    def __init__(self, sp: Span):
        self.span = sp
        self._token = None

    def __enter__(self) -> Span:
        self._token = _CURRENT.set(self.span.context())
        return self.span

    def __exit__(self, exc_type, exc, tb):
        sp = self.span
        sp.t1_ns = time.perf_counter_ns()
        if exc_type is not None:
            sp.status = "error"
            sp.attrs.setdefault("error", exc_type.__name__)
            if exc is not None:
                sp.attrs.setdefault("error_msg", str(exc)[:200])
        _CURRENT.reset(self._token)
        if sp.sampled:
            _record(sp)
        return False


class _CtxOnlyCm:
    """Publish a context without recording anything: the unsampled
    branch of :func:`span` (children of a dropped trace inherit the
    drop) and the explicit-scope form :func:`under` share it."""

    __slots__ = ("_ctx", "_token")

    def __init__(self, ctx: SpanContext):
        self._ctx = ctx
        self._token = None

    def __enter__(self):
        self._token = _CURRENT.set(self._ctx)
        return _NULL

    def __exit__(self, *exc):
        _CURRENT.reset(self._token)
        return False


def span(name: str, subsystem: str = "app", **attrs):
    """``with trace.span("serve.request", "serve", model=m) as sp:`` —
    the one instrumentation primitive. Child of the ambient context;
    a new root (with the ``MXTRACE_SAMPLE`` decision) when there is
    none. Returns a no-op span when tracing is off."""
    gen, on, sample = _flags()
    if not on:
        return _NULL
    parent = _CURRENT.get()
    if parent is None:
        sampled = sample >= 1.0 or _RNG.random() < sample
        if not sampled:
            return _CtxOnlyCm(SpanContext(_new_trace_id(),
                                          _new_span_id(), False))
        sp = Span(name, subsystem, _new_trace_id(), _new_span_id(),
                  None, sampled=True)
    else:
        if not parent.sampled:
            return _CtxOnlyCm(SpanContext(parent.trace_id,
                                          _new_span_id(), False))
        sp = Span(name, subsystem, parent.trace_id, _new_span_id(),
                  parent.span_id, sampled=True)
    if attrs:
        sp.attrs.update(attrs)
    return _SpanCm(sp)


def emit(name: str, subsystem: str, t0_ns: int, t1_ns: int,
         parent: Optional[SpanContext] = None,
         attrs: Optional[dict] = None,
         status: str = "ok") -> Optional[Span]:
    """Record a RETROACTIVE span over an already-measured interval
    under an explicit parent — the cross-thread form (the scheduler's
    queue/decode phases, measured by stamps on the sequence and
    emitted when the phase closes). No parent = no span (internal
    phases never start their own traces)."""
    if parent is None or not parent.sampled or not enabled():
        return None
    sp = Span(name, subsystem, parent.trace_id, _new_span_id(),
              parent.span_id, t0_ns=t0_ns, sampled=True)
    sp.t1_ns = t1_ns
    sp.status = status
    if attrs:
        sp.attrs.update(attrs)
    _record(sp)
    return sp


def emit_root(name: str, subsystem: str, t0_ns: int, t1_ns: int,
              trace_id: str, span_id: str,
              attrs: Optional[dict] = None,
              status: str = "ok") -> Optional[Span]:
    """Record a retroactive ROOT span with EXPLICIT identity — the
    cross-process stitching hook (mxnet_tpu/obs/): every rank derives
    the same (trace_id, span_id) from control-plane state, exactly one
    designated rank emits the root, and the others parent their local
    trees under it, so `mxprof trace --dir` reassembles one tree from
    per-rank span files. Per-process ids stay counter-based; only
    deliberately-shared roots take this path."""
    if not enabled():
        return None
    sp = Span(name, subsystem, str(trace_id), str(span_id), None,
              t0_ns=t0_ns, sampled=True)
    sp.t1_ns = t1_ns
    sp.status = status
    if attrs:
        sp.attrs.update(attrs)
    _record(sp)
    return sp


def under(ctx: Optional[SpanContext]):
    """``with trace.under(seq_ctx): ...`` — run a block with an
    explicit ambient context (cross-thread propagation: nested
    ``span()`` calls parent to ``ctx``). With ``ctx=None`` the block
    runs unchanged: spans inside root their own traces, which is what
    a standalone (un-attributed) engine wants."""
    if ctx is None:
        return contextlib.nullcontext(_NULL)
    return _CtxOnlyCm(ctx)


def current_context() -> Optional[SpanContext]:
    """The ambient span context (None outside any span) — what a
    cross-thread submitter stores for later ``emit``/``under``."""
    if not enabled():
        return None
    return _CURRENT.get()
