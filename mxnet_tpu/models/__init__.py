from . import transformer  # noqa: F401
from .transformer import (  # noqa: F401
    MultiHeadAttention, TransformerEncoderLayer, TransformerLM, BERTModel,
    tensor_parallel_shardings,
)
