"""Transformer / BERT model family (flagship).

The reference era predates transformers as first-class citizens — its BERT
support lives in GluonNLP built on the kernels listed in SURVEY.md
Appendix C config 3 (Embedding, LayerNorm, GELU, FullyConnected, batch_dot,
softmax, dropout, AdamW, AMP). This module provides the model family
natively, TPU-first:

- attention runs through one switchable backend: dense local attention,
  ring attention over a 'seq' mesh axis (lax.ppermute ring), or Ulysses
  all-to-all (SURVEY.md §5.7 beyond-reference requirement);
- all shapes static, all control flow compiler-friendly;
- tensor-parallel sharding specs for the Dense weights are provided by
  `tensor_parallel_shardings` (Megatron-style column/row split, executed
  by GSPMD from pjit annotations — no hand-written collectives).
"""
from __future__ import annotations

import math
from functools import partial
from typing import Optional

import numpy as onp

from ..gluon import nn
from ..gluon.block import HybridBlock
from ..ndarray.ndarray import NDArray, invoke
from ..ops.pallas_kernels import flash_attention_available as _fa_available
from ..parallel.ring_attention import local_attention
from ..parallel.mesh import P

__all__ = ["MultiHeadAttention", "TransformerEncoderLayer", "TransformerLM",
           "BERTModel", "tensor_parallel_shardings"]


def _on_tpu() -> bool:
    import jax
    return any(d.platform == "tpu" for d in jax.devices())


class MultiHeadAttention(HybridBlock):
    """Self-attention with a pluggable context-parallel backend."""

    def __init__(self, units, num_heads, dropout=0.0, use_bias=True,
                 **kwargs):
        super().__init__(**kwargs)
        assert units % num_heads == 0
        self._units = units
        self._num_heads = num_heads
        self._head_dim = units // num_heads
        # context-parallel config (set via set_context_parallel)
        self._cp_mesh = None
        self._cp_axis = "seq"
        self._cp_strategy = "ring"
        self._cp_block_size = None
        self._causal = False
        with self.name_scope():
            self.qkv = nn.Dense(3 * units, flatten=False, use_bias=use_bias,
                                prefix="qkv_")
            self.proj = nn.Dense(units, flatten=False, use_bias=use_bias,
                                 prefix="proj_")
            self.drop = nn.Dropout(dropout)

    def set_context_parallel(self, mesh, seq_axis="seq", strategy="ring",
                             block_size=None):
        self._cp_mesh = mesh
        self._cp_axis = seq_axis
        self._cp_strategy = strategy
        self._cp_block_size = block_size
        self._cached = {}

    def hybrid_forward(self, F, x):
        # x: (B, T, C)
        B, T, C = x.shape
        qkv = self.qkv(x)  # (B, T, 3C)
        qkv = qkv.reshape((B, T, 3, self._num_heads, self._head_dim))
        qkv = qkv.transpose((2, 0, 3, 1, 4))  # (3, B, H, T, D)
        q, k, v = qkv[0], qkv[1], qkv[2]

        mesh = self._cp_mesh
        causal = self._causal
        if mesh is not None:
            from ..parallel.ring_attention import context_parallel_attention
            fn = partial(context_parallel_attention, mesh=mesh,
                         seq_axis=self._cp_axis, causal=causal,
                         strategy=self._cp_strategy,
                         block_size=getattr(self, "_cp_block_size", None))
        elif _on_tpu() and _fa_available(T, T, self._head_dim):
            # two valid backends on TPU: the Pallas flash kernel (O(T)
            # memory) and XLA dense attention. Which is faster depends
            # on T/D/dtype — measured once on the eager warm-up forward
            # (operator_tune cache), flash as the default under a trace
            from .. import operator_tune as _otune
            from ..ops.pallas_kernels import flash_attention
            _, fn = _otune.choose(
                "attention",
                [("flash", partial(flash_attention, causal=causal)),
                 ("dense", partial(local_attention, causal=causal))],
                q, k, v,
                key=(f"attention|T={T}|D={self._head_dim}"
                     f"|H={self._num_heads}|causal={causal}"
                     f"|{getattr(q, 'dtype', '?')}"))
        else:
            fn = partial(local_attention, causal=causal)
        out = invoke(fn, [q, k, v])  # (B, H, T, D)
        out = out.transpose((0, 2, 1, 3)).reshape((B, T, C))
        return self.drop(self.proj(out))


class TransformerEncoderLayer(HybridBlock):
    def __init__(self, units, num_heads, hidden_size, dropout=0.0,
                 pre_norm=True, num_experts=0, num_experts_per_tok=2,
                 **kwargs):
        super().__init__(**kwargs)
        self._pre_norm = pre_norm
        self._moe = num_experts > 0
        with self.name_scope():
            self.attn = MultiHeadAttention(units, num_heads, dropout)
            self.ln1 = nn.LayerNorm(in_channels=units)
            self.ln2 = nn.LayerNorm(in_channels=units)
            if self._moe:
                # expert-parallel FFN (SURVEY §2.4 ep axis)
                from ..parallel.moe import MoEFFN
                self.moe = MoEFFN(units, hidden_size,
                                  num_experts=num_experts,
                                  num_experts_per_tok=num_experts_per_tok)
            else:
                self.ffn1 = nn.Dense(hidden_size, flatten=False,
                                     prefix="ffn1_")
                self.ffn2 = nn.Dense(units, flatten=False, prefix="ffn2_")
            self.drop = nn.Dropout(dropout)

    def _ffn(self, F, h):
        if self._moe:
            return self.moe(h)
        return self.ffn2(F.LeakyReLU(self.ffn1(h), act_type="gelu"))

    def hybrid_forward(self, F, x):
        if self._pre_norm:
            x = x + self.attn(self.ln1(x))
            h = self.ln2(x)
            return x + self.drop(self._ffn(F, h))
        x = self.ln1(x + self.attn(x))
        return self.ln2(x + self.drop(self._ffn(F, x)))


class TransformerLM(HybridBlock):
    """Decoder-only / encoder LM over token ids.

    Covers both the BERT-base pretraining config (causal=False + MLM head)
    and a GPT-style causal LM (causal=True)."""

    def __init__(self, vocab_size, units=256, num_layers=4, num_heads=8,
                 hidden_size=1024, max_len=512, dropout=0.0, causal=False,
                 num_experts=0, num_experts_per_tok=2, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._max_len = max_len
        self._causal = causal
        with self.name_scope():
            self.embed = nn.Embedding(vocab_size, units)
            self.pos_embed = nn.Embedding(max_len, units)
            self.layers = nn.HybridSequential(prefix="layers_")
            with self.layers.name_scope():
                for _ in range(num_layers):
                    self.layers.add(TransformerEncoderLayer(
                        units, num_heads, hidden_size, dropout,
                        num_experts=num_experts,
                        num_experts_per_tok=num_experts_per_tok))
            self.ln_f = nn.LayerNorm(in_channels=units)
            self.head = nn.Dense(vocab_size, flatten=False, prefix="head_")
        for layer in self.layers:
            layer.attn._causal = causal

    def set_context_parallel(self, mesh, seq_axis="seq", strategy="ring",
                             block_size=None):
        for layer in self.layers:
            layer.attn.set_context_parallel(mesh, seq_axis, strategy,
                                            block_size)

    def hybrid_forward(self, F, tokens):
        # tokens: (B, T) int
        B, T = tokens.shape
        from .. import ndarray as nd_ns
        pos = nd_ns.arange(0, T, dtype="int32")
        x = self.embed(tokens)
        x = x + self.pos_embed(pos).expand_dims(0)
        x = self.layers(x)
        x = self.ln_f(x)
        return self.head(x)


class BERTModel(TransformerLM):
    """BERT-base-style encoder (config 3 in BASELINE.json)."""

    def __init__(self, vocab_size=30522, units=768, num_layers=12,
                 num_heads=12, hidden_size=3072, max_len=512, dropout=0.1,
                 **kwargs):
        super().__init__(vocab_size, units, num_layers, num_heads,
                         hidden_size, max_len, dropout, causal=False,
                         **kwargs)


def tensor_parallel_shardings(block, model_axis: str = "model"):
    """Megatron-style PartitionSpecs for a TransformerLM's parameters:
    qkv/ffn1 column-parallel (shard output dim), proj/ffn2 row-parallel
    (shard input dim), embeddings sharded on vocab. Feed to
    ParallelTrainer(param_shardings=...) — GSPMD inserts the all-reduces
    the reference would have hand-coded."""
    specs = {}
    for name, p in block._collect_params_with_prefix().items():
        if p.shape is None:
            spec = P()
        elif "qkv_weight" in name or "ffn1_weight" in name:
            spec = P(model_axis, None)
        elif "qkv_bias" in name or "ffn1_bias" in name:
            spec = P(model_axis)
        elif "proj_weight" in name or "ffn2_weight" in name:
            spec = P(None, model_axis)
        elif "head_weight" in name or name.endswith("embed_weight") or \
                "embedding" in name and name.endswith("weight"):
            spec = P(model_axis, None) if len(p.shape) == 2 else P()
        else:
            # leave unmatched params OUT of the dict (ParallelTrainer
            # defaults them to replicated): an explicit P() here would
            # clobber other sharding helpers' specs — e.g.
            # expert_parallel_shardings — depending on merge order
            continue
        specs[name] = spec
    return specs
