from .io import (  # noqa: F401
    DataDesc, DataBatch, DataIter, NDArrayIter, ResizeIter, PrefetchingIter,
    MXDataIter, ImageRecordIter, ImageDetRecordIter, DetRecordIter,
    MNISTIter, CSVIter, LibSVMIter,
)
