"""Data iterators.

ref: python/mxnet/io/io.py (DataIter :180, NDArrayIter :491, ResizeIter,
PrefetchingIter :617) and the C++ iterator registry
(src/io/iter_image_recordio_2.cc:880 MXNET_REGISTER_IO_ITER). The C++
threaded decode pipeline's role is filled by the native reader in
mxnet_tpu/native plus background-thread prefetch here.
"""
from __future__ import annotations

import queue
import threading
import time
from collections import namedtuple
from typing import Dict, List, Optional

import numpy as onp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray, array

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "MXDataIter", "ImageRecordIter",
           "ImageDetRecordIter", "DetRecordIter", "MNISTIter",
           "CSVIter", "LibSVMIter"]


class DataDesc(namedtuple("DataDesc", ["name", "shape", "dtype", "layout"])):
    """ref: io.py DataDesc."""

    def __new__(cls, name, shape, dtype=onp.float32, layout="NCHW"):
        return super().__new__(cls, name, tuple(shape), dtype, layout)

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")


class DataBatch:
    """ref: io.py DataBatch."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter:
    """ref: io.py:180 DataIter."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def __next__(self):
        return self.next()

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


def _init_data(data, allow_empty, default_name):
    """ref: io/utils.py _init_data."""
    if data is None:
        data = []
    if isinstance(data, (onp.ndarray, NDArray)):
        data = [data]
    if isinstance(data, list):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {f"_{i}_{default_name}": d for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, list or dict")
    out = {}
    for k, v in data.items():
        out[k] = v if isinstance(v, NDArray) else array(v)
    return list(sorted(out.items()))


class NDArrayIter(DataIter):
    """ref: io.py:491 NDArrayIter — batching/shuffle/pad over in-memory
    arrays."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 shuffle_seed=0,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True,
                                default_name=label_name)
        self.num_data = self.data[0][1].shape[0]
        self.idx = onp.arange(self.num_data)
        if shuffle:
            rng = onp.random.RandomState(shuffle_seed or None)
            rng.shuffle(self.idx)
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.cursor = -batch_size
        self._cached = {k: v.asnumpy() for k, v in self.data + self.label}
        if last_batch_handle == "discard":
            self.num_batches = self.num_data // batch_size
        else:
            self.num_batches = (self.num_data + batch_size - 1) // batch_size

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:],
                         v.dtype) for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:],
                         v.dtype) for k, v in self.label]

    def hard_reset(self):
        self.cursor = -self.batch_size

    def reset(self):
        if self.shuffle:
            onp.random.shuffle(self.idx)
        self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        if self.last_batch_handle == "discard":
            return self.cursor + self.batch_size <= self.num_data
        return self.cursor < self.num_data

    def _take(self, arrs):
        end = self.cursor + self.batch_size
        if end <= self.num_data:
            sel = self.idx[self.cursor:end]
        else:
            # pad by wrapping from the start, cycling if the batch is
            # larger than the dataset (idx[:pad] alone under-fills then,
            # emitting a short batch whose pad exceeds its length)
            pad = end - self.num_data
            sel = onp.concatenate([self.idx[self.cursor:],
                                   onp.resize(self.idx, pad)])
        return [array(self._cached[k][sel]) for k, _ in arrs]

    def getdata(self):
        return self._take(self.data)

    def getlabel(self):
        return self._take(self.label)

    def getpad(self):
        end = self.cursor + self.batch_size
        if self.last_batch_handle == "pad" and end > self.num_data:
            return end - self.num_data
        return 0


class ResizeIter(DataIter):
    """ref: io.py ResizeIter — clip/loop an iterator to `size` batches."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None

    @property
    def provide_data(self):
        return self.data_iter.provide_data

    @property
    def provide_label(self):
        return self.data_iter.provide_label

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class _PrefetchError:
    """Queue sentinel carrying a worker-thread exception to the consumer."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


class PrefetchingIter(DataIter):
    """ref: io.py:617 PrefetchingIter — background-thread double buffering
    (the role of src/io/iter_prefetcher.h)."""

    def __init__(self, iters, rename_data=None, rename_label=None,
                 prefetch_depth=2):
        if not isinstance(iters, list):
            iters = [iters]
        super().__init__(iters[0].batch_size)
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.prefetch_depth = prefetch_depth
        self._queue: "queue.Queue" = queue.Queue(maxsize=prefetch_depth)
        self._stop = threading.Event()
        self._thread = None
        self._start()

    def _start(self):
        # the worker binds ITS epoch's queue/stop-event, not self._…:
        # if reset() times out joining a worker that is stuck in a slow
        # it.next(), the straggler's final put() lands in the orphaned
        # old queue instead of poisoning the new epoch with a stale batch
        q, stop = self._queue, self._stop

        def worker():
            from ..resil import faultplan as _faultplan

            while not stop.is_set():
                try:
                    # resil 'io' site: MXRESIL_FAULT_PLAN stalls/faults
                    # the prefetch worker here — an injected raise rides
                    # the existing sentinel path below, so drills prove
                    # the consumer is never stranded
                    _faultplan.inject("io")
                    batches = [it.next() for it in self.iters]
                except StopIteration:
                    q.put(None)
                    return
                except BaseException as e:  # noqa: BLE001
                    # a dying worker must not strand the consumer: ship
                    # the exception through the queue (next() re-raises
                    # it) instead of exiting silently and leaving
                    # queue.get() blocked forever
                    q.put(_PrefetchError(e))
                    return
                q.put(batches)

        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     if isinstance(r, dict) else x
                     for x in i.provide_data]
                    for r, i in zip(self.rename_data, self.iters)], [])

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     if isinstance(r, dict) else x
                     for x in i.provide_label]
                    for r, i in zip(self.rename_label, self.iters)], [])

    def reset(self):
        # drain-and-join until the worker is dead (bounded): each drain
        # unblocks a worker stuck in queue.put, letting it see the stop
        # event. A worker stuck >5 s inside it.next() is abandoned as a
        # straggler — it holds the OLD queue/stop bindings (see _start)
        # so it cannot poison the new epoch's queue, but it may still
        # race it.reset() on the shared underlying iterators; nothing
        # short of an unbounded wait can close that, so we bound.
        self._stop.set()
        deadline = time.monotonic() + 5.0
        while True:
            try:
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
            if self._thread is None or not self._thread.is_alive() \
                    or time.monotonic() > deadline:
                break
            self._thread.join(timeout=0.1)
        for it in self.iters:
            it.reset()
        self._stop = threading.Event()
        self._queue = queue.Queue(maxsize=self.prefetch_depth)
        self._start()

    def next(self):
        batches = self._queue.get()
        if batches is None:
            # re-enqueue the one-shot end marker: the worker is dead, so
            # a second next() after exhaustion must raise StopIteration
            # again instead of blocking forever on an empty queue
            self._queue.put(None)
            raise StopIteration
        if isinstance(batches, _PrefetchError):
            # keep the sentinel available so every subsequent next()
            # fails the same way instead of blocking on an empty queue
            self._queue.put(batches)
            raise batches.exc
        if len(batches) == 1:
            return batches[0]
        return DataBatch(
            data=sum([b.data for b in batches], []),
            label=sum([b.label for b in batches], []),
            pad=batches[0].pad, index=batches[0].index)

    def iter_next(self):
        try:
            self._peek = self.next()
            return True
        except StopIteration:
            return False


class MXDataIter(DataIter):
    """Placeholder for C-registered iterators (ref: io.py MXDataIter)."""

    def __init__(self, *a, **kw):
        raise MXNetError("MXDataIter: use the named iterator classes")


def MNISTIter(image="train-images-idx3-ubyte", label="train-labels-idx1-ubyte",
              batch_size=128, shuffle=True, flat=False, seed=0,
              silent=False, data_shape=(1, 28, 28), **kwargs):
    """ref: src/io/iter_mnist.cc — reads idx-ubyte MNIST files."""
    import gzip
    import os
    import struct

    def read_idx(path):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            magic = struct.unpack(">I", f.read(4))[0]
            ndim = magic & 0xFF
            dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
            return onp.frombuffer(f.read(), dtype=onp.uint8).reshape(dims)

    imgs = read_idx(image).astype(onp.float32) / 255.0
    labels = read_idx(label).astype(onp.float32)
    if flat:
        imgs = imgs.reshape(imgs.shape[0], -1)
    else:
        imgs = imgs.reshape((-1,) + tuple(data_shape))
    return NDArrayIter(imgs, labels, batch_size=batch_size, shuffle=shuffle,
                       last_batch_handle="discard")


def CSVIter(data_csv, data_shape, label_csv=None, label_shape=(1,),
            batch_size=128, round_batch=True, **kwargs):
    """ref: src/io/iter_csv.cc"""
    data = onp.loadtxt(data_csv, delimiter=",", dtype=onp.float32)
    data = data.reshape((-1,) + tuple(data_shape))
    label = None
    if label_csv:
        label = onp.loadtxt(label_csv, delimiter=",", dtype=onp.float32)
        label = label.reshape((-1,) + tuple(label_shape))
    return NDArrayIter(data, label, batch_size=batch_size)


def LibSVMIter(data_libsvm, data_shape, batch_size=128, **kwargs):
    """ref: src/io/iter_libsvm.cc — parses libsvm text into dense batches."""
    feats = []
    labels = []
    dim = int(onp.prod(data_shape))
    with open(data_libsvm) as f:
        for line in f:
            parts = line.strip().split()
            if not parts:
                continue
            labels.append(float(parts[0]))
            row = onp.zeros(dim, dtype=onp.float32)
            for tok in parts[1:]:
                i, v = tok.split(":")
                row[int(i)] = float(v)
            feats.append(row)
    data = onp.stack(feats).reshape((-1,) + tuple(data_shape))
    return NDArrayIter(data, onp.asarray(labels, onp.float32),
                       batch_size=batch_size)


def _first_record_is_jpeg(path_imgrec) -> bool:
    """The native pipeline decodes JPEG only; PNG/other records (e.g.
    pack_img(img_fmt='.png')) must take the cv2/PIL fallback rather than
    silently decode to zeros."""
    try:
        from .. import recordio as rio
        from ..native import NativeRecordIO
        reader = NativeRecordIO(path_imgrec)
        if len(reader) == 0:
            reader.close()
            return False
        _, payload = rio.unpack(reader.read_idx(0))
        reader.close()
        return payload[:2] == b"\xff\xd8"  # JPEG SOI
    except Exception:
        return False


class _NativeImageRecordIter(DataIter):
    """DataIter over the native C++ decode+augment pipeline
    (native/image_pipeline.cc — the iter_image_recordio_2.cc analog)."""

    def __init__(self, path_imgrec, data_shape, batch_size, label_width,
                 shuffle, mean_r=0, mean_g=0, mean_b=0, std_r=1, std_g=1,
                 std_b=1, rand_crop=False, rand_mirror=False, resize=0,
                 seed=0, preprocess_threads=0, **_ignored):
        super().__init__(batch_size)
        from ..native import NativeImagePipeline
        self._pipe = NativeImagePipeline(
            path_imgrec, batch_size, data_shape=data_shape,
            label_width=label_width, shuffle=shuffle, resize=resize,
            rand_crop=rand_crop, rand_mirror=rand_mirror,
            mean=(mean_r, mean_g, mean_b), std=(std_r, std_g, std_b),
            seed=seed, num_workers=preprocess_threads)
        self._iter = iter(self._pipe)
        self.data_shape = tuple(data_shape)
        self.label_width = label_width

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 \
            else (self.batch_size, self.label_width)
        return [DataDesc("softmax_label", shape)]

    def reset(self):
        self._pipe.reset()
        self._iter = iter(self._pipe)

    def next(self):
        from ..ndarray.ndarray import array as nd_array
        try:
            data, labels = next(self._iter)
        except StopIteration:
            raise StopIteration
        if self.label_width == 1:
            labels = labels.reshape(-1)
        # the native pipeline wrap-pads the final partial batch and
        # reports the count per batch (delivery order is not index order
        # with multiple decode workers) — ref: ImageRecordIter
        # last_batch_handle='pad' semantics
        return DataBatch(data=[nd_array(data)], label=[nd_array(labels)],
                         pad=self._pipe.last_pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def iter_next(self):
        raise NotImplementedError  # next() is overridden directly


class ImageDetRecordIter(DataIter):
    """Detection RecordIO iterator (ref: src/io/iter_image_det_recordio.cc
    ImageDetRecordIter + image_det_aug_default.cc, the SSD input tier).

    Records are pack()'d with an array label
    ``[header_width, object_width, <extra header...>,
    (cls, xmin, ymin, xmax, ymax) * N]`` in normalized coordinates
    (tools/im2rec-for-detection convention). Decode runs on the native
    C++ pipeline; detection-aware augmentation (horizontal flip moves
    the boxes with the pixels) is applied on the decoded batch.
    """

    def __init__(self, path_imgrec=None, data_shape=(3, 300, 300),
                 batch_size=1, shuffle=False, label_pad_width=0,
                 label_pad_value=-1.0, rand_mirror=False, resize=0,
                 mean_r=0, mean_g=0, mean_b=0, std_r=1, std_g=1, std_b=1,
                 seed=0, preprocess_threads=0, **_ignored):
        super().__init__(batch_size)
        from .. import recordio as rio
        from ..base import MXNetError
        from ..native import NativeImagePipeline, NativeRecordIO
        if not _first_record_is_jpeg(path_imgrec):
            raise MXNetError(
                "ImageDetRecordIter requires JPEG-encoded records "
                "(the native decode path has no PNG support)")
        if label_pad_width <= 0:
            # scan headers for the max label width (the reference's
            # first-pass estimate, iter_image_det_recordio.cc:332)
            reader = NativeRecordIO(path_imgrec)
            for i in range(len(reader)):
                hdr, _ = rio.unpack(reader.read_idx(i))
                width = 1 if isinstance(hdr.label, float) \
                    else len(hdr.label)
                label_pad_width = max(label_pad_width, width)
            reader.close()
        self.label_pad_width = label_pad_width
        self.label_pad_value = float(label_pad_value)
        self._rand_mirror = rand_mirror
        self._rng = onp.random.RandomState(seed)
        self.data_shape = tuple(data_shape)
        # native decode with force_resize: images are WARPED to
        # data_shape (no crop), so normalized box coordinates stay valid
        # (the det augmenter default, image_det_aug_default.cc);
        # geometric label-changing augs are handled here
        self._pipe = NativeImagePipeline(
            path_imgrec, batch_size, data_shape=data_shape,
            label_width=label_pad_width, shuffle=shuffle,
            rand_crop=False, rand_mirror=False, force_resize=True,
            mean=(mean_r, mean_g, mean_b), std=(std_r, std_g, std_b),
            seed=seed, num_workers=preprocess_threads,
            label_pad_value=self.label_pad_value)
        self._iter = iter(self._pipe)

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        n_obj = (self.label_pad_width - 2) // 5
        return [DataDesc("label", (self.batch_size, n_obj, 5))]

    def reset(self):
        self._pipe.reset()
        self._iter = iter(self._pipe)

    def next(self):
        from ..ndarray.ndarray import array as nd_array
        data, labels = next(self._iter)
        B = data.shape[0]
        n_obj = (self.label_pad_width - 2) // 5
        boxes = onp.full((B, n_obj, 5), self.label_pad_value, "float32")
        for b in range(B):
            row = labels[b]
            hw = int(row[0]) if row[0] > 0 else 2
            ow = int(row[1]) if row[1] > 0 else 5
            body = row[hw:]
            k = 0
            for o in range(min(n_obj, len(body) // ow)):
                rec = body[o * ow:(o + 1) * ow]
                if rec[0] < 0:  # padding
                    continue
                boxes[b, k, :5] = rec[:5]
                k += 1
        if self._rand_mirror:
            flip = self._rng.rand(B) < 0.5
            for b in onp.where(flip)[0]:
                data[b] = data[b][:, :, ::-1]
                valid = boxes[b, :, 0] >= 0
                x1 = boxes[b, valid, 1].copy()
                x2 = boxes[b, valid, 3].copy()
                boxes[b, valid, 1] = 1.0 - x2
                boxes[b, valid, 3] = 1.0 - x1
        return DataBatch(data=[nd_array(onp.ascontiguousarray(data))],
                         label=[nd_array(boxes)], pad=self._pipe.last_pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def iter_next(self):
        raise NotImplementedError  # next() is overridden directly


DetRecordIter = ImageDetRecordIter


def ImageRecordIter(path_imgrec=None, data_shape=(3, 224, 224), batch_size=1,
                    label_width=1, shuffle=False, **kwargs):
    """RecordIO image pipeline (ref: src/io/iter_image_recordio_2.cc
    ImageRecordIter2). The native C++ decode+augment pipeline
    (native/image_pipeline.cc) is the default path; Python cv2/PIL
    decode is the fallback when the toolchain/libjpeg is unavailable."""
    from .. import native
    if native.available() and _first_record_is_jpeg(path_imgrec):
        try:
            return _NativeImageRecordIter(
                path_imgrec, data_shape, batch_size, label_width, shuffle,
                **kwargs)
        except Exception:
            pass  # fall back to the python pipeline
    from ..image import ImageRecordIterPy
    return ImageRecordIterPy(path_imgrec=path_imgrec, data_shape=data_shape,
                             batch_size=batch_size, label_width=label_width,
                             shuffle=shuffle, **kwargs)
