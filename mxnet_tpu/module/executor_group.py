"""DataParallelExecutorGroup: per-device executors + batch slicing.

ref: python/mxnet/module/executor_group.py:144 (decide_slices :282,
forward/backward fan-out, grad aggregation). On a TPU mesh the preferred
path is one pjit-compiled executor over all chips (parallel/), but this
class keeps the reference's explicit multi-context semantics for API
parity and for CPU multi-device tests.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as onp

from ..base import MXNetError
from ..context import Context
from ..io.io import DataDesc
from ..ndarray.ndarray import NDArray, concatenate, zeros as nd_zeros

__all__ = ["DataParallelExecutorGroup"]


def _split_input_slice(batch_size, work_load_list):
    """ref: executor_manager.py _split_input_slice."""
    total = sum(work_load_list)
    slices = []
    start = 0
    for i, w in enumerate(work_load_list):
        end = batch_size if i == len(work_load_list) - 1 else \
            start + int(round(batch_size * w / total))
        slices.append(slice(start, end))
        start = end
    return slices


class DataParallelExecutorGroup:
    def __init__(self, symbol, contexts: List[Context], workload,
                 data_shapes, label_shapes, param_names, for_training,
                 inputs_need_grad, shared_group=None, logger=None,
                 fixed_param_names=None, grad_req="write", state_names=None):
        self.symbol = symbol
        self.contexts = contexts
        self.workload = workload or [1] * len(contexts)
        self.param_names = param_names
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.fixed_param_names = set(fixed_param_names or [])
        self.state_names = state_names or []
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()

        self.data_shapes = [DataDesc(*d) if not isinstance(d, DataDesc) else d
                            for d in data_shapes]
        self.label_shapes = [DataDesc(*l) if not isinstance(l, DataDesc)
                             else l for l in (label_shapes or [])]
        self.data_names = [d.name for d in self.data_shapes]
        self.label_names = [l.name for l in self.label_shapes]

        self.batch_size = self.data_shapes[0].shape[0]
        self.slices = _split_input_slice(self.batch_size, self.workload)

        self.execs = []
        self._default_execs = None
        self._bind_exec(shared_group)

    def _grad_req_for(self, name):
        if not self.for_training:
            return "null"
        if name in self.fixed_param_names:
            return "null"
        if name in self.data_names:
            return "write" if self.inputs_need_grad else "null"
        if name in self.label_names or name in self.state_names:
            return "null"
        return "write"

    def _bind_exec(self, shared_group):
        self.execs = []
        for i, ctx in enumerate(self.contexts):
            sl = self.slices[i]
            nbatch = sl.stop - sl.start
            shapes = {}
            for d in self.data_shapes:
                shapes[d.name] = (nbatch,) + tuple(d.shape[1:])
            for l in self.label_shapes:
                shapes[l.name] = (nbatch,) + tuple(l.shape[1:])
            grad_req = {n: self._grad_req_for(n) for n in self.arg_names}
            self.execs.append(self.symbol.simple_bind(
                ctx, grad_req=grad_req, **shapes))

    # ------------------------------------------------------------------
    @property
    def param_arrays(self):
        return [[e.arg_dict[n] for e in self.execs]
                for n in self.param_names]

    @property
    def grad_arrays(self):
        return [[e.grad_dict.get(n) for e in self.execs]
                for n in self.param_names]

    @property
    def aux_arrays(self):
        return [[e.aux_dict[n] for e in self.execs] for n in self.aux_names]

    @property
    def data_arrays(self):
        return [[(sl, e.arg_dict[name]) for sl, e in
                 zip(self.slices, self.execs)] for name in self.data_names]

    def set_params(self, arg_params, aux_params, allow_extra=False):
        for e in self.execs:
            e.copy_params_from(arg_params, aux_params,
                               allow_extra_params=allow_extra)

    def get_params(self, arg_params, aux_params):
        for name in self.param_names:
            arrs = [e.arg_dict[name] for e in self.execs]
            avg = arrs[0]
            if len(arrs) > 1:
                total = arrs[0]._data
                for a in arrs[1:]:
                    total = total + a._data.astype(total.dtype)
                from ..ndarray.ndarray import _wrap
                avg = _wrap(total / len(arrs))
            arg_params[name]._rebind(avg._data.astype(
                arg_params[name]._data.dtype))
        for name in self.aux_names:
            arrs = [e.aux_dict[name] for e in self.execs]
            from ..ndarray.ndarray import _wrap
            total = arrs[0]._data
            for a in arrs[1:]:
                total = total + a._data
            aux_params[name]._rebind(total / len(arrs))

    # ------------------------------------------------------------------
    def _load_slice(self, batch_data, names):
        for name, full in zip(names, batch_data):
            for sl, e in zip(self.slices, self.execs):
                if name in e.arg_dict:
                    e.arg_dict[name]._rebind(full[sl.start:sl.stop]._data
                                             .astype(e.arg_dict[name]._data.dtype))

    def forward(self, data_batch, is_train=None):
        if is_train is None:
            is_train = self.for_training
        self._load_slice(data_batch.data, self.data_names)
        if self.label_names and data_batch.label:
            self._load_slice(data_batch.label, self.label_names)
        for e in self.execs:
            e.forward(is_train=is_train)

    def backward(self, out_grads=None):
        if not self.for_training:
            raise MXNetError("re-bind with for_training=True for backward")
        for i, e in enumerate(self.execs):
            if out_grads is None:
                e.backward()
            else:
                sl = self.slices[i]
                e.backward([g[sl.start:sl.stop] for g in out_grads])

    def get_outputs(self, merge_multi_context=True):
        outs = [[e.outputs[i] for e in self.execs]
                for i in range(len(self.execs[0].outputs))]
        if merge_multi_context:
            return [o[0] if len(o) == 1 else concatenate(o, axis=0)
                    for o in outs]
        return outs

    def get_input_grads(self, merge_multi_context=True):
        grads = [[e.grad_dict.get(n) for e in self.execs]
                 for n in self.data_names]
        if merge_multi_context:
            return [g[0] if len(g) == 1 else concatenate(g, axis=0)
                    for g in grads]
        return grads

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        for i, e in enumerate(self.execs):
            sl = self.slices[i]
            labels_slice = [l[sl.start:sl.stop] for l in labels] \
                if not pre_sliced else labels[i]
            # only visible outputs feed metrics
            eval_metric.update_dict(
                dict(zip(self.label_names, labels_slice)),
                dict(zip(self.symbol.list_outputs(), e.outputs)))

    def install_monitor(self, mon):
        for e in self.execs:
            mon.install(e)

    def bind_ith_exec(self, i, data_shapes, label_shapes, shared_group):
        raise NotImplementedError
