"""Module: symbol + executor-group training module.

ref: python/mxnet/module/module.py — bind :364, init_params :243,
init_optimizer :479, forward/backward/update :600-670.
"""
from __future__ import annotations

import logging
from typing import Dict, List, Optional

from .. import optimizer as opt_mod
from ..base import MXNetError
from ..context import Context, cpu, current_context
from ..initializer import InitDesc, Uniform
from ..io.io import DataDesc
from ..model import (_create_kvstore, _initialize_kvstore, _update_params,
                     _update_params_on_kvstore, load_checkpoint)
from ..ndarray.ndarray import NDArray, zeros as nd_zeros
from .base_module import BaseModule, _as_list
from .executor_group import DataParallelExecutorGroup

__all__ = ["Module"]


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None,
                 compression_params=None):
        super().__init__(logger=logger)
        if context is None:
            context = current_context()
        if isinstance(context, Context):
            context = [context]
        self._context = context
        self._work_load_list = work_load_list or [1] * len(context)

        self._symbol = symbol
        data_names = list(data_names) if data_names else []
        label_names = list(label_names) if label_names is not None else []

        arg_names = symbol.list_arguments()
        input_names = data_names + label_names + list(state_names or [])
        self._param_names = [n for n in arg_names if n not in input_names]
        self._fixed_param_names = list(fixed_param_names or [])
        self._aux_names = symbol.list_auxiliary_states()
        self._data_names = data_names
        self._label_names = label_names
        self._state_names = list(state_names or [])
        self._output_names = symbol.list_outputs()

        self._arg_params = None
        self._aux_params = None
        self._params_dirty = False
        self._compression_params = compression_params
        self._optimizer = None
        self._kvstore = None
        self._update_on_kvstore = None
        self._updater = None
        self._preload_opt_states = None
        self._exec_group = None
        self._data_shapes = None
        self._label_shapes = None

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        """ref: module.py load."""
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        mod.params_initialized = True
        if load_optimizer_states:
            mod._preload_opt_states = "%s-%04d.states" % (prefix, epoch)
        return mod

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False,
                        remove_amp_cast=True):
        """ref: module.py save_checkpoint."""
        self._symbol.save(f"{prefix}-symbol.json")
        param_name = "%s-%04d.params" % (prefix, epoch)
        self.save_params(param_name)
        if save_optimizer_states:
            state_name = "%s-%04d.states" % (prefix, epoch)
            self.save_optimizer_states(state_name)

    # ------------------------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        assert self.binded
        return self._data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        outs = self._exec_group.execs[0].outputs
        return list(zip(self._output_names, [o.shape for o in outs]))

    def get_params(self):
        assert self.binded and self.params_initialized
        if self._params_dirty:
            self._sync_params_from_devices()
        return (self._arg_params, self._aux_params)

    # ------------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        """ref: module.py:364."""
        if force_rebind:
            if self._exec_group is not None and self._params_dirty:
                # latest weights live only in the executors; pull them
                # back before discarding or the re-bound executors get
                # stale host params and training silently regresses
                self._sync_params_from_devices()
            self._exec_group = None
            self.binded = False
        if self.binded:
            self.logger.warning("Already bound, ignoring bind()")
            return

        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True

        self._data_shapes = [d if isinstance(d, DataDesc) else DataDesc(*d)
                             for d in data_shapes]
        self._label_shapes = [l if isinstance(l, DataDesc) else DataDesc(*l)
                              for l in (label_shapes or [])]

        self._exec_group = DataParallelExecutorGroup(
            self._symbol, self._context, self._work_load_list,
            self._data_shapes, self._label_shapes, self._param_names,
            for_training, inputs_need_grad, shared_group=None,
            logger=self.logger, fixed_param_names=self._fixed_param_names,
            grad_req=grad_req, state_names=self._state_names)

        if self._arg_params is None:
            ex = self._exec_group.execs[0]
            self._arg_params = {n: nd_zeros(ex.arg_dict[n].shape,
                                            dtype=str(ex.arg_dict[n].dtype))
                                for n in self._param_names}
            self._aux_params = {n: nd_zeros(ex.aux_dict[n].shape)
                                for n in self._aux_names}
        elif self.params_initialized:
            # params were loaded before bind (Module.load -> bind): the
            # fresh executors must receive them, as the reference's bind
            # does (module.py:430 exec_group.set_params when
            # params_initialized) — otherwise a loaded checkpoint
            # silently trains from uninitialized buffers
            self._exec_group.set_params(self._arg_params, self._aux_params,
                                        allow_extra=True)

    # ------------------------------------------------------------------
    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        """ref: module.py:243."""
        if self.params_initialized and not force_init:
            return
        assert self.binded, "call bind before initializing the parameters"
        if initializer is None and not (arg_params or aux_params):
            initializer = Uniform(0.01)

        def _impl(name, arr, cache):
            if cache is not None and name in cache:
                cache_arr = cache[name]
                if cache_arr is not arr:
                    arr._rebind(cache_arr._data.astype(arr._data.dtype))
            elif not allow_missing and cache is not None:
                raise RuntimeError(f"{name} is not presented")
            elif initializer is not None:
                initializer(InitDesc(name), arr)

        attrs = self._symbol.attr_dict()
        for name, arr in sorted(self._arg_params.items()):
            desc = InitDesc(name, attrs.get(name))
            if arg_params is not None or aux_params is not None:
                _impl(name, arr, arg_params)
            elif initializer is not None:
                initializer(desc, arr)
        for name, arr in sorted(self._aux_params.items()):
            if arg_params is not None or aux_params is not None:
                _impl(name, arr, aux_params)
            elif initializer is not None:
                initializer(InitDesc(name), arr)

        self.params_initialized = True
        self._params_dirty = False
        self._exec_group.set_params(self._arg_params, self._aux_params,
                                    allow_extra=True)

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        """ref: module.py:479."""
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring...")
            return
        if self._params_dirty:
            self._sync_params_from_devices()

        (kvstore, update_on_kvstore) = _create_kvstore(
            kvstore, len(self._context), self._arg_params)
        batch_size = self._exec_group.batch_size
        if kvstore and "dist" in kvstore.type and "_async" in kvstore.type:
            batch_size *= kvstore.num_workers
        rescale_grad = 1.0 / batch_size

        idx2name = {}
        if update_on_kvstore:
            idx2name.update(enumerate(self._exec_group.param_names))
        else:
            for k1, ctx in enumerate(self._context):
                idx2name.update({i * len(self._context) + k1: n
                                 for i, n in
                                 enumerate(self._exec_group.param_names)})

        if isinstance(optimizer, str):
            optimizer_params = dict(optimizer_params)
            if "rescale_grad" not in optimizer_params:
                optimizer_params["rescale_grad"] = rescale_grad
            optimizer = opt_mod.create(optimizer, sym=self.symbol,
                                       param_idx2name=idx2name,
                                       **optimizer_params)
        else:
            assert isinstance(optimizer, opt_mod.Optimizer)
            if optimizer.rescale_grad != rescale_grad:
                self.logger.warning(
                    "Optimizer created manually outside Module but "
                    "rescale_grad is not normalized to 1.0/batch_size/"
                    "num_workers (%s vs. %s).", optimizer.rescale_grad,
                    rescale_grad)

        self._optimizer = optimizer
        self._kvstore = kvstore
        self._update_on_kvstore = update_on_kvstore
        self._updater = None

        if kvstore:
            if self._compression_params:
                kvstore.set_gradient_compression(self._compression_params)
            _initialize_kvstore(kvstore=kvstore,
                                param_arrays=self._exec_group.param_arrays,
                                arg_params=self._arg_params,
                                param_names=self._param_names,
                                update_on_kvstore=update_on_kvstore)
        if update_on_kvstore:
            kvstore.set_optimizer(self._optimizer)
        else:
            self._updater = opt_mod.get_updater(optimizer)

        self.optimizer_initialized = True
        if self._preload_opt_states is not None:
            self.load_optimizer_states(self._preload_opt_states)
            self._preload_opt_states = None

    # ------------------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        self._exec_group.forward(data_batch, is_train)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._exec_group.backward(out_grads=out_grads)

    def update(self):
        """ref: module.py update — kvstore push/pull or local updater."""
        assert self.binded and self.params_initialized \
            and self.optimizer_initialized
        self._params_dirty = True
        if self._update_on_kvstore:
            _update_params_on_kvstore(self._exec_group.param_arrays,
                                      self._exec_group.grad_arrays,
                                      self._kvstore,
                                      self._exec_group.param_names)
        else:
            _update_params(self._exec_group.param_arrays,
                           self._exec_group.grad_arrays,
                           updater=self._updater,
                           num_device=len(self._context),
                           kvstore=self._kvstore,
                           param_names=self._exec_group.param_names)

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._exec_group.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized \
            and self.inputs_need_grad
        return self._exec_group.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        self._exec_group.update_metric(eval_metric, labels, pre_sliced)

    def _sync_params_from_devices(self):
        self._exec_group.get_params(self._arg_params, self._aux_params)
        if self._kvstore and self._update_on_kvstore:
            pass
        self._params_dirty = False

    def install_monitor(self, mon):
        assert self.binded
        self._exec_group.install_monitor(mon)

    def save_optimizer_states(self, fname):
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname)
        else:
            with open(fname, "wb") as fout:
                fout.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
        else:
            with open(fname, "rb") as fin:
                self._updater.set_states(fin.read())

    def reshape(self, data_shapes, label_shapes=None):
        assert self.binded
        # bind(force_rebind) syncs dirty params out of the old executors
        # and installs them into the fresh ones when params_initialized
        self.bind(data_shapes, label_shapes, self.for_training,
                  self.inputs_need_grad, force_rebind=True)

    def borrow_optimizer(self, shared_module):
        assert shared_module.optimizer_initialized
        self._optimizer = shared_module._optimizer
        self._kvstore = shared_module._kvstore
        self._update_on_kvstore = shared_module._update_on_kvstore
        self._updater = shared_module._updater
        self.optimizer_initialized = True
