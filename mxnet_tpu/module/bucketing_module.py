"""BucketingModule: variable-length sequence training.

ref: python/mxnet/module/bucketing_module.py — one Module per bucket key,
parameters shared; the reference's answer to dynamic shapes, and the right
TPU answer too (bucketed jit caches — SURVEY.md hard part (b)).
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from .base_module import BaseModule
from .module import Module

__all__ = ["BucketingModule"]


class BucketingModule(BaseModule):
    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None, compression_params=None):
        super().__init__(logger=logger)
        assert default_bucket_key is not None
        self._default_bucket_key = default_bucket_key
        self._sym_gen = sym_gen
        self._ctx = context
        self._work_load_list = work_load_list
        self._fixed_param_names = fixed_param_names
        self._state_names = state_names
        self._compression_params = compression_params
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._monitor = None
        self._grad_req = None
        self._preload_params = None  # set by BucketingModule.load
        # monotonically increasing parameter version: each bucket module
        # records the version it last received, so switch_bucket knows
        # exactly when a module's device params are stale. The
        # _params_dirty flag alone cannot carry this — get_params()
        # clears it after syncing only the CURRENT bucket, leaving other
        # buckets stale with no record (params-shared executors make
        # this moot in the reference; here params are copied on switch)
        self._param_version = 0

    def _gen_symbol(self, key):
        sym, data_names, label_names = self._sym_gen(key)
        return sym, data_names, label_names

    @property
    def data_names(self):
        if self.binded:
            return self._curr_module.data_names
        return self._gen_symbol(self._default_bucket_key)[1]

    @property
    def output_names(self):
        if self.binded:
            return self._curr_module.output_names
        return self._gen_symbol(self._default_bucket_key)[0].list_outputs()

    @property
    def data_shapes(self):
        assert self.binded
        return self._curr_module.data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._curr_module.label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return self._curr_module.output_shapes

    def get_params(self):
        assert self.params_initialized
        self._curr_module._params_dirty = self._params_dirty
        params = self._curr_module.get_params()
        self._params_dirty = False
        return params

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded
        self._curr_module.init_params(initializer=initializer,
                                      arg_params=arg_params,
                                      aux_params=aux_params,
                                      allow_missing=allow_missing,
                                      force_init=force_init,
                                      allow_extra=allow_extra)
        self._params_dirty = False
        self.params_initialized = True
        # a fresh param install is a new version — other bucket modules
        # must refresh on their next switch (set_params routes here with
        # force_init, so this also covers external param injection)
        self._param_version += 1
        self._curr_module._bucket_param_version = self._param_version

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        assert shared_module is None
        self._grad_req = grad_req
        if force_rebind:
            self._buckets = {}
            self.binded = False
        if self.binded:
            self.logger.warning("Already bound, ignoring bind()")
            return

        sym, dnames, lnames = self._gen_symbol(self._default_bucket_key)
        module = Module(sym, dnames, lnames, logger=self.logger,
                        context=self._ctx,
                        work_load_list=self._work_load_list,
                        fixed_param_names=self._fixed_param_names,
                        state_names=self._state_names,
                        compression_params=self._compression_params)
        module.bind(data_shapes, label_shapes, for_training,
                    inputs_need_grad, force_rebind=False,
                    grad_req=self._grad_req)
        self._curr_module = module
        self._curr_bucket_key = self._default_bucket_key
        self._buckets[self._default_bucket_key] = module
        self.binded = True
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._params_dirty = False
        if self._preload_params is not None:
            # checkpoint loaded before bind (BucketingModule.load):
            # install into the fresh executors, like Module's own
            # preloaded-params path
            arg_params, aux_params = self._preload_params
            module._arg_params = arg_params
            module._aux_params = aux_params
            module.params_initialized = True
            module._exec_group.set_params(arg_params, aux_params,
                                          allow_extra=True)
            module._bucket_param_version = self._param_version
            self.params_initialized = True
            self._preload_params = None

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """ref: bucketing_module.py switch_bucket."""
        assert self.binded
        if bucket_key not in self._buckets:
            sym, dnames, lnames = self._gen_symbol(bucket_key)
            module = Module(sym, dnames, lnames, logger=self.logger,
                            context=self._ctx,
                            work_load_list=self._work_load_list,
                            fixed_param_names=self._fixed_param_names,
                            state_names=self._state_names,
                            compression_params=self._compression_params)
            module.bind(data_shapes, label_shapes,
                        self._curr_module.for_training,
                        self._curr_module.inputs_need_grad,
                        force_rebind=False, grad_req=self._grad_req)
            if self._monitor is not None:
                module.install_monitor(self._monitor)
            if self._curr_module.optimizer_initialized:
                module.borrow_optimizer(self._curr_module)
            self._buckets[bucket_key] = module
        else:
            module = self._buckets[bucket_key]
            if not module.optimizer_initialized and \
                    self._curr_module.optimizer_initialized:
                module.borrow_optimizer(self._curr_module)
        if self.params_initialized and \
                getattr(module, "_bucket_param_version", -1) != \
                self._param_version:
            # this module last saw an older parameter version: refresh
            # from the current (freshest) module BEFORE switching
            arg_params, aux_params = self.get_params()
            module.init_params(arg_params=arg_params,
                               aux_params=aux_params, allow_missing=True,
                               force_init=True, allow_extra=True)
            module._bucket_param_version = self._param_version
        self._curr_module = module
        self._curr_bucket_key = bucket_key

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        self._curr_module.init_optimizer(kvstore, optimizer, optimizer_params,
                                         force_init=force_init)
        for mod in self._buckets.values():
            if mod is not self._curr_module and \
                    not mod.optimizer_initialized:
                mod.borrow_optimizer(self._curr_module)
        self.optimizer_initialized = True

    def prepare(self, data_batch, sparse_row_id_fn=None):
        assert self.binded and self.params_initialized
        bucket_key = data_batch.bucket_key
        original = self._curr_bucket_key
        self.switch_bucket(bucket_key, data_batch.provide_data,
                           data_batch.provide_label)
        if original != bucket_key:
            self.switch_bucket(original, None, None) \
                if False else None  # stay on new bucket (forward follows)

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        self.switch_bucket(data_batch.bucket_key, data_batch.provide_data,
                           data_batch.provide_label)
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads=out_grads)

    def update(self):
        self._params_dirty = True
        self._param_version += 1
        self._curr_module.update()
        self._curr_module._bucket_param_version = self._param_version

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        return self._curr_module.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        self._curr_module.update_metric(eval_metric, labels, pre_sliced)

    @property
    def symbol(self):
        assert self.binded
        return self._curr_module.symbol

    @staticmethod
    def _bucket_tag(key):
        """Filename-safe rendering of a bucket key (int, str, or tuple
        like seq2seq's (enc_len, dec_len))."""
        import re
        if isinstance(key, (tuple, list)):
            raw = "_".join(str(k) for k in key)
        else:
            raw = str(key)
        return re.sub(r"[^A-Za-z0-9_.-]", "_", raw)

    def save_checkpoint(self, prefix, epoch, remove_amp_cast=False):
        """ref: bucketing_module.py:563 — shared params, one symbol
        JSON per trained bucket, and an epoch-scoped JSON manifest of
        the bucket keys (tuple keys preserved as lists)."""
        assert self._buckets, "empty BucketingModule cannot be saved"
        import json

        self.save_params("%s-%04d.params" % (prefix, epoch))
        tags = {}
        for key in self._buckets:
            s, _, _ = self._gen_symbol(key)
            tag = self._bucket_tag(key)
            s.save("%s-%s-symbol.json" % (prefix, tag))
            tags[tag] = list(key) if isinstance(key, (tuple, list)) \
                else key
        with open("%s-%04d.buckets.json" % (prefix, epoch), "w") as f:
            json.dump(tags, f)

    @staticmethod
    def load(prefix, epoch, sym_gen=None, default_bucket_key=None,
             **kwargs):
        """ref: bucketing_module.py:584 — sym_gen cannot be serialized,
        so the caller supplies it; params install into the executors at
        the next bind. The manifest, when present, validates that the
        requested default bucket was part of the checkpoint."""
        import json
        import os

        assert sym_gen is not None, \
            "sym_gen is required for loading BucketingModule"
        assert default_bucket_key is not None, \
            "default_bucket_key is required for loading BucketingModule"
        manifest = "%s-%04d.buckets.json" % (prefix, epoch)
        if os.path.exists(manifest):
            with open(manifest) as f:
                tags = json.load(f)
            want = BucketingModule._bucket_tag(default_bucket_key)
            if want not in tags:
                raise ValueError(
                    f"default_bucket_key {default_bucket_key!r} was not "
                    f"in the checkpoint (buckets: {sorted(tags.values())})")
        from ..model import load_params as _load_params
        mod = BucketingModule(sym_gen,
                              default_bucket_key=default_bucket_key,
                              **kwargs)
        mod._preload_params = _load_params(prefix, epoch)
        return mod

    def install_monitor(self, mon):
        assert self.binded
        self._monitor = mon
        for mod in self._buckets.values():
            mod.install_monitor(mon)
