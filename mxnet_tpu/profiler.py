"""Profiler: jax.profiler + chrome-trace export.

ref: src/profiler/profiler.h:251 + python/mxnet/profiler.py — the reference
emits chrome://tracing JSON per engine event. On TPU the deep trace comes
from jax.profiler (XProf/TensorBoard); this module keeps the reference's
control surface (set_config/set_state/dump, scoped ranges, REAL
pause/resume) and emits a chrome-trace JSON of the Python-level scopes
for parity. The telemetry layer (mxnet_tpu/telemetry/) feeds it op-name
duration events, recompile instants, and memory counter samples, so one
``dump()`` carries the whole attribution story; ``tools/mxprof.py``
summarizes it.

Domains mirror the reference's config bits and are HONORED here
(ref: profiler.h kSymbolic/kImperative/kMemory/kAPI): events tagged with
a domain are dropped unless the matching ``profile_<domain>`` config is
on (``profile_all`` overrides).
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import List, Optional

import jax

__all__ = ["set_config", "set_state", "dump", "dumps", "pause", "resume",
           "is_running", "is_paused", "Scope", "scope", "Task", "Frame",
           "Event", "Marker", "Domain"]

_state = threading.local()
_config = {"filename": "profile.json", "profile_all": False,
           "profile_symbolic": True, "profile_imperative": True,
           "profile_memory": True, "profile_api": True,
           "aggregate_stats": False}
_events: List[dict] = []
_events_lock = threading.Lock()
_running = False
_paused = False
_jax_dir: Optional[str] = None


def set_config(**kwargs):
    """ref: python/mxnet/profiler.py set_config / MXSetProcessProfilerConfig"""
    _config.update(kwargs)


def set_state(state="stop", profile_process="worker"):
    global _running, _paused, _jax_dir
    if profile_process == "server":
        # remote/server profiling: command the parameter server (ref:
        # kvstore_dist.h:99 kSetProfilerParams;
        # tests/nightly/test_server_profiling.py)
        _send_server_command("profiler_state", state)
        return
    if state == "run" and not _running:
        _running = True
        _paused = False
        _jax_dir = os.path.splitext(_config["filename"])[0] + "_xprof"
        try:
            jax.profiler.start_trace(_jax_dir)
        except Exception:
            _jax_dir = None
    elif state == "stop" and _running:
        _running = False
        _paused = False
        if _jax_dir:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass


def pause(profile_process="worker"):
    """Suppress event collection without tearing down the trace session
    (ref: MXProfilePause — the reference stops attributing engine events
    while paused; here every _append_event/_agg_update is dropped)."""
    global _paused
    if profile_process == "server":
        _send_server_command("profiler_pause", "1")
        return
    _paused = True


def resume(profile_process="worker"):
    global _paused
    if profile_process == "server":
        _send_server_command("profiler_pause", "0")
        return
    _paused = False


def is_running() -> bool:
    return _running


def is_paused() -> bool:
    return _paused


def _active() -> bool:
    """Events are collected: running and not paused."""
    return _running and not _paused


def _domain_enabled(domain: Optional[str]) -> bool:
    """Honor the per-domain config bits (profile_all overrides).
    Unknown/None domains are always collected."""
    if domain is None or _config.get("profile_all"):
        return True
    return bool(_config.get(f"profile_{domain}", True))


def _append_event(ev: dict):
    """Collect one chrome-trace event — the single gate every producer
    (Scope, telemetry tracing/recompile/memory) goes through."""
    if not _active():
        return
    with _events_lock:
        _events.append(ev)


def events(category: Optional[str] = None) -> List[dict]:
    """Snapshot of collected events, optionally filtered by ``cat``."""
    with _events_lock:
        evs = list(_events)
    if category is None:
        return evs
    return [e for e in evs if e.get("cat") == category]


def reset():
    """Drop collected events and aggregate stats (tests / fresh run)."""
    with _events_lock:
        _events.clear()
    with _agg_lock:
        _agg.clear()


def dumps(reset=False) -> str:
    """Chrome-trace JSON, or the aggregate statistics table when
    aggregate_stats is configured (ref: src/profiler/aggregate_stats.cc
    DumpTable via MXAggregateProfileStatsPrint)."""
    if _config.get("aggregate_stats"):
        out = _aggregate_table()
    else:
        with _events_lock:
            out = json.dumps({"traceEvents": list(_events)}, indent=1)
    if reset:
        with _events_lock:
            _events.clear()
        with _agg_lock:
            _agg.clear()
    return out


def dump(finished=True, profile_process="worker"):
    if profile_process == "server":
        _send_server_command("profiler_dump", "")
        return
    with _events_lock:
        payload = json.dumps({"traceEvents": list(_events)}, indent=1)
    with open(_config["filename"], "w") as f:
        f.write(payload)


# -- aggregate stats (ref: profiler.h:327-331 + aggregate_stats.cc) ---------

_agg: dict = {}
_agg_lock = threading.Lock()


def _agg_update(name: str, dur_us: float):
    if not _active():
        return
    with _agg_lock:
        ent = _agg.get(name)
        if ent is None:
            _agg[name] = [1, dur_us, dur_us, dur_us]
        else:
            ent[0] += 1
            ent[1] += dur_us
            ent[2] = min(ent[2], dur_us)
            ent[3] = max(ent[3], dur_us)


def _aggregate_table(top_k: Optional[int] = None) -> str:
    if top_k is None:
        from .base import get_env
        top_k = int(get_env("MXNET_PROFILER_TOPK", 0))
    lines = ["Profile Statistics:",
             f"{'Name':<40}{'Total Count':>12}{'Time (ms)':>14}"
             f"{'Min (ms)':>12}{'Max (ms)':>12}{'Avg (ms)':>12}",
             "-" * 102]
    with _agg_lock:
        rows = sorted(_agg.items(), key=lambda kv: -kv[1][1])
    if top_k and top_k > 0:
        dropped = len(rows) - top_k
        rows = rows[:top_k]
    else:
        dropped = 0
    for name, (count, total, mn, mx) in rows:
        lines.append(f"{name[:39]:<40}{count:>12}{total / 1e3:>14.4f}"
                     f"{mn / 1e3:>12.4f}{mx / 1e3:>12.4f}"
                     f"{total / count / 1e3:>12.4f}")
    if dropped > 0:
        lines.append(f"... {dropped} more name(s) below the top-{top_k} "
                     f"cut (MXNET_PROFILER_TOPK)")
    return "\n".join(lines)


def get_summary(reset=False, top_k: Optional[int] = None) -> str:
    """ref: MXAggregateProfileStatsPrint — always the aggregate table,
    sorted by total time; ``top_k`` (default MXNET_PROFILER_TOPK, 0 =
    all) bounds the row count."""
    out = _aggregate_table(top_k)
    if reset:
        with _agg_lock:
            _agg.clear()
    return out


def _send_server_command(head: str, body: str):
    """Route a profiler command to the parameter-server role (ref:
    kvstore_dist.h:99 SendCommandToServers)."""
    from . import kvstore_server as srv
    addr = srv.server_address()
    if addr is None:
        return  # no server in this job
    try:
        client = srv.KVClient(addr, retries=5)
        client.request(head, None, body)
        client._sock.close()
    except Exception:
        pass


class Scope:
    """Named profiling scope (ref: profiler.scope; also jax named scopes).

    ``domain`` tags the emitted event for the per-domain filter —
    user-level scopes default to the ``api`` domain (ref: the kAPI
    profiler mode bit)."""

    _current = threading.local()

    def __init__(self, name="<unk>:", domain="api"):
        self.name = name
        self.domain = domain

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        self._jctx = jax.profiler.TraceAnnotation(self.name)
        self._jctx.__enter__()
        return self

    def __exit__(self, *exc):
        self._jctx.__exit__(*exc)
        t1 = time.perf_counter_ns()
        if _active() and _domain_enabled(self.domain):
            dur_us = (t1 - self._t0) / 1000.0
            _append_event({
                "name": self.name, "ph": "X", "cat": self.domain,
                "pid": os.getpid(), "tid": threading.get_ident(),
                "ts": self._t0 / 1000.0, "dur": dur_us,
            })
            _agg_update(self.name, dur_us)


scope = Scope


class _Named:
    def __init__(self, name, domain=None):
        self.name = getattr(name, "name", name)
        self._domain = getattr(domain, "name", domain) or "api"

    def start(self):
        self._scope = Scope(self.name, domain=self._domain)
        self._scope.__enter__()

    def stop(self):
        self._scope.__exit__(None, None, None)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()


class Domain:
    def __init__(self, name):
        self.name = name

    def new_task(self, name):
        return Task(name, self)

    def new_frame(self, name):
        return Frame(name, self)

    def new_event(self, name):
        return Event(name, self)

    def new_marker(self, name):
        return Marker(name, self)


class Task(_Named):
    pass


class Frame(_Named):
    pass


class Event(_Named):
    pass


class Marker:
    def __init__(self, name, domain=None):
        self.name = name

    def mark(self, scope_name="process"):
        if _domain_enabled("api"):
            _append_event({"name": self.name, "ph": "i", "cat": "api",
                           "pid": os.getpid(),
                           "ts": time.perf_counter_ns() / 1000.0,
                           "s": scope_name[0]})


# MXNET_PROFILER_AUTOSTART / MXNET_PROFILER_MODE (ref: env_var.md): start
# profiling at import with the configured mode bitmask.
def _maybe_autostart():
    from .base import get_env
    if get_env("MXNET_PROFILER_AUTOSTART", False):
        mode = int(get_env("MXNET_PROFILER_MODE", 0))
        if mode:
            set_config(profile_all=True)
        set_state("run")


_maybe_autostart()
