"""Profiler: jax.profiler + chrome-trace export.

ref: src/profiler/profiler.h:251 + python/mxnet/profiler.py — the reference
emits chrome://tracing JSON per engine event. On TPU the deep trace comes
from jax.profiler (XProf/TensorBoard); this module keeps the reference's
control surface (set_config/set_state/dump, scoped ranges) and emits a
chrome-trace JSON of the Python-level scopes for parity.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import List, Optional

import jax

__all__ = ["set_config", "set_state", "dump", "dumps", "pause", "resume",
           "Scope", "scope", "Task", "Frame", "Event", "Marker"]

_state = threading.local()
_config = {"filename": "profile.json", "profile_all": False,
           "profile_symbolic": True, "profile_imperative": True,
           "profile_memory": True, "profile_api": True,
           "aggregate_stats": False}
_events: List[dict] = []
_running = False
_jax_dir: Optional[str] = None


def set_config(**kwargs):
    """ref: python/mxnet/profiler.py set_config / MXSetProcessProfilerConfig"""
    _config.update(kwargs)


def set_state(state="stop", profile_process="worker"):
    global _running, _jax_dir
    if state == "run" and not _running:
        _running = True
        _jax_dir = os.path.splitext(_config["filename"])[0] + "_xprof"
        try:
            jax.profiler.start_trace(_jax_dir)
        except Exception:
            _jax_dir = None
    elif state == "stop" and _running:
        _running = False
        if _jax_dir:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass


def pause(profile_process="worker"):
    pass


def resume(profile_process="worker"):
    pass


def is_running() -> bool:
    return _running


def dumps(reset=False) -> str:
    out = json.dumps({"traceEvents": list(_events)}, indent=1)
    if reset:
        _events.clear()
    return out


def dump(finished=True, profile_process="worker"):
    with open(_config["filename"], "w") as f:
        f.write(dumps())


class Scope:
    """Named profiling scope (ref: profiler.scope; also jax named scopes)."""

    _current = threading.local()

    def __init__(self, name="<unk>:"):
        self.name = name

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        self._jctx = jax.profiler.TraceAnnotation(self.name)
        self._jctx.__enter__()
        return self

    def __exit__(self, *exc):
        self._jctx.__exit__(*exc)
        t1 = time.perf_counter_ns()
        if _running:
            _events.append({
                "name": self.name, "ph": "X", "pid": os.getpid(),
                "tid": threading.get_ident(),
                "ts": self._t0 / 1000.0, "dur": (t1 - self._t0) / 1000.0,
            })


scope = Scope


class _Named:
    def __init__(self, name, domain=None):
        self.name = getattr(name, "name", name)

    def start(self):
        self._scope = Scope(self.name)
        self._scope.__enter__()

    def stop(self):
        self._scope.__exit__(None, None, None)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()


class Domain:
    def __init__(self, name):
        self.name = name

    def new_task(self, name):
        return Task(name, self)

    def new_frame(self, name):
        return Frame(name, self)

    def new_event(self, name):
        return Event(name, self)

    def new_marker(self, name):
        return Marker(name, self)


class Task(_Named):
    pass


class Frame(_Named):
    pass


class Event(_Named):
    pass


class Marker:
    def __init__(self, name, domain=None):
        self.name = name

    def mark(self, scope_name="process"):
        if _running:
            _events.append({"name": self.name, "ph": "i", "pid": os.getpid(),
                            "ts": time.perf_counter_ns() / 1000.0,
                            "s": scope_name[0]})


# MXNET_PROFILER_AUTOSTART / MXNET_PROFILER_MODE (ref: env_var.md): start
# profiling at import with the configured mode bitmask.
def _maybe_autostart():
    from .base import get_env
    if get_env("MXNET_PROFILER_AUTOSTART", False):
        mode = int(get_env("MXNET_PROFILER_MODE", 0))
        if mode:
            set_config(profile_all=True)
        set_state("run")


_maybe_autostart()
