"""Profiler: jax.profiler + chrome-trace export.

ref: src/profiler/profiler.h:251 + python/mxnet/profiler.py — the reference
emits chrome://tracing JSON per engine event. On TPU the deep trace comes
from jax.profiler (XProf/TensorBoard); this module keeps the reference's
control surface (set_config/set_state/dump, scoped ranges) and emits a
chrome-trace JSON of the Python-level scopes for parity.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import List, Optional

import jax

__all__ = ["set_config", "set_state", "dump", "dumps", "pause", "resume",
           "Scope", "scope", "Task", "Frame", "Event", "Marker"]

_state = threading.local()
_config = {"filename": "profile.json", "profile_all": False,
           "profile_symbolic": True, "profile_imperative": True,
           "profile_memory": True, "profile_api": True,
           "aggregate_stats": False}
_events: List[dict] = []
_running = False
_jax_dir: Optional[str] = None


def set_config(**kwargs):
    """ref: python/mxnet/profiler.py set_config / MXSetProcessProfilerConfig"""
    _config.update(kwargs)


def set_state(state="stop", profile_process="worker"):
    global _running, _jax_dir
    if profile_process == "server":
        # remote/server profiling: command the parameter server (ref:
        # kvstore_dist.h:99 kSetProfilerParams;
        # tests/nightly/test_server_profiling.py)
        _send_server_command("profiler_state", state)
        return
    if state == "run" and not _running:
        _running = True
        _jax_dir = os.path.splitext(_config["filename"])[0] + "_xprof"
        try:
            jax.profiler.start_trace(_jax_dir)
        except Exception:
            _jax_dir = None
    elif state == "stop" and _running:
        _running = False
        if _jax_dir:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass


def pause(profile_process="worker"):
    pass


def resume(profile_process="worker"):
    pass


def is_running() -> bool:
    return _running


def dumps(reset=False) -> str:
    """Chrome-trace JSON, or the aggregate statistics table when
    aggregate_stats is configured (ref: src/profiler/aggregate_stats.cc
    DumpTable via MXAggregateProfileStatsPrint)."""
    if _config.get("aggregate_stats"):
        out = _aggregate_table()
    else:
        out = json.dumps({"traceEvents": list(_events)}, indent=1)
    if reset:
        _events.clear()
        _agg.clear()
    return out


def dump(finished=True, profile_process="worker"):
    if profile_process == "server":
        _send_server_command("profiler_dump", "")
        return
    with open(_config["filename"], "w") as f:
        f.write(json.dumps({"traceEvents": list(_events)}, indent=1))


# -- aggregate stats (ref: profiler.h:327-331 + aggregate_stats.cc) ---------

_agg: dict = {}
_agg_lock = threading.Lock()


def _agg_update(name: str, dur_us: float):
    with _agg_lock:
        ent = _agg.get(name)
        if ent is None:
            _agg[name] = [1, dur_us, dur_us, dur_us]
        else:
            ent[0] += 1
            ent[1] += dur_us
            ent[2] = min(ent[2], dur_us)
            ent[3] = max(ent[3], dur_us)


def _aggregate_table() -> str:
    lines = ["Profile Statistics:",
             f"{'Name':<40}{'Total Count':>12}{'Time (ms)':>14}"
             f"{'Min (ms)':>12}{'Max (ms)':>12}{'Avg (ms)':>12}",
             "-" * 102]
    with _agg_lock:
        rows = sorted(_agg.items(), key=lambda kv: -kv[1][1])
    for name, (count, total, mn, mx) in rows:
        lines.append(f"{name[:39]:<40}{count:>12}{total / 1e3:>14.4f}"
                     f"{mn / 1e3:>12.4f}{mx / 1e3:>12.4f}"
                     f"{total / count / 1e3:>12.4f}")
    return "\n".join(lines)


def get_summary(reset=False) -> str:
    """ref: MXAggregateProfileStatsPrint — always the aggregate table."""
    out = _aggregate_table()
    if reset:
        with _agg_lock:
            _agg.clear()
    return out


def _send_server_command(head: str, body: str):
    """Route a profiler command to the parameter-server role (ref:
    kvstore_dist.h:99 SendCommandToServers)."""
    from . import kvstore_server as srv
    addr = srv.server_address()
    if addr is None:
        return  # no server in this job
    try:
        client = srv.KVClient(addr, retries=5)
        client.request(head, None, body)
        client._sock.close()
    except Exception:
        pass


class Scope:
    """Named profiling scope (ref: profiler.scope; also jax named scopes)."""

    _current = threading.local()

    def __init__(self, name="<unk>:"):
        self.name = name

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        self._jctx = jax.profiler.TraceAnnotation(self.name)
        self._jctx.__enter__()
        return self

    def __exit__(self, *exc):
        self._jctx.__exit__(*exc)
        t1 = time.perf_counter_ns()
        if _running:
            dur_us = (t1 - self._t0) / 1000.0
            _events.append({
                "name": self.name, "ph": "X", "pid": os.getpid(),
                "tid": threading.get_ident(),
                "ts": self._t0 / 1000.0, "dur": dur_us,
            })
            _agg_update(self.name, dur_us)


scope = Scope


class _Named:
    def __init__(self, name, domain=None):
        self.name = getattr(name, "name", name)

    def start(self):
        self._scope = Scope(self.name)
        self._scope.__enter__()

    def stop(self):
        self._scope.__exit__(None, None, None)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()


class Domain:
    def __init__(self, name):
        self.name = name

    def new_task(self, name):
        return Task(name, self)

    def new_frame(self, name):
        return Frame(name, self)

    def new_event(self, name):
        return Event(name, self)

    def new_marker(self, name):
        return Marker(name, self)


class Task(_Named):
    pass


class Frame(_Named):
    pass


class Event(_Named):
    pass


class Marker:
    def __init__(self, name, domain=None):
        self.name = name

    def mark(self, scope_name="process"):
        if _running:
            _events.append({"name": self.name, "ph": "i", "pid": os.getpid(),
                            "ts": time.perf_counter_ns() / 1000.0,
                            "s": scope_name[0]})


# MXNET_PROFILER_AUTOSTART / MXNET_PROFILER_MODE (ref: env_var.md): start
# profiling at import with the configured mode bitmask.
def _maybe_autostart():
    from .base import get_env
    if get_env("MXNET_PROFILER_AUTOSTART", False):
        mode = int(get_env("MXNET_PROFILER_MODE", 0))
        if mode:
            set_config(profile_all=True)
        set_state("run")


_maybe_autostart()
