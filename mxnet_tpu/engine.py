"""Execution-engine facade.

The reference's dependency engine (ref: src/engine/ — ThreadedEnginePerDevice
with per-var read/write queues, include/mxnet/engine.h:117) exists to overlap
async op execution with Python; on TPU, PJRT's async dispatch + XLA's data-flow
ordering provide the same guarantees by construction (SURVEY.md §5.2: "XLA
removes intra-graph races by construction"). This module keeps the *control*
surface: engine-type selection (Naive = synchronous debugging mode, ref:
MXNET_ENGINE_TYPE in src/engine/engine.cc:32-56), bulking knobs, and the
WaitForAll / exception-surfacing entry points.
"""
from __future__ import annotations

import contextlib
import threading

import jax

from .base import get_env

__all__ = ["set_bulk_size", "bulk", "is_sync", "eager_sync",
           "wait_for_all", "set_engine_type"]

_state = threading.local()


def _engine_type() -> str:
    return getattr(_state, "engine_type",
                   get_env("MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice"))


def set_engine_type(name: str):
    """'NaiveEngine' forces synchronous dispatch for debugging
    (ref: docs/faq/env_var.md:110-114)."""
    _state.engine_type = name


_SYNC_CACHE = [-1, False]  # [config generation, value]


def is_sync() -> bool:
    """Called on every eager op dispatch — cached against the config
    generation so the common (off) case is two attribute reads, not an
    env lookup. MXNET_ENFORCE_DETERMINISM forces the deterministic
    synchronous dispatch order (the TPU reinterpretation of refusing
    non-deterministic kernels, docs/faq/env_var.md)."""
    et = getattr(_state, "engine_type", None)
    if et is not None:
        return et == "NaiveEngine" \
            or get_env("MXNET_ENFORCE_DETERMINISM", False)
    from . import config as _config
    gen = _config.generation()
    if _SYNC_CACHE[0] != gen:
        _SYNC_CACHE[1] = (
            get_env("MXNET_ENGINE_TYPE",
                    "ThreadedEnginePerDevice") == "NaiveEngine"
            or get_env("MXNET_ENFORCE_DETERMINISM", False))
        _SYNC_CACHE[0] = gen
    return _SYNC_CACHE[1]


def maybe_sync(arr):
    """Called by the nd layer after each op when in NaiveEngine mode: blocks
    so exceptions surface at the op that raised them (ref: engine exception
    chains, src/engine/threaded_engine.h:64-65,387)."""
    if is_sync():
        jax.block_until_ready(arr)
    return arr


_EAGER_SYNC_CACHE = [-1, False]  # [config generation, value]


def eager_sync() -> bool:
    """Should the eager dispatch path block after every op?

    Default NO — PJRT pipelines eager chains asynchronously and XLA
    overlaps them (the per-op block was costing the eager mutation
    path its pipelining; ISSUE 5 satellite). Blocking is opt-in:

    - ``MXNET_EAGER_SYNC=1`` — explicit debugging knob;
    - profiler recording the ``imperative`` domain — per-op wall times
      are meaningless when the op only enqueued work;
    - NaiveEngine / MXNET_ENFORCE_DETERMINISM (``is_sync``) — the
      reference's synchronous dispatch contract.
    """
    if is_sync():
        return True
    from . import config as _config
    gen = _config.generation()
    if _EAGER_SYNC_CACHE[0] != gen:
        _EAGER_SYNC_CACHE[1] = get_env("MXNET_EAGER_SYNC", False)
        _EAGER_SYNC_CACHE[0] = gen
    if _EAGER_SYNC_CACHE[1]:
        return True
    from . import profiler as _prof
    return _prof._active() and _prof._domain_enabled("imperative")


_BULK_SIZE = get_env("MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN", 15)


def set_bulk_size(size: int) -> int:
    """ref: Engine::set_bulk_size (include/mxnet/engine.h:311-317). Bulking
    ≙ XLA fusion; the knob is kept for API parity and is advisory."""
    global _BULK_SIZE
    prev, _BULK_SIZE = _BULK_SIZE, size
    return prev


@contextlib.contextmanager
def bulk(size: int):
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(prev)


def wait_for_all():
    """ref: Engine::WaitForAll (include/mxnet/engine.h:234)."""
    try:
        jax.effects_barrier()
    except AttributeError:
        pass
