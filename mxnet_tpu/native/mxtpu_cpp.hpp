/*
 * C++ bindings for mxnet_tpu — header-only RAII layer over the C ABI.
 *
 * TPU-native analog of the reference's cpp-package
 * (ref: cpp-package/include/mxnet-cpp/ndarray.h, operator.h, symbol.h,
 * executor.h): NDArray / Operator / Symbol / Executor / Predictor
 * classes with automatic handle lifetime, exceptions instead of return
 * codes, and chainable imperative op invocation:
 *
 *   mxtpu::NDArray x({2, 6});
 *   auto out = mxtpu::Operator("FullyConnected")
 *                  .SetParam("num_hidden", 8)
 *                  .PushInput(x).PushInput(w).PushInput(b)
 *                  .Invoke();
 *
 * Link against libmxtpu_capi.so (built by mxnet_tpu.native.build_capi).
 * Every failure throws mxtpu::Error carrying MXGetLastError().
 */
#ifndef MXTPU_CPP_HPP_
#define MXTPU_CPP_HPP_

#include <cstdint>
#include <map>
#include <memory>
#include <numeric>
#include <sstream>
#include <stdexcept>
#include <string>
#include <utility>
#include <vector>

#include "mxtpu_predict.h"

namespace mxtpu {

class Error : public std::runtime_error {
 public:
  explicit Error(const std::string& what) : std::runtime_error(what) {}
};

inline void Check(int rc) {
  if (rc != 0) {
    const char* msg = MXGetLastError();
    throw Error(msg ? msg : "unknown mxtpu error");
  }
}

inline int Version() {
  int v = 0;
  Check(MXGetVersion(&v));
  return v;
}

inline std::vector<std::string> ListAllOpNames() {
  uint32_t n = 0;
  const char** names = nullptr;
  Check(MXListAllOpNames(&n, &names));
  return std::vector<std::string>(names, names + n);
}

/* Device placement (ref: cpp-package/include/mxnet-cpp/base.h DeviceType;
 * dev_type 1 = cpu, 2 = accelerator/tpu). */
struct Context {
  int dev_type;
  int dev_id;
  static Context cpu(int id = 0) { return {1, id}; }
  static Context tpu(int id = 0) { return {2, id}; }
  static Context gpu(int id = 0) { return {2, id}; }  // alias
};

/* ------------------------------------------------------------------ */

class NDArray {
 public:
  NDArray() = default;

  explicit NDArray(const std::vector<uint32_t>& shape,
                   const std::string& dtype = "float32") {
    NDArrayHandle h = nullptr;
    Check(MXNDArrayCreate(shape.data(),
                          static_cast<uint32_t>(shape.size()),
                          dtype.c_str(), &h));
    reset(h);
  }

  NDArray(const float* data, const std::vector<uint32_t>& shape) {
    uint64_t n = 1;
    for (uint32_t d : shape) n *= d;
    NDArrayHandle h = nullptr;
    Check(MXNDArrayCreateFromBytes(data, n * sizeof(float), shape.data(),
                                   static_cast<uint32_t>(shape.size()),
                                   "float32", &h));
    reset(h);
  }

  NDArray(const std::vector<float>& data,
          const std::vector<uint32_t>& shape)
      : NDArray(data.data(), shape) {}

  /* Adopt a handle returned by the C ABI (takes ownership). */
  static NDArray FromHandle(NDArrayHandle h) {
    NDArray a;
    a.reset(h);
    return a;
  }

  NDArrayHandle handle() const { return h_.get(); }
  bool defined() const { return static_cast<bool>(h_); }

  std::vector<uint32_t> Shape() const {
    uint32_t ndim = 0;
    const uint32_t* dims = nullptr;
    Check(MXNDArrayGetShape(h_.get(), &ndim, &dims));
    return std::vector<uint32_t>(dims, dims + ndim);
  }

  std::string DType() const {
    const char* s = nullptr;
    Check(MXNDArrayGetDType(h_.get(), &s));
    return s ? s : "";
  }

  uint64_t Size() const {
    auto shape = Shape();
    return std::accumulate(shape.begin(), shape.end(), uint64_t{1},
                           std::multiplies<uint64_t>());
  }

  /* Blocking device->host copy (ref: ndarray.h SyncCopyToCPU). */
  std::vector<float> CopyToHost() const {
    std::vector<float> out(Size());
    Check(MXNDArraySyncCopyToCPU(h_.get(), out.data(),
                                 out.size() * sizeof(float)));
    return out;
  }

  void CopyFromHost(const float* data, uint64_t count) {
    Check(MXNDArraySyncCopyFromCPU(h_.get(), data,
                                   count * sizeof(float)));
  }

  static void Save(const std::string& fname,
                   const std::map<std::string, NDArray>& arrays) {
    std::vector<NDArrayHandle> handles;
    std::vector<const char*> keys;
    for (const auto& kv : arrays) {
      keys.push_back(kv.first.c_str());
      handles.push_back(kv.second.handle());
    }
    Check(MXNDArraySave(fname.c_str(),
                        static_cast<uint32_t>(handles.size()),
                        handles.data(), keys.data()));
  }

  static std::map<std::string, NDArray> Load(const std::string& fname) {
    uint32_t n = 0, n_names = 0;
    NDArrayHandle* arrs = nullptr;
    const char** names = nullptr;
    Check(MXNDArrayLoad(fname.c_str(), &n, &arrs, &n_names, &names));
    std::map<std::string, NDArray> out;
    for (uint32_t i = 0; i < n; ++i) {
      std::string key = (i < n_names && names[i]) ? names[i]
                                                  : std::to_string(i);
      out.emplace(key, FromHandle(arrs[i]));
    }
    return out;
  }

  /* Views & metadata over the expanded ABI (ref:
   * cpp-package/include/mxnet-cpp/ndarray.h Slice/At/Reshape/
   * GetContext/WaitToRead). */
  NDArray Slice(uint32_t begin, uint32_t end) const {
    NDArrayHandle h = nullptr;
    Check(MXNDArraySlice(h_.get(), begin, end, &h));
    return FromHandle(h);
  }

  NDArray At(uint32_t idx) const {
    NDArrayHandle h = nullptr;
    Check(MXNDArrayAt(h_.get(), idx, &h));
    return FromHandle(h);
  }

  NDArray Reshape(const std::vector<int>& dims) const {
    NDArrayHandle h = nullptr;
    Check(MXNDArrayReshape(h_.get(), static_cast<int>(dims.size()),
                           dims.data(), &h));
    return FromHandle(h);
  }

  Context GetContext() const {
    int dev_type = 0, dev_id = 0;
    Check(MXNDArrayGetContext(h_.get(), &dev_type, &dev_id));
    return Context{dev_type, dev_id};
  }

  void WaitToRead() const { Check(MXNDArrayWaitToRead(h_.get())); }

  static void WaitAll() { Check(MXNDArrayWaitAll()); }

  /* Gradient buffer after autograd::Backward; !defined() if none. */
  NDArray Grad() const {
    NDArrayHandle h = nullptr;
    Check(MXNDArrayGetGrad(h_.get(), &h));
    NDArray g;
    if (h) g.reset(h);
    return g;
  }

 private:
  void reset(NDArrayHandle h) {
    h_ = std::shared_ptr<void>(h, [](void* p) {
      if (p) MXNDArrayFree(p);
    });
  }
  std::shared_ptr<void> h_;
};

/* ------------------------------------------------------------------ */

/* Stringified key/value params + c_str marshalling, shared by every
 * SetParam-style builder (Operator, DataIter). */
class ParamPack {
 public:
  template <typename T>
  void Set(const std::string& key, const T& value) {
    std::ostringstream os;
    os << value;
    keys_.push_back(key);
    vals_.push_back(os.str());
  }

  std::vector<const char*> KeyPtrs() const { return ptrs(keys_); }
  std::vector<const char*> ValPtrs() const { return ptrs(vals_); }
  uint32_t Size() const { return static_cast<uint32_t>(keys_.size()); }

 private:
  static std::vector<const char*> ptrs(const std::vector<std::string>& v) {
    std::vector<const char*> out;
    out.reserve(v.size());
    for (const auto& s : v) out.push_back(s.c_str());
    return out;
  }
  std::vector<std::string> keys_, vals_;
};

/* Chainable imperative op invocation
 * (ref: cpp-package/include/mxnet-cpp/operator.h Operator::SetParam/
 * PushInput/Invoke over MXImperativeInvokeEx). */
class Operator {
 public:
  explicit Operator(const std::string& op_name) : name_(op_name) {}

  template <typename T>
  Operator& SetParam(const std::string& key, const T& value) {
    params_.Set(key, value);
    return *this;
  }

  Operator& PushInput(const NDArray& nd) {
    inputs_.push_back(nd);
    return *this;
  }

  Operator& operator()(const NDArray& nd) { return PushInput(nd); }

  std::vector<NDArray> Invoke() {
    std::vector<NDArrayHandle> in;
    for (const auto& a : inputs_) in.push_back(a.handle());
    auto ks = params_.KeyPtrs();
    auto vs = params_.ValPtrs();
    int n_out = 0;
    NDArrayHandle* outs = nullptr;
    Check(MXImperativeInvoke(name_.c_str(),
                             static_cast<int>(in.size()), in.data(),
                             &n_out, &outs,
                             static_cast<int>(ks.size()), ks.data(),
                             vs.data()));
    std::vector<NDArray> result;
    result.reserve(static_cast<size_t>(n_out));
    for (int i = 0; i < n_out; ++i)
      result.push_back(NDArray::FromHandle(outs[i]));
    return result;
  }

 private:
  std::string name_;
  std::vector<NDArray> inputs_;
  ParamPack params_;
};

inline NDArray InvokeOne(Operator& op) { return op.Invoke().at(0); }

inline NDArray operator+(const NDArray& a, const NDArray& b) {
  return Operator("broadcast_add").PushInput(a).PushInput(b).Invoke().at(0);
}
inline NDArray operator-(const NDArray& a, const NDArray& b) {
  return Operator("broadcast_sub").PushInput(a).PushInput(b).Invoke().at(0);
}
inline NDArray operator*(const NDArray& a, const NDArray& b) {
  return Operator("broadcast_mul").PushInput(a).PushInput(b).Invoke().at(0);
}
inline NDArray operator/(const NDArray& a, const NDArray& b) {
  return Operator("broadcast_div").PushInput(a).PushInput(b).Invoke().at(0);
}

/* ------------------------------------------------------------------ */

class Symbol {
 public:
  Symbol() = default;

  static Symbol FromJSON(const std::string& json) {
    SymbolHandle h = nullptr;
    Check(MXSymbolCreateFromJSON(json.c_str(), &h));
    Symbol s;
    s.reset(h);
    return s;
  }

  SymbolHandle handle() const { return h_.get(); }
  bool defined() const { return static_cast<bool>(h_); }

  std::string ToJSON() const {
    const char* s = nullptr;
    Check(MXSymbolSaveToJSON(h_.get(), &s));
    return s ? s : "";
  }

  std::vector<std::string> ListArguments() const {
    return list(&MXSymbolListArguments);
  }
  std::vector<std::string> ListOutputs() const {
    return list(&MXSymbolListOutputs);
  }
  std::vector<std::string> ListAuxiliaryStates() const {
    return list(&MXSymbolListAuxiliaryStates);
  }

 private:
  using ListFn = int (*)(SymbolHandle, uint32_t*, const char***);
  std::vector<std::string> list(ListFn fn) const {
    uint32_t n = 0;
    const char** arr = nullptr;
    Check(fn(h_.get(), &n, &arr));
    return std::vector<std::string>(arr, arr + n);
  }
  void reset(SymbolHandle h) {
    h_ = std::shared_ptr<void>(h, [](void* p) {
      if (p) MXSymbolFree(p);
    });
  }
  std::shared_ptr<void> h_;
};

/* ------------------------------------------------------------------ */

/* Bound computation graph (ref: cpp-package/include/mxnet-cpp/executor.h;
 * args are NDArrays in ListArguments() order; grad_req "write" enables
 * Backward()). The arg NDArrays stay owned by the caller. */
class Executor {
 public:
  Executor(const Symbol& sym, const Context& ctx,
           const std::vector<NDArray>& args,
           const std::string& grad_req = "null")
      : args_(args) {
    std::vector<NDArrayHandle> hs;
    for (const auto& a : args_) hs.push_back(a.handle());
    ExecutorHandle h = nullptr;
    Check(MXExecutorBind(sym.handle(), ctx.dev_type, ctx.dev_id,
                         static_cast<uint32_t>(hs.size()), hs.data(),
                         grad_req.c_str(), &h));
    h_ = std::shared_ptr<void>(h, [](void* p) {
      if (p) MXExecutorFree(p);
    });
  }

  std::vector<NDArray> Forward(bool is_train = false) {
    uint32_t n = 0;
    NDArrayHandle* outs = nullptr;
    Check(MXExecutorForward(h_.get(), is_train ? 1 : 0, &n, &outs));
    std::vector<NDArray> result;
    for (uint32_t i = 0; i < n; ++i)
      result.push_back(NDArray::FromHandle(outs[i]));
    return result;
  }

  /* One gradient per argument, in ListArguments() order; arguments
   * without a gradient come back !defined() so positions never shift. */
  std::vector<NDArray> Backward() {
    uint32_t n = 0;
    NDArrayHandle* grads = nullptr;
    Check(MXExecutorBackward(h_.get(), &n, &grads));
    std::vector<NDArray> result;
    for (uint32_t i = 0; i < n; ++i)
      result.push_back(NDArray::FromHandle(grads[i]));
    return result;
  }

 private:
  std::vector<NDArray> args_;  // keep arg handles alive over the bind
  std::shared_ptr<void> h_;
};

/* ------------------------------------------------------------------ */

/* Deployment predictor (ref: c_predict_api.h consumer pattern:
 * Create -> GetOutputShape -> SetInput -> Forward -> GetOutput). */
class Predictor {
 public:
  Predictor(const std::string& symbol_json, const std::string& param_bytes,
            const Context& ctx,
            const std::map<std::string, std::vector<uint32_t>>& input_shapes) {
    std::vector<const char*> keys;
    std::vector<uint32_t> indptr{0};
    std::vector<uint32_t> shape_data;
    for (const auto& kv : input_shapes) {
      keys.push_back(kv.first.c_str());
      shape_data.insert(shape_data.end(), kv.second.begin(),
                        kv.second.end());
      indptr.push_back(static_cast<uint32_t>(shape_data.size()));
    }
    PredictorHandle h = nullptr;
    Check(MXPredCreate(symbol_json.c_str(), param_bytes.data(),
                       static_cast<int>(param_bytes.size()), ctx.dev_type,
                       ctx.dev_id, static_cast<uint32_t>(keys.size()),
                       keys.data(), indptr.data(), shape_data.data(), &h));
    h_ = std::shared_ptr<void>(h, [](void* p) {
      if (p) MXPredFree(p);
    });
  }

  uint32_t OutputCount() const {
    uint32_t n = 0;
    Check(MXPredGetOutputCount(h_.get(), &n));
    return n;
  }

  std::vector<uint32_t> OutputShape(uint32_t index) const {
    uint32_t* dims = nullptr;
    uint32_t ndim = 0;
    Check(MXPredGetOutputShape(h_.get(), index, &dims, &ndim));
    return std::vector<uint32_t>(dims, dims + ndim);
  }

  void SetInput(const std::string& key, const std::vector<float>& data) {
    Check(MXPredSetInput(h_.get(), key.c_str(), data.data(),
                         static_cast<uint32_t>(data.size())));
  }

  void Forward() { Check(MXPredForward(h_.get())); }

  std::vector<float> GetOutput(uint32_t index) const {
    auto shape = OutputShape(index);
    uint64_t n = std::accumulate(shape.begin(), shape.end(), uint64_t{1},
                                 std::multiplies<uint64_t>());
    std::vector<float> out(n);
    Check(MXPredGetOutput(h_.get(), index, out.data(),
                          static_cast<uint32_t>(n)));
    return out;
  }

 private:
  std::shared_ptr<void> h_;
};

/* ------------------------------------------------------------------ */

/* Autograd over the expanded ABI (ref: cpp-package has no autograd;
 * this mirrors python/mxnet/autograd.py record()/mark_variables()/
 * backward() so C++ consumers can train imperatively). */
namespace autograd {

/* RAII recording scope: `{ autograd::RecordScope rec; ... }` */
class RecordScope {
 public:
  explicit RecordScope(bool train_mode = true) {
    Check(MXAutogradSetIsRecording(1, &prev_rec_));
    try {
      Check(MXAutogradSetIsTraining(train_mode ? 1 : 0, &prev_train_));
    } catch (...) {
      // half-constructed scope: the destructor won't run, so restore
      // the recording flag here or it stays enabled process-wide
      int ignore = 0;
      MXAutogradSetIsRecording(prev_rec_, &ignore);
      throw;
    }
  }
  ~RecordScope() {
    int ignore = 0;
    MXAutogradSetIsRecording(prev_rec_, &ignore);
    MXAutogradSetIsTraining(prev_train_, &ignore);
  }
  RecordScope(const RecordScope&) = delete;
  RecordScope& operator=(const RecordScope&) = delete;

 private:
  int prev_rec_ = 0;
  int prev_train_ = 0;
};

/* grad_req: 1 = write, 2 = add (0 = null needs no grad buffer). */
inline void MarkVariable(const NDArray& var, const NDArray& grad,
                         uint32_t grad_req = 1) {
  NDArrayHandle vh = var.handle(), gh = grad.handle();
  Check(MXAutogradMarkVariables(1, &vh, &grad_req, &gh));
}

inline void Backward(const std::vector<NDArray>& outputs,
                     bool retain_graph = false, bool train_mode = true) {
  std::vector<NDArrayHandle> hs;
  for (const auto& o : outputs) hs.push_back(o.handle());
  Check(MXAutogradBackward(static_cast<uint32_t>(hs.size()), hs.data(),
                           nullptr, retain_graph ? 1 : 0,
                           train_mode ? 1 : 0));
}

}  // namespace autograd

/* ------------------------------------------------------------------ */

/* Distributed key-value store (ref: cpp-package/include/mxnet-cpp/
 * kvstore.h over MXKVStore*; types "local"/"device"/"dist_sync"/
 * "dist_async"). */
class KVStore {
 public:
  explicit KVStore(const std::string& type = "local") {
    KVStoreHandle h = nullptr;
    Check(MXKVStoreCreate(type.c_str(), &h));
    h_ = std::shared_ptr<void>(h, [](void* p) {
      if (p) MXKVStoreFree(p);
    });
  }

  void Init(const std::string& key, const NDArray& val) {
    const char* k = key.c_str();
    NDArrayHandle v = val.handle();
    Check(MXKVStoreInit(h_.get(), 1, &k, &v));
  }

  void Push(const std::string& key, const NDArray& val, int priority = 0) {
    const char* k = key.c_str();
    NDArrayHandle v = val.handle();
    Check(MXKVStorePush(h_.get(), 1, &k, &v, priority));
  }

  void Pull(const std::string& key, NDArray* out, int priority = 0) {
    const char* k = key.c_str();
    NDArrayHandle v = out->handle();
    Check(MXKVStorePull(h_.get(), 1, &k, &v, priority));
  }

  int GetRank() const {
    int rank = 0;
    Check(MXKVStoreGetRank(h_.get(), &rank));
    return rank;
  }

  int GetNumWorkers() const {
    int n = 0;
    Check(MXKVStoreGetGroupSize(h_.get(), &n));
    return n;
  }

  std::string GetType() const {
    const char* t = nullptr;
    Check(MXKVStoreGetType(h_.get(), &t));
    return t ? t : "";
  }

  void Barrier() { Check(MXKVStoreBarrier(h_.get())); }

 private:
  std::shared_ptr<void> h_;
};

/* ------------------------------------------------------------------ */

/* File-based data iterator (ref: cpp-package/include/mxnet-cpp/io.h
 * MXDataIter::SetParam/CreateDataIter over MXDataIter*). */
class DataIter {
 public:
  explicit DataIter(const std::string& name) : name_(name) {}

  template <typename T>
  DataIter& SetParam(const std::string& key, const T& value) {
    params_.Set(key, value);
    return *this;
  }

  /* Materialize the iterator; params are fixed from here on. */
  void Create() {
    auto ks = params_.KeyPtrs();
    auto vs = params_.ValPtrs();
    DataIterHandle h = nullptr;
    Check(MXDataIterCreateIter(name_.c_str(), params_.Size(),
                               ks.data(), vs.data(), &h));
    h_ = std::shared_ptr<void>(h, [](void* p) {
      if (p) MXDataIterFree(p);
    });
  }

  bool Next() {
    int more = 0;
    Check(MXDataIterNext(h_.get(), &more));
    return more != 0;
  }

  void Reset() { Check(MXDataIterBeforeFirst(h_.get())); }

  NDArray GetData() {
    NDArrayHandle h = nullptr;
    Check(MXDataIterGetData(h_.get(), &h));
    return NDArray::FromHandle(h);
  }

  NDArray GetLabel() {
    NDArrayHandle h = nullptr;
    Check(MXDataIterGetLabel(h_.get(), &h));
    return NDArray::FromHandle(h);
  }

  static std::vector<std::string> List() {
    uint32_t n = 0;
    const char** names = nullptr;
    Check(MXListDataIters(&n, &names));
    std::vector<std::string> out;
    for (uint32_t i = 0; i < n; ++i) out.emplace_back(names[i]);
    return out;
  }

 private:
  std::string name_;
  ParamPack params_;
  std::shared_ptr<void> h_;
};

}  // namespace mxtpu

#endif  // MXTPU_CPP_HPP_
