/*
 * C predict API for mxnet_tpu.
 *
 * Drop-in subset of the reference's standalone inference ABI
 * (ref: include/mxnet/c_predict_api.h — MXPredCreate/MXPredSetInput/
 * MXPredForward/MXPredGetOutputShape/MXPredGetOutput/MXPredFree, and
 * include/mxnet/c_api.h MXGetVersion/MXGetLastError/MXListAllOpNames).
 * The implementation (c_predict_api.cc) embeds CPython and executes the
 * jax/XLA graph through mxnet_tpu.c_api_backend; callers link only
 * against this C ABI, exactly like a reference deployment.
 *
 * All functions return 0 on success, -1 on failure (then consult
 * MXGetLastError).
 */
#ifndef MXTPU_PREDICT_H_
#define MXTPU_PREDICT_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void *PredictorHandle;

/* Library-wide */
int MXGetVersion(int *out);
const char *MXGetLastError(void);
int MXListAllOpNames(uint32_t *out_size, const char ***out_array);

/* Predictor lifecycle (ref: c_predict_api.h MXPredCreate):
 *   symbol_json_str  – symbol graph as JSON (Symbol.tojson / file)
 *   param_bytes/size – serialized parameters (nd.save format, the
 *                      "<prefix>-0000.params" checkpoint file contents)
 *   dev_type         – 1 = cpu, 2 = accelerator (tpu)
 *   num_input_nodes / input_keys / input_shape_indptr / input_shape_data
 *                    – CSR-packed input shapes, as in the reference
 */
int MXPredCreate(const char *symbol_json_str, const void *param_bytes,
                 int param_size, int dev_type, int dev_id,
                 uint32_t num_input_nodes, const char **input_keys,
                 const uint32_t *input_shape_indptr,
                 const uint32_t *input_shape_data, PredictorHandle *out);

/* As MXPredCreate but keeping only the listed outputs
 * (ref: c_predict_api.h MXPredCreatePartialOut). */
int MXPredCreatePartialOut(const char *symbol_json_str,
                           const void *param_bytes, int param_size,
                           int dev_type, int dev_id,
                           uint32_t num_input_nodes, const char **input_keys,
                           const uint32_t *input_shape_indptr,
                           const uint32_t *input_shape_data,
                           uint32_t num_output_nodes,
                           const char **output_keys, PredictorHandle *out);

int MXPredGetOutputCount(PredictorHandle handle, uint32_t *out);
int MXPredSetInput(PredictorHandle handle, const char *key,
                   const float *data, uint32_t size);
int MXPredForward(PredictorHandle handle);
int MXPredGetOutputShape(PredictorHandle handle, uint32_t index,
                         uint32_t **shape_data, uint32_t *shape_ndim);
int MXPredGetOutput(PredictorHandle handle, uint32_t index, float *data,
                    uint32_t size);
int MXPredFree(PredictorHandle handle);


/* ------------------------------------------------------------------------
 * General MX* ABI subset (ref: include/mxnet/c_api.h): NDArray / Symbol /
 * Executor handles + imperative invoke. Handles are opaque ids owned by
 * the embedded runtime; every function returns 0 on success, -1 on error
 * (message via MXGetLastError).
 * --------------------------------------------------------------------- */

typedef void *NDArrayHandle;
typedef void *SymbolHandle;
typedef void *ExecutorHandle;

int MXNDArrayCreate(const uint32_t *shape, uint32_t ndim,
                              const char *dtype, NDArrayHandle *out);
int MXNDArrayCreateFromBytes(const void *data, uint64_t nbytes,
                                       const uint32_t *shape, uint32_t ndim,
                                       const char *dtype, NDArrayHandle *out);
int MXNDArrayFree(NDArrayHandle handle);
int MXNDArrayGetShape(NDArrayHandle handle, uint32_t *out_dim,
                                const uint32_t **out_pdata);
int MXNDArrayGetDType(NDArrayHandle handle, const char **out);
int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void *data,
                                     uint64_t size);
int MXNDArraySyncCopyFromCPU(NDArrayHandle handle,
                                       const void *data, uint64_t size);
int MXNDArraySave(const char *fname, uint32_t num,
                            NDArrayHandle *handles, const char **keys);
int MXNDArrayLoad(const char *fname, uint32_t *out_size,
                            NDArrayHandle **out_arr,
                            uint32_t *out_name_size,
                            const char ***out_names);
int MXImperativeInvoke(const char *op_name, int num_inputs,
                                 NDArrayHandle *inputs, int *num_outputs,
                                 NDArrayHandle **outputs, int num_params,
                                 const char **param_keys,
                                 const char **param_vals);
int MXSymbolCreateFromJSON(const char *json, SymbolHandle *out);
int MXSymbolSaveToJSON(SymbolHandle handle, const char **out_json);
int MXSymbolListArguments(SymbolHandle handle, uint32_t *out_size,
                                    const char ***out_arr);
int MXSymbolListOutputs(SymbolHandle handle, uint32_t *out_size,
                                  const char ***out_arr);
int MXSymbolListAuxiliaryStates(SymbolHandle handle,
                                          uint32_t *out_size,
                                          const char ***out_arr);
int MXSymbolFree(SymbolHandle handle);
int MXExecutorBind(SymbolHandle sym, int dev_type, int dev_id,
                   uint32_t num_args, NDArrayHandle *args,
                   const char *grad_req, ExecutorHandle *out);
int MXExecutorBackward(ExecutorHandle handle, uint32_t *out_size,
                       NDArrayHandle **grads);
int MXExecutorForward(ExecutorHandle handle, int is_train,
                                uint32_t *out_size, NDArrayHandle **outputs);
int MXExecutorFree(ExecutorHandle handle);

#ifdef __cplusplus
} /* extern "C" */
#endif

#endif /* MXTPU_PREDICT_H_ */
