/*
 * C predict API for mxnet_tpu.
 *
 * Drop-in subset of the reference's standalone inference ABI
 * (ref: include/mxnet/c_predict_api.h — MXPredCreate/MXPredSetInput/
 * MXPredForward/MXPredGetOutputShape/MXPredGetOutput/MXPredFree, and
 * include/mxnet/c_api.h MXGetVersion/MXGetLastError/MXListAllOpNames).
 * The implementation (c_predict_api.cc) embeds CPython and executes the
 * jax/XLA graph through mxnet_tpu.c_api_backend; callers link only
 * against this C ABI, exactly like a reference deployment.
 *
 * All functions return 0 on success, -1 on failure (then consult
 * MXGetLastError).
 */
#ifndef MXTPU_PREDICT_H_
#define MXTPU_PREDICT_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void *PredictorHandle;

/* Library-wide */
int MXGetVersion(int *out);
const char *MXGetLastError(void);
int MXListAllOpNames(uint32_t *out_size, const char ***out_array);

/* Predictor lifecycle (ref: c_predict_api.h MXPredCreate):
 *   symbol_json_str  – symbol graph as JSON (Symbol.tojson / file)
 *   param_bytes/size – serialized parameters (nd.save format, the
 *                      "<prefix>-0000.params" checkpoint file contents)
 *   dev_type         – 1 = cpu, 2 = accelerator (tpu)
 *   num_input_nodes / input_keys / input_shape_indptr / input_shape_data
 *                    – CSR-packed input shapes, as in the reference
 */
int MXPredCreate(const char *symbol_json_str, const void *param_bytes,
                 int param_size, int dev_type, int dev_id,
                 uint32_t num_input_nodes, const char **input_keys,
                 const uint32_t *input_shape_indptr,
                 const uint32_t *input_shape_data, PredictorHandle *out);

/* As MXPredCreate but keeping only the listed outputs
 * (ref: c_predict_api.h MXPredCreatePartialOut). */
int MXPredCreatePartialOut(const char *symbol_json_str,
                           const void *param_bytes, int param_size,
                           int dev_type, int dev_id,
                           uint32_t num_input_nodes, const char **input_keys,
                           const uint32_t *input_shape_indptr,
                           const uint32_t *input_shape_data,
                           uint32_t num_output_nodes,
                           const char **output_keys, PredictorHandle *out);

int MXPredGetOutputCount(PredictorHandle handle, uint32_t *out);
int MXPredSetInput(PredictorHandle handle, const char *key,
                   const float *data, uint32_t size);
int MXPredForward(PredictorHandle handle);
int MXPredGetOutputShape(PredictorHandle handle, uint32_t index,
                         uint32_t **shape_data, uint32_t *shape_ndim);
int MXPredGetOutput(PredictorHandle handle, uint32_t index, float *data,
                    uint32_t size);
int MXPredFree(PredictorHandle handle);

#ifdef __cplusplus
} /* extern "C" */
#endif

#endif /* MXTPU_PREDICT_H_ */
