/*
 * C predict API for mxnet_tpu.
 *
 * Drop-in subset of the reference's standalone inference ABI
 * (ref: include/mxnet/c_predict_api.h — MXPredCreate/MXPredSetInput/
 * MXPredForward/MXPredGetOutputShape/MXPredGetOutput/MXPredFree, and
 * include/mxnet/c_api.h MXGetVersion/MXGetLastError/MXListAllOpNames).
 * The implementation (c_predict_api.cc) embeds CPython and executes the
 * jax/XLA graph through mxnet_tpu.c_api_backend; callers link only
 * against this C ABI, exactly like a reference deployment.
 *
 * All functions return 0 on success, -1 on failure (then consult
 * MXGetLastError).
 */
#ifndef MXTPU_PREDICT_H_
#define MXTPU_PREDICT_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void *PredictorHandle;

/* Library-wide */
int MXGetVersion(int *out);
const char *MXGetLastError(void);
int MXListAllOpNames(uint32_t *out_size, const char ***out_array);

/* Predictor lifecycle (ref: c_predict_api.h MXPredCreate):
 *   symbol_json_str  – symbol graph as JSON (Symbol.tojson / file)
 *   param_bytes/size – serialized parameters (nd.save format, the
 *                      "<prefix>-0000.params" checkpoint file contents)
 *   dev_type         – 1 = cpu, 2 = accelerator (tpu)
 *   num_input_nodes / input_keys / input_shape_indptr / input_shape_data
 *                    – CSR-packed input shapes, as in the reference
 */
int MXPredCreate(const char *symbol_json_str, const void *param_bytes,
                 int param_size, int dev_type, int dev_id,
                 uint32_t num_input_nodes, const char **input_keys,
                 const uint32_t *input_shape_indptr,
                 const uint32_t *input_shape_data, PredictorHandle *out);

/* As MXPredCreate but keeping only the listed outputs
 * (ref: c_predict_api.h MXPredCreatePartialOut). */
int MXPredCreatePartialOut(const char *symbol_json_str,
                           const void *param_bytes, int param_size,
                           int dev_type, int dev_id,
                           uint32_t num_input_nodes, const char **input_keys,
                           const uint32_t *input_shape_indptr,
                           const uint32_t *input_shape_data,
                           uint32_t num_output_nodes,
                           const char **output_keys, PredictorHandle *out);

int MXPredGetOutputCount(PredictorHandle handle, uint32_t *out);
int MXPredSetInput(PredictorHandle handle, const char *key,
                   const float *data, uint32_t size);
int MXPredForward(PredictorHandle handle);
int MXPredGetOutputShape(PredictorHandle handle, uint32_t index,
                         uint32_t **shape_data, uint32_t *shape_ndim);
int MXPredGetOutput(PredictorHandle handle, uint32_t index, float *data,
                    uint32_t size);
int MXPredFree(PredictorHandle handle);


/* ------------------------------------------------------------------------
 * General MX* ABI subset (ref: include/mxnet/c_api.h): NDArray / Symbol /
 * Executor handles + imperative invoke. Handles are opaque ids owned by
 * the embedded runtime; every function returns 0 on success, -1 on error
 * (message via MXGetLastError).
 * --------------------------------------------------------------------- */

typedef void *NDArrayHandle;
typedef void *SymbolHandle;
typedef void *ExecutorHandle;

int MXNDArrayCreate(const uint32_t *shape, uint32_t ndim,
                              const char *dtype, NDArrayHandle *out);
int MXNDArrayCreateFromBytes(const void *data, uint64_t nbytes,
                                       const uint32_t *shape, uint32_t ndim,
                                       const char *dtype, NDArrayHandle *out);
int MXNDArrayFree(NDArrayHandle handle);
int MXNDArrayGetShape(NDArrayHandle handle, uint32_t *out_dim,
                                const uint32_t **out_pdata);
int MXNDArrayGetDType(NDArrayHandle handle, const char **out);
int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void *data,
                                     uint64_t size);
int MXNDArraySyncCopyFromCPU(NDArrayHandle handle,
                                       const void *data, uint64_t size);
int MXNDArraySave(const char *fname, uint32_t num,
                            NDArrayHandle *handles, const char **keys);
int MXNDArrayLoad(const char *fname, uint32_t *out_size,
                            NDArrayHandle **out_arr,
                            uint32_t *out_name_size,
                            const char ***out_names);
int MXImperativeInvoke(const char *op_name, int num_inputs,
                                 NDArrayHandle *inputs, int *num_outputs,
                                 NDArrayHandle **outputs, int num_params,
                                 const char **param_keys,
                                 const char **param_vals);
int MXSymbolCreateFromJSON(const char *json, SymbolHandle *out);
int MXSymbolSaveToJSON(SymbolHandle handle, const char **out_json);
int MXSymbolListArguments(SymbolHandle handle, uint32_t *out_size,
                                    const char ***out_arr);
int MXSymbolListOutputs(SymbolHandle handle, uint32_t *out_size,
                                  const char ***out_arr);
int MXSymbolListAuxiliaryStates(SymbolHandle handle,
                                          uint32_t *out_size,
                                          const char ***out_arr);
int MXSymbolFree(SymbolHandle handle);
int MXExecutorBind(SymbolHandle sym, int dev_type, int dev_id,
                   uint32_t num_args, NDArrayHandle *args,
                   const char *grad_req, ExecutorHandle *out);
int MXExecutorBackward(ExecutorHandle handle, uint32_t *out_size,
                       NDArrayHandle **grads);
int MXExecutorForward(ExecutorHandle handle, int is_train,
                                uint32_t *out_size, NDArrayHandle **outputs);
int MXExecutorFree(ExecutorHandle handle);

/* ------------------------------------------------------------------------
 * Expanded MX* families (ref: include/mxnet/c_api.h): NDArray extras,
 * autograd, symbol composition & inference, KVStore, DataIter, misc.
 * Same conventions: 0 on success, -1 on error (MXGetLastError).
 * --------------------------------------------------------------------- */

typedef void *KVStoreHandle;
typedef void *DataIterHandle;

/* NDArray extras (ref: MXNDArraySlice/At/Reshape/GetContext/WaitToRead/
 * WaitAll/GetGrad). Slice/At operate on the first axis; GetGrad sets
 * *out to NULL when no gradient buffer is attached. dev_type: 1=cpu,
 * 2=accelerator. */
int MXNDArraySlice(NDArrayHandle handle, uint32_t begin, uint32_t end,
                   NDArrayHandle *out);
int MXNDArrayAt(NDArrayHandle handle, uint32_t idx, NDArrayHandle *out);
int MXNDArrayReshape(NDArrayHandle handle, int ndim, const int *dims,
                     NDArrayHandle *out);
int MXNDArrayGetContext(NDArrayHandle handle, int *out_dev_type,
                        int *out_dev_id);
int MXNDArrayWaitToRead(NDArrayHandle handle);
int MXNDArrayWaitAll(void);
int MXNDArrayGetGrad(NDArrayHandle handle, NDArrayHandle *out);

/* Autograd (ref: MXAutogradSetIsRecording/SetIsTraining/IsRecording/
 * IsTraining/MarkVariables/Backward). grad_reqs codes: 0=null, 1=write,
 * 2=add. ograd_handles may be NULL (ones-like heads). */
int MXAutogradSetIsRecording(int is_recording, int *prev);
int MXAutogradSetIsTraining(int is_training, int *prev);
int MXAutogradIsRecording(int *out);
int MXAutogradIsTraining(int *out);
int MXAutogradMarkVariables(uint32_t num, NDArrayHandle *var_handles,
                            uint32_t *grad_reqs,
                            NDArrayHandle *grad_handles);
int MXAutogradBackward(uint32_t num_output, NDArrayHandle *output_handles,
                       NDArrayHandle *ograd_handles, int retain_graph,
                       int train_mode);

/* Symbol composition & inference (ref: MXSymbolCreateVariable/
 * CreateAtomicSymbol/Compose/Copy/GetInternals/GetName/InferShape/
 * InferType). CreateAtomicSymbol + Compose is the reference's two-step
 * graph-building protocol: params at create, inputs (positional, in
 * declared op order) at compose; Compose mutates its handle in place.
 * InferShape takes CSR-packed known arg shapes and returns borrowed
 * per-group (arg/out/aux) shape arrays, valid until the next call on
 * this thread. InferType uses dtype strings ("float32", ...). */
int MXSymbolCreateVariable(const char *name, SymbolHandle *out);
int MXSymbolCreateAtomicSymbol(const char *op_name, uint32_t num_param,
                               const char **keys, const char **vals,
                               SymbolHandle *out);
int MXSymbolCompose(SymbolHandle handle, const char *name,
                    uint32_t num_args, const char **keys,
                    SymbolHandle *args);
int MXSymbolCopy(SymbolHandle handle, SymbolHandle *out);
int MXSymbolGetInternals(SymbolHandle handle, SymbolHandle *out);
int MXSymbolGetName(SymbolHandle handle, const char **out);
int MXSymbolInferShape(SymbolHandle handle, uint32_t num_args,
                       const char **keys, const uint32_t *arg_ind_ptr,
                       const uint32_t *arg_shape_data,
                       uint32_t *in_shape_size,
                       const uint32_t **in_shape_ndim,
                       const uint32_t ***in_shape_data,
                       uint32_t *out_shape_size,
                       const uint32_t **out_shape_ndim,
                       const uint32_t ***out_shape_data,
                       uint32_t *aux_shape_size,
                       const uint32_t **aux_shape_ndim,
                       const uint32_t ***aux_shape_data);
int MXSymbolInferType(SymbolHandle handle, uint32_t num_args,
                      const char **keys, const char **arg_dtypes,
                      uint32_t *in_type_size, const char ***in_types,
                      uint32_t *out_type_size, const char ***out_types,
                      uint32_t *aux_type_size, const char ***aux_types);

/* KVStore (ref: MXKVStoreCreate/Free/Init/Push/Pull/GetRank/
 * GetGroupSize/GetType/Barrier; types: "local", "device", "dist_sync",
 * "dist_async"). */
int MXKVStoreCreate(const char *type, KVStoreHandle *out);
int MXKVStoreFree(KVStoreHandle handle);
int MXKVStoreInit(KVStoreHandle handle, uint32_t num, const char **keys,
                  NDArrayHandle *vals);
int MXKVStorePush(KVStoreHandle handle, uint32_t num, const char **keys,
                  NDArrayHandle *vals, int priority);
int MXKVStorePull(KVStoreHandle handle, uint32_t num, const char **keys,
                  NDArrayHandle *vals, int priority);
int MXKVStoreGetRank(KVStoreHandle handle, int *rank);
int MXKVStoreGetGroupSize(KVStoreHandle handle, int *size);
int MXKVStoreGetType(KVStoreHandle handle, const char **type);
int MXKVStoreBarrier(KVStoreHandle handle);

/* Data iterators (ref: MXListDataIters/MXDataIterCreateIter/Next/
 * BeforeFirst/GetData/GetLabel/Free). Creator params are string
 * key/value pairs, Python-literal encoded where structured (e.g.
 * "(3,224,224)"). Next sets *out to 1 while a batch is available. */
int MXListDataIters(uint32_t *out_size, const char ***out_array);
int MXDataIterCreateIter(const char *name, uint32_t num_param,
                         const char **keys, const char **vals,
                         DataIterHandle *out);
int MXDataIterNext(DataIterHandle handle, int *out);
int MXDataIterBeforeFirst(DataIterHandle handle);
int MXDataIterGetData(DataIterHandle handle, NDArrayHandle *out);
int MXDataIterGetLabel(DataIterHandle handle, NDArrayHandle *out);
int MXDataIterFree(DataIterHandle handle);

/* Misc (ref: MXRandomSeed/MXGetGPUCount/MXSetProfilerState/
 * MXDumpProfile/MXNotifyShutdown). */
int MXRandomSeed(int seed);
int MXGetGPUCount(int *out);
int MXSetProfilerState(int state);
int MXDumpProfile(void);
int MXNotifyShutdown(void);

#ifdef __cplusplus
} /* extern "C" */
#endif

#endif /* MXTPU_PREDICT_H_ */
