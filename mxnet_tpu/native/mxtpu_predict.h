/*
 * C predict API for mxnet_tpu.
 *
 * Drop-in subset of the reference's standalone inference ABI
 * (ref: include/mxnet/c_predict_api.h — MXPredCreate/MXPredSetInput/
 * MXPredForward/MXPredGetOutputShape/MXPredGetOutput/MXPredFree, and
 * include/mxnet/c_api.h MXGetVersion/MXGetLastError/MXListAllOpNames).
 * The implementation (c_predict_api.cc) embeds CPython and executes the
 * jax/XLA graph through mxnet_tpu.c_api_backend; callers link only
 * against this C ABI, exactly like a reference deployment.
 *
 * All functions return 0 on success, -1 on failure (then consult
 * MXGetLastError).
 */
#ifndef MXTPU_PREDICT_H_
#define MXTPU_PREDICT_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void *PredictorHandle;

/* Library-wide */
int MXGetVersion(int *out);
const char *MXGetLastError(void);
int MXListAllOpNames(uint32_t *out_size, const char ***out_array);

/* Predictor lifecycle (ref: c_predict_api.h MXPredCreate):
 *   symbol_json_str  – symbol graph as JSON (Symbol.tojson / file)
 *   param_bytes/size – serialized parameters (nd.save format, the
 *                      "<prefix>-0000.params" checkpoint file contents)
 *   dev_type         – 1 = cpu, 2 = accelerator (tpu)
 *   num_input_nodes / input_keys / input_shape_indptr / input_shape_data
 *                    – CSR-packed input shapes, as in the reference
 */
int MXPredCreate(const char *symbol_json_str, const void *param_bytes,
                 int param_size, int dev_type, int dev_id,
                 uint32_t num_input_nodes, const char **input_keys,
                 const uint32_t *input_shape_indptr,
                 const uint32_t *input_shape_data, PredictorHandle *out);

/* As MXPredCreate but keeping only the listed outputs
 * (ref: c_predict_api.h MXPredCreatePartialOut). */
int MXPredCreatePartialOut(const char *symbol_json_str,
                           const void *param_bytes, int param_size,
                           int dev_type, int dev_id,
                           uint32_t num_input_nodes, const char **input_keys,
                           const uint32_t *input_shape_indptr,
                           const uint32_t *input_shape_data,
                           uint32_t num_output_nodes,
                           const char **output_keys, PredictorHandle *out);

int MXPredGetOutputCount(PredictorHandle handle, uint32_t *out);
int MXPredSetInput(PredictorHandle handle, const char *key,
                   const float *data, uint32_t size);
int MXPredForward(PredictorHandle handle);
int MXPredGetOutputShape(PredictorHandle handle, uint32_t index,
                         uint32_t **shape_data, uint32_t *shape_ndim);
int MXPredGetOutput(PredictorHandle handle, uint32_t index, float *data,
                    uint32_t size);
int MXPredFree(PredictorHandle handle);


/* ------------------------------------------------------------------------
 * General MX* ABI subset (ref: include/mxnet/c_api.h): NDArray / Symbol /
 * Executor handles + imperative invoke. Handles are opaque ids owned by
 * the embedded runtime; every function returns 0 on success, -1 on error
 * (message via MXGetLastError).
 * --------------------------------------------------------------------- */

typedef void *NDArrayHandle;
typedef void *SymbolHandle;
typedef void *ExecutorHandle;

int MXNDArrayCreate(const uint32_t *shape, uint32_t ndim,
                              const char *dtype, NDArrayHandle *out);
int MXNDArrayCreateFromBytes(const void *data, uint64_t nbytes,
                                       const uint32_t *shape, uint32_t ndim,
                                       const char *dtype, NDArrayHandle *out);
int MXNDArrayFree(NDArrayHandle handle);
int MXNDArrayGetShape(NDArrayHandle handle, uint32_t *out_dim,
                                const uint32_t **out_pdata);
int MXNDArrayGetDType(NDArrayHandle handle, const char **out);
int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void *data,
                                     uint64_t size);
int MXNDArraySyncCopyFromCPU(NDArrayHandle handle,
                                       const void *data, uint64_t size);
int MXNDArraySave(const char *fname, uint32_t num,
                            NDArrayHandle *handles, const char **keys);
int MXNDArrayLoad(const char *fname, uint32_t *out_size,
                            NDArrayHandle **out_arr,
                            uint32_t *out_name_size,
                            const char ***out_names);
int MXImperativeInvoke(const char *op_name, int num_inputs,
                                 NDArrayHandle *inputs, int *num_outputs,
                                 NDArrayHandle **outputs, int num_params,
                                 const char **param_keys,
                                 const char **param_vals);
int MXSymbolCreateFromJSON(const char *json, SymbolHandle *out);
int MXSymbolSaveToJSON(SymbolHandle handle, const char **out_json);
int MXSymbolListArguments(SymbolHandle handle, uint32_t *out_size,
                                    const char ***out_arr);
int MXSymbolListOutputs(SymbolHandle handle, uint32_t *out_size,
                                  const char ***out_arr);
int MXSymbolListAuxiliaryStates(SymbolHandle handle,
                                          uint32_t *out_size,
                                          const char ***out_arr);
int MXSymbolFree(SymbolHandle handle);
int MXExecutorBind(SymbolHandle sym, int dev_type, int dev_id,
                   uint32_t num_args, NDArrayHandle *args,
                   const char *grad_req, ExecutorHandle *out);
int MXExecutorBackward(ExecutorHandle handle, uint32_t *out_size,
                       NDArrayHandle **grads);
int MXExecutorForward(ExecutorHandle handle, int is_train,
                                uint32_t *out_size, NDArrayHandle **outputs);
int MXExecutorFree(ExecutorHandle handle);

/* ------------------------------------------------------------------------
 * Expanded MX* families (ref: include/mxnet/c_api.h): NDArray extras,
 * autograd, symbol composition & inference, KVStore, DataIter, misc.
 * Same conventions: 0 on success, -1 on error (MXGetLastError).
 * --------------------------------------------------------------------- */

typedef void *KVStoreHandle;
typedef void *DataIterHandle;

/* NDArray extras (ref: MXNDArraySlice/At/Reshape/GetContext/WaitToRead/
 * WaitAll/GetGrad). Slice/At operate on the first axis; GetGrad sets
 * *out to NULL when no gradient buffer is attached. dev_type: 1=cpu,
 * 2=accelerator. */
int MXNDArraySlice(NDArrayHandle handle, uint32_t begin, uint32_t end,
                   NDArrayHandle *out);
int MXNDArrayAt(NDArrayHandle handle, uint32_t idx, NDArrayHandle *out);
int MXNDArrayReshape(NDArrayHandle handle, int ndim, const int *dims,
                     NDArrayHandle *out);
int MXNDArrayGetContext(NDArrayHandle handle, int *out_dev_type,
                        int *out_dev_id);
int MXNDArrayWaitToRead(NDArrayHandle handle);
int MXNDArrayWaitAll(void);
int MXNDArrayGetGrad(NDArrayHandle handle, NDArrayHandle *out);

/* Autograd (ref: MXAutogradSetIsRecording/SetIsTraining/IsRecording/
 * IsTraining/MarkVariables/Backward). grad_reqs codes: 0=null, 1=write,
 * 2=add. ograd_handles may be NULL (ones-like heads). */
int MXAutogradSetIsRecording(int is_recording, int *prev);
int MXAutogradSetIsTraining(int is_training, int *prev);
int MXAutogradIsRecording(int *out);
int MXAutogradIsTraining(int *out);
int MXAutogradMarkVariables(uint32_t num, NDArrayHandle *var_handles,
                            uint32_t *grad_reqs,
                            NDArrayHandle *grad_handles);
int MXAutogradBackward(uint32_t num_output, NDArrayHandle *output_handles,
                       NDArrayHandle *ograd_handles, int retain_graph,
                       int train_mode);

/* Symbol composition & inference (ref: MXSymbolCreateVariable/
 * CreateAtomicSymbol/Compose/Copy/GetInternals/GetName/InferShape/
 * InferType). CreateAtomicSymbol + Compose is the reference's two-step
 * graph-building protocol: params at create, inputs (positional, in
 * declared op order) at compose; Compose mutates its handle in place.
 * InferShape takes CSR-packed known arg shapes and returns borrowed
 * per-group (arg/out/aux) shape arrays, valid until the next call on
 * this thread. InferType uses dtype strings ("float32", ...). */
int MXSymbolCreateVariable(const char *name, SymbolHandle *out);
int MXSymbolCreateAtomicSymbol(const char *op_name, uint32_t num_param,
                               const char **keys, const char **vals,
                               SymbolHandle *out);
int MXSymbolCompose(SymbolHandle handle, const char *name,
                    uint32_t num_args, const char **keys,
                    SymbolHandle *args);
int MXSymbolCopy(SymbolHandle handle, SymbolHandle *out);
int MXSymbolGetInternals(SymbolHandle handle, SymbolHandle *out);
int MXSymbolGetName(SymbolHandle handle, const char **out);
int MXSymbolInferShape(SymbolHandle handle, uint32_t num_args,
                       const char **keys, const uint32_t *arg_ind_ptr,
                       const uint32_t *arg_shape_data,
                       uint32_t *in_shape_size,
                       const uint32_t **in_shape_ndim,
                       const uint32_t ***in_shape_data,
                       uint32_t *out_shape_size,
                       const uint32_t **out_shape_ndim,
                       const uint32_t ***out_shape_data,
                       uint32_t *aux_shape_size,
                       const uint32_t **aux_shape_ndim,
                       const uint32_t ***aux_shape_data);
int MXSymbolInferType(SymbolHandle handle, uint32_t num_args,
                      const char **keys, const char **arg_dtypes,
                      uint32_t *in_type_size, const char ***in_types,
                      uint32_t *out_type_size, const char ***out_types,
                      uint32_t *aux_type_size, const char ***aux_types);

/* KVStore (ref: MXKVStoreCreate/Free/Init/Push/Pull/GetRank/
 * GetGroupSize/GetType/Barrier; types: "local", "device", "dist_sync",
 * "dist_async"). */
int MXKVStoreCreate(const char *type, KVStoreHandle *out);
int MXKVStoreFree(KVStoreHandle handle);
int MXKVStoreInit(KVStoreHandle handle, uint32_t num, const char **keys,
                  NDArrayHandle *vals);
int MXKVStorePush(KVStoreHandle handle, uint32_t num, const char **keys,
                  NDArrayHandle *vals, int priority);
int MXKVStorePull(KVStoreHandle handle, uint32_t num, const char **keys,
                  NDArrayHandle *vals, int priority);
int MXKVStoreGetRank(KVStoreHandle handle, int *rank);
int MXKVStoreGetGroupSize(KVStoreHandle handle, int *size);
int MXKVStoreGetType(KVStoreHandle handle, const char **type);
int MXKVStoreBarrier(KVStoreHandle handle);

/* Data iterators (ref: MXListDataIters/MXDataIterCreateIter/Next/
 * BeforeFirst/GetData/GetLabel/Free). Creator params are string
 * key/value pairs, Python-literal encoded where structured (e.g.
 * "(3,224,224)"). Next sets *out to 1 while a batch is available. */
int MXListDataIters(uint32_t *out_size, const char ***out_array);
int MXDataIterCreateIter(const char *name, uint32_t num_param,
                         const char **keys, const char **vals,
                         DataIterHandle *out);
int MXDataIterNext(DataIterHandle handle, int *out);
int MXDataIterBeforeFirst(DataIterHandle handle);
int MXDataIterGetData(DataIterHandle handle, NDArrayHandle *out);
int MXDataIterGetLabel(DataIterHandle handle, NDArrayHandle *out);
int MXDataIterFree(DataIterHandle handle);

/* Misc (ref: MXRandomSeed/MXGetGPUCount/MXSetProfilerState/
 * MXDumpProfile/MXNotifyShutdown). */
int MXRandomSeed(int seed);
int MXGetGPUCount(int *out);
int MXSetProfilerState(int state);
int MXDumpProfile(void);
int MXNotifyShutdown(void);


/* ---------------------------------------------------------------------
 * Round-3 ABI completion (ref: include/mxnet/c_api.h): CachedOp, symbol
 * attrs/structure, executor simple_bind/reshape, autograd extras,
 * kvstore updater + roles, profiler objects, RecordIO, legacy Function
 * API, ndarray extras + 64-bit variants, quantization passes, DLPack.
 * ------------------------------------------------------------------ */

typedef void *CachedOpHandle;
typedef void *ProfileHandle;
typedef void *RecordIOHandle;
typedef const void *FunctionHandle;
typedef void *RtcHandle;
typedef void *CudaModuleHandle;
typedef void *CudaKernelHandle;
typedef void *DLManagedTensorHandle;
typedef int64_t dim_t;

struct LibFeature {
  const char *name;
  int enabled; /* bool in the reference; int keeps the C ABI simple */
};

/* CachedOp */
int MXCreateCachedOp(SymbolHandle sym, CachedOpHandle *out);
int MXCreateCachedOpEx(SymbolHandle sym, int num_flags, const char **keys,
                       const char **vals, CachedOpHandle *out);
int MXInvokeCachedOp(CachedOpHandle handle, int num_inputs,
                     NDArrayHandle *inputs, int *num_outputs,
                     NDArrayHandle **outputs);
int MXInvokeCachedOpEx(CachedOpHandle handle, int num_inputs,
                       NDArrayHandle *inputs, int *num_outputs,
                       NDArrayHandle **outputs, const int **out_stypes);
int MXFreeCachedOp(CachedOpHandle handle);

/* Symbol attrs / structure */
int MXSymbolGetAttr(SymbolHandle sym, const char *key, const char **out,
                    int *success);
int MXSymbolSetAttr(SymbolHandle sym, const char *key, const char *value);
int MXSymbolListAttr(SymbolHandle sym, uint32_t *out_size,
                     const char ***out);
int MXSymbolListAttrShallow(SymbolHandle sym, uint32_t *out_size,
                            const char ***out);
int MXSymbolGetNumOutputs(SymbolHandle sym, uint32_t *out);
int MXSymbolGetOutput(SymbolHandle sym, uint32_t index, SymbolHandle *out);
int MXSymbolGetChildren(SymbolHandle sym, SymbolHandle *out);
int MXSymbolPrint(SymbolHandle sym, const char **out_str);
int MXSymbolCreateFromFile(const char *fname, SymbolHandle *out);
int MXSymbolSaveToFile(SymbolHandle sym, const char *fname);
int MXSymbolCreateGroup(uint32_t num_symbols, SymbolHandle *symbols,
                        SymbolHandle *out);
int MXGenAtomicSymbolFromSymbol(SymbolHandle sym, SymbolHandle *out);
int MXSymbolRemoveAmpCast(SymbolHandle sym, SymbolHandle *out);
int MXShallowCopySymbol(SymbolHandle sym, SymbolHandle *out);
int MXShallowCopyNDArray(NDArrayHandle nd, NDArrayHandle *out);
int MXSymbolGrad(SymbolHandle sym, uint32_t num_wrt, const char **wrt,
                 SymbolHandle *out);
int MXSymbolInferShapePartial(
    SymbolHandle sym, uint32_t num_args, const char **keys,
    const uint32_t *arg_ind_ptr, const uint32_t *arg_shape_data,
    uint32_t *in_shape_size, const uint32_t **in_shape_ndim,
    const uint32_t ***in_shape_data, uint32_t *out_shape_size,
    const uint32_t **out_shape_ndim, const uint32_t ***out_shape_data,
    uint32_t *aux_shape_size, const uint32_t **aux_shape_ndim,
    const uint32_t ***aux_shape_data, int *complete);
int MXSymbolInferTypePartial(SymbolHandle sym, uint32_t num_args,
                             const char **keys, const char **arg_dtypes,
                             uint32_t *in_type_size,
                             const char ***in_type_data,
                             uint32_t *out_type_size,
                             const char ***out_type_data,
                             uint32_t *aux_type_size,
                             const char ***aux_type_data);

/* Executor */
int MXExecutorSimpleBind(SymbolHandle sym, int dev_type, int dev_id,
                         uint32_t num_args, const char **arg_names,
                         const uint32_t *arg_ind_ptr,
                         const uint32_t *arg_shape_data,
                         const char *grad_req, ExecutorHandle *out,
                         uint32_t *num_arg_arrays, NDArrayHandle **arg_arrays,
                         NDArrayHandle **grad_arrays, uint32_t *num_aux,
                         NDArrayHandle **aux_arrays);
int MXExecutorReshape(int partial_shaping, int allow_up_sizing, int dev_type,
                      int dev_id, uint32_t num_args, const char **arg_names,
                      const uint32_t *arg_ind_ptr,
                      const uint32_t *arg_shape_data,
                      ExecutorHandle shared_exec, ExecutorHandle *out,
                      uint32_t *num_arg_arrays, NDArrayHandle **arg_arrays,
                      NDArrayHandle **grad_arrays, uint32_t *num_aux,
                      NDArrayHandle **aux_arrays);
int MXExecutorOutputs(ExecutorHandle handle, uint32_t *out_size,
                      NDArrayHandle **out);
int MXExecutorPrint(ExecutorHandle handle, const char **out_str);
int MXExecutorGetOptimizedSymbol(ExecutorHandle handle, SymbolHandle *out);
typedef void (*ExecutorMonitorCallback)(const char *, NDArrayHandle, void *);
int MXExecutorSetMonitorCallback(ExecutorHandle handle,
                                 ExecutorMonitorCallback callback,
                                 void *callback_handle);
#ifdef __cplusplus
int MXExecutorSetMonitorCallbackEX(ExecutorHandle handle,
                                   ExecutorMonitorCallback callback,
                                   void *callback_handle, bool monitor_all);
#endif

/* Autograd extras */
int MXAutogradBackwardEx(uint32_t num_output, NDArrayHandle *output_handles,
                         NDArrayHandle *ograd_handles,
                         uint32_t num_variables, NDArrayHandle *var_handles,
                         int retain_graph, int create_graph, int is_train,
                         NDArrayHandle **grad_handles, int **grad_stypes);
int MXAutogradComputeGradient(uint32_t num_output,
                              NDArrayHandle *output_handles);
int MXAutogradGetSymbol(NDArrayHandle handle, SymbolHandle *out);

/* KVStore updater / roles / commands */
typedef void (*MXKVStoreUpdater)(int, NDArrayHandle, NDArrayHandle, void *);
typedef void (*MXKVStoreStrUpdater)(const char *, NDArrayHandle,
                                    NDArrayHandle, void *);
int MXKVStoreSetUpdater(KVStoreHandle handle, MXKVStoreUpdater updater,
                        void *updater_handle);
int MXKVStoreSetUpdaterEx(KVStoreHandle handle, MXKVStoreUpdater updater,
                          MXKVStoreStrUpdater str_updater,
                          void *updater_handle);
int MXKVStoreIsWorkerNode(int *ret);
int MXKVStoreIsServerNode(int *ret);
int MXKVStoreIsSchedulerNode(int *ret);
int MXKVStoreRunServer(KVStoreHandle handle,
                       void (*controller)(int, const char *, void *),
                       void *controller_handle);
int MXKVStoreSendCommmandToServers(KVStoreHandle handle, int cmd_id,
                                   const char *cmd_body);
int MXKVStoreSetBarrierBeforeExit(KVStoreHandle handle,
                                  int barrier_before_exit);
int MXKVStoreGetNumDeadNode(KVStoreHandle handle, int node_id, int *number);
int MXKVStoreSetGradientCompression(KVStoreHandle handle,
                                    uint32_t num_params, const char **keys,
                                    const char **vals);
int MXInitPSEnv(uint32_t num_vars, const char **keys, const char **vals);
int MXKVStoreInitEx(KVStoreHandle handle, uint32_t num, const char **keys,
                    NDArrayHandle *vals);
int MXKVStorePushEx(KVStoreHandle handle, uint32_t num, const char **keys,
                    NDArrayHandle *vals, int priority);
int MXKVStorePullEx(KVStoreHandle handle, uint32_t num, const char **keys,
                    NDArrayHandle *vals, int priority);

/* Profiler */
int MXSetProfilerConfig(int num_params, const char *const *keys,
                        const char *const *vals);
int MXSetProcessProfilerConfig(int num_params, const char *const *keys,
                               const char *const *vals,
                               KVStoreHandle kv_handle);
int MXSetProcessProfilerState(int state, int profile_process,
                              KVStoreHandle kv_handle);
int MXDumpProcessProfile(int finished, int profile_process,
                         KVStoreHandle kv_handle);
int MXAggregateProfileStatsPrint(const char **out_str, int reset);
int MXAggregateProfileStatsPrintEx(const char **out_str, int reset,
                                   int format, int sort_by, int ascending);
int MXProfilePause(int paused);
int MXProcessProfilePause(int paused, int profile_process,
                          KVStoreHandle kv_handle);
int MXProfileCreateDomain(const char *domain, ProfileHandle *out);
int MXProfileCreateTask(ProfileHandle domain, const char *task_name,
                        ProfileHandle *out);
int MXProfileCreateFrame(ProfileHandle domain, const char *frame_name,
                         ProfileHandle *out);
int MXProfileCreateEvent(const char *event_name, ProfileHandle *out);
int MXProfileCreateCounter(ProfileHandle domain, const char *counter_name,
                           ProfileHandle *out);
int MXProfileDestroyHandle(ProfileHandle handle);
int MXProfileDurationStart(ProfileHandle handle);
int MXProfileDurationStop(ProfileHandle handle);
int MXProfileSetCounter(ProfileHandle handle, uint64_t value);
int MXProfileAdjustCounter(ProfileHandle handle, int64_t value);
int MXProfileSetMarker(ProfileHandle domain, const char *name,
                       const char *scope);

/* RecordIO */
int MXRecordIOWriterCreate(const char *uri, RecordIOHandle *out);
int MXRecordIOWriterFree(RecordIOHandle handle);
int MXRecordIOWriterWriteRecord(RecordIOHandle handle, const char *buf,
                                size_t size);
int MXRecordIOWriterTell(RecordIOHandle handle, size_t *pos);
int MXRecordIOReaderCreate(const char *uri, RecordIOHandle *out);
int MXRecordIOReaderFree(RecordIOHandle handle);
int MXRecordIOReaderReadRecord(RecordIOHandle handle, char const **buf,
                               size_t *size);
int MXRecordIOReaderSeek(RecordIOHandle handle, size_t pos);
int MXRecordIOReaderTell(RecordIOHandle handle, size_t *pos);

/* Legacy Function API */
int MXListFunctions(uint32_t *out_size, FunctionHandle **out_array);
int MXGetFunction(const char *name, FunctionHandle *out);
int MXFuncGetInfo(FunctionHandle fun, const char **name,
                  const char **description, uint32_t *num_args,
                  const char ***arg_names, const char ***arg_type_infos,
                  const char ***arg_descriptions, const char **return_type);
int MXFuncDescribe(FunctionHandle fun, uint32_t *num_use_vars,
                   uint32_t *num_scalars, uint32_t *num_mutate_vars,
                   int *type_mask);
int MXFuncInvoke(FunctionHandle fun, NDArrayHandle *use_vars, float *scalars,
                 NDArrayHandle *mutate_vars);
int MXFuncInvokeEx(FunctionHandle fun, NDArrayHandle *use_vars,
                   float *scalars, NDArrayHandle *mutate_vars,
                   int num_params, char **param_keys, char **param_vals);

/* NDArray extras / 64-bit */
int MXNDArrayCreateEx(const uint32_t *shape, uint32_t ndim, int dev_type,
                      int dev_id, int delay_alloc, int dtype,
                      NDArrayHandle *out);
int MXNDArrayCreateEx64(const int64_t *shape, int ndim, int dev_type,
                        int dev_id, int delay_alloc, int dtype,
                        NDArrayHandle *out);
int MXNDArrayCreateNone(NDArrayHandle *out);
int MXNDArrayGetShapeEx(NDArrayHandle handle, int *out_dim,
                        const int **out_pdata);
int MXNDArrayGetShape64(NDArrayHandle handle, int *out_dim,
                        const int64_t **out_pdata);
int MXNDArrayGetShapeEx64(NDArrayHandle handle, int *out_dim,
                          const int64_t **out_pdata);
int MXNDArrayAt64(NDArrayHandle handle, int64_t idx, NDArrayHandle *out);
int MXNDArraySlice64(NDArrayHandle handle, int64_t begin, int64_t end,
                     NDArrayHandle *out);
#ifdef __cplusplus
int MXNDArrayReshape64(NDArrayHandle handle, int ndim, dim_t *dims,
                       bool reverse, NDArrayHandle *out);
#endif
int MXNDArrayGetStorageType(NDArrayHandle handle, int *out_storage_type);
int MXNDArrayWaitToWrite(NDArrayHandle handle);
int MXNDArrayDetach(NDArrayHandle handle, NDArrayHandle *out);
int MXNDArraySetGradState(NDArrayHandle handle, int state);
int MXNDArrayGetGradState(NDArrayHandle handle, int *out);
int MXNDArraySaveRawBytes(NDArrayHandle handle, size_t *out_size,
                          const char **out_buf);
int MXNDArrayLoadFromRawBytes(const void *buf, size_t size,
                              NDArrayHandle *out);
int MXNDArrayLoadFromBuffer(const void *buf, size_t size,
                            uint32_t *out_size, NDArrayHandle **out_arr,
                            uint32_t *out_name_size,
                            const char ***out_names);
int MXNDArrayLoadFromBuffer64(const void *buf, size_t size,
                              uint32_t *out_size, NDArrayHandle **out_arr,
                              uint32_t *out_name_size,
                              const char ***out_names);
int MXNDArrayLoad64(const char *fname, uint32_t *out_size,
                    NDArrayHandle **out_arr, uint32_t *out_name_size,
                    const char ***out_names);
int MXNDArraySyncCopyFromNDArray(NDArrayHandle handle_dst,
                                 const NDArrayHandle handle_src, int i);
#ifdef __cplusplus
int MXNDArraySyncCheckFormat(NDArrayHandle handle, const bool full_check);
#endif
int MXNDArrayGetData(NDArrayHandle handle, void **out_pdata);
int MXNDArrayGetDataNDArray(NDArrayHandle handle, NDArrayHandle *out);
int MXNDArrayCreateSparseEx(int storage_type, const uint32_t *shape,
                            uint32_t ndim, int dev_type, int dev_id,
                            int delay_alloc, int dtype, uint32_t num_aux,
                            int *aux_type, uint32_t *aux_ndims,
                            const uint32_t *aux_shape, NDArrayHandle *out);
int MXNDArrayCreateSparseEx64(int storage_type, const int64_t *shape,
                              int ndim, int dev_type, int dev_id,
                              int delay_alloc, int dtype, uint32_t num_aux,
                              int *aux_type, int *aux_ndims,
                              const int64_t *aux_shape, NDArrayHandle *out);
int MXNDArrayGetAuxType(NDArrayHandle handle, uint32_t i, int *out_type);
int MXNDArrayGetAuxType64(NDArrayHandle handle, int64_t i, int *out_type);
int MXNDArrayGetAuxNDArray(NDArrayHandle handle, uint32_t i,
                           NDArrayHandle *out);
int MXNDArrayGetAuxNDArray64(NDArrayHandle handle, int64_t i,
                             NDArrayHandle *out);
int MXNDArrayGetSharedMemHandle(NDArrayHandle handle, int *shared_pid,
                                int *shared_id);
int MXNDArrayCreateFromSharedMem(int shared_pid, int shared_id,
                                 const uint32_t *shape, uint32_t ndim,
                                 int dtype, NDArrayHandle *out);
int MXNDArrayCreateFromSharedMemEx(int shared_pid, int shared_id,
                                   const int *shape, int ndim, int dtype,
                                   NDArrayHandle *out);

/* DLPack */
int MXNDArrayToDLPack(NDArrayHandle handle,
                      DLManagedTensorHandle *out_dlpack);
int MXNDArrayFromDLPack(DLManagedTensorHandle dlpack, NDArrayHandle *out);
#ifdef __cplusplus
int MXNDArrayFromDLPackEx(DLManagedTensorHandle dlpack,
                          const bool transient_handle, NDArrayHandle *out);
#endif
int MXNDArrayCallDLPackDeleter(DLManagedTensorHandle dlpack);

/* Engine (NaiveEngine semantics) */
int MXEngineSetBulkSize(int bulk_size, int *prev_bulk_size);
typedef void (*EngineSyncFunc)(void *, void *);
typedef void (*EngineAsyncFunc)(void *, void *, void *);
typedef void (*EngineFuncParamDeleter)(void *);
int MXEnginePushSync(EngineSyncFunc sync_func, void *func_param,
                     EngineFuncParamDeleter deleter, void *ctx_handle,
                     void *const_vars_handle, int num_const_vars,
                     void *mutable_vars_handle, int num_mutable_vars,
                     void *prop_handle, int priority, const char *opr_name);
#ifdef __cplusplus
int MXEnginePushAsync(EngineAsyncFunc async_func, void *func_param,
                      EngineFuncParamDeleter deleter, void *ctx_handle,
                      void *const_vars_handle, int num_const_vars,
                      void *mutable_vars_handle, int num_mutable_vars,
                      void *prop_handle, int priority, const char *opr_name,
                      bool wait);
#endif
int MXEnginePushSyncND(EngineSyncFunc sync_func, void *func_param,
                       EngineFuncParamDeleter deleter, void *ctx_handle,
                       NDArrayHandle *const_nds, int num_const_nds,
                       NDArrayHandle *mutable_nds, int num_mutable_nds,
                       void *prop_handle, int priority,
                       const char *opr_name);
#ifdef __cplusplus
int MXEnginePushAsyncND(EngineAsyncFunc async_func, void *func_param,
                        EngineFuncParamDeleter deleter, void *ctx_handle,
                        NDArrayHandle *const_nds, int num_const_nds,
                        NDArrayHandle *mutable_nds, int num_mutable_nds,
                        void *prop_handle, int priority,
                        const char *opr_name, bool wait);
#endif

/* Quantization / graph passes */
#ifdef __cplusplus
int MXQuantizeSymbol(SymbolHandle sym_handle, SymbolHandle *ret_sym_handle,
                     const uint32_t num_excluded_symbols,
                     const char **excluded_symbols,
                     const uint32_t num_offline,
                     const char **offline_params,
                     const char *quantized_dtype, const bool calib_quantize);
#endif
int MXReducePrecisionSymbol(SymbolHandle sym_handle,
                            SymbolHandle *ret_sym_handle, uint32_t num_args,
                            const int *arg_type_data, uint32_t num_ind_ptr,
                            const int *ind_ptr, const int *target_dtype,
                            const int cast_optional_params,
                            const uint32_t num_target_dtype_ops,
                            const char **target_dtype_ops,
                            const uint32_t num_fp32_ops,
                            const char **fp32_ops,
                            const uint32_t num_widest_dtype_ops,
                            const char **widest_dtype_ops,
                            const uint32_t num_conditional_fp32_ops,
                            const char **conditional_fp32_ops,
                            const uint32_t num_excluded_symbols,
                            const char **excluded_symbols,
                            const char **arg_names);
int MXSetCalibTableToQuantizedSymbol(SymbolHandle qsym_handle,
                                     const uint32_t num_layers,
                                     const char **layer_names,
                                     const float *low_quantiles,
                                     const float *high_quantiles,
                                     SymbolHandle *ret_sym_handle);
int MXGenBackendSubgraph(SymbolHandle sym_handle, const char *backend,
                         SymbolHandle *ret_sym_handle);
int MXOptimizeForBackend(SymbolHandle sym_handle, const char *backend,
                         const int dev_type, SymbolHandle *ret_sym_handle,
                         const uint32_t args_len, NDArrayHandle *in_args,
                         const uint32_t aux_len, NDArrayHandle *in_aux,
                         const uint32_t num_options, const char **keys,
                         const char **vals, int **new_args_cnt,
                         NDArrayHandle **new_args_handle,
                         char ***new_arg_names_handle, int **new_aux_cnt,
                         NDArrayHandle **new_aux_handle,
                         char ***new_aux_names_handle);

/* Misc */
int MXIsNumpyShape(int *curr);
int MXSetIsNumpyShape(int is_np_shape, int *prev);
int MXSetNumOMPThreads(int thread_num);
int MXStorageEmptyCache(int dev_type, int dev_id);
int MXGetGPUMemoryInformation(int dev, int *free_mem, int *total_mem);
int MXGetGPUMemoryInformation64(int dev, uint64_t *free_mem,
                                uint64_t *total_mem);
int MXLibInfoFeatures(const struct LibFeature **lib_feature, size_t *size);
int MXRandomSeedContext(int seed, int dev_type, int dev_id);
int MXLoadLib(const char *path);

/* CUDA-only families: exported with honest unsupported errors */
int MXRtcCreate(char *name, uint32_t num_input, uint32_t num_output,
                char **input_names, char **output_names,
                NDArrayHandle *inputs, NDArrayHandle *outputs, char *kernel,
                RtcHandle *out);
int MXRtcPush(RtcHandle handle, uint32_t num_input, uint32_t num_output,
              NDArrayHandle *inputs, NDArrayHandle *outputs,
              uint32_t gridDimX, uint32_t gridDimY, uint32_t gridDimZ,
              uint32_t blockDimX, uint32_t blockDimY, uint32_t blockDimZ);
int MXRtcFree(RtcHandle handle);
int MXRtcCudaModuleCreate(const char *source, int num_options,
                          const char **options, int num_exports,
                          const char **exports, CudaModuleHandle *out);
int MXRtcCudaModuleFree(CudaModuleHandle handle);
int MXRtcCudaKernelCreate(CudaModuleHandle handle, const char *name,
                          int num_args, int *is_ndarray, int *is_const,
                          int *arg_types, CudaKernelHandle *out);
int MXRtcCudaKernelFree(CudaKernelHandle handle);
int MXRtcCudaKernelCall(CudaKernelHandle handle, int dev_id, void **args,
                        uint32_t grid_dim_x, uint32_t grid_dim_y,
                        uint32_t grid_dim_z, uint32_t block_dim_x,
                        uint32_t block_dim_y, uint32_t block_dim_z,
                        uint32_t shared_mem);
int MXLoadTVMOp(const char *libpath);
int MXCustomOpRegister(const char *op_type, void *creator);
int MXCustomFunctionRecord(int num_inputs, NDArrayHandle *inputs,
                           int num_outputs, NDArrayHandle *outputs,
                           void *callbacks);


/* Final delegation tier */
typedef const void *AtomicSymbolCreator;
typedef const void *DataIterCreator;
int MXDataIterGetIndex(DataIterHandle handle, uint64_t **out_index,
                       uint64_t *out_size);
int MXDataIterGetPadNum(DataIterHandle handle, int *pad);
int MXDataIterGetIterInfo(DataIterCreator creator, const char **name,
                          const char **description, uint32_t *num_args,
                          const char ***arg_names,
                          const char ***arg_type_infos,
                          const char ***arg_descriptions);
int MXExecutorBackwardEx(ExecutorHandle handle, uint32_t len,
                         NDArrayHandle *head_grads, int is_train);
int MXExecutorBindX(SymbolHandle sym, int dev_type, int dev_id,
                    uint32_t num_map_keys, const char **map_keys,
                    const int *map_dev_types, const int *map_dev_ids,
                    uint32_t len, NDArrayHandle *in_args,
                    NDArrayHandle *arg_grad_store, uint32_t *grad_req_type,
                    uint32_t aux_states_len, NDArrayHandle *aux_states,
                    ExecutorHandle *out);
int MXExecutorBindEX(SymbolHandle sym, int dev_type, int dev_id,
                     uint32_t num_map_keys, const char **map_keys,
                     const int *map_dev_types, const int *map_dev_ids,
                     uint32_t len, NDArrayHandle *in_args,
                     NDArrayHandle *arg_grad_store, uint32_t *grad_req_type,
                     uint32_t aux_states_len, NDArrayHandle *aux_states,
                     ExecutorHandle shared_exec, ExecutorHandle *out);
int MXExecutorSimpleBindEx(SymbolHandle sym, int dev_type, int dev_id,
                           uint32_t num_args, const char **arg_names,
                           const uint32_t *arg_ind_ptr,
                           const uint32_t *arg_shape_data,
                           const char *grad_req, ExecutorHandle *out,
                           uint32_t *num_arg_arrays,
                           NDArrayHandle **arg_arrays,
                           NDArrayHandle **grad_arrays, uint32_t *num_aux,
                           NDArrayHandle **aux_arrays);
int MXExecutorReshapeEx(int partial_shaping, int allow_up_sizing,
                        int dev_type, int dev_id, uint32_t num_args,
                        const char **arg_names, const uint32_t *arg_ind_ptr,
                        const uint32_t *arg_shape_data,
                        ExecutorHandle shared_exec, ExecutorHandle *out,
                        uint32_t *num_arg_arrays,
                        NDArrayHandle **arg_arrays,
                        NDArrayHandle **grad_arrays, uint32_t *num_aux,
                        NDArrayHandle **aux_arrays);
int MXImperativeInvokeEx(const char *op_name, int num_inputs,
                         NDArrayHandle *inputs, int *num_outputs,
                         NDArrayHandle ***outputs, int num_params,
                         const char **param_keys, const char **param_vals,
                         const int **out_stypes);
int MXKVStorePullRowSparse(KVStoreHandle handle, uint32_t num,
                           const int *keys, NDArrayHandle *vals,
                           const NDArrayHandle *row_ids, int priority);
int MXKVStorePullRowSparseEx(KVStoreHandle handle, uint32_t num,
                             const char **keys, NDArrayHandle *vals,
                             const NDArrayHandle *row_ids, int priority);
#ifdef __cplusplus
int MXKVStorePullWithSparse(KVStoreHandle handle, uint32_t num,
                            const int *keys, NDArrayHandle *vals,
                            int priority, bool ignore_sparse);
int MXKVStorePullWithSparseEx(KVStoreHandle handle, uint32_t num,
                              const char **keys, NDArrayHandle *vals,
                              int priority, bool ignore_sparse);
#endif
int MXSymbolListAtomicSymbolCreators(uint32_t *out_size,
                                     AtomicSymbolCreator **out_array);
int MXSymbolGetAtomicSymbolName(AtomicSymbolCreator creator,
                                const char **name);
int MXSymbolGetAtomicSymbolInfo(
    AtomicSymbolCreator creator, const char **name,
    const char **description, uint32_t *num_args, const char ***arg_names,
    const char ***arg_type_infos, const char ***arg_descriptions,
    const char **key_var_num_args, const char **return_type);
int MXSymbolCutSubgraph(SymbolHandle sym, SymbolHandle **input_symbols,
                        uint32_t *input_size);
int MXSymbolGetInputSymbols(SymbolHandle sym, SymbolHandle **inputs,
                            int *input_size);
int MXSymbolInferShapeEx(SymbolHandle sym, uint32_t num_args,
                         const char **keys, const uint32_t *arg_ind_ptr,
                         const int *arg_shape_data, uint32_t *in_shape_size,
                         const int **in_shape_ndim,
                         const int ***in_shape_data,
                         uint32_t *out_shape_size,
                         const int **out_shape_ndim,
                         const int ***out_shape_data,
                         uint32_t *aux_shape_size,
                         const int **aux_shape_ndim,
                         const int ***aux_shape_data, int *complete);
int MXSymbolInferShape64(SymbolHandle sym, uint32_t num_args,
                         const char **keys, const int64_t *arg_ind_ptr,
                         const int64_t *arg_shape_data,
                         size_t *in_shape_size, const int **in_shape_ndim,
                         const int64_t ***in_shape_data,
                         size_t *out_shape_size, const int **out_shape_ndim,
                         const int64_t ***out_shape_data,
                         size_t *aux_shape_size, const int **aux_shape_ndim,
                         const int64_t ***aux_shape_data, int *complete);
int MXSymbolInferShapeEx64(SymbolHandle sym, uint32_t num_args,
                           const char **keys, const int64_t *arg_ind_ptr,
                           const int64_t *arg_shape_data,
                           size_t *in_shape_size,
                           const int **in_shape_ndim,
                           const int64_t ***in_shape_data,
                           size_t *out_shape_size,
                           const int **out_shape_ndim,
                           const int64_t ***out_shape_data,
                           size_t *aux_shape_size,
                           const int **aux_shape_ndim,
                           const int64_t ***aux_shape_data, int *complete);
int MXSymbolInferShapePartialEx(
    SymbolHandle sym, uint32_t num_args, const char **keys,
    const uint32_t *arg_ind_ptr, const int *arg_shape_data,
    uint32_t *in_shape_size, const int **in_shape_ndim,
    const int ***in_shape_data, uint32_t *out_shape_size,
    const int **out_shape_ndim, const int ***out_shape_data,
    uint32_t *aux_shape_size, const int **aux_shape_ndim,
    const int ***aux_shape_data, int *complete);
int MXSymbolInferShapePartial64(
    SymbolHandle sym, uint32_t num_args, const char **keys,
    const int64_t *arg_ind_ptr, const int64_t *arg_shape_data,
    size_t *in_shape_size, const int **in_shape_ndim,
    const int64_t ***in_shape_data, size_t *out_shape_size,
    const int **out_shape_ndim, const int64_t ***out_shape_data,
    size_t *aux_shape_size, const int **aux_shape_ndim,
    const int64_t ***aux_shape_data, int *complete);
int MXSymbolInferShapePartialEx64(
    SymbolHandle sym, uint32_t num_args, const char **keys,
    const int64_t *arg_ind_ptr, const int64_t *arg_shape_data,
    size_t *in_shape_size, const int **in_shape_ndim,
    const int64_t ***in_shape_data, size_t *out_shape_size,
    const int **out_shape_ndim, const int64_t ***out_shape_data,
    size_t *aux_shape_size, const int **aux_shape_ndim,
    const int64_t ***aux_shape_data, int *complete);

#ifdef __cplusplus
} /* extern "C" */
#endif

#endif /* MXTPU_PREDICT_H_ */
