"""Native runtime components (C++ via ctypes).

The reference's native runtime surface — dmlc RecordIO reader, threaded IO
parser/prefetcher (src/io/) — re-implemented TPU-host-side in C++
(recordio.cc). Built on demand with g++ (no pybind11 in this image; plain
C ABI + ctypes). `lib()` compiles lazily and caches the .so next to the
source; all Python-level classes degrade gracefully to the pure-Python
implementations when a toolchain is unavailable.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as onp

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRCS = [os.path.join(_HERE, "recordio.cc"),
         os.path.join(_HERE, "image_pipeline.cc")]
_SRC = _SRCS[0]  # kept for external references
_SO = os.path.join(_HERE, "libmxtpu_native.so")

_lock = threading.Lock()
_lib = None
_build_error = None


def build(force: bool = False) -> str:
    """Compile the native library (cached)."""
    if not force and os.path.exists(_SO) and \
            all(os.path.getmtime(_SO) >= os.path.getmtime(s)
                for s in _SRCS):
        return _SO
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           *_SRCS, "-o", _SO, "-ljpeg"]
    subprocess.run(cmd, check=True, capture_output=True)
    return _SO


def lib():
    """Load (building if needed) the native library, or None."""
    global _lib, _build_error
    with _lock:
        if _lib is not None or _build_error is not None:
            return _lib
        try:
            path = build()
            L = ctypes.CDLL(path)
            L.rio_open.restype = ctypes.c_void_p
            L.rio_open.argtypes = [ctypes.c_char_p]
            L.rio_error.restype = ctypes.c_char_p
            L.rio_error.argtypes = [ctypes.c_void_p]
            L.rio_count.restype = ctypes.c_int64
            L.rio_count.argtypes = [ctypes.c_void_p]
            L.rio_get.restype = ctypes.c_int64
            L.rio_get.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                  ctypes.POINTER(ctypes.POINTER(
                                      ctypes.c_uint8))]
            L.rio_close.argtypes = [ctypes.c_void_p]
            L.rio_writer_open.restype = ctypes.c_void_p
            L.rio_writer_open.argtypes = [ctypes.c_char_p]
            L.rio_writer_write.restype = ctypes.c_int
            L.rio_writer_write.argtypes = [ctypes.c_void_p,
                                           ctypes.c_char_p, ctypes.c_int64]
            L.rio_writer_close.argtypes = [ctypes.c_void_p]
            L.rio_batch_server_create.restype = ctypes.c_void_p
            L.rio_batch_server_create.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
                ctypes.c_uint64, ctypes.c_int]
            L.rio_batch_next.restype = ctypes.c_void_p
            L.rio_batch_next.argtypes = [ctypes.c_void_p]
            L.rio_batch_total_bytes.restype = ctypes.c_int64
            L.rio_batch_total_bytes.argtypes = [ctypes.c_void_p]
            L.rio_batch_data.restype = ctypes.POINTER(ctypes.c_uint8)
            L.rio_batch_data.argtypes = [ctypes.c_void_p]
            L.rio_batch_offsets.restype = ctypes.POINTER(ctypes.c_int64)
            L.rio_batch_offsets.argtypes = [ctypes.c_void_p]
            L.rio_batch_lengths.restype = ctypes.POINTER(ctypes.c_int64)
            L.rio_batch_lengths.argtypes = [ctypes.c_void_p]
            L.rio_batch_size.restype = ctypes.c_int64
            L.rio_batch_size.argtypes = [ctypes.c_void_p]
            L.rio_batch_free.argtypes = [ctypes.c_void_p]
            L.rio_batch_server_reset.argtypes = [ctypes.c_void_p]
            L.rio_batch_server_destroy.argtypes = [ctypes.c_void_p]
            L.imgpipe_create.restype = ctypes.c_void_p
            L.imgpipe_create.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                ctypes.c_int, ctypes.c_int, ctypes.c_int,
                ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_float), ctypes.c_uint64,
                ctypes.c_int, ctypes.c_float, ctypes.c_int]
            L.imgpipe_next.restype = ctypes.c_void_p
            L.imgpipe_next.argtypes = [ctypes.c_void_p]
            L.imgpipe_batch_data.restype = ctypes.POINTER(ctypes.c_float)
            L.imgpipe_batch_data.argtypes = [ctypes.c_void_p]
            L.imgpipe_batch_labels.restype = ctypes.POINTER(ctypes.c_float)
            L.imgpipe_batch_labels.argtypes = [ctypes.c_void_p]
            L.imgpipe_batch_n.restype = ctypes.c_int64
            L.imgpipe_batch_n.argtypes = [ctypes.c_void_p]
            L.imgpipe_batch_pad.restype = ctypes.c_int64
            L.imgpipe_batch_pad.argtypes = [ctypes.c_void_p]
            L.imgpipe_batch_free.argtypes = [ctypes.c_void_p]
            L.imgpipe_reset.argtypes = [ctypes.c_void_p]
            L.imgpipe_decode_failures.restype = ctypes.c_int64
            L.imgpipe_decode_failures.argtypes = [ctypes.c_void_p]
            L.imgpipe_destroy.argtypes = [ctypes.c_void_p]
            _lib = L
        except Exception as e:  # toolchain missing → python fallback
            _build_error = e
            _lib = None
        return _lib


def available() -> bool:
    return lib() is not None


class NativeRecordIO:
    """mmap'd zero-copy indexed reader (drop-in fast path for
    recordio.MXRecordIO read access)."""

    def __init__(self, path: str):
        L = lib()
        if L is None:
            raise RuntimeError(f"native lib unavailable: {_build_error}")
        self._L = L
        self._h = L.rio_open(path.encode())
        if not self._h:
            raise IOError(f"cannot open {path}")
        err = L.rio_error(self._h)
        if err:
            raise IOError(err.decode())

    def __len__(self):
        return int(self._L.rio_count(self._h))

    def read_idx(self, i: int) -> bytes:
        ptr = ctypes.POINTER(ctypes.c_uint8)()
        n = self._L.rio_get(self._h, i, ctypes.byref(ptr))
        if n < 0:
            raise IndexError(i)
        return ctypes.string_at(ptr, n)

    def close(self):
        if self._h:
            self._L.rio_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeRecordIOWriter:
    def __init__(self, path: str):
        L = lib()
        if L is None:
            raise RuntimeError(f"native lib unavailable: {_build_error}")
        self._L = L
        self._h = L.rio_writer_open(path.encode())
        if not self._h:
            raise IOError(f"cannot open {path}")

    def write(self, buf: bytes):
        if self._L.rio_writer_write(self._h, buf, len(buf)) != 0:
            raise IOError("write failed")

    def close(self):
        if self._h:
            self._L.rio_writer_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeBatchServer:
    """Threaded shuffled batch prefetcher (the iter_prefetcher.h /
    parser-thread role of the reference's C++ IO pipeline)."""

    def __init__(self, path: str, batch_size: int, shuffle: bool = False,
                 seed: int = 0, num_workers: int = 0):
        if num_workers <= 0:
            # MXNET_CPU_WORKER_NTHREADS sizes the native IO thread pool
            # (ref: env_var.md:25 — the CPU engine worker count)
            from ..base import get_env
            num_workers = max(2, int(get_env("MXNET_CPU_WORKER_NTHREADS",
                                             1)))
        self._reader = NativeRecordIO(path)
        self._L = self._reader._L
        self._h = self._L.rio_batch_server_create(
            self._reader._h, batch_size, int(shuffle), seed, num_workers)
        self.batch_size = batch_size

    def __iter__(self):
        while True:
            b = self._L.rio_batch_next(self._h)
            if not b:
                return
            n = int(self._L.rio_batch_size(b))
            total = int(self._L.rio_batch_total_bytes(b))
            data = onp.ctypeslib.as_array(self._L.rio_batch_data(b),
                                          shape=(total,)).copy()
            offs = onp.ctypeslib.as_array(self._L.rio_batch_offsets(b),
                                          shape=(n,)).copy()
            lens = onp.ctypeslib.as_array(self._L.rio_batch_lengths(b),
                                          shape=(n,)).copy()
            self._L.rio_batch_free(b)
            yield [data[o:o + l].tobytes()
                   for o, l in zip(offs.tolist(), lens.tolist())]

    def reset(self):
        self._L.rio_batch_server_reset(self._h)

    def close(self):
        if getattr(self, "_h", None):
            self._L.rio_batch_server_destroy(self._h)
            self._h = None
            self._reader.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# C predict API library (ref: src/c_api/c_predict_api.cc — the standalone
# inference ABI). Separate .so because it links libpython (the RecordIO
# library stays interpreter-free).
# ---------------------------------------------------------------------------

_CAPI_SRC = os.path.join(_HERE, "c_predict_api.cc")
_CAPI_SO = os.path.join(_HERE, "libmxtpu_capi.so")


_CAPI_HDR = os.path.join(_HERE, "mxtpu_predict.h")


def build_capi(force: bool = False) -> str:
    """Compile libmxtpu_capi.so (cached by source+header mtime)."""
    src_mtime = max(os.path.getmtime(_CAPI_SRC),
                    os.path.getmtime(_CAPI_HDR))
    if not force and os.path.exists(_CAPI_SO) and \
            os.path.getmtime(_CAPI_SO) >= src_mtime:
        return _CAPI_SO
    import sysconfig
    inc = sysconfig.get_path("include")
    libdir = sysconfig.get_config_var("LIBDIR")
    ldver = sysconfig.get_config_var("LDVERSION")
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", _CAPI_SRC,
           f"-I{inc}", f"-L{libdir}", f"-lpython{ldver}",
           f"-Wl,-rpath,{libdir}", "-o", _CAPI_SO]
    subprocess.run(cmd, check=True, capture_output=True)
    return _CAPI_SO


class NativeImagePipeline:
    """Threaded JPEG decode + augment + batch pipeline (image_pipeline.cc;
    ref: src/io/iter_image_recordio_2.cc parser threads +
    image_aug_default.cc). Yields (data, label) float32 numpy batches,
    NCHW by default."""

    def __init__(self, path: str, batch_size: int, data_shape=(3, 224, 224),
                 label_width: int = 1, shuffle: bool = False, resize: int = 0,
                 rand_crop: bool = False, rand_mirror: bool = False,
                 mean=None, std=None, seed: int = 0, num_workers: int = 0,
                 layout: str = "NCHW", label_pad_value: float = 0.0,
                 force_resize: bool = False):
        L = lib()
        if L is None:
            raise RuntimeError(f"native lib unavailable: {_build_error}")
        if num_workers <= 0:
            # MXNET_CPU_WORKER_NTHREADS sizes the native IO thread pool
            from ..base import get_env
            num_workers = max(2, int(get_env("MXNET_CPU_WORKER_NTHREADS",
                                             1)))
        self._L = L
        self._reader = NativeRecordIO(path)
        c, h, w = data_shape
        m = (ctypes.c_float * 3)(*(mean if mean is not None else (0, 0, 0)))
        s = (ctypes.c_float * 3)(*(std if std is not None else (1, 1, 1)))
        self._nhwc = layout == "NHWC"
        self._h = L.imgpipe_create(
            self._reader._h, batch_size, c, h, w, int(resize),
            int(label_width), int(rand_crop), int(rand_mirror),
            int(shuffle), int(self._nhwc), m, s, seed, num_workers,
            float(label_pad_value), int(force_resize))
        if not self._h:
            self._reader.close()
            raise ValueError(
                f"imgpipe_create rejected batch_size={batch_size}")
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width

    def __iter__(self):
        c, h, w = self.data_shape
        shape = (self.batch_size, h, w, c) if self._nhwc \
            else (self.batch_size, c, h, w)
        n_img = self.batch_size * c * h * w
        n_lbl = self.batch_size * self.label_width
        while True:
            b = self._L.imgpipe_next(self._h)
            if not b:
                return
            data = onp.ctypeslib.as_array(
                self._L.imgpipe_batch_data(b), shape=(n_img,)).copy()
            labels = onp.ctypeslib.as_array(
                self._L.imgpipe_batch_labels(b), shape=(n_lbl,)).copy()
            self.last_pad = int(self._L.imgpipe_batch_pad(b))
            self._L.imgpipe_batch_free(b)
            yield (data.reshape(shape),
                   labels.reshape(self.batch_size, self.label_width))

    def reset(self):
        self._L.imgpipe_reset(self._h)

    @property
    def decode_failures(self) -> int:
        return int(self._L.imgpipe_decode_failures(self._h))

    def close(self):
        if getattr(self, "_h", None):
            self._L.imgpipe_destroy(self._h)
            self._h = None
        if getattr(self, "_reader", None) is not None:
            self._reader.close()
            self._reader = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
