// Native RecordIO reader/writer + prefetching batch server.
//
// TPU-native equivalent of the reference's native IO path: dmlc-core's
// RecordIOReader/Writer (consumed per SURVEY.md Appendix B) and the
// threaded parser pipeline of src/io/iter_image_recordio_2.cc (parser
// threads + prefetch). Design differences from the reference:
//  - the file is mmap'd once and records are served zero-copy (the host
//    side of a TPU input pipeline is bandwidth-bound; no per-record
//    memcpy);
//  - a background thread pool assembles shuffled batches of raw payloads
//    into pinned host buffers which Python hands to jax.device_put —
//    decode/augment stays in Python (cv2/PIL) or downstream;
//  - exposed as a C ABI for ctypes (no pybind11 in this image).
//
// Record framing is bit-compatible with the reference format:
// [u32 magic=0xced7230a][u32 lrec: cflag(3 bits)<<29 | len(29 bits)]
// [payload][pad to 4B].

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint32_t kMagic = 0xced7230a;

struct Record {
  uint64_t offset;  // payload offset in file
  uint32_t length;
};

struct Reader {
  int fd = -1;
  const uint8_t* base = nullptr;
  size_t size = 0;
  std::vector<Record> records;
  std::string error;
};

bool index_records(Reader* r) {
  size_t pos = 0;
  while (pos + 8 <= r->size) {
    uint32_t magic, lrec;
    std::memcpy(&magic, r->base + pos, 4);
    std::memcpy(&lrec, r->base + pos + 4, 4);
    if (magic != kMagic) {
      r->error = "bad magic at offset " + std::to_string(pos);
      return false;
    }
    uint32_t len = lrec & ((1u << 29) - 1);
    if (pos + 8 + len > r->size) {
      r->error = "truncated record";
      return false;
    }
    r->records.push_back({pos + 8, len});
    size_t padded = (len + 3u) & ~3u;
    pos += 8 + padded;
  }
  return true;
}

// ---------------------------------------------------------------------------
// Prefetching batch server: worker threads pull shuffled index ranges and
// pack payloads into contiguous buffers (lengths + offsets sidecar), the
// analog of iter_batchloader.h + iter_prefetcher.h rolled together.
// ---------------------------------------------------------------------------

struct Batch {
  std::vector<uint8_t> data;     // concatenated payloads
  std::vector<int64_t> offsets;  // per-record start in `data`
  std::vector<int64_t> lengths;
};

struct BatchServer {
  Reader* reader = nullptr;
  int batch_size = 0;
  bool shuffle = false;
  uint64_t seed = 0;
  int epoch = 0;

  std::vector<uint32_t> order;
  size_t cursor = 0;

  std::deque<Batch*> ready;
  std::mutex mu;
  std::condition_variable cv_ready, cv_space;
  size_t max_ready = 4;
  std::vector<std::thread> workers;
  std::atomic<bool> stop{false};
  std::atomic<int> active{0};
  std::mutex cursor_mu;

  ~BatchServer() { shutdown(); }

  void reset_order() {
    order.resize(reader->records.size());
    for (uint32_t i = 0; i < order.size(); ++i) order[i] = i;
    if (shuffle) {
      std::mt19937_64 rng(seed + static_cast<uint64_t>(epoch));
      std::shuffle(order.begin(), order.end(), rng);
    }
    cursor = 0;
  }

  bool next_indices(std::vector<uint32_t>* idx) {
    std::lock_guard<std::mutex> lk(cursor_mu);
    if (cursor >= order.size()) return false;
    size_t end = std::min(cursor + batch_size, order.size());
    idx->assign(order.begin() + cursor, order.begin() + end);
    cursor = end;
    // pad final batch by wrapping (reference last_batch_handle="pad")
    size_t need = batch_size - idx->size();
    for (size_t i = 0; i < need; ++i) idx->push_back(order[i % order.size()]);
    return true;
  }

  void worker_loop() {
    std::vector<uint32_t> idx;
    while (!stop.load()) {
      if (!next_indices(&idx)) break;
      Batch* b = new Batch();
      size_t total = 0;
      for (uint32_t i : idx) total += reader->records[i].length;
      b->data.resize(total);
      b->offsets.reserve(idx.size());
      b->lengths.reserve(idx.size());
      size_t at = 0;
      for (uint32_t i : idx) {
        const Record& rec = reader->records[i];
        std::memcpy(b->data.data() + at, reader->base + rec.offset,
                    rec.length);
        b->offsets.push_back(static_cast<int64_t>(at));
        b->lengths.push_back(rec.length);
        at += rec.length;
      }
      std::unique_lock<std::mutex> lk(mu);
      cv_space.wait(lk, [this] {
        return ready.size() < max_ready || stop.load();
      });
      if (stop.load()) {
        delete b;
        active.fetch_sub(1);
        return;
      }
      ready.push_back(b);
      cv_ready.notify_one();
    }
    // only the LAST exiting worker marks end-of-epoch — an earlier
    // marker would make the consumer drop batches still in flight
    if (active.fetch_sub(1) == 1) {
      std::unique_lock<std::mutex> lk(mu);
      ready.push_back(nullptr);
      cv_ready.notify_all();
    }
  }

  void start(int num_workers) {
    stop.store(false);
    reset_order();
    active.store(num_workers);
    for (int i = 0; i < num_workers; ++i)
      workers.emplace_back([this] { worker_loop(); });
  }

  void shutdown() {
    stop.store(true);
    cv_space.notify_all();
    cv_ready.notify_all();
    for (auto& t : workers)
      if (t.joinable()) t.join();
    workers.clear();
    for (Batch* b : ready) delete b;
    ready.clear();
  }
};

}  // namespace

extern "C" {

void* rio_open(const char* path) {
  Reader* r = new Reader();
  r->fd = open(path, O_RDONLY);
  if (r->fd < 0) {
    delete r;
    return nullptr;
  }
  struct stat st;
  fstat(r->fd, &st);
  r->size = static_cast<size_t>(st.st_size);
  if (r->size > 0) {
    void* m = mmap(nullptr, r->size, PROT_READ, MAP_PRIVATE, r->fd, 0);
    if (m == MAP_FAILED) {
      close(r->fd);
      delete r;
      return nullptr;
    }
    r->base = static_cast<const uint8_t*>(m);
    madvise(const_cast<uint8_t*>(r->base), r->size, MADV_SEQUENTIAL);
  }
  if (!index_records(r)) {
    // leave error retrievable via rio_error
  }
  return r;
}

const char* rio_error(void* h) {
  return static_cast<Reader*>(h)->error.c_str();
}

int64_t rio_count(void* h) {
  return static_cast<int64_t>(static_cast<Reader*>(h)->records.size());
}

int64_t rio_get(void* h, int64_t i, const uint8_t** ptr) {
  Reader* r = static_cast<Reader*>(h);
  if (i < 0 || static_cast<size_t>(i) >= r->records.size()) return -1;
  const Record& rec = r->records[i];
  *ptr = r->base + rec.offset;
  return rec.length;
}

void rio_close(void* h) {
  Reader* r = static_cast<Reader*>(h);
  if (r->base) munmap(const_cast<uint8_t*>(r->base), r->size);
  if (r->fd >= 0) close(r->fd);
  delete r;
}

// -- writer -----------------------------------------------------------------

void* rio_writer_open(const char* path) {
  FILE* f = fopen(path, "wb");
  return f;
}

int rio_writer_write(void* h, const uint8_t* data, int64_t len) {
  FILE* f = static_cast<FILE*>(h);
  uint32_t magic = kMagic;
  uint32_t lrec = static_cast<uint32_t>(len) & ((1u << 29) - 1);
  if (fwrite(&magic, 4, 1, f) != 1) return -1;
  if (fwrite(&lrec, 4, 1, f) != 1) return -1;
  if (len > 0 && fwrite(data, 1, len, f) != static_cast<size_t>(len))
    return -1;
  static const uint8_t zeros[4] = {0, 0, 0, 0};
  size_t pad = (4 - (len % 4)) % 4;
  if (pad && fwrite(zeros, 1, pad, f) != pad) return -1;
  return 0;
}

void rio_writer_close(void* h) { fclose(static_cast<FILE*>(h)); }

// -- batch server -----------------------------------------------------------

void* rio_batch_server_create(void* reader, int batch_size, int shuffle,
                              uint64_t seed, int num_workers) {
  BatchServer* s = new BatchServer();
  s->reader = static_cast<Reader*>(reader);
  s->batch_size = batch_size;
  s->shuffle = shuffle != 0;
  s->seed = seed;
  s->start(num_workers > 0 ? num_workers : 2);
  return s;
}

// Returns a Batch* or nullptr at end of epoch.
void* rio_batch_next(void* server) {
  BatchServer* s = static_cast<BatchServer*>(server);
  std::unique_lock<std::mutex> lk(s->mu);
  s->cv_ready.wait(lk, [s] { return !s->ready.empty() || s->stop.load(); });
  if (s->ready.empty()) return nullptr;
  Batch* b = s->ready.front();
  s->ready.pop_front();
  s->cv_space.notify_one();
  return b;
}

int64_t rio_batch_total_bytes(void* batch) {
  return static_cast<int64_t>(static_cast<Batch*>(batch)->data.size());
}

const uint8_t* rio_batch_data(void* batch) {
  return static_cast<Batch*>(batch)->data.data();
}

const int64_t* rio_batch_offsets(void* batch) {
  return static_cast<Batch*>(batch)->offsets.data();
}

const int64_t* rio_batch_lengths(void* batch) {
  return static_cast<Batch*>(batch)->lengths.data();
}

int64_t rio_batch_size(void* batch) {
  return static_cast<int64_t>(static_cast<Batch*>(batch)->offsets.size());
}

void rio_batch_free(void* batch) { delete static_cast<Batch*>(batch); }

void rio_batch_server_reset(void* server) {
  BatchServer* s = static_cast<BatchServer*>(server);
  int workers = static_cast<int>(s->workers.size());
  s->shutdown();
  s->epoch += 1;
  s->start(workers > 0 ? workers : 2);
}

void rio_batch_server_destroy(void* server) {
  delete static_cast<BatchServer*>(server);
}

}  // extern "C"
