/*
 * Native C predict API: embeds CPython and drives mxnet_tpu.c_api_backend.
 *
 * TPU-native inversion of the reference ABI stack: there, Python sits on a
 * C++ core (src/c_api/c_predict_api.cc wraps the GraphExecutor); here the
 * compute core is jax/XLA behind Python, so the C ABI embeds the
 * interpreter once per process and marshals tensors as raw byte buffers.
 * The exported contract (mxtpu_predict.h) matches the reference's
 * c_predict_api.h subset, with API_BEGIN/API_END-style error capture into
 * a per-process last-error string (ref: src/c_api/c_api_error.cc).
 *
 * Build: g++ -O2 -std=c++17 -shared -fPIC c_predict_api.cc
 *            $(python3-config --includes) -L$LIBDIR -lpython3.12
 *            -o libmxtpu_capi.so
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

extern "C" {
#include "mxtpu_predict.h"
}

namespace {

std::mutex g_mutex;
// per-thread last error, like the reference's thread-local error ring
// (src/c_api/c_api_error.cc) — readable without locks
thread_local std::string g_last_error;
PyObject *g_backend = nullptr;  // mxnet_tpu.c_api_backend module

// op-name list storage for MXListAllOpNames
std::vector<std::string> g_op_names;
std::vector<const char *> g_op_name_ptrs;

struct Predictor {
  long handle;                          // backend-side id
  std::vector<std::vector<uint32_t>> out_shapes;  // per-output cache
};

void set_error(const std::string &msg) { g_last_error = msg; }

// marshal a Python list of str into C-string storage; non-UTF-8 entries
// are kept as "" so list positions stay aligned with handle arrays
void load_string_list(PyObject *list, std::vector<std::string> &names,
                      std::vector<const char *> &ptrs) {
  names.clear();
  ptrs.clear();
  Py_ssize_t n = PyList_Size(list);
  for (Py_ssize_t i = 0; i < n; ++i) {
    const char *s = PyUnicode_AsUTF8(PyList_GetItem(list, i));
    if (!s) PyErr_Clear();
    names.emplace_back(s ? s : "");
  }
  for (const auto &v : names) ptrs.push_back(v.c_str());
}

std::string fetch_py_error() {
  PyObject *type = nullptr, *value = nullptr, *trace = nullptr;
  PyErr_Fetch(&type, &value, &trace);
  PyErr_NormalizeException(&type, &value, &trace);
  std::string msg = "unknown python error";
  if (value) {
    PyObject *s = PyObject_Str(value);
    if (s) {
      const char *utf8 = PyUnicode_AsUTF8(s);
      if (utf8) {
        msg = utf8;
      } else {
        PyErr_Clear();  // non-representable message; keep the placeholder
      }
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(trace);
  return msg;
}

// Initialize the interpreter + import the backend module once.
bool ensure_backend() {
  if (g_backend) return true;
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);  // no signal handlers: stay a polite guest library
    // Py_InitializeEx leaves this thread holding the GIL; hand it back so
    // every entry point can use the PyGILState API uniformly
    PyEval_SaveThread();
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *mod = PyImport_ImportModule("mxnet_tpu.c_api_backend");
  if (!mod) {
    set_error("failed to import mxnet_tpu.c_api_backend (is PYTHONPATH "
              "set?): " + fetch_py_error());
    PyGILState_Release(gil);
    return false;
  }
  g_backend = mod;  // keep the reference for process lifetime
  PyGILState_Release(gil);
  return true;
}

// Call backend.<fn>(*args); returns new reference or nullptr (error set).
PyObject *call_backend(const char *fn, PyObject *args) {
  PyObject *f = PyObject_GetAttrString(g_backend, fn);
  if (!f) {
    set_error(std::string("backend missing function ") + fn);
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject *ret = PyObject_CallObject(f, args);
  Py_DECREF(f);
  Py_XDECREF(args);
  if (!ret) set_error(fetch_py_error());
  return ret;
}

}  // namespace

extern "C" {

const char *MXGetLastError(void) { return g_last_error.c_str(); }

int MXGetVersion(int *out) {
  std::lock_guard<std::mutex> lk(g_mutex);
  if (!ensure_backend()) return -1;
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *ret = call_backend("version", PyTuple_New(0));
  int rc = -1;
  if (ret) {
    *out = static_cast<int>(PyLong_AsLong(ret));
    Py_DECREF(ret);
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXListAllOpNames(uint32_t *out_size, const char ***out_array) {
  std::lock_guard<std::mutex> lk(g_mutex);
  if (!ensure_backend()) return -1;
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *ret = call_backend("list_op_names", PyTuple_New(0));
  int rc = -1;
  if (ret) {
    load_string_list(ret, g_op_names, g_op_name_ptrs);
    *out_size = static_cast<uint32_t>(g_op_names.size());
    *out_array = g_op_name_ptrs.data();
    Py_DECREF(ret);
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

static int pred_create_impl(const char *symbol_json_str,
                            const void *param_bytes, int param_size,
                            int dev_type, int dev_id,
                            uint32_t num_input_nodes,
                            const char **input_keys,
                            const uint32_t *input_shape_indptr,
                            const uint32_t *input_shape_data,
                            uint32_t num_output_nodes,
                            const char **output_keys, PredictorHandle *out) {
  std::lock_guard<std::mutex> lk(g_mutex);
  if (!ensure_backend()) return -1;
  PyGILState_STATE gil = PyGILState_Ensure();

  PyObject *names = PyList_New(num_input_nodes);
  PyObject *shapes = PyList_New(num_input_nodes);
  for (uint32_t i = 0; i < num_input_nodes; ++i) {
    PyList_SetItem(names, i, PyUnicode_FromString(input_keys[i]));
    uint32_t lo = input_shape_indptr[i], hi = input_shape_indptr[i + 1];
    PyObject *shp = PyList_New(hi - lo);
    for (uint32_t j = lo; j < hi; ++j)
      PyList_SetItem(shp, j - lo, PyLong_FromUnsignedLong(
                                      input_shape_data[j]));
    PyList_SetItem(shapes, i, shp);
  }
  PyObject *outputs = PyList_New(num_output_nodes);
  for (uint32_t i = 0; i < num_output_nodes; ++i)
    PyList_SetItem(outputs, i, PyUnicode_FromString(output_keys[i]));

  PyObject *args = Py_BuildValue(
      "(sy#iiOOO)", symbol_json_str,
      static_cast<const char *>(param_bytes),
      static_cast<Py_ssize_t>(param_size), dev_type, dev_id, names, shapes,
      outputs);
  Py_DECREF(names);
  Py_DECREF(shapes);
  Py_DECREF(outputs);
  PyObject *ret = call_backend("create", args);
  int rc = -1;
  if (ret) {
    auto *p = new Predictor{PyLong_AsLong(ret), {}};
    Py_DECREF(ret);
    *out = p;
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXPredCreate(const char *symbol_json_str, const void *param_bytes,
                 int param_size, int dev_type, int dev_id,
                 uint32_t num_input_nodes, const char **input_keys,
                 const uint32_t *input_shape_indptr,
                 const uint32_t *input_shape_data, PredictorHandle *out) {
  return pred_create_impl(symbol_json_str, param_bytes, param_size, dev_type,
                          dev_id, num_input_nodes, input_keys,
                          input_shape_indptr, input_shape_data, 0, nullptr,
                          out);
}

int MXPredCreatePartialOut(const char *symbol_json_str,
                           const void *param_bytes, int param_size,
                           int dev_type, int dev_id,
                           uint32_t num_input_nodes, const char **input_keys,
                           const uint32_t *input_shape_indptr,
                           const uint32_t *input_shape_data,
                           uint32_t num_output_nodes,
                           const char **output_keys, PredictorHandle *out) {
  return pred_create_impl(symbol_json_str, param_bytes, param_size, dev_type,
                          dev_id, num_input_nodes, input_keys,
                          input_shape_indptr, input_shape_data,
                          num_output_nodes, output_keys, out);
}

int MXPredGetOutputCount(PredictorHandle handle, uint32_t *out) {
  std::lock_guard<std::mutex> lk(g_mutex);
  auto *p = static_cast<Predictor *>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *ret = call_backend("num_outputs", Py_BuildValue("(l)", p->handle));
  int rc = -1;
  if (ret) {
    *out = static_cast<uint32_t>(PyLong_AsLong(ret));
    Py_DECREF(ret);
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXPredSetInput(PredictorHandle handle, const char *key,
                   const float *data, uint32_t size) {
  std::lock_guard<std::mutex> lk(g_mutex);
  auto *p = static_cast<Predictor *>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  // shape [] → backend reshapes to the declared input shape; we pass the
  // flat length and let numpy reshape on the python side
  PyObject *args = Py_BuildValue(
      "(lsy#[I]s)", p->handle, key, reinterpret_cast<const char *>(data),
      static_cast<Py_ssize_t>(size * sizeof(float)), size, "float32");
  PyObject *ret = call_backend("set_input_flat", args);
  int rc = -1;
  if (ret) {
    Py_DECREF(ret);
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXPredForward(PredictorHandle handle) {
  std::lock_guard<std::mutex> lk(g_mutex);
  auto *p = static_cast<Predictor *>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *ret = call_backend("forward", Py_BuildValue("(l)", p->handle));
  int rc = -1;
  if (ret) {
    Py_DECREF(ret);
    p->out_shapes.clear();
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXPredGetOutputShape(PredictorHandle handle, uint32_t index,
                         uint32_t **shape_data, uint32_t *shape_ndim) {
  std::lock_guard<std::mutex> lk(g_mutex);
  auto *p = static_cast<Predictor *>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *ret = call_backend("get_output_shape",
                               Py_BuildValue("(lI)", p->handle, index));
  int rc = -1;
  if (ret) {
    if (p->out_shapes.size() <= index) p->out_shapes.resize(index + 1);
    auto &shp = p->out_shapes[index];
    shp.clear();
    Py_ssize_t nd = PyTuple_Size(ret);
    for (Py_ssize_t i = 0; i < nd; ++i)
      shp.push_back(static_cast<uint32_t>(
          PyLong_AsLong(PyTuple_GetItem(ret, i))));
    Py_DECREF(ret);
    *shape_data = shp.data();
    *shape_ndim = static_cast<uint32_t>(shp.size());
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXPredGetOutput(PredictorHandle handle, uint32_t index, float *data,
                    uint32_t size) {
  std::lock_guard<std::mutex> lk(g_mutex);
  auto *p = static_cast<Predictor *>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *ret = call_backend("get_output",
                               Py_BuildValue("(lI)", p->handle, index));
  int rc = -1;
  if (ret) {
    char *buf = nullptr;
    Py_ssize_t n = 0;
    if (PyBytes_AsStringAndSize(ret, &buf, &n) == 0) {
      if (static_cast<uint32_t>(n) != size * sizeof(float)) {
        set_error("MXPredGetOutput: caller buffer holds " +
                  std::to_string(size) + " floats but output has " +
                  std::to_string(n / sizeof(float)));
      } else {
        std::memcpy(data, buf, n);
        rc = 0;
      }
    } else {
      set_error(fetch_py_error());
    }
    Py_DECREF(ret);
  }
  PyGILState_Release(gil);
  return rc;
}

int MXPredFree(PredictorHandle handle) {
  std::lock_guard<std::mutex> lk(g_mutex);
  auto *p = static_cast<Predictor *>(handle);
  if (!p) return 0;
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *ret = call_backend("free", Py_BuildValue("(l)", p->handle));
  Py_XDECREF(ret);
  PyGILState_Release(gil);
  delete p;
  return 0;
}

}  // extern "C"

/* ------------------------------------------------------------------------
 * General MX* ABI subset beyond MXPred: NDArray / Symbol / Executor /
 * imperative invoke (ref: include/mxnet/c_api.h — MXNDArrayCreateEx,
 * MXNDArraySyncCopy*, MXNDArraySave/Load, MXImperativeInvokeEx,
 * MXSymbolCreateFromJSON, MXExecutorBind/Forward). Handles are opaque
 * integer ids owned by the Python backend.
 * --------------------------------------------------------------------- */

namespace {

thread_local std::vector<uint32_t> g_shape_buf;
thread_local std::string g_str_buf;
thread_local std::vector<void *> g_handle_buf;
thread_local std::vector<std::string> g_name_buf;
thread_local std::vector<const char *> g_name_ptr_buf;

long as_id(void *h) { return reinterpret_cast<intptr_t>(h); }

// PyTuple_Pack does NOT steal references; this does (so inline-created
// argument objects are owned by the tuple and freed with it)
template <typename... Os>
PyObject *pack_steal(Os... objs) {
  constexpr Py_ssize_t n = sizeof...(objs);
  PyObject *arr[] = {objs...};
  PyObject *t = PyTuple_New(n);
  for (Py_ssize_t i = 0; i < n; ++i) PyTuple_SetItem(t, i, arr[i]);
  return t;
}
void *as_handle(long id) {
  return reinterpret_cast<void *>(static_cast<intptr_t>(id));
}

// run fn under lock+GIL; fn returns new ref or nullptr
template <typename F>
int with_backend(F &&fn) {
  std::lock_guard<std::mutex> lk(g_mutex);
  if (!ensure_backend()) return -1;
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = fn() ? 0 : -1;
  PyGILState_Release(gil);
  return rc;
}

PyObject *shape_list(const uint32_t *shape, uint32_t ndim) {
  PyObject *s = PyList_New(ndim);
  for (uint32_t i = 0; i < ndim; ++i)
    PyList_SetItem(s, i, PyLong_FromUnsignedLong(shape[i]));
  return s;
}

}  // namespace

extern "C" {

int MXNDArrayCreate(const uint32_t *shape, uint32_t ndim, const char *dtype,
                    void **out) {
  return with_backend([&]() -> bool {
    PyObject *args = pack_steal(shape_list(shape, ndim),
                                  PyUnicode_FromString(dtype));
    PyObject *ret = call_backend("ndarray_create", args);
    if (!ret) return false;
    *out = as_handle(PyLong_AsLong(ret));
    Py_DECREF(ret);
    return true;
  });
}

int MXNDArrayCreateFromBytes(const void *data, uint64_t nbytes,
                             const uint32_t *shape, uint32_t ndim,
                             const char *dtype, void **out) {
  return with_backend([&]() -> bool {
    PyObject *args = pack_steal(
        PyBytes_FromStringAndSize(static_cast<const char *>(data),
                                  static_cast<Py_ssize_t>(nbytes)),
        shape_list(shape, ndim), PyUnicode_FromString(dtype));
    PyObject *ret = call_backend("ndarray_from_bytes", args);
    if (!ret) return false;
    *out = as_handle(PyLong_AsLong(ret));
    Py_DECREF(ret);
    return true;
  });
}

int MXNDArrayFree(void *handle) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "ndarray_free", pack_steal(PyLong_FromLong(as_id(handle))));
    Py_XDECREF(ret);
    return ret != nullptr;
  });
}

int MXNDArrayGetShape(void *handle, uint32_t *out_dim,
                      const uint32_t **out_pdata) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "ndarray_get_shape",
        pack_steal(PyLong_FromLong(as_id(handle))));
    if (!ret) return false;
    Py_ssize_t n = PyTuple_Size(ret);
    g_shape_buf.resize(static_cast<size_t>(n));
    for (Py_ssize_t i = 0; i < n; ++i)
      g_shape_buf[i] = static_cast<uint32_t>(
          PyLong_AsLong(PyTuple_GetItem(ret, i)));
    Py_DECREF(ret);
    *out_dim = static_cast<uint32_t>(n);
    *out_pdata = g_shape_buf.data();
    return true;
  });
}

int MXNDArrayGetDType(void *handle, const char **out) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "ndarray_get_dtype",
        pack_steal(PyLong_FromLong(as_id(handle))));
    if (!ret) return false;
    const char *s = PyUnicode_AsUTF8(ret);
    g_str_buf = s ? s : "";
    Py_DECREF(ret);
    *out = g_str_buf.c_str();
    return true;
  });
}

int MXNDArraySyncCopyToCPU(void *handle, void *data, uint64_t size) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "ndarray_sync_copy_to_cpu",
        pack_steal(PyLong_FromLong(as_id(handle))));
    if (!ret) return false;
    char *buf = nullptr;
    Py_ssize_t n = 0;
    PyBytes_AsStringAndSize(ret, &buf, &n);
    if (static_cast<uint64_t>(n) > size) {
      set_error("MXNDArraySyncCopyToCPU: buffer too small");
      Py_DECREF(ret);
      return false;
    }
    std::memcpy(data, buf, static_cast<size_t>(n));
    Py_DECREF(ret);
    return true;
  });
}

int MXNDArraySyncCopyFromCPU(void *handle, const void *data, uint64_t size) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "ndarray_sync_copy_from_cpu",
        pack_steal(PyLong_FromLong(as_id(handle)),
                     PyBytes_FromStringAndSize(
                         static_cast<const char *>(data),
                         static_cast<Py_ssize_t>(size))));
    Py_XDECREF(ret);
    return ret != nullptr;
  });
}

int MXNDArraySave(const char *fname, uint32_t num, void **handles,
                  const char **keys) {
  return with_backend([&]() -> bool {
    PyObject *hs = PyList_New(num);
    PyObject *ks = PyList_New(keys ? num : 0);
    for (uint32_t i = 0; i < num; ++i) {
      PyList_SetItem(hs, i, PyLong_FromLong(as_id(handles[i])));
      if (keys) PyList_SetItem(ks, i, PyUnicode_FromString(keys[i]));
    }
    PyObject *ret = call_backend(
        "ndarray_save",
        pack_steal(PyUnicode_FromString(fname), hs, ks));
    Py_XDECREF(ret);
    return ret != nullptr;
  });
}

int MXNDArrayLoad(const char *fname, uint32_t *out_size, void ***out_arr,
                  uint32_t *out_name_size, const char ***out_names) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "ndarray_load", pack_steal(PyUnicode_FromString(fname)));
    if (!ret) return false;
    PyObject *hs = PyTuple_GetItem(ret, 0);
    PyObject *ns = PyTuple_GetItem(ret, 1);
    Py_ssize_t n = PyList_Size(hs);
    g_handle_buf.resize(static_cast<size_t>(n));
    for (Py_ssize_t i = 0; i < n; ++i)
      g_handle_buf[i] = as_handle(PyLong_AsLong(PyList_GetItem(hs, i)));
    load_string_list(ns, g_name_buf, g_name_ptr_buf);
    Py_DECREF(ret);
    *out_size = static_cast<uint32_t>(n);
    *out_arr = g_handle_buf.data();
    *out_name_size = static_cast<uint32_t>(g_name_buf.size());
    *out_names = g_name_ptr_buf.data();
    return true;
  });
}

int MXImperativeInvoke(const char *op_name, int num_inputs, void **inputs,
                       int *num_outputs, void ***outputs, int num_params,
                       const char **param_keys, const char **param_vals) {
  return with_backend([&]() -> bool {
    PyObject *ins = PyList_New(num_inputs);
    for (int i = 0; i < num_inputs; ++i)
      PyList_SetItem(ins, i, PyLong_FromLong(as_id(inputs[i])));
    PyObject *ks = PyList_New(num_params);
    PyObject *vs = PyList_New(num_params);
    for (int i = 0; i < num_params; ++i) {
      PyList_SetItem(ks, i, PyUnicode_FromString(param_keys[i]));
      PyList_SetItem(vs, i, PyUnicode_FromString(param_vals[i]));
    }
    PyObject *ret = call_backend(
        "imperative_invoke",
        pack_steal(PyUnicode_FromString(op_name), ins, ks, vs));
    if (!ret) return false;
    Py_ssize_t n = PyList_Size(ret);
    g_handle_buf.resize(static_cast<size_t>(n));
    for (Py_ssize_t i = 0; i < n; ++i)
      g_handle_buf[i] = as_handle(PyLong_AsLong(PyList_GetItem(ret, i)));
    Py_DECREF(ret);
    *num_outputs = static_cast<int>(n);
    *outputs = g_handle_buf.data();
    return true;
  });
}

int MXSymbolCreateFromJSON(const char *json, void **out) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "symbol_create_from_json",
        pack_steal(PyUnicode_FromString(json)));
    if (!ret) return false;
    *out = as_handle(PyLong_AsLong(ret));
    Py_DECREF(ret);
    return true;
  });
}

int MXSymbolSaveToJSON(void *handle, const char **out_json) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "symbol_save_to_json",
        pack_steal(PyLong_FromLong(as_id(handle))));
    if (!ret) return false;
    const char *s = PyUnicode_AsUTF8(ret);
    g_str_buf = s ? s : "";
    Py_DECREF(ret);
    *out_json = g_str_buf.c_str();
    return true;
  });
}

static int list_strings(const char *fn, void *handle, uint32_t *out_size,
                        const char ***out_arr) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        fn, pack_steal(PyLong_FromLong(as_id(handle))));
    if (!ret) return false;
    Py_ssize_t n = PyList_Size(ret);
    g_name_buf.clear();
    g_name_ptr_buf.clear();
    for (Py_ssize_t i = 0; i < n; ++i) {
      const char *s = PyUnicode_AsUTF8(PyList_GetItem(ret, i));
      g_name_buf.emplace_back(s ? s : "");
    }
    for (const auto &s : g_name_buf) g_name_ptr_buf.push_back(s.c_str());
    Py_DECREF(ret);
    *out_size = static_cast<uint32_t>(n);
    *out_arr = g_name_ptr_buf.data();
    return true;
  });
}

int MXSymbolListArguments(void *handle, uint32_t *out_size,
                          const char ***out_arr) {
  return list_strings("symbol_list_arguments", handle, out_size, out_arr);
}

int MXSymbolListOutputs(void *handle, uint32_t *out_size,
                        const char ***out_arr) {
  return list_strings("symbol_list_outputs", handle, out_size, out_arr);
}

int MXSymbolListAuxiliaryStates(void *handle, uint32_t *out_size,
                                const char ***out_arr) {
  return list_strings("symbol_list_auxiliary_states", handle, out_size,
                      out_arr);
}

int MXSymbolFree(void *handle) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "symbol_free", pack_steal(PyLong_FromLong(as_id(handle))));
    Py_XDECREF(ret);
    return ret != nullptr;
  });
}

int MXExecutorBind(void *sym_handle, int dev_type, int dev_id,
                   uint32_t num_args, void **arg_handles,
                   const char *grad_req, void **out) {
  return with_backend([&]() -> bool {
    PyObject *args_list = PyList_New(num_args);
    for (uint32_t i = 0; i < num_args; ++i)
      PyList_SetItem(args_list, i,
                     PyLong_FromLong(as_id(arg_handles[i])));
    PyObject *ret = call_backend(
        "executor_bind",
        pack_steal(PyLong_FromLong(as_id(sym_handle)),
                   PyLong_FromLong(dev_type), PyLong_FromLong(dev_id),
                   args_list,
                   PyUnicode_FromString(grad_req ? grad_req : "null")));
    if (!ret) return false;
    *out = as_handle(PyLong_AsLong(ret));
    Py_DECREF(ret);
    return true;
  });
}

int MXExecutorBackward(void *handle, uint32_t *out_size, void ***grads) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "executor_backward",
        pack_steal(PyLong_FromLong(as_id(handle))));
    if (!ret) return false;
    Py_ssize_t n = PyList_Size(ret);
    g_handle_buf.resize(static_cast<size_t>(n));
    for (Py_ssize_t i = 0; i < n; ++i)
      g_handle_buf[i] = as_handle(PyLong_AsLong(PyList_GetItem(ret, i)));
    Py_DECREF(ret);
    *out_size = static_cast<uint32_t>(n);
    *grads = g_handle_buf.data();
    return true;
  });
}

int MXExecutorForward(void *handle, int is_train, uint32_t *out_size,
                      void ***outputs) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "executor_forward",
        pack_steal(PyLong_FromLong(as_id(handle)),
                     PyBool_FromLong(is_train)));
    if (!ret) return false;
    Py_ssize_t n = PyList_Size(ret);
    g_handle_buf.resize(static_cast<size_t>(n));
    for (Py_ssize_t i = 0; i < n; ++i)
      g_handle_buf[i] = as_handle(PyLong_AsLong(PyList_GetItem(ret, i)));
    Py_DECREF(ret);
    *out_size = static_cast<uint32_t>(n);
    *outputs = g_handle_buf.data();
    return true;
  });
}

int MXExecutorFree(void *handle) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "executor_free", pack_steal(PyLong_FromLong(as_id(handle))));
    Py_XDECREF(ret);
    return ret != nullptr;
  });
}

}  // extern "C"

/* ------------------------------------------------------------------------
 * Expanded MX* families: NDArray extras, autograd, symbol composition &
 * inference, KVStore, DataIter, misc (ref: include/mxnet/c_api.h).
 * --------------------------------------------------------------------- */

namespace {

// shared small helpers for the expanded families
bool ret_handle(PyObject *ret, void **out) {
  if (!ret) return false;
  *out = as_handle(PyLong_AsLong(ret));
  Py_DECREF(ret);
  return true;
}

bool ret_void(PyObject *ret) {
  Py_XDECREF(ret);
  return ret != nullptr;
}

bool ret_int(PyObject *ret, int *out) {
  if (!ret) return false;
  *out = static_cast<int>(PyLong_AsLong(ret));
  Py_DECREF(ret);
  return true;
}

bool ret_string(PyObject *ret, const char **out) {
  if (!ret) return false;
  const char *s = PyUnicode_AsUTF8(ret);
  if (!s) PyErr_Clear();
  g_str_buf = s ? s : "";
  Py_DECREF(ret);
  *out = g_str_buf.c_str();
  return true;
}

PyObject *handle_list(uint32_t num, void **handles) {
  PyObject *l = PyList_New(num);
  for (uint32_t i = 0; i < num; ++i)
    PyList_SetItem(l, i, PyLong_FromLong(as_id(handles[i])));
  return l;
}

PyObject *string_list(uint32_t num, const char **strs) {
  PyObject *l = PyList_New(num);
  for (uint32_t i = 0; i < num; ++i)
    PyList_SetItem(l, i, PyUnicode_FromString(strs[i]));
  return l;
}

// per-group storage for CSR-style shape outputs (InferShape): each group
// owns its rows so the pointers stay valid until the next call
struct ShapeGroup {
  std::vector<uint32_t> ndim;
  std::vector<std::vector<uint32_t>> rows;
  std::vector<const uint32_t *> ptrs;

  void load(PyObject *tuples) {  // list of tuples of ints
    Py_ssize_t n = PyList_Size(tuples);
    ndim.resize(static_cast<size_t>(n));
    rows.assign(static_cast<size_t>(n), {});
    ptrs.resize(static_cast<size_t>(n));
    for (Py_ssize_t i = 0; i < n; ++i) {
      PyObject *t = PyList_GetItem(tuples, i);
      Py_ssize_t d = PyTuple_Check(t) ? PyTuple_Size(t) : 0;
      ndim[i] = static_cast<uint32_t>(d);
      rows[i].resize(static_cast<size_t>(d));
      for (Py_ssize_t j = 0; j < d; ++j)
        rows[i][j] = static_cast<uint32_t>(
            PyLong_AsUnsignedLong(PyTuple_GetItem(t, j)));
      ptrs[i] = rows[i].data();
    }
  }
};

thread_local ShapeGroup g_in_shapes, g_out_shapes, g_aux_shapes;

// string-list groups for InferType outputs
struct StrGroup {
  std::vector<std::string> vals;
  std::vector<const char *> ptrs;

  void load(PyObject *list) { load_string_list(list, vals, ptrs); }
};

thread_local StrGroup g_in_types, g_out_types, g_aux_types;

}  // namespace

extern "C" {

/* --- NDArray extras --------------------------------------------------- */

int MXNDArraySlice(void *handle, uint32_t begin, uint32_t end, void **out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "ndarray_slice",
        pack_steal(PyLong_FromLong(as_id(handle)),
                   PyLong_FromUnsignedLong(begin),
                   PyLong_FromUnsignedLong(end))), out);
  });
}

int MXNDArrayAt(void *handle, uint32_t idx, void **out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "ndarray_at", pack_steal(PyLong_FromLong(as_id(handle)),
                                 PyLong_FromUnsignedLong(idx))), out);
  });
}

int MXNDArrayReshape(void *handle, int ndim, const int *dims, void **out) {
  return with_backend([&]() -> bool {
    PyObject *s = PyList_New(ndim);
    for (int i = 0; i < ndim; ++i)
      PyList_SetItem(s, i, PyLong_FromLong(dims[i]));
    return ret_handle(call_backend(
        "ndarray_reshape",
        pack_steal(PyLong_FromLong(as_id(handle)), s)), out);
  });
}

int MXNDArrayGetContext(void *handle, int *out_dev_type, int *out_dev_id) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "ndarray_get_context", pack_steal(PyLong_FromLong(as_id(handle))));
    if (!ret) return false;
    *out_dev_type = static_cast<int>(PyLong_AsLong(PyTuple_GetItem(ret, 0)));
    *out_dev_id = static_cast<int>(PyLong_AsLong(PyTuple_GetItem(ret, 1)));
    Py_DECREF(ret);
    return true;
  });
}

int MXNDArrayWaitToRead(void *handle) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend(
        "ndarray_wait_to_read", pack_steal(PyLong_FromLong(as_id(handle)))));
  });
}

int MXNDArrayWaitAll(void) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend("ndarray_wait_all", PyTuple_New(0)));
  });
}

int MXNDArrayGetGrad(void *handle, void **out) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "ndarray_get_grad", pack_steal(PyLong_FromLong(as_id(handle))));
    if (!ret) return false;
    long id = PyLong_AsLong(ret);
    Py_DECREF(ret);
    *out = id ? as_handle(id) : nullptr;  /* NULL: no grad attached */
    return true;
  });
}

/* --- autograd --------------------------------------------------------- */

int MXAutogradSetIsRecording(int is_recording, int *prev) {
  return with_backend([&]() -> bool {
    return ret_int(call_backend("autograd_set_is_recording",
                                pack_steal(PyLong_FromLong(is_recording))),
                   prev);
  });
}

int MXAutogradSetIsTraining(int is_training, int *prev) {
  return with_backend([&]() -> bool {
    return ret_int(call_backend("autograd_set_is_training",
                                pack_steal(PyLong_FromLong(is_training))),
                   prev);
  });
}

int MXAutogradIsRecording(int *out) {
  return with_backend([&]() -> bool {
    return ret_int(call_backend("autograd_is_recording", PyTuple_New(0)),
                   out);
  });
}

int MXAutogradIsTraining(int *out) {
  return with_backend([&]() -> bool {
    return ret_int(call_backend("autograd_is_training", PyTuple_New(0)),
                   out);
  });
}

int MXAutogradMarkVariables(uint32_t num, void **var_handles,
                            uint32_t *grad_reqs, void **grad_handles) {
  return with_backend([&]() -> bool {
    PyObject *reqs = PyList_New(num);
    for (uint32_t i = 0; i < num; ++i)
      PyList_SetItem(reqs, i, PyLong_FromUnsignedLong(grad_reqs[i]));
    return ret_void(call_backend(
        "autograd_mark_variables",
        pack_steal(handle_list(num, var_handles),
                   handle_list(num, grad_handles), reqs)));
  });
}

int MXAutogradBackward(uint32_t num_output, void **output_handles,
                       void **ograd_handles, int retain_graph,
                       int train_mode) {
  return with_backend([&]() -> bool {
    PyObject *ograds;
    if (ograd_handles) {
      ograds = PyList_New(num_output);
      for (uint32_t i = 0; i < num_output; ++i)
        PyList_SetItem(ograds, i,
                       PyLong_FromLong(ograd_handles[i]
                                           ? as_id(ograd_handles[i]) : 0));
    } else {
      ograds = PyList_New(0);
    }
    return ret_void(call_backend(
        "autograd_backward",
        pack_steal(handle_list(num_output, output_handles), ograds,
                   PyLong_FromLong(retain_graph),
                   PyLong_FromLong(train_mode))));
  });
}

/* --- symbol composition & inference ----------------------------------- */

int MXSymbolCreateVariable(const char *name, void **out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "symbol_create_variable",
        pack_steal(PyUnicode_FromString(name))), out);
  });
}

int MXSymbolCreateAtomicSymbol(const char *op_name, uint32_t num_param,
                               const char **keys, const char **vals,
                               void **out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "symbol_create_atomic",
        pack_steal(PyUnicode_FromString(op_name),
                   string_list(num_param, keys),
                   string_list(num_param, vals))), out);
  });
}

int MXSymbolCompose(void *handle, const char *name, uint32_t num_args,
                    const char **keys, void **args) {
  return with_backend([&]() -> bool {
    /* keys == NULL: positional, in declared op-input order; otherwise
     * named binding resolved by the backend */
    PyObject *key_list = keys ? string_list(num_args, keys)
                              : PyList_New(0);
    return ret_void(call_backend(
        "symbol_compose",
        pack_steal(PyLong_FromLong(as_id(handle)),
                   PyUnicode_FromString(name ? name : ""), key_list,
                   handle_list(num_args, args))));
  });
}

int MXSymbolCopy(void *handle, void **out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "symbol_copy", pack_steal(PyLong_FromLong(as_id(handle)))), out);
  });
}

int MXSymbolGetInternals(void *handle, void **out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "symbol_get_internals",
        pack_steal(PyLong_FromLong(as_id(handle)))), out);
  });
}

int MXSymbolGetName(void *handle, const char **out) {
  return with_backend([&]() -> bool {
    return ret_string(call_backend(
        "symbol_get_name", pack_steal(PyLong_FromLong(as_id(handle)))),
                      out);
  });
}

int MXSymbolInferShape(void *handle, uint32_t num_args, const char **keys,
                       const uint32_t *arg_ind_ptr,
                       const uint32_t *arg_shape_data,
                       uint32_t *in_shape_size,
                       const uint32_t **in_shape_ndim,
                       const uint32_t ***in_shape_data,
                       uint32_t *out_shape_size,
                       const uint32_t **out_shape_ndim,
                       const uint32_t ***out_shape_data,
                       uint32_t *aux_shape_size,
                       const uint32_t **aux_shape_ndim,
                       const uint32_t ***aux_shape_data) {
  return with_backend([&]() -> bool {
    PyObject *names = string_list(num_args, keys);
    PyObject *shapes = PyList_New(num_args);
    for (uint32_t i = 0; i < num_args; ++i) {
      uint32_t lo = arg_ind_ptr[i], hi = arg_ind_ptr[i + 1];
      PyList_SetItem(shapes, i, shape_list(arg_shape_data + lo, hi - lo));
    }
    PyObject *ret = call_backend(
        "symbol_infer_shape",
        pack_steal(PyLong_FromLong(as_id(handle)), names, shapes));
    if (!ret) return false;
    g_in_shapes.load(PyTuple_GetItem(ret, 0));
    g_out_shapes.load(PyTuple_GetItem(ret, 1));
    g_aux_shapes.load(PyTuple_GetItem(ret, 2));
    Py_DECREF(ret);
    *in_shape_size = static_cast<uint32_t>(g_in_shapes.ndim.size());
    *in_shape_ndim = g_in_shapes.ndim.data();
    *in_shape_data = g_in_shapes.ptrs.data();
    *out_shape_size = static_cast<uint32_t>(g_out_shapes.ndim.size());
    *out_shape_ndim = g_out_shapes.ndim.data();
    *out_shape_data = g_out_shapes.ptrs.data();
    *aux_shape_size = static_cast<uint32_t>(g_aux_shapes.ndim.size());
    *aux_shape_ndim = g_aux_shapes.ndim.data();
    *aux_shape_data = g_aux_shapes.ptrs.data();
    return true;
  });
}

int MXSymbolInferType(void *handle, uint32_t num_args, const char **keys,
                      const char **arg_dtypes, uint32_t *in_type_size,
                      const char ***in_types, uint32_t *out_type_size,
                      const char ***out_types, uint32_t *aux_type_size,
                      const char ***aux_types) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "symbol_infer_type",
        pack_steal(PyLong_FromLong(as_id(handle)),
                   string_list(num_args, keys),
                   string_list(num_args, arg_dtypes)));
    if (!ret) return false;
    g_in_types.load(PyTuple_GetItem(ret, 0));
    g_out_types.load(PyTuple_GetItem(ret, 1));
    g_aux_types.load(PyTuple_GetItem(ret, 2));
    Py_DECREF(ret);
    *in_type_size = static_cast<uint32_t>(g_in_types.ptrs.size());
    *in_types = g_in_types.ptrs.data();
    *out_type_size = static_cast<uint32_t>(g_out_types.ptrs.size());
    *out_types = g_out_types.ptrs.data();
    *aux_type_size = static_cast<uint32_t>(g_aux_types.ptrs.size());
    *aux_types = g_aux_types.ptrs.data();
    return true;
  });
}

/* --- kvstore ----------------------------------------------------------- */

int MXKVStoreCreate(const char *type, void **out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "kvstore_create",
        pack_steal(PyUnicode_FromString(type ? type : "local"))), out);
  });
}

int MXKVStoreFree(void *handle) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend(
        "kvstore_free", pack_steal(PyLong_FromLong(as_id(handle)))));
  });
}

static int kv_apply(const char *fn, void *handle, uint32_t num,
                    const char **keys, void **vals, int priority,
                    bool with_priority) {
  return with_backend([&]() -> bool {
    PyObject *args =
        with_priority
            ? pack_steal(PyLong_FromLong(as_id(handle)),
                         string_list(num, keys), handle_list(num, vals),
                         PyLong_FromLong(priority))
            : pack_steal(PyLong_FromLong(as_id(handle)),
                         string_list(num, keys), handle_list(num, vals));
    return ret_void(call_backend(fn, args));
  });
}

int MXKVStoreInit(void *handle, uint32_t num, const char **keys,
                  void **vals) {
  return kv_apply("kvstore_init", handle, num, keys, vals, 0, false);
}

int MXKVStorePush(void *handle, uint32_t num, const char **keys,
                  void **vals, int priority) {
  return kv_apply("kvstore_push", handle, num, keys, vals, priority, true);
}

int MXKVStorePull(void *handle, uint32_t num, const char **keys,
                  void **vals, int priority) {
  return kv_apply("kvstore_pull", handle, num, keys, vals, priority, true);
}

int MXKVStoreGetRank(void *handle, int *rank) {
  return with_backend([&]() -> bool {
    return ret_int(call_backend(
        "kvstore_get_rank", pack_steal(PyLong_FromLong(as_id(handle)))),
                   rank);
  });
}

int MXKVStoreGetGroupSize(void *handle, int *size) {
  return with_backend([&]() -> bool {
    return ret_int(call_backend(
        "kvstore_get_group_size",
        pack_steal(PyLong_FromLong(as_id(handle)))), size);
  });
}

int MXKVStoreGetType(void *handle, const char **type) {
  return with_backend([&]() -> bool {
    return ret_string(call_backend(
        "kvstore_get_type", pack_steal(PyLong_FromLong(as_id(handle)))),
                      type);
  });
}

int MXKVStoreBarrier(void *handle) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend(
        "kvstore_barrier", pack_steal(PyLong_FromLong(as_id(handle)))));
  });
}

/* --- data iterators ---------------------------------------------------- */

int MXListDataIters(uint32_t *out_size, const char ***out_array) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend("list_data_iters", PyTuple_New(0));
    if (!ret) return false;
    // dedicated buffers: g_name_buf backs MXNDArrayLoad's returned name
    // array, which must stay valid across unrelated ABI calls
    thread_local std::vector<std::string> iter_names;
    thread_local std::vector<const char *> iter_ptrs;
    load_string_list(ret, iter_names, iter_ptrs);
    Py_DECREF(ret);
    *out_size = static_cast<uint32_t>(iter_names.size());
    *out_array = iter_ptrs.data();
    return true;
  });
}

int MXDataIterCreateIter(const char *name, uint32_t num_param,
                         const char **keys, const char **vals, void **out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "data_iter_create",
        pack_steal(PyUnicode_FromString(name), string_list(num_param, keys),
                   string_list(num_param, vals))), out);
  });
}

int MXDataIterNext(void *handle, int *out) {
  return with_backend([&]() -> bool {
    return ret_int(call_backend(
        "data_iter_next", pack_steal(PyLong_FromLong(as_id(handle)))), out);
  });
}

int MXDataIterBeforeFirst(void *handle) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend(
        "data_iter_before_first",
        pack_steal(PyLong_FromLong(as_id(handle)))));
  });
}

int MXDataIterGetData(void *handle, void **out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "data_iter_get_data",
        pack_steal(PyLong_FromLong(as_id(handle)))), out);
  });
}

int MXDataIterGetLabel(void *handle, void **out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "data_iter_get_label",
        pack_steal(PyLong_FromLong(as_id(handle)))), out);
  });
}

int MXDataIterFree(void *handle) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend(
        "data_iter_free", pack_steal(PyLong_FromLong(as_id(handle)))));
  });
}

/* --- misc --------------------------------------------------------------- */

int MXRandomSeed(int seed) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend("random_seed",
                                 pack_steal(PyLong_FromLong(seed))));
  });
}

int MXGetGPUCount(int *out) {
  return with_backend([&]() -> bool {
    return ret_int(call_backend("get_gpu_count", PyTuple_New(0)), out);
  });
}

int MXSetProfilerState(int state) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend(
        "profiler_set_state",
        pack_steal(PyUnicode_FromString(state ? "run" : "stop"))));
  });
}

int MXDumpProfile(void) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend("profiler_dump", PyTuple_New(0)));
  });
}

int MXNotifyShutdown(void) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend("notify_shutdown", PyTuple_New(0)));
  });
}

}  // extern "C"
