/*
 * Native C predict API: embeds CPython and drives mxnet_tpu.c_api_backend.
 *
 * TPU-native inversion of the reference ABI stack: there, Python sits on a
 * C++ core (src/c_api/c_predict_api.cc wraps the GraphExecutor); here the
 * compute core is jax/XLA behind Python, so the C ABI embeds the
 * interpreter once per process and marshals tensors as raw byte buffers.
 * The exported contract (mxtpu_predict.h) matches the reference's
 * c_predict_api.h subset, with API_BEGIN/API_END-style error capture into
 * a per-process last-error string (ref: src/c_api/c_api_error.cc).
 *
 * Build: g++ -O2 -std=c++17 -shared -fPIC c_predict_api.cc
 *            $(python3-config --includes) -L$LIBDIR -lpython3.12
 *            -o libmxtpu_capi.so
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

extern "C" {
#include "mxtpu_predict.h"
}

namespace {

std::mutex g_mutex;
// per-thread last error, like the reference's thread-local error ring
// (src/c_api/c_api_error.cc) — readable without locks
thread_local std::string g_last_error;
PyObject *g_backend = nullptr;  // mxnet_tpu.c_api_backend module

// op-name list storage for MXListAllOpNames
std::vector<std::string> g_op_names;
std::vector<const char *> g_op_name_ptrs;

struct Predictor {
  long handle;                          // backend-side id
  std::vector<std::vector<uint32_t>> out_shapes;  // per-output cache
};

void set_error(const std::string &msg) { g_last_error = msg; }

std::string fetch_py_error() {
  PyObject *type = nullptr, *value = nullptr, *trace = nullptr;
  PyErr_Fetch(&type, &value, &trace);
  PyErr_NormalizeException(&type, &value, &trace);
  std::string msg = "unknown python error";
  if (value) {
    PyObject *s = PyObject_Str(value);
    if (s) {
      const char *utf8 = PyUnicode_AsUTF8(s);
      if (utf8) {
        msg = utf8;
      } else {
        PyErr_Clear();  // non-representable message; keep the placeholder
      }
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(trace);
  return msg;
}

// Initialize the interpreter + import the backend module once.
bool ensure_backend() {
  if (g_backend) return true;
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);  // no signal handlers: stay a polite guest library
    // Py_InitializeEx leaves this thread holding the GIL; hand it back so
    // every entry point can use the PyGILState API uniformly
    PyEval_SaveThread();
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *mod = PyImport_ImportModule("mxnet_tpu.c_api_backend");
  if (!mod) {
    set_error("failed to import mxnet_tpu.c_api_backend (is PYTHONPATH "
              "set?): " + fetch_py_error());
    PyGILState_Release(gil);
    return false;
  }
  g_backend = mod;  // keep the reference for process lifetime
  PyGILState_Release(gil);
  return true;
}

// Call backend.<fn>(*args); returns new reference or nullptr (error set).
PyObject *call_backend(const char *fn, PyObject *args) {
  PyObject *f = PyObject_GetAttrString(g_backend, fn);
  if (!f) {
    set_error(std::string("backend missing function ") + fn);
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject *ret = PyObject_CallObject(f, args);
  Py_DECREF(f);
  Py_XDECREF(args);
  if (!ret) set_error(fetch_py_error());
  return ret;
}

}  // namespace

extern "C" {

const char *MXGetLastError(void) { return g_last_error.c_str(); }

int MXGetVersion(int *out) {
  std::lock_guard<std::mutex> lk(g_mutex);
  if (!ensure_backend()) return -1;
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *ret = call_backend("version", PyTuple_New(0));
  int rc = -1;
  if (ret) {
    *out = static_cast<int>(PyLong_AsLong(ret));
    Py_DECREF(ret);
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXListAllOpNames(uint32_t *out_size, const char ***out_array) {
  std::lock_guard<std::mutex> lk(g_mutex);
  if (!ensure_backend()) return -1;
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *ret = call_backend("list_op_names", PyTuple_New(0));
  int rc = -1;
  if (ret) {
    g_op_names.clear();
    g_op_name_ptrs.clear();
    Py_ssize_t n = PyList_Size(ret);
    for (Py_ssize_t i = 0; i < n; ++i) {
      const char *utf8 = PyUnicode_AsUTF8(PyList_GetItem(ret, i));
      if (!utf8) {  // skip non-UTF-8-representable names
        PyErr_Clear();
        continue;
      }
      g_op_names.emplace_back(utf8);
    }
    for (const auto &s : g_op_names) g_op_name_ptrs.push_back(s.c_str());
    *out_size = static_cast<uint32_t>(g_op_names.size());
    *out_array = g_op_name_ptrs.data();
    Py_DECREF(ret);
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

static int pred_create_impl(const char *symbol_json_str,
                            const void *param_bytes, int param_size,
                            int dev_type, int dev_id,
                            uint32_t num_input_nodes,
                            const char **input_keys,
                            const uint32_t *input_shape_indptr,
                            const uint32_t *input_shape_data,
                            uint32_t num_output_nodes,
                            const char **output_keys, PredictorHandle *out) {
  std::lock_guard<std::mutex> lk(g_mutex);
  if (!ensure_backend()) return -1;
  PyGILState_STATE gil = PyGILState_Ensure();

  PyObject *names = PyList_New(num_input_nodes);
  PyObject *shapes = PyList_New(num_input_nodes);
  for (uint32_t i = 0; i < num_input_nodes; ++i) {
    PyList_SetItem(names, i, PyUnicode_FromString(input_keys[i]));
    uint32_t lo = input_shape_indptr[i], hi = input_shape_indptr[i + 1];
    PyObject *shp = PyList_New(hi - lo);
    for (uint32_t j = lo; j < hi; ++j)
      PyList_SetItem(shp, j - lo, PyLong_FromUnsignedLong(
                                      input_shape_data[j]));
    PyList_SetItem(shapes, i, shp);
  }
  PyObject *outputs = PyList_New(num_output_nodes);
  for (uint32_t i = 0; i < num_output_nodes; ++i)
    PyList_SetItem(outputs, i, PyUnicode_FromString(output_keys[i]));

  PyObject *args = Py_BuildValue(
      "(sy#iiOOO)", symbol_json_str,
      static_cast<const char *>(param_bytes),
      static_cast<Py_ssize_t>(param_size), dev_type, dev_id, names, shapes,
      outputs);
  Py_DECREF(names);
  Py_DECREF(shapes);
  Py_DECREF(outputs);
  PyObject *ret = call_backend("create", args);
  int rc = -1;
  if (ret) {
    auto *p = new Predictor{PyLong_AsLong(ret), {}};
    Py_DECREF(ret);
    *out = p;
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXPredCreate(const char *symbol_json_str, const void *param_bytes,
                 int param_size, int dev_type, int dev_id,
                 uint32_t num_input_nodes, const char **input_keys,
                 const uint32_t *input_shape_indptr,
                 const uint32_t *input_shape_data, PredictorHandle *out) {
  return pred_create_impl(symbol_json_str, param_bytes, param_size, dev_type,
                          dev_id, num_input_nodes, input_keys,
                          input_shape_indptr, input_shape_data, 0, nullptr,
                          out);
}

int MXPredCreatePartialOut(const char *symbol_json_str,
                           const void *param_bytes, int param_size,
                           int dev_type, int dev_id,
                           uint32_t num_input_nodes, const char **input_keys,
                           const uint32_t *input_shape_indptr,
                           const uint32_t *input_shape_data,
                           uint32_t num_output_nodes,
                           const char **output_keys, PredictorHandle *out) {
  return pred_create_impl(symbol_json_str, param_bytes, param_size, dev_type,
                          dev_id, num_input_nodes, input_keys,
                          input_shape_indptr, input_shape_data,
                          num_output_nodes, output_keys, out);
}

int MXPredGetOutputCount(PredictorHandle handle, uint32_t *out) {
  std::lock_guard<std::mutex> lk(g_mutex);
  auto *p = static_cast<Predictor *>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *ret = call_backend("num_outputs", Py_BuildValue("(l)", p->handle));
  int rc = -1;
  if (ret) {
    *out = static_cast<uint32_t>(PyLong_AsLong(ret));
    Py_DECREF(ret);
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXPredSetInput(PredictorHandle handle, const char *key,
                   const float *data, uint32_t size) {
  std::lock_guard<std::mutex> lk(g_mutex);
  auto *p = static_cast<Predictor *>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  // shape [] → backend reshapes to the declared input shape; we pass the
  // flat length and let numpy reshape on the python side
  PyObject *args = Py_BuildValue(
      "(lsy#[I]s)", p->handle, key, reinterpret_cast<const char *>(data),
      static_cast<Py_ssize_t>(size * sizeof(float)), size, "float32");
  PyObject *ret = call_backend("set_input_flat", args);
  int rc = -1;
  if (ret) {
    Py_DECREF(ret);
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXPredForward(PredictorHandle handle) {
  std::lock_guard<std::mutex> lk(g_mutex);
  auto *p = static_cast<Predictor *>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *ret = call_backend("forward", Py_BuildValue("(l)", p->handle));
  int rc = -1;
  if (ret) {
    Py_DECREF(ret);
    p->out_shapes.clear();
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXPredGetOutputShape(PredictorHandle handle, uint32_t index,
                         uint32_t **shape_data, uint32_t *shape_ndim) {
  std::lock_guard<std::mutex> lk(g_mutex);
  auto *p = static_cast<Predictor *>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *ret = call_backend("get_output_shape",
                               Py_BuildValue("(lI)", p->handle, index));
  int rc = -1;
  if (ret) {
    if (p->out_shapes.size() <= index) p->out_shapes.resize(index + 1);
    auto &shp = p->out_shapes[index];
    shp.clear();
    Py_ssize_t nd = PyTuple_Size(ret);
    for (Py_ssize_t i = 0; i < nd; ++i)
      shp.push_back(static_cast<uint32_t>(
          PyLong_AsLong(PyTuple_GetItem(ret, i))));
    Py_DECREF(ret);
    *shape_data = shp.data();
    *shape_ndim = static_cast<uint32_t>(shp.size());
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXPredGetOutput(PredictorHandle handle, uint32_t index, float *data,
                    uint32_t size) {
  std::lock_guard<std::mutex> lk(g_mutex);
  auto *p = static_cast<Predictor *>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *ret = call_backend("get_output",
                               Py_BuildValue("(lI)", p->handle, index));
  int rc = -1;
  if (ret) {
    char *buf = nullptr;
    Py_ssize_t n = 0;
    if (PyBytes_AsStringAndSize(ret, &buf, &n) == 0) {
      if (static_cast<uint32_t>(n) != size * sizeof(float)) {
        set_error("MXPredGetOutput: caller buffer holds " +
                  std::to_string(size) + " floats but output has " +
                  std::to_string(n / sizeof(float)));
      } else {
        std::memcpy(data, buf, n);
        rc = 0;
      }
    } else {
      set_error(fetch_py_error());
    }
    Py_DECREF(ret);
  }
  PyGILState_Release(gil);
  return rc;
}

int MXPredFree(PredictorHandle handle) {
  std::lock_guard<std::mutex> lk(g_mutex);
  auto *p = static_cast<Predictor *>(handle);
  if (!p) return 0;
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *ret = call_backend("free", Py_BuildValue("(l)", p->handle));
  Py_XDECREF(ret);
  PyGILState_Release(gil);
  delete p;
  return 0;
}

}  // extern "C"

/* ------------------------------------------------------------------------
 * General MX* ABI subset beyond MXPred: NDArray / Symbol / Executor /
 * imperative invoke (ref: include/mxnet/c_api.h — MXNDArrayCreateEx,
 * MXNDArraySyncCopy*, MXNDArraySave/Load, MXImperativeInvokeEx,
 * MXSymbolCreateFromJSON, MXExecutorBind/Forward). Handles are opaque
 * integer ids owned by the Python backend.
 * --------------------------------------------------------------------- */

namespace {

thread_local std::vector<uint32_t> g_shape_buf;
thread_local std::string g_str_buf;
thread_local std::vector<void *> g_handle_buf;
thread_local std::vector<std::string> g_name_buf;
thread_local std::vector<const char *> g_name_ptr_buf;

long as_id(void *h) { return reinterpret_cast<intptr_t>(h); }

// PyTuple_Pack does NOT steal references; this does (so inline-created
// argument objects are owned by the tuple and freed with it)
template <typename... Os>
PyObject *pack_steal(Os... objs) {
  constexpr Py_ssize_t n = sizeof...(objs);
  PyObject *arr[] = {objs...};
  PyObject *t = PyTuple_New(n);
  for (Py_ssize_t i = 0; i < n; ++i) PyTuple_SetItem(t, i, arr[i]);
  return t;
}
void *as_handle(long id) {
  return reinterpret_cast<void *>(static_cast<intptr_t>(id));
}

// run fn under lock+GIL; fn returns new ref or nullptr
template <typename F>
int with_backend(F &&fn) {
  std::lock_guard<std::mutex> lk(g_mutex);
  if (!ensure_backend()) return -1;
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = fn() ? 0 : -1;
  PyGILState_Release(gil);
  return rc;
}

PyObject *shape_list(const uint32_t *shape, uint32_t ndim) {
  PyObject *s = PyList_New(ndim);
  for (uint32_t i = 0; i < ndim; ++i)
    PyList_SetItem(s, i, PyLong_FromUnsignedLong(shape[i]));
  return s;
}

}  // namespace

extern "C" {

int MXNDArrayCreate(const uint32_t *shape, uint32_t ndim, const char *dtype,
                    void **out) {
  return with_backend([&]() -> bool {
    PyObject *args = pack_steal(shape_list(shape, ndim),
                                  PyUnicode_FromString(dtype));
    PyObject *ret = call_backend("ndarray_create", args);
    if (!ret) return false;
    *out = as_handle(PyLong_AsLong(ret));
    Py_DECREF(ret);
    return true;
  });
}

int MXNDArrayCreateFromBytes(const void *data, uint64_t nbytes,
                             const uint32_t *shape, uint32_t ndim,
                             const char *dtype, void **out) {
  return with_backend([&]() -> bool {
    PyObject *args = PyTuple_Pack(
        3,
        PyBytes_FromStringAndSize(static_cast<const char *>(data),
                                  static_cast<Py_ssize_t>(nbytes)),
        shape_list(shape, ndim), PyUnicode_FromString(dtype));
    PyObject *ret = call_backend("ndarray_from_bytes", args);
    if (!ret) return false;
    *out = as_handle(PyLong_AsLong(ret));
    Py_DECREF(ret);
    return true;
  });
}

int MXNDArrayFree(void *handle) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "ndarray_free", pack_steal(PyLong_FromLong(as_id(handle))));
    Py_XDECREF(ret);
    return ret != nullptr;
  });
}

int MXNDArrayGetShape(void *handle, uint32_t *out_dim,
                      const uint32_t **out_pdata) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "ndarray_get_shape",
        pack_steal(PyLong_FromLong(as_id(handle))));
    if (!ret) return false;
    Py_ssize_t n = PyTuple_Size(ret);
    g_shape_buf.resize(static_cast<size_t>(n));
    for (Py_ssize_t i = 0; i < n; ++i)
      g_shape_buf[i] = static_cast<uint32_t>(
          PyLong_AsLong(PyTuple_GetItem(ret, i)));
    Py_DECREF(ret);
    *out_dim = static_cast<uint32_t>(n);
    *out_pdata = g_shape_buf.data();
    return true;
  });
}

int MXNDArrayGetDType(void *handle, const char **out) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "ndarray_get_dtype",
        pack_steal(PyLong_FromLong(as_id(handle))));
    if (!ret) return false;
    const char *s = PyUnicode_AsUTF8(ret);
    g_str_buf = s ? s : "";
    Py_DECREF(ret);
    *out = g_str_buf.c_str();
    return true;
  });
}

int MXNDArraySyncCopyToCPU(void *handle, void *data, uint64_t size) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "ndarray_sync_copy_to_cpu",
        pack_steal(PyLong_FromLong(as_id(handle))));
    if (!ret) return false;
    char *buf = nullptr;
    Py_ssize_t n = 0;
    PyBytes_AsStringAndSize(ret, &buf, &n);
    if (static_cast<uint64_t>(n) > size) {
      set_error("MXNDArraySyncCopyToCPU: buffer too small");
      Py_DECREF(ret);
      return false;
    }
    std::memcpy(data, buf, static_cast<size_t>(n));
    Py_DECREF(ret);
    return true;
  });
}

int MXNDArraySyncCopyFromCPU(void *handle, const void *data, uint64_t size) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "ndarray_sync_copy_from_cpu",
        pack_steal(PyLong_FromLong(as_id(handle)),
                     PyBytes_FromStringAndSize(
                         static_cast<const char *>(data),
                         static_cast<Py_ssize_t>(size))));
    Py_XDECREF(ret);
    return ret != nullptr;
  });
}

int MXNDArraySave(const char *fname, uint32_t num, void **handles,
                  const char **keys) {
  return with_backend([&]() -> bool {
    PyObject *hs = PyList_New(num);
    PyObject *ks = PyList_New(keys ? num : 0);
    for (uint32_t i = 0; i < num; ++i) {
      PyList_SetItem(hs, i, PyLong_FromLong(as_id(handles[i])));
      if (keys) PyList_SetItem(ks, i, PyUnicode_FromString(keys[i]));
    }
    PyObject *ret = call_backend(
        "ndarray_save",
        pack_steal(PyUnicode_FromString(fname), hs, ks));
    Py_XDECREF(ret);
    return ret != nullptr;
  });
}

int MXNDArrayLoad(const char *fname, uint32_t *out_size, void ***out_arr,
                  uint32_t *out_name_size, const char ***out_names) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "ndarray_load", pack_steal(PyUnicode_FromString(fname)));
    if (!ret) return false;
    PyObject *hs = PyTuple_GetItem(ret, 0);
    PyObject *ns = PyTuple_GetItem(ret, 1);
    Py_ssize_t n = PyList_Size(hs), nn = PyList_Size(ns);
    g_handle_buf.resize(static_cast<size_t>(n));
    for (Py_ssize_t i = 0; i < n; ++i)
      g_handle_buf[i] = as_handle(PyLong_AsLong(PyList_GetItem(hs, i)));
    g_name_buf.clear();
    g_name_ptr_buf.clear();
    for (Py_ssize_t i = 0; i < nn; ++i) {
      const char *s = PyUnicode_AsUTF8(PyList_GetItem(ns, i));
      g_name_buf.emplace_back(s ? s : "");
    }
    for (const auto &s : g_name_buf) g_name_ptr_buf.push_back(s.c_str());
    Py_DECREF(ret);
    *out_size = static_cast<uint32_t>(n);
    *out_arr = g_handle_buf.data();
    *out_name_size = static_cast<uint32_t>(nn);
    *out_names = g_name_ptr_buf.data();
    return true;
  });
}

int MXImperativeInvoke(const char *op_name, int num_inputs, void **inputs,
                       int *num_outputs, void ***outputs, int num_params,
                       const char **param_keys, const char **param_vals) {
  return with_backend([&]() -> bool {
    PyObject *ins = PyList_New(num_inputs);
    for (int i = 0; i < num_inputs; ++i)
      PyList_SetItem(ins, i, PyLong_FromLong(as_id(inputs[i])));
    PyObject *ks = PyList_New(num_params);
    PyObject *vs = PyList_New(num_params);
    for (int i = 0; i < num_params; ++i) {
      PyList_SetItem(ks, i, PyUnicode_FromString(param_keys[i]));
      PyList_SetItem(vs, i, PyUnicode_FromString(param_vals[i]));
    }
    PyObject *ret = call_backend(
        "imperative_invoke",
        pack_steal(PyUnicode_FromString(op_name), ins, ks, vs));
    if (!ret) return false;
    Py_ssize_t n = PyList_Size(ret);
    g_handle_buf.resize(static_cast<size_t>(n));
    for (Py_ssize_t i = 0; i < n; ++i)
      g_handle_buf[i] = as_handle(PyLong_AsLong(PyList_GetItem(ret, i)));
    Py_DECREF(ret);
    *num_outputs = static_cast<int>(n);
    *outputs = g_handle_buf.data();
    return true;
  });
}

int MXSymbolCreateFromJSON(const char *json, void **out) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "symbol_create_from_json",
        pack_steal(PyUnicode_FromString(json)));
    if (!ret) return false;
    *out = as_handle(PyLong_AsLong(ret));
    Py_DECREF(ret);
    return true;
  });
}

int MXSymbolSaveToJSON(void *handle, const char **out_json) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "symbol_save_to_json",
        pack_steal(PyLong_FromLong(as_id(handle))));
    if (!ret) return false;
    const char *s = PyUnicode_AsUTF8(ret);
    g_str_buf = s ? s : "";
    Py_DECREF(ret);
    *out_json = g_str_buf.c_str();
    return true;
  });
}

static int list_strings(const char *fn, void *handle, uint32_t *out_size,
                        const char ***out_arr) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        fn, pack_steal(PyLong_FromLong(as_id(handle))));
    if (!ret) return false;
    Py_ssize_t n = PyList_Size(ret);
    g_name_buf.clear();
    g_name_ptr_buf.clear();
    for (Py_ssize_t i = 0; i < n; ++i) {
      const char *s = PyUnicode_AsUTF8(PyList_GetItem(ret, i));
      g_name_buf.emplace_back(s ? s : "");
    }
    for (const auto &s : g_name_buf) g_name_ptr_buf.push_back(s.c_str());
    Py_DECREF(ret);
    *out_size = static_cast<uint32_t>(n);
    *out_arr = g_name_ptr_buf.data();
    return true;
  });
}

int MXSymbolListArguments(void *handle, uint32_t *out_size,
                          const char ***out_arr) {
  return list_strings("symbol_list_arguments", handle, out_size, out_arr);
}

int MXSymbolListOutputs(void *handle, uint32_t *out_size,
                        const char ***out_arr) {
  return list_strings("symbol_list_outputs", handle, out_size, out_arr);
}

int MXSymbolListAuxiliaryStates(void *handle, uint32_t *out_size,
                                const char ***out_arr) {
  return list_strings("symbol_list_auxiliary_states", handle, out_size,
                      out_arr);
}

int MXSymbolFree(void *handle) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "symbol_free", pack_steal(PyLong_FromLong(as_id(handle))));
    Py_XDECREF(ret);
    return ret != nullptr;
  });
}

int MXExecutorBind(void *sym_handle, int dev_type, int dev_id,
                   uint32_t num_args, void **arg_handles,
                   const char *grad_req, void **out) {
  return with_backend([&]() -> bool {
    PyObject *args_list = PyList_New(num_args);
    for (uint32_t i = 0; i < num_args; ++i)
      PyList_SetItem(args_list, i,
                     PyLong_FromLong(as_id(arg_handles[i])));
    PyObject *ret = call_backend(
        "executor_bind",
        pack_steal(PyLong_FromLong(as_id(sym_handle)),
                   PyLong_FromLong(dev_type), PyLong_FromLong(dev_id),
                   args_list,
                   PyUnicode_FromString(grad_req ? grad_req : "null")));
    if (!ret) return false;
    *out = as_handle(PyLong_AsLong(ret));
    Py_DECREF(ret);
    return true;
  });
}

int MXExecutorBackward(void *handle, uint32_t *out_size, void ***grads) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "executor_backward",
        pack_steal(PyLong_FromLong(as_id(handle))));
    if (!ret) return false;
    Py_ssize_t n = PyList_Size(ret);
    g_handle_buf.resize(static_cast<size_t>(n));
    for (Py_ssize_t i = 0; i < n; ++i)
      g_handle_buf[i] = as_handle(PyLong_AsLong(PyList_GetItem(ret, i)));
    Py_DECREF(ret);
    *out_size = static_cast<uint32_t>(n);
    *grads = g_handle_buf.data();
    return true;
  });
}

int MXExecutorForward(void *handle, int is_train, uint32_t *out_size,
                      void ***outputs) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "executor_forward",
        pack_steal(PyLong_FromLong(as_id(handle)),
                     PyBool_FromLong(is_train)));
    if (!ret) return false;
    Py_ssize_t n = PyList_Size(ret);
    g_handle_buf.resize(static_cast<size_t>(n));
    for (Py_ssize_t i = 0; i < n; ++i)
      g_handle_buf[i] = as_handle(PyLong_AsLong(PyList_GetItem(ret, i)));
    Py_DECREF(ret);
    *out_size = static_cast<uint32_t>(n);
    *outputs = g_handle_buf.data();
    return true;
  });
}

int MXExecutorFree(void *handle) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "executor_free", pack_steal(PyLong_FromLong(as_id(handle))));
    Py_XDECREF(ret);
    return ret != nullptr;
  });
}

}  // extern "C"
