/*
 * Native C predict API: embeds CPython and drives mxnet_tpu.c_api_backend.
 *
 * TPU-native inversion of the reference ABI stack: there, Python sits on a
 * C++ core (src/c_api/c_predict_api.cc wraps the GraphExecutor); here the
 * compute core is jax/XLA behind Python, so the C ABI embeds the
 * interpreter once per process and marshals tensors as raw byte buffers.
 * The exported contract (mxtpu_predict.h) matches the reference's
 * c_predict_api.h subset, with API_BEGIN/API_END-style error capture into
 * a per-process last-error string (ref: src/c_api/c_api_error.cc).
 *
 * Build: g++ -O2 -std=c++17 -shared -fPIC c_predict_api.cc
 *            $(python3-config --includes) -L$LIBDIR -lpython3.12
 *            -o libmxtpu_capi.so
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#ifdef __linux__
#include <dlfcn.h>
#endif

#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <set>
#include <string>
#include <vector>

extern "C" {
#include "mxtpu_predict.h"
}

namespace {

// RECURSIVE: C callbacks invoked from inside an ABI call (the kvstore
// updater) legitimately call back into MX* on the same thread; a plain
// mutex would self-deadlock there. PyGILState_Ensure nests fine too.
std::recursive_mutex g_mutex;
// per-thread last error, like the reference's thread-local error ring
// (src/c_api/c_api_error.cc) — readable without locks
thread_local std::string g_last_error;
PyObject *g_backend = nullptr;  // mxnet_tpu.c_api_backend module

// op-name list storage for MXListAllOpNames
std::vector<std::string> g_op_names;
std::vector<const char *> g_op_name_ptrs;

struct Predictor {
  long handle;                          // backend-side id
  std::vector<std::vector<uint32_t>> out_shapes;  // per-output cache
};

void set_error(const std::string &msg) { g_last_error = msg; }

// marshal a Python list of str into C-string storage; non-UTF-8 entries
// are kept as "" so list positions stay aligned with handle arrays
void load_string_list(PyObject *list, std::vector<std::string> &names,
                      std::vector<const char *> &ptrs) {
  names.clear();
  ptrs.clear();
  Py_ssize_t n = PyList_Size(list);
  for (Py_ssize_t i = 0; i < n; ++i) {
    const char *s = PyUnicode_AsUTF8(PyList_GetItem(list, i));
    if (!s) PyErr_Clear();
    names.emplace_back(s ? s : "");
  }
  for (const auto &v : names) ptrs.push_back(v.c_str());
}

std::string fetch_py_error() {
  PyObject *type = nullptr, *value = nullptr, *trace = nullptr;
  PyErr_Fetch(&type, &value, &trace);
  PyErr_NormalizeException(&type, &value, &trace);
  std::string msg = "unknown python error";
  if (value) {
    PyObject *s = PyObject_Str(value);
    if (s) {
      const char *utf8 = PyUnicode_AsUTF8(s);
      if (utf8) {
        msg = utf8;
      } else {
        PyErr_Clear();  // non-representable message; keep the placeholder
      }
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(trace);
  return msg;
}

// Initialize the interpreter + import the backend module once.
bool ensure_backend() {
  if (g_backend) return true;
  if (!Py_IsInitialized()) {
    // Hosts that dlopen this library WITHOUT RTLD_GLOBAL (perl XSLoader,
    // Java JNI, lua...) leave libpython's symbols local — python C
    // extension modules (numpy's _multiarray_umath, ...) then fail to
    // resolve them and numpy dies with a misleading "source directory"
    // error. Re-open libpython with RTLD_GLOBAL|RTLD_NOLOAD to promote
    // the already-mapped library's symbols.
#ifdef __linux__
    {
      char pylib[64];
      snprintf(pylib, sizeof(pylib), "libpython%d.%d.so.1.0",
               PY_MAJOR_VERSION, PY_MINOR_VERSION);
      if (!dlopen(pylib, RTLD_NOW | RTLD_GLOBAL | RTLD_NOLOAD)) {
        snprintf(pylib, sizeof(pylib), "libpython%d.%d.so",
                 PY_MAJOR_VERSION, PY_MINOR_VERSION);
        dlopen(pylib, RTLD_NOW | RTLD_GLOBAL | RTLD_NOLOAD);
      }
    }
#endif
    Py_InitializeEx(0);  // no signal handlers: stay a polite guest library
    // Py_InitializeEx leaves this thread holding the GIL; hand it back so
    // every entry point can use the PyGILState API uniformly
    PyEval_SaveThread();
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *mod = PyImport_ImportModule("mxnet_tpu.c_api_backend");
  if (!mod) {
    set_error("failed to import mxnet_tpu.c_api_backend (is PYTHONPATH "
              "set?): " + fetch_py_error());
    PyGILState_Release(gil);
    return false;
  }
  g_backend = mod;  // keep the reference for process lifetime
  PyGILState_Release(gil);
  return true;
}

// Call backend.<fn>(*args); returns new reference or nullptr (error set).
PyObject *call_backend(const char *fn, PyObject *args) {
  PyObject *f = PyObject_GetAttrString(g_backend, fn);
  if (!f) {
    set_error(std::string("backend missing function ") + fn);
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject *ret = PyObject_CallObject(f, args);
  Py_DECREF(f);
  Py_XDECREF(args);
  if (!ret) set_error(fetch_py_error());
  return ret;
}

}  // namespace

extern "C" {

const char *MXGetLastError(void) { return g_last_error.c_str(); }

int MXGetVersion(int *out) {
  std::lock_guard<std::recursive_mutex> lk(g_mutex);
  if (!ensure_backend()) return -1;
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *ret = call_backend("version", PyTuple_New(0));
  int rc = -1;
  if (ret) {
    *out = static_cast<int>(PyLong_AsLong(ret));
    Py_DECREF(ret);
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXListAllOpNames(uint32_t *out_size, const char ***out_array) {
  std::lock_guard<std::recursive_mutex> lk(g_mutex);
  if (!ensure_backend()) return -1;
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *ret = call_backend("list_op_names", PyTuple_New(0));
  int rc = -1;
  if (ret) {
    load_string_list(ret, g_op_names, g_op_name_ptrs);
    *out_size = static_cast<uint32_t>(g_op_names.size());
    *out_array = g_op_name_ptrs.data();
    Py_DECREF(ret);
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

static int pred_create_impl(const char *symbol_json_str,
                            const void *param_bytes, int param_size,
                            int dev_type, int dev_id,
                            uint32_t num_input_nodes,
                            const char **input_keys,
                            const uint32_t *input_shape_indptr,
                            const uint32_t *input_shape_data,
                            uint32_t num_output_nodes,
                            const char **output_keys, PredictorHandle *out) {
  std::lock_guard<std::recursive_mutex> lk(g_mutex);
  if (!ensure_backend()) return -1;
  PyGILState_STATE gil = PyGILState_Ensure();

  PyObject *names = PyList_New(num_input_nodes);
  PyObject *shapes = PyList_New(num_input_nodes);
  for (uint32_t i = 0; i < num_input_nodes; ++i) {
    PyList_SetItem(names, i, PyUnicode_FromString(input_keys[i]));
    uint32_t lo = input_shape_indptr[i], hi = input_shape_indptr[i + 1];
    PyObject *shp = PyList_New(hi - lo);
    for (uint32_t j = lo; j < hi; ++j)
      PyList_SetItem(shp, j - lo, PyLong_FromUnsignedLong(
                                      input_shape_data[j]));
    PyList_SetItem(shapes, i, shp);
  }
  PyObject *outputs = PyList_New(num_output_nodes);
  for (uint32_t i = 0; i < num_output_nodes; ++i)
    PyList_SetItem(outputs, i, PyUnicode_FromString(output_keys[i]));

  PyObject *args = Py_BuildValue(
      "(sy#iiOOO)", symbol_json_str,
      static_cast<const char *>(param_bytes),
      static_cast<Py_ssize_t>(param_size), dev_type, dev_id, names, shapes,
      outputs);
  Py_DECREF(names);
  Py_DECREF(shapes);
  Py_DECREF(outputs);
  PyObject *ret = call_backend("create", args);
  int rc = -1;
  if (ret) {
    auto *p = new Predictor{PyLong_AsLong(ret), {}};
    Py_DECREF(ret);
    *out = p;
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXPredCreate(const char *symbol_json_str, const void *param_bytes,
                 int param_size, int dev_type, int dev_id,
                 uint32_t num_input_nodes, const char **input_keys,
                 const uint32_t *input_shape_indptr,
                 const uint32_t *input_shape_data, PredictorHandle *out) {
  return pred_create_impl(symbol_json_str, param_bytes, param_size, dev_type,
                          dev_id, num_input_nodes, input_keys,
                          input_shape_indptr, input_shape_data, 0, nullptr,
                          out);
}

int MXPredCreatePartialOut(const char *symbol_json_str,
                           const void *param_bytes, int param_size,
                           int dev_type, int dev_id,
                           uint32_t num_input_nodes, const char **input_keys,
                           const uint32_t *input_shape_indptr,
                           const uint32_t *input_shape_data,
                           uint32_t num_output_nodes,
                           const char **output_keys, PredictorHandle *out) {
  return pred_create_impl(symbol_json_str, param_bytes, param_size, dev_type,
                          dev_id, num_input_nodes, input_keys,
                          input_shape_indptr, input_shape_data,
                          num_output_nodes, output_keys, out);
}

int MXPredGetOutputCount(PredictorHandle handle, uint32_t *out) {
  std::lock_guard<std::recursive_mutex> lk(g_mutex);
  auto *p = static_cast<Predictor *>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *ret = call_backend("num_outputs", Py_BuildValue("(l)", p->handle));
  int rc = -1;
  if (ret) {
    *out = static_cast<uint32_t>(PyLong_AsLong(ret));
    Py_DECREF(ret);
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXPredSetInput(PredictorHandle handle, const char *key,
                   const float *data, uint32_t size) {
  std::lock_guard<std::recursive_mutex> lk(g_mutex);
  auto *p = static_cast<Predictor *>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  // shape [] → backend reshapes to the declared input shape; we pass the
  // flat length and let numpy reshape on the python side
  PyObject *args = Py_BuildValue(
      "(lsy#[I]s)", p->handle, key, reinterpret_cast<const char *>(data),
      static_cast<Py_ssize_t>(size * sizeof(float)), size, "float32");
  PyObject *ret = call_backend("set_input_flat", args);
  int rc = -1;
  if (ret) {
    Py_DECREF(ret);
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXPredForward(PredictorHandle handle) {
  std::lock_guard<std::recursive_mutex> lk(g_mutex);
  auto *p = static_cast<Predictor *>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *ret = call_backend("forward", Py_BuildValue("(l)", p->handle));
  int rc = -1;
  if (ret) {
    Py_DECREF(ret);
    p->out_shapes.clear();
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXPredGetOutputShape(PredictorHandle handle, uint32_t index,
                         uint32_t **shape_data, uint32_t *shape_ndim) {
  std::lock_guard<std::recursive_mutex> lk(g_mutex);
  auto *p = static_cast<Predictor *>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *ret = call_backend("get_output_shape",
                               Py_BuildValue("(lI)", p->handle, index));
  int rc = -1;
  if (ret) {
    if (p->out_shapes.size() <= index) p->out_shapes.resize(index + 1);
    auto &shp = p->out_shapes[index];
    shp.clear();
    Py_ssize_t nd = PyTuple_Size(ret);
    for (Py_ssize_t i = 0; i < nd; ++i)
      shp.push_back(static_cast<uint32_t>(
          PyLong_AsLong(PyTuple_GetItem(ret, i))));
    Py_DECREF(ret);
    *shape_data = shp.data();
    *shape_ndim = static_cast<uint32_t>(shp.size());
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXPredGetOutput(PredictorHandle handle, uint32_t index, float *data,
                    uint32_t size) {
  std::lock_guard<std::recursive_mutex> lk(g_mutex);
  auto *p = static_cast<Predictor *>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *ret = call_backend("get_output",
                               Py_BuildValue("(lI)", p->handle, index));
  int rc = -1;
  if (ret) {
    char *buf = nullptr;
    Py_ssize_t n = 0;
    if (PyBytes_AsStringAndSize(ret, &buf, &n) == 0) {
      if (static_cast<uint32_t>(n) != size * sizeof(float)) {
        set_error("MXPredGetOutput: caller buffer holds " +
                  std::to_string(size) + " floats but output has " +
                  std::to_string(n / sizeof(float)));
      } else {
        std::memcpy(data, buf, n);
        rc = 0;
      }
    } else {
      set_error(fetch_py_error());
    }
    Py_DECREF(ret);
  }
  PyGILState_Release(gil);
  return rc;
}

int MXPredFree(PredictorHandle handle) {
  std::lock_guard<std::recursive_mutex> lk(g_mutex);
  auto *p = static_cast<Predictor *>(handle);
  if (!p) return 0;
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *ret = call_backend("free", Py_BuildValue("(l)", p->handle));
  Py_XDECREF(ret);
  PyGILState_Release(gil);
  delete p;
  return 0;
}

}  // extern "C"

/* ------------------------------------------------------------------------
 * General MX* ABI subset beyond MXPred: NDArray / Symbol / Executor /
 * imperative invoke (ref: include/mxnet/c_api.h — MXNDArrayCreateEx,
 * MXNDArraySyncCopy*, MXNDArraySave/Load, MXImperativeInvokeEx,
 * MXSymbolCreateFromJSON, MXExecutorBind/Forward). Handles are opaque
 * integer ids owned by the Python backend.
 * --------------------------------------------------------------------- */

namespace {

thread_local std::vector<uint32_t> g_shape_buf;
thread_local std::string g_str_buf;
thread_local std::vector<void *> g_handle_buf;
thread_local std::vector<std::string> g_name_buf;
thread_local std::vector<const char *> g_name_ptr_buf;

long as_id(void *h) { return reinterpret_cast<intptr_t>(h); }

// PyTuple_Pack does NOT steal references; this does (so inline-created
// argument objects are owned by the tuple and freed with it)
template <typename... Os>
PyObject *pack_steal(Os... objs) {
  constexpr Py_ssize_t n = sizeof...(objs);
  PyObject *arr[] = {objs...};
  PyObject *t = PyTuple_New(n);
  for (Py_ssize_t i = 0; i < n; ++i) PyTuple_SetItem(t, i, arr[i]);
  return t;
}
void *as_handle(long id) {
  return reinterpret_cast<void *>(static_cast<intptr_t>(id));
}

// executor monitor callbacks (MXExecutorSetMonitorCallback): keyed by
// executor handle, fired per output after each MXExecutorForward
typedef void (*ExecutorMonitorCallback_)(const char *, void *, void *);
std::map<void *, std::pair<ExecutorMonitorCallback_, void *>> g_monitors;

void fire_monitors(void *exec_handle, uint32_t n, void **outputs) {
  auto it = g_monitors.find(exec_handle);
  if (it == g_monitors.end()) return;
  char name[32];
  for (uint32_t i = 0; i < n; ++i) {
    snprintf(name, sizeof(name), "output%u", i);
    it->second.first(name, outputs[i], it->second.second);
  }
}

// run fn under lock+GIL; fn returns new ref or nullptr
template <typename F>
int with_backend(F &&fn) {
  std::lock_guard<std::recursive_mutex> lk(g_mutex);
  if (!ensure_backend()) return -1;
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = fn() ? 0 : -1;
  PyGILState_Release(gil);
  return rc;
}

PyObject *shape_list(const uint32_t *shape, uint32_t ndim) {
  PyObject *s = PyList_New(ndim);
  for (uint32_t i = 0; i < ndim; ++i)
    PyList_SetItem(s, i, PyLong_FromUnsignedLong(shape[i]));
  return s;
}

}  // namespace

extern "C" {

int MXNDArrayCreate(const uint32_t *shape, uint32_t ndim, const char *dtype,
                    void **out) {
  return with_backend([&]() -> bool {
    PyObject *args = pack_steal(shape_list(shape, ndim),
                                  PyUnicode_FromString(dtype));
    PyObject *ret = call_backend("ndarray_create", args);
    if (!ret) return false;
    *out = as_handle(PyLong_AsLong(ret));
    Py_DECREF(ret);
    return true;
  });
}

int MXNDArrayCreateFromBytes(const void *data, uint64_t nbytes,
                             const uint32_t *shape, uint32_t ndim,
                             const char *dtype, void **out) {
  return with_backend([&]() -> bool {
    PyObject *args = pack_steal(
        PyBytes_FromStringAndSize(static_cast<const char *>(data),
                                  static_cast<Py_ssize_t>(nbytes)),
        shape_list(shape, ndim), PyUnicode_FromString(dtype));
    PyObject *ret = call_backend("ndarray_from_bytes", args);
    if (!ret) return false;
    *out = as_handle(PyLong_AsLong(ret));
    Py_DECREF(ret);
    return true;
  });
}

int MXNDArrayFree(void *handle) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "ndarray_free", pack_steal(PyLong_FromLong(as_id(handle))));
    Py_XDECREF(ret);
    return ret != nullptr;
  });
}

int MXNDArrayGetShape(void *handle, uint32_t *out_dim,
                      const uint32_t **out_pdata) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "ndarray_get_shape",
        pack_steal(PyLong_FromLong(as_id(handle))));
    if (!ret) return false;
    Py_ssize_t n = PyTuple_Size(ret);
    g_shape_buf.resize(static_cast<size_t>(n));
    for (Py_ssize_t i = 0; i < n; ++i)
      g_shape_buf[i] = static_cast<uint32_t>(
          PyLong_AsLong(PyTuple_GetItem(ret, i)));
    Py_DECREF(ret);
    *out_dim = static_cast<uint32_t>(n);
    *out_pdata = g_shape_buf.data();
    return true;
  });
}

int MXNDArrayGetDType(void *handle, const char **out) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "ndarray_get_dtype",
        pack_steal(PyLong_FromLong(as_id(handle))));
    if (!ret) return false;
    const char *s = PyUnicode_AsUTF8(ret);
    g_str_buf = s ? s : "";
    Py_DECREF(ret);
    *out = g_str_buf.c_str();
    return true;
  });
}

int MXNDArraySyncCopyToCPU(void *handle, void *data, uint64_t size) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "ndarray_sync_copy_to_cpu",
        pack_steal(PyLong_FromLong(as_id(handle))));
    if (!ret) return false;
    char *buf = nullptr;
    Py_ssize_t n = 0;
    PyBytes_AsStringAndSize(ret, &buf, &n);
    if (static_cast<uint64_t>(n) > size) {
      set_error("MXNDArraySyncCopyToCPU: buffer too small");
      Py_DECREF(ret);
      return false;
    }
    std::memcpy(data, buf, static_cast<size_t>(n));
    Py_DECREF(ret);
    return true;
  });
}

int MXNDArraySyncCopyFromCPU(void *handle, const void *data, uint64_t size) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "ndarray_sync_copy_from_cpu",
        pack_steal(PyLong_FromLong(as_id(handle)),
                     PyBytes_FromStringAndSize(
                         static_cast<const char *>(data),
                         static_cast<Py_ssize_t>(size))));
    Py_XDECREF(ret);
    return ret != nullptr;
  });
}

int MXNDArraySave(const char *fname, uint32_t num, void **handles,
                  const char **keys) {
  return with_backend([&]() -> bool {
    PyObject *hs = PyList_New(num);
    PyObject *ks = PyList_New(keys ? num : 0);
    for (uint32_t i = 0; i < num; ++i) {
      PyList_SetItem(hs, i, PyLong_FromLong(as_id(handles[i])));
      if (keys) PyList_SetItem(ks, i, PyUnicode_FromString(keys[i]));
    }
    PyObject *ret = call_backend(
        "ndarray_save",
        pack_steal(PyUnicode_FromString(fname), hs, ks));
    Py_XDECREF(ret);
    return ret != nullptr;
  });
}

int MXNDArrayLoad(const char *fname, uint32_t *out_size, void ***out_arr,
                  uint32_t *out_name_size, const char ***out_names) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "ndarray_load", pack_steal(PyUnicode_FromString(fname)));
    if (!ret) return false;
    PyObject *hs = PyTuple_GetItem(ret, 0);
    PyObject *ns = PyTuple_GetItem(ret, 1);
    Py_ssize_t n = PyList_Size(hs);
    g_handle_buf.resize(static_cast<size_t>(n));
    for (Py_ssize_t i = 0; i < n; ++i)
      g_handle_buf[i] = as_handle(PyLong_AsLong(PyList_GetItem(hs, i)));
    load_string_list(ns, g_name_buf, g_name_ptr_buf);
    Py_DECREF(ret);
    *out_size = static_cast<uint32_t>(n);
    *out_arr = g_handle_buf.data();
    *out_name_size = static_cast<uint32_t>(g_name_buf.size());
    *out_names = g_name_ptr_buf.data();
    return true;
  });
}

int MXImperativeInvoke(const char *op_name, int num_inputs, void **inputs,
                       int *num_outputs, void ***outputs, int num_params,
                       const char **param_keys, const char **param_vals) {
  return with_backend([&]() -> bool {
    PyObject *ins = PyList_New(num_inputs);
    for (int i = 0; i < num_inputs; ++i)
      PyList_SetItem(ins, i, PyLong_FromLong(as_id(inputs[i])));
    PyObject *ks = PyList_New(num_params);
    PyObject *vs = PyList_New(num_params);
    for (int i = 0; i < num_params; ++i) {
      PyList_SetItem(ks, i, PyUnicode_FromString(param_keys[i]));
      PyList_SetItem(vs, i, PyUnicode_FromString(param_vals[i]));
    }
    PyObject *ret = call_backend(
        "imperative_invoke",
        pack_steal(PyUnicode_FromString(op_name), ins, ks, vs));
    if (!ret) return false;
    Py_ssize_t n = PyList_Size(ret);
    g_handle_buf.resize(static_cast<size_t>(n));
    for (Py_ssize_t i = 0; i < n; ++i)
      g_handle_buf[i] = as_handle(PyLong_AsLong(PyList_GetItem(ret, i)));
    Py_DECREF(ret);
    *num_outputs = static_cast<int>(n);
    *outputs = g_handle_buf.data();
    return true;
  });
}

int MXSymbolCreateFromJSON(const char *json, void **out) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "symbol_create_from_json",
        pack_steal(PyUnicode_FromString(json)));
    if (!ret) return false;
    *out = as_handle(PyLong_AsLong(ret));
    Py_DECREF(ret);
    return true;
  });
}

int MXSymbolSaveToJSON(void *handle, const char **out_json) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "symbol_save_to_json",
        pack_steal(PyLong_FromLong(as_id(handle))));
    if (!ret) return false;
    const char *s = PyUnicode_AsUTF8(ret);
    g_str_buf = s ? s : "";
    Py_DECREF(ret);
    *out_json = g_str_buf.c_str();
    return true;
  });
}

static int list_strings(const char *fn, void *handle, uint32_t *out_size,
                        const char ***out_arr) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        fn, pack_steal(PyLong_FromLong(as_id(handle))));
    if (!ret) return false;
    Py_ssize_t n = PyList_Size(ret);
    g_name_buf.clear();
    g_name_ptr_buf.clear();
    for (Py_ssize_t i = 0; i < n; ++i) {
      const char *s = PyUnicode_AsUTF8(PyList_GetItem(ret, i));
      g_name_buf.emplace_back(s ? s : "");
    }
    for (const auto &s : g_name_buf) g_name_ptr_buf.push_back(s.c_str());
    Py_DECREF(ret);
    *out_size = static_cast<uint32_t>(n);
    *out_arr = g_name_ptr_buf.data();
    return true;
  });
}

int MXSymbolListArguments(void *handle, uint32_t *out_size,
                          const char ***out_arr) {
  return list_strings("symbol_list_arguments", handle, out_size, out_arr);
}

int MXSymbolListOutputs(void *handle, uint32_t *out_size,
                        const char ***out_arr) {
  return list_strings("symbol_list_outputs", handle, out_size, out_arr);
}

int MXSymbolListAuxiliaryStates(void *handle, uint32_t *out_size,
                                const char ***out_arr) {
  return list_strings("symbol_list_auxiliary_states", handle, out_size,
                      out_arr);
}

int MXSymbolFree(void *handle) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "symbol_free", pack_steal(PyLong_FromLong(as_id(handle))));
    Py_XDECREF(ret);
    return ret != nullptr;
  });
}

int MXExecutorBind(void *sym_handle, int dev_type, int dev_id,
                   uint32_t num_args, void **arg_handles,
                   const char *grad_req, void **out) {
  return with_backend([&]() -> bool {
    PyObject *args_list = PyList_New(num_args);
    for (uint32_t i = 0; i < num_args; ++i)
      PyList_SetItem(args_list, i,
                     PyLong_FromLong(as_id(arg_handles[i])));
    PyObject *ret = call_backend(
        "executor_bind",
        pack_steal(PyLong_FromLong(as_id(sym_handle)),
                   PyLong_FromLong(dev_type), PyLong_FromLong(dev_id),
                   args_list,
                   PyUnicode_FromString(grad_req ? grad_req : "null")));
    if (!ret) return false;
    *out = as_handle(PyLong_AsLong(ret));
    Py_DECREF(ret);
    return true;
  });
}

int MXExecutorBackward(void *handle, uint32_t *out_size, void ***grads) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "executor_backward",
        pack_steal(PyLong_FromLong(as_id(handle))));
    if (!ret) return false;
    Py_ssize_t n = PyList_Size(ret);
    g_handle_buf.resize(static_cast<size_t>(n));
    for (Py_ssize_t i = 0; i < n; ++i)
      g_handle_buf[i] = as_handle(PyLong_AsLong(PyList_GetItem(ret, i)));
    Py_DECREF(ret);
    *out_size = static_cast<uint32_t>(n);
    *grads = g_handle_buf.data();
    return true;
  });
}

int MXExecutorForward(void *handle, int is_train, uint32_t *out_size,
                      void ***outputs) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "executor_forward",
        pack_steal(PyLong_FromLong(as_id(handle)),
                     PyBool_FromLong(is_train)));
    if (!ret) return false;
    Py_ssize_t n = PyList_Size(ret);
    g_handle_buf.resize(static_cast<size_t>(n));
    for (Py_ssize_t i = 0; i < n; ++i)
      g_handle_buf[i] = as_handle(PyLong_AsLong(PyList_GetItem(ret, i)));
    Py_DECREF(ret);
    *out_size = static_cast<uint32_t>(n);
    *outputs = g_handle_buf.data();
    fire_monitors(handle, static_cast<uint32_t>(n), g_handle_buf.data());
    return true;
  });
}

int MXExecutorFree(void *handle) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "executor_free", pack_steal(PyLong_FromLong(as_id(handle))));
    Py_XDECREF(ret);
    return ret != nullptr;
  });
}

}  // extern "C"

/* ------------------------------------------------------------------------
 * Expanded MX* families: NDArray extras, autograd, symbol composition &
 * inference, KVStore, DataIter, misc (ref: include/mxnet/c_api.h).
 * --------------------------------------------------------------------- */

namespace {

// shared small helpers for the expanded families
bool ret_handle(PyObject *ret, void **out) {
  if (!ret) return false;
  *out = as_handle(PyLong_AsLong(ret));
  Py_DECREF(ret);
  return true;
}

bool ret_void(PyObject *ret) {
  Py_XDECREF(ret);
  return ret != nullptr;
}

bool ret_int(PyObject *ret, int *out) {
  if (!ret) return false;
  *out = static_cast<int>(PyLong_AsLong(ret));
  Py_DECREF(ret);
  return true;
}

bool ret_string(PyObject *ret, const char **out) {
  if (!ret) return false;
  const char *s = PyUnicode_AsUTF8(ret);
  if (!s) PyErr_Clear();
  g_str_buf = s ? s : "";
  Py_DECREF(ret);
  *out = g_str_buf.c_str();
  return true;
}

PyObject *handle_list(uint32_t num, void **handles) {
  PyObject *l = PyList_New(num);
  for (uint32_t i = 0; i < num; ++i)
    PyList_SetItem(l, i, PyLong_FromLong(as_id(handles[i])));
  return l;
}

PyObject *string_list(uint32_t num, const char **strs) {
  PyObject *l = PyList_New(num);
  for (uint32_t i = 0; i < num; ++i)
    PyList_SetItem(l, i, PyUnicode_FromString(strs[i]));
  return l;
}

// per-group storage for CSR-style shape outputs (InferShape): each group
// owns its rows so the pointers stay valid until the next call
struct ShapeGroup {
  std::vector<uint32_t> ndim;
  std::vector<std::vector<uint32_t>> rows;
  std::vector<const uint32_t *> ptrs;

  void load(PyObject *tuples) {  // list of tuples of ints
    Py_ssize_t n = PyList_Size(tuples);
    ndim.resize(static_cast<size_t>(n));
    rows.assign(static_cast<size_t>(n), {});
    ptrs.resize(static_cast<size_t>(n));
    for (Py_ssize_t i = 0; i < n; ++i) {
      PyObject *t = PyList_GetItem(tuples, i);
      Py_ssize_t d = PyTuple_Check(t) ? PyTuple_Size(t) : 0;
      ndim[i] = static_cast<uint32_t>(d);
      rows[i].resize(static_cast<size_t>(d));
      for (Py_ssize_t j = 0; j < d; ++j)
        rows[i][j] = static_cast<uint32_t>(
            PyLong_AsUnsignedLong(PyTuple_GetItem(t, j)));
      ptrs[i] = rows[i].data();
    }
  }
};

thread_local ShapeGroup g_in_shapes, g_out_shapes, g_aux_shapes;

// string-list groups for InferType outputs
struct StrGroup {
  std::vector<std::string> vals;
  std::vector<const char *> ptrs;

  void load(PyObject *list) { load_string_list(list, vals, ptrs); }
};

thread_local StrGroup g_in_types, g_out_types, g_aux_types;

}  // namespace

extern "C" {

/* --- NDArray extras --------------------------------------------------- */

int MXNDArraySlice(void *handle, uint32_t begin, uint32_t end, void **out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "ndarray_slice",
        pack_steal(PyLong_FromLong(as_id(handle)),
                   PyLong_FromUnsignedLong(begin),
                   PyLong_FromUnsignedLong(end))), out);
  });
}

int MXNDArrayAt(void *handle, uint32_t idx, void **out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "ndarray_at", pack_steal(PyLong_FromLong(as_id(handle)),
                                 PyLong_FromUnsignedLong(idx))), out);
  });
}

int MXNDArrayReshape(void *handle, int ndim, const int *dims, void **out) {
  return with_backend([&]() -> bool {
    PyObject *s = PyList_New(ndim);
    for (int i = 0; i < ndim; ++i)
      PyList_SetItem(s, i, PyLong_FromLong(dims[i]));
    return ret_handle(call_backend(
        "ndarray_reshape",
        pack_steal(PyLong_FromLong(as_id(handle)), s)), out);
  });
}

int MXNDArrayGetContext(void *handle, int *out_dev_type, int *out_dev_id) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "ndarray_get_context", pack_steal(PyLong_FromLong(as_id(handle))));
    if (!ret) return false;
    *out_dev_type = static_cast<int>(PyLong_AsLong(PyTuple_GetItem(ret, 0)));
    *out_dev_id = static_cast<int>(PyLong_AsLong(PyTuple_GetItem(ret, 1)));
    Py_DECREF(ret);
    return true;
  });
}

int MXNDArrayWaitToRead(void *handle) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend(
        "ndarray_wait_to_read", pack_steal(PyLong_FromLong(as_id(handle)))));
  });
}

int MXNDArrayWaitAll(void) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend("ndarray_wait_all", PyTuple_New(0)));
  });
}

int MXNDArrayGetGrad(void *handle, void **out) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "ndarray_get_grad", pack_steal(PyLong_FromLong(as_id(handle))));
    if (!ret) return false;
    long id = PyLong_AsLong(ret);
    Py_DECREF(ret);
    *out = id ? as_handle(id) : nullptr;  /* NULL: no grad attached */
    return true;
  });
}

/* --- autograd --------------------------------------------------------- */

int MXAutogradSetIsRecording(int is_recording, int *prev) {
  return with_backend([&]() -> bool {
    return ret_int(call_backend("autograd_set_is_recording",
                                pack_steal(PyLong_FromLong(is_recording))),
                   prev);
  });
}

int MXAutogradSetIsTraining(int is_training, int *prev) {
  return with_backend([&]() -> bool {
    return ret_int(call_backend("autograd_set_is_training",
                                pack_steal(PyLong_FromLong(is_training))),
                   prev);
  });
}

int MXAutogradIsRecording(int *out) {
  return with_backend([&]() -> bool {
    return ret_int(call_backend("autograd_is_recording", PyTuple_New(0)),
                   out);
  });
}

int MXAutogradIsTraining(int *out) {
  return with_backend([&]() -> bool {
    return ret_int(call_backend("autograd_is_training", PyTuple_New(0)),
                   out);
  });
}

int MXAutogradMarkVariables(uint32_t num, void **var_handles,
                            uint32_t *grad_reqs, void **grad_handles) {
  return with_backend([&]() -> bool {
    PyObject *reqs = PyList_New(num);
    for (uint32_t i = 0; i < num; ++i)
      PyList_SetItem(reqs, i, PyLong_FromUnsignedLong(grad_reqs[i]));
    return ret_void(call_backend(
        "autograd_mark_variables",
        pack_steal(handle_list(num, var_handles),
                   handle_list(num, grad_handles), reqs)));
  });
}

int MXAutogradBackward(uint32_t num_output, void **output_handles,
                       void **ograd_handles, int retain_graph,
                       int train_mode) {
  return with_backend([&]() -> bool {
    PyObject *ograds;
    if (ograd_handles) {
      ograds = PyList_New(num_output);
      for (uint32_t i = 0; i < num_output; ++i)
        PyList_SetItem(ograds, i,
                       PyLong_FromLong(ograd_handles[i]
                                           ? as_id(ograd_handles[i]) : 0));
    } else {
      ograds = PyList_New(0);
    }
    return ret_void(call_backend(
        "autograd_backward",
        pack_steal(handle_list(num_output, output_handles), ograds,
                   PyLong_FromLong(retain_graph),
                   PyLong_FromLong(train_mode))));
  });
}

/* --- symbol composition & inference ----------------------------------- */

int MXSymbolCreateVariable(const char *name, void **out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "symbol_create_variable",
        pack_steal(PyUnicode_FromString(name))), out);
  });
}

int MXSymbolCreateAtomicSymbol(const char *op_name, uint32_t num_param,
                               const char **keys, const char **vals,
                               void **out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "symbol_create_atomic",
        pack_steal(PyUnicode_FromString(op_name),
                   string_list(num_param, keys),
                   string_list(num_param, vals))), out);
  });
}

int MXSymbolCompose(void *handle, const char *name, uint32_t num_args,
                    const char **keys, void **args) {
  return with_backend([&]() -> bool {
    /* keys == NULL: positional, in declared op-input order; otherwise
     * named binding resolved by the backend */
    PyObject *key_list = keys ? string_list(num_args, keys)
                              : PyList_New(0);
    return ret_void(call_backend(
        "symbol_compose",
        pack_steal(PyLong_FromLong(as_id(handle)),
                   PyUnicode_FromString(name ? name : ""), key_list,
                   handle_list(num_args, args))));
  });
}

int MXSymbolCopy(void *handle, void **out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "symbol_copy", pack_steal(PyLong_FromLong(as_id(handle)))), out);
  });
}

int MXSymbolGetInternals(void *handle, void **out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "symbol_get_internals",
        pack_steal(PyLong_FromLong(as_id(handle)))), out);
  });
}

int MXSymbolGetName(void *handle, const char **out) {
  return with_backend([&]() -> bool {
    return ret_string(call_backend(
        "symbol_get_name", pack_steal(PyLong_FromLong(as_id(handle)))),
                      out);
  });
}

int MXSymbolInferShape(void *handle, uint32_t num_args, const char **keys,
                       const uint32_t *arg_ind_ptr,
                       const uint32_t *arg_shape_data,
                       uint32_t *in_shape_size,
                       const uint32_t **in_shape_ndim,
                       const uint32_t ***in_shape_data,
                       uint32_t *out_shape_size,
                       const uint32_t **out_shape_ndim,
                       const uint32_t ***out_shape_data,
                       uint32_t *aux_shape_size,
                       const uint32_t **aux_shape_ndim,
                       const uint32_t ***aux_shape_data) {
  return with_backend([&]() -> bool {
    PyObject *names = string_list(num_args, keys);
    PyObject *shapes = PyList_New(num_args);
    for (uint32_t i = 0; i < num_args; ++i) {
      uint32_t lo = arg_ind_ptr[i], hi = arg_ind_ptr[i + 1];
      PyList_SetItem(shapes, i, shape_list(arg_shape_data + lo, hi - lo));
    }
    PyObject *ret = call_backend(
        "symbol_infer_shape",
        pack_steal(PyLong_FromLong(as_id(handle)), names, shapes));
    if (!ret) return false;
    g_in_shapes.load(PyTuple_GetItem(ret, 0));
    g_out_shapes.load(PyTuple_GetItem(ret, 1));
    g_aux_shapes.load(PyTuple_GetItem(ret, 2));
    Py_DECREF(ret);
    *in_shape_size = static_cast<uint32_t>(g_in_shapes.ndim.size());
    *in_shape_ndim = g_in_shapes.ndim.data();
    *in_shape_data = g_in_shapes.ptrs.data();
    *out_shape_size = static_cast<uint32_t>(g_out_shapes.ndim.size());
    *out_shape_ndim = g_out_shapes.ndim.data();
    *out_shape_data = g_out_shapes.ptrs.data();
    *aux_shape_size = static_cast<uint32_t>(g_aux_shapes.ndim.size());
    *aux_shape_ndim = g_aux_shapes.ndim.data();
    *aux_shape_data = g_aux_shapes.ptrs.data();
    return true;
  });
}

int MXSymbolInferType(void *handle, uint32_t num_args, const char **keys,
                      const char **arg_dtypes, uint32_t *in_type_size,
                      const char ***in_types, uint32_t *out_type_size,
                      const char ***out_types, uint32_t *aux_type_size,
                      const char ***aux_types) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "symbol_infer_type",
        pack_steal(PyLong_FromLong(as_id(handle)),
                   string_list(num_args, keys),
                   string_list(num_args, arg_dtypes)));
    if (!ret) return false;
    g_in_types.load(PyTuple_GetItem(ret, 0));
    g_out_types.load(PyTuple_GetItem(ret, 1));
    g_aux_types.load(PyTuple_GetItem(ret, 2));
    Py_DECREF(ret);
    *in_type_size = static_cast<uint32_t>(g_in_types.ptrs.size());
    *in_types = g_in_types.ptrs.data();
    *out_type_size = static_cast<uint32_t>(g_out_types.ptrs.size());
    *out_types = g_out_types.ptrs.data();
    *aux_type_size = static_cast<uint32_t>(g_aux_types.ptrs.size());
    *aux_types = g_aux_types.ptrs.data();
    return true;
  });
}

/* --- kvstore ----------------------------------------------------------- */

int MXKVStoreCreate(const char *type, void **out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "kvstore_create",
        pack_steal(PyUnicode_FromString(type ? type : "local"))), out);
  });
}

int MXKVStoreFree(void *handle) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend(
        "kvstore_free", pack_steal(PyLong_FromLong(as_id(handle)))));
  });
}

static int kv_apply(const char *fn, void *handle, uint32_t num,
                    const char **keys, void **vals, int priority,
                    bool with_priority) {
  return with_backend([&]() -> bool {
    PyObject *args =
        with_priority
            ? pack_steal(PyLong_FromLong(as_id(handle)),
                         string_list(num, keys), handle_list(num, vals),
                         PyLong_FromLong(priority))
            : pack_steal(PyLong_FromLong(as_id(handle)),
                         string_list(num, keys), handle_list(num, vals));
    return ret_void(call_backend(fn, args));
  });
}

int MXKVStoreInit(void *handle, uint32_t num, const char **keys,
                  void **vals) {
  return kv_apply("kvstore_init", handle, num, keys, vals, 0, false);
}

int MXKVStorePush(void *handle, uint32_t num, const char **keys,
                  void **vals, int priority) {
  return kv_apply("kvstore_push", handle, num, keys, vals, priority, true);
}

int MXKVStorePull(void *handle, uint32_t num, const char **keys,
                  void **vals, int priority) {
  return kv_apply("kvstore_pull", handle, num, keys, vals, priority, true);
}

int MXKVStoreGetRank(void *handle, int *rank) {
  return with_backend([&]() -> bool {
    return ret_int(call_backend(
        "kvstore_get_rank", pack_steal(PyLong_FromLong(as_id(handle)))),
                   rank);
  });
}

int MXKVStoreGetGroupSize(void *handle, int *size) {
  return with_backend([&]() -> bool {
    return ret_int(call_backend(
        "kvstore_get_group_size",
        pack_steal(PyLong_FromLong(as_id(handle)))), size);
  });
}

int MXKVStoreGetType(void *handle, const char **type) {
  return with_backend([&]() -> bool {
    return ret_string(call_backend(
        "kvstore_get_type", pack_steal(PyLong_FromLong(as_id(handle)))),
                      type);
  });
}

int MXKVStoreBarrier(void *handle) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend(
        "kvstore_barrier", pack_steal(PyLong_FromLong(as_id(handle)))));
  });
}

/* --- data iterators ---------------------------------------------------- */

int MXListDataIters(uint32_t *out_size, const char ***out_array) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend("list_data_iters", PyTuple_New(0));
    if (!ret) return false;
    // dedicated buffers: g_name_buf backs MXNDArrayLoad's returned name
    // array, which must stay valid across unrelated ABI calls
    thread_local std::vector<std::string> iter_names;
    thread_local std::vector<const char *> iter_ptrs;
    load_string_list(ret, iter_names, iter_ptrs);
    Py_DECREF(ret);
    *out_size = static_cast<uint32_t>(iter_names.size());
    *out_array = iter_ptrs.data();
    return true;
  });
}

int MXDataIterCreateIter(const char *name, uint32_t num_param,
                         const char **keys, const char **vals, void **out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "data_iter_create",
        pack_steal(PyUnicode_FromString(name), string_list(num_param, keys),
                   string_list(num_param, vals))), out);
  });
}

int MXDataIterNext(void *handle, int *out) {
  return with_backend([&]() -> bool {
    return ret_int(call_backend(
        "data_iter_next", pack_steal(PyLong_FromLong(as_id(handle)))), out);
  });
}

int MXDataIterBeforeFirst(void *handle) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend(
        "data_iter_before_first",
        pack_steal(PyLong_FromLong(as_id(handle)))));
  });
}

int MXDataIterGetData(void *handle, void **out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "data_iter_get_data",
        pack_steal(PyLong_FromLong(as_id(handle)))), out);
  });
}

int MXDataIterGetLabel(void *handle, void **out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "data_iter_get_label",
        pack_steal(PyLong_FromLong(as_id(handle)))), out);
  });
}

int MXDataIterFree(void *handle) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend(
        "data_iter_free", pack_steal(PyLong_FromLong(as_id(handle)))));
  });
}

/* --- misc --------------------------------------------------------------- */

int MXRandomSeed(int seed) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend("random_seed",
                                 pack_steal(PyLong_FromLong(seed))));
  });
}

int MXGetGPUCount(int *out) {
  return with_backend([&]() -> bool {
    return ret_int(call_backend("get_gpu_count", PyTuple_New(0)), out);
  });
}

int MXSetProfilerState(int state) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend(
        "profiler_set_state",
        pack_steal(PyUnicode_FromString(state ? "run" : "stop"))));
  });
}

int MXDumpProfile(void) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend("profiler_dump", PyTuple_New(0)));
  });
}

int MXNotifyShutdown(void) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend("notify_shutdown", PyTuple_New(0)));
  });
}

}  // extern "C"

/* ------------------------------------------------------------------------
 * Round-3 ABI completion (ref: include/mxnet/c_api.h): CachedOp, symbol
 * attrs/structure, executor simple_bind/reshape/outputs, autograd extras,
 * kvstore updater + node roles, profiler objects, RecordIO, legacy
 * Function API, ndarray extras + 64-bit variants, quantization passes,
 * misc. CUDA-only families (MXRtc*, TVM) export honest unsupported
 * errors, mirroring the reference's disabled-build-flag behavior.
 * --------------------------------------------------------------------- */

namespace {

// marshal a vector of python ints into the thread-local handle buffer
bool ret_handle_vec(PyObject *ret, int *num, void ***out) {
  if (!ret) return false;
  Py_ssize_t n = PyList_Check(ret) ? PyList_Size(ret) : 0;
  g_handle_buf.resize(static_cast<size_t>(n));
  for (Py_ssize_t i = 0; i < n; ++i)
    g_handle_buf[i] = as_handle(PyLong_AsLong(PyList_GetItem(ret, i)));
  Py_DECREF(ret);
  if (num) *num = static_cast<int>(n);
  if (out) *out = g_handle_buf.data();
  return true;
}

// (exec, args, grads, aux) quad returned by simple_bind / reshape
thread_local std::vector<void *> g_bind_args, g_bind_grads, g_bind_aux;

bool ret_bind_quad(PyObject *ret, void **exec_out, uint32_t *num_args,
                   void ***args_out, void ***grads_out, uint32_t *num_aux,
                   void ***aux_out) {
  if (!ret) return false;
  PyObject *eh = PyTuple_GetItem(ret, 0);
  PyObject *args = PyTuple_GetItem(ret, 1);
  PyObject *grads = PyTuple_GetItem(ret, 2);
  PyObject *aux = PyTuple_GetItem(ret, 3);
  auto fill = [](PyObject *l, std::vector<void *> &buf) {
    Py_ssize_t n = PyList_Size(l);
    buf.resize(static_cast<size_t>(n));
    for (Py_ssize_t i = 0; i < n; ++i)
      buf[i] = as_handle(PyLong_AsLong(PyList_GetItem(l, i)));
    return static_cast<uint32_t>(n);
  };
  uint32_t na = fill(args, g_bind_args);
  fill(grads, g_bind_grads);
  uint32_t nx = fill(aux, g_bind_aux);
  *exec_out = as_handle(PyLong_AsLong(eh));
  Py_DECREF(ret);
  if (num_args) *num_args = na;
  if (args_out) *args_out = g_bind_args.data();
  if (grads_out) *grads_out = g_bind_grads.data();
  if (num_aux) *num_aux = nx;
  if (aux_out) *aux_out = g_bind_aux.data();
  return true;
}

PyObject *shape_list64(const int64_t *shape, uint32_t ndim) {
  PyObject *s = PyList_New(ndim);
  for (uint32_t i = 0; i < ndim; ++i)
    PyList_SetItem(s, i, PyLong_FromLongLong(shape[i]));
  return s;
}

thread_local std::vector<int64_t> g_shape64_buf;
thread_local std::vector<std::string> g_attr_buf;
thread_local std::vector<const char *> g_attr_ptr_buf;
thread_local std::string g_bytes_buf;

int unsupported(const char *what, const char *hint) {
  set_error(std::string(what) +
            " is not supported on the TPU backend: " + hint);
  return -1;
}

}  // namespace

extern "C" {

/* -- CachedOp (ref: c_api_ndarray.cc MXCreateCachedOpEx/MXInvokeCachedOp) */

int MXCreateCachedOp(SymbolHandle sym, CachedOpHandle *out) {
  return with_backend([&]() -> bool {
    return ret_handle(
        call_backend("cachedop_create",
                     pack_steal(PyLong_FromLong(as_id(sym)),
                                PyList_New(0), PyList_New(0))),
        out);
  });
}

int MXCreateCachedOpEx(SymbolHandle sym, int num_flags, const char **keys,
                       const char **vals, CachedOpHandle *out) {
  return with_backend([&]() -> bool {
    return ret_handle(
        call_backend("cachedop_create",
                     pack_steal(PyLong_FromLong(as_id(sym)),
                                string_list(num_flags, keys),
                                string_list(num_flags, vals))),
        out);
  });
}

int MXInvokeCachedOp(CachedOpHandle handle, int num_inputs, void **inputs,
                     int *num_outputs, void ***outputs) {
  return with_backend([&]() -> bool {
    return ret_handle_vec(
        call_backend("cachedop_invoke",
                     pack_steal(PyLong_FromLong(as_id(handle)),
                                handle_list(num_inputs, inputs))),
        num_outputs, outputs);
  });
}

int MXInvokeCachedOpEx(CachedOpHandle handle, int num_inputs, void **inputs,
                       int *num_outputs, void ***outputs,
                       const int **out_stypes) {
  static thread_local std::vector<int> stypes;
  int rc = MXInvokeCachedOp(handle, num_inputs, inputs, num_outputs,
                            outputs);
  if (rc == 0) {
    stypes.assign(static_cast<size_t>(*num_outputs), 0);  // dense
    *out_stypes = stypes.data();
  }
  return rc;
}

int MXFreeCachedOp(CachedOpHandle handle) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend(
        "cachedop_free", pack_steal(PyLong_FromLong(as_id(handle)))));
  });
}

/* -- symbol attrs / structure ----------------------------------------- */

int MXSymbolGetAttr(SymbolHandle sym, const char *key, const char **out,
                    int *success) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "symbol_get_attr",
        pack_steal(PyLong_FromLong(as_id(sym)), PyUnicode_FromString(key)));
    if (!ret) return false;
    const char *s = PyUnicode_AsUTF8(PyTuple_GetItem(ret, 0));
    g_str_buf = s ? s : "";
    *success = static_cast<int>(PyLong_AsLong(PyTuple_GetItem(ret, 1)));
    *out = *success ? g_str_buf.c_str() : nullptr;
    Py_DECREF(ret);
    return true;
  });
}

int MXSymbolSetAttr(SymbolHandle sym, const char *key, const char *value) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend(
        "symbol_set_attr",
        pack_steal(PyLong_FromLong(as_id(sym)), PyUnicode_FromString(key),
                   PyUnicode_FromString(value))));
  });
}

static int list_attr_impl(const char *fn, SymbolHandle sym, uint32_t *out_size,
                          const char ***out) {
  return with_backend([&]() -> bool {
    PyObject *ret =
        call_backend(fn, pack_steal(PyLong_FromLong(as_id(sym))));
    if (!ret) return false;
    load_string_list(ret, g_attr_buf, g_attr_ptr_buf);
    *out_size = static_cast<uint32_t>(g_attr_buf.size() / 2);
    *out = g_attr_ptr_buf.data();
    Py_DECREF(ret);
    return true;
  });
}

int MXSymbolListAttr(SymbolHandle sym, uint32_t *out_size,
                     const char ***out) {
  return list_attr_impl("symbol_list_attr", sym, out_size, out);
}

int MXSymbolListAttrShallow(SymbolHandle sym, uint32_t *out_size,
                            const char ***out) {
  return list_attr_impl("symbol_list_attr_shallow", sym, out_size, out);
}

int MXSymbolGetNumOutputs(SymbolHandle sym, uint32_t *out) {
  return with_backend([&]() -> bool {
    int v = 0;
    if (!ret_int(call_backend("symbol_get_num_outputs",
                              pack_steal(PyLong_FromLong(as_id(sym)))),
                 &v))
      return false;
    *out = static_cast<uint32_t>(v);
    return true;
  });
}

int MXSymbolGetOutput(SymbolHandle sym, uint32_t index, SymbolHandle *out) {
  return with_backend([&]() -> bool {
    return ret_handle(
        call_backend("symbol_get_output",
                     pack_steal(PyLong_FromLong(as_id(sym)),
                                PyLong_FromUnsignedLong(index))),
        out);
  });
}

int MXSymbolGetChildren(SymbolHandle sym, SymbolHandle *out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "symbol_get_children", pack_steal(PyLong_FromLong(as_id(sym)))),
        out);
  });
}

int MXSymbolPrint(SymbolHandle sym, const char **out_str) {
  return with_backend([&]() -> bool {
    return ret_string(call_backend(
        "symbol_print", pack_steal(PyLong_FromLong(as_id(sym)))), out_str);
  });
}

int MXSymbolCreateFromFile(const char *fname, SymbolHandle *out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend("symbol_create_from_file",
                                   pack_steal(PyUnicode_FromString(fname))),
                      out);
  });
}

int MXSymbolSaveToFile(SymbolHandle sym, const char *fname) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend(
        "symbol_save_to_file",
        pack_steal(PyLong_FromLong(as_id(sym)),
                   PyUnicode_FromString(fname))));
  });
}

int MXSymbolCreateGroup(uint32_t num_symbols, SymbolHandle *symbols,
                        SymbolHandle *out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend("symbol_create_group",
                                   pack_steal(handle_list(num_symbols,
                                                          symbols))),
                      out);
  });
}

int MXGenAtomicSymbolFromSymbol(SymbolHandle sym, SymbolHandle *out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "gen_atomic_symbol_from_symbol",
        pack_steal(PyLong_FromLong(as_id(sym)))), out);
  });
}

int MXSymbolRemoveAmpCast(SymbolHandle sym, SymbolHandle *out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "symbol_remove_amp_cast",
        pack_steal(PyLong_FromLong(as_id(sym)))), out);
  });
}

int MXShallowCopySymbol(SymbolHandle sym, SymbolHandle *out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "shallow_copy_symbol",
        pack_steal(PyLong_FromLong(as_id(sym)))), out);
  });
}

int MXShallowCopyNDArray(NDArrayHandle nd, NDArrayHandle *out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "shallow_copy_ndarray",
        pack_steal(PyLong_FromLong(as_id(nd)))), out);
  });
}

int MXSymbolGrad(SymbolHandle sym, uint32_t num_wrt, const char **wrt,
                 SymbolHandle *out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "symbol_grad", pack_steal(PyLong_FromLong(as_id(sym)),
                                  string_list(num_wrt, wrt))), out);
  });
}

/* -- infer shape/type partial + 64-bit ------------------------------- */

int MXSymbolInferShapePartial(
    SymbolHandle sym, uint32_t num_args, const char **keys,
    const uint32_t *arg_ind_ptr, const uint32_t *arg_shape_data,
    uint32_t *in_shape_size, const uint32_t **in_shape_ndim,
    const uint32_t ***in_shape_data, uint32_t *out_shape_size,
    const uint32_t **out_shape_ndim, const uint32_t ***out_shape_data,
    uint32_t *aux_shape_size, const uint32_t **aux_shape_ndim,
    const uint32_t ***aux_shape_data, int *complete) {
  return with_backend([&]() -> bool {
    PyObject *names = PyList_New(num_args);
    PyObject *shapes = PyList_New(num_args);
    for (uint32_t i = 0; i < num_args; ++i) {
      PyList_SetItem(names, i, PyUnicode_FromString(keys[i]));
      uint32_t lo = arg_ind_ptr[i], hi = arg_ind_ptr[i + 1];
      PyObject *shp = PyList_New(hi - lo);
      for (uint32_t j = lo; j < hi; ++j)
        PyList_SetItem(shp, j - lo,
                       PyLong_FromUnsignedLong(arg_shape_data[j]));
      PyList_SetItem(shapes, i, shp);
    }
    PyObject *ret = call_backend(
        "symbol_infer_shape_partial",
        pack_steal(PyLong_FromLong(as_id(sym)), names, shapes));
    if (!ret) return false;
    g_in_shapes.load(PyTuple_GetItem(ret, 0));
    g_out_shapes.load(PyTuple_GetItem(ret, 1));
    g_aux_shapes.load(PyTuple_GetItem(ret, 2));
    Py_DECREF(ret);
    *in_shape_size = static_cast<uint32_t>(g_in_shapes.ndim.size());
    *in_shape_ndim = g_in_shapes.ndim.data();
    *in_shape_data = g_in_shapes.ptrs.data();
    *out_shape_size = static_cast<uint32_t>(g_out_shapes.ndim.size());
    *out_shape_ndim = g_out_shapes.ndim.data();
    *out_shape_data = g_out_shapes.ptrs.data();
    *aux_shape_size = static_cast<uint32_t>(g_aux_shapes.ndim.size());
    *aux_shape_ndim = g_aux_shapes.ndim.data();
    *aux_shape_data = g_aux_shapes.ptrs.data();
    // complete only when EVERY shape (args, outputs, aux) is known —
    // partial callers allocate buffers from these rows
    bool all_known = true;
    for (auto *g : {&g_in_shapes, &g_out_shapes, &g_aux_shapes})
      for (auto &r : g->rows) all_known &= !r.empty();
    *complete = all_known ? 1 : 0;
    return true;
  });
}

int MXSymbolInferTypePartial(SymbolHandle sym, uint32_t num_args,
                             const char **keys, const char **arg_dtypes,
                             uint32_t *in_type_size,
                             const char ***in_type_data,
                             uint32_t *out_type_size,
                             const char ***out_type_data,
                             uint32_t *aux_type_size,
                             const char ***aux_type_data) {
  /* delegate to the strict variant (this ABI names dtypes, it does not
   * use the reference int codes); on failure report incomplete */
  int rc = MXSymbolInferType(sym, num_args, keys, arg_dtypes, in_type_size,
                             in_type_data, out_type_size, out_type_data,
                             aux_type_size, aux_type_data);
  if (rc != 0) {
    *in_type_size = *out_type_size = *aux_type_size = 0;
    return 0;
  }
  return rc;
}

/* -- executor simple_bind / reshape / outputs -------------------------- */

int MXExecutorSimpleBind(SymbolHandle sym, int dev_type, int dev_id,
                         uint32_t num_args, const char **arg_names,
                         const uint32_t *arg_ind_ptr,
                         const uint32_t *arg_shape_data, const char *grad_req,
                         ExecutorHandle *out, uint32_t *num_arg_arrays,
                         NDArrayHandle **arg_arrays,
                         NDArrayHandle **grad_arrays, uint32_t *num_aux,
                         NDArrayHandle **aux_arrays) {
  return with_backend([&]() -> bool {
    PyObject *names = PyList_New(num_args);
    PyObject *shapes = PyList_New(num_args);
    for (uint32_t i = 0; i < num_args; ++i) {
      PyList_SetItem(names, i, PyUnicode_FromString(arg_names[i]));
      uint32_t lo = arg_ind_ptr[i], hi = arg_ind_ptr[i + 1];
      PyObject *shp = PyList_New(hi - lo);
      for (uint32_t j = lo; j < hi; ++j)
        PyList_SetItem(shp, j - lo,
                       PyLong_FromUnsignedLong(arg_shape_data[j]));
      PyList_SetItem(shapes, i, shp);
    }
    return ret_bind_quad(
        call_backend("executor_simple_bind",
                     pack_steal(PyLong_FromLong(as_id(sym)),
                                PyLong_FromLong(dev_type),
                                PyLong_FromLong(dev_id), names, shapes,
                                PyUnicode_FromString(grad_req))),
        out, num_arg_arrays, arg_arrays, grad_arrays, num_aux, aux_arrays);
  });
}

int MXExecutorReshape(int partial_shaping, int allow_up_sizing, int dev_type,
                      int dev_id, uint32_t num_args, const char **arg_names,
                      const uint32_t *arg_ind_ptr,
                      const uint32_t *arg_shape_data,
                      ExecutorHandle shared_exec, ExecutorHandle *out,
                      uint32_t *num_arg_arrays, NDArrayHandle **arg_arrays,
                      NDArrayHandle **grad_arrays, uint32_t *num_aux,
                      NDArrayHandle **aux_arrays) {
  (void)dev_type;
  (void)dev_id;
  return with_backend([&]() -> bool {
    PyObject *names = PyList_New(num_args);
    PyObject *shapes = PyList_New(num_args);
    for (uint32_t i = 0; i < num_args; ++i) {
      PyList_SetItem(names, i, PyUnicode_FromString(arg_names[i]));
      uint32_t lo = arg_ind_ptr[i], hi = arg_ind_ptr[i + 1];
      PyObject *shp = PyList_New(hi - lo);
      for (uint32_t j = lo; j < hi; ++j)
        PyList_SetItem(shp, j - lo,
                       PyLong_FromUnsignedLong(arg_shape_data[j]));
      PyList_SetItem(shapes, i, shp);
    }
    return ret_bind_quad(
        call_backend("executor_reshape",
                     pack_steal(PyLong_FromLong(as_id(shared_exec)), names,
                                shapes, PyLong_FromLong(partial_shaping),
                                PyLong_FromLong(allow_up_sizing))),
        out, num_arg_arrays, arg_arrays, grad_arrays, num_aux, aux_arrays);
  });
}

int MXExecutorOutputs(ExecutorHandle handle, uint32_t *out_size,
                      NDArrayHandle **out) {
  return with_backend([&]() -> bool {
    int n = 0;
    if (!ret_handle_vec(
            call_backend("executor_outputs",
                         pack_steal(PyLong_FromLong(as_id(handle)))),
            &n, out))
      return false;
    *out_size = static_cast<uint32_t>(n);
    return true;
  });
}

int MXExecutorPrint(ExecutorHandle handle, const char **out_str) {
  return with_backend([&]() -> bool {
    return ret_string(call_backend(
        "executor_print", pack_steal(PyLong_FromLong(as_id(handle)))),
        out_str);
  });
}

int MXExecutorGetOptimizedSymbol(ExecutorHandle handle, SymbolHandle *out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "executor_get_optimized_symbol",
        pack_steal(PyLong_FromLong(as_id(handle)))), out);
  });
}

/* monitor callback: invoked per executor output after each forward
 * (simplified relative to the reference's per-op hook — the XLA graph
 * has no per-op boundary to observe); storage + firing live beside the
 * helpers (fire_monitors), called from MXExecutorForward. */
typedef void (*ExecutorMonitorCallback)(const char *, NDArrayHandle, void *);

int MXExecutorSetMonitorCallback(ExecutorHandle handle,
                                 ExecutorMonitorCallback callback,
                                 void *callback_handle) {
  std::lock_guard<std::recursive_mutex> lk(g_mutex);
  if (callback)
    g_monitors[handle] = {
        reinterpret_cast<ExecutorMonitorCallback_>(callback),
        callback_handle};
  else
    g_monitors.erase(handle);
  return 0;
}

int MXExecutorSetMonitorCallbackEX(ExecutorHandle handle,
                                   ExecutorMonitorCallback callback,
                                   void *callback_handle, bool monitor_all) {
  (void)monitor_all;
  return MXExecutorSetMonitorCallback(handle, callback, callback_handle);
}

/* -- autograd extras --------------------------------------------------- */

int MXAutogradBackwardEx(uint32_t num_output, NDArrayHandle *output_handles,
                         NDArrayHandle *ograd_handles, uint32_t num_variables,
                         NDArrayHandle *var_handles, int retain_graph,
                         int create_graph, int is_train,
                         NDArrayHandle **grad_handles, int **grad_stypes) {
  return with_backend([&]() -> bool {
    PyObject *ograds;
    if (ograd_handles) {
      ograds = handle_list(num_output, ograd_handles);
    } else {
      ograds = PyList_New(0);
    }
    int n = 0;
    if (!ret_handle_vec(
            call_backend(
                "autograd_backward_ex",
                pack_steal(handle_list(num_output, output_handles), ograds,
                           handle_list(num_variables, var_handles),
                           PyLong_FromLong(retain_graph),
                           PyLong_FromLong(create_graph),
                           PyLong_FromLong(is_train))),
            &n, grad_handles))
      return false;
    if (grad_stypes) {
      static thread_local std::vector<int> stypes;
      stypes.assign(static_cast<size_t>(n), 0);
      *grad_stypes = stypes.data();
    }
    return true;
  });
}

int MXAutogradComputeGradient(uint32_t num_output,
                              NDArrayHandle *output_handles) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend(
        "autograd_compute_gradient",
        pack_steal(handle_list(num_output, output_handles))));
  });
}

int MXAutogradGetSymbol(NDArrayHandle handle, SymbolHandle *out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "autograd_get_symbol",
        pack_steal(PyLong_FromLong(as_id(handle)))), out);
  });
}

/* -- kvstore updater / roles / commands -------------------------------- */

typedef void (*MXKVStoreUpdater)(int, NDArrayHandle, NDArrayHandle, void *);
typedef void (*MXKVStoreStrUpdater)(const char *, NDArrayHandle,
                                    NDArrayHandle, void *);

int MXKVStoreSetUpdater(KVStoreHandle handle, MXKVStoreUpdater updater,
                        void *updater_handle) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend(
        "kvstore_set_updater",
        pack_steal(PyLong_FromLong(as_id(handle)),
                   PyLong_FromVoidPtr(reinterpret_cast<void *>(updater)),
                   PyLong_FromVoidPtr(updater_handle))));
  });
}

int MXKVStoreSetUpdaterEx(KVStoreHandle handle, MXKVStoreUpdater updater,
                          MXKVStoreStrUpdater str_updater,
                          void *updater_handle) {
  if (str_updater) {
    return with_backend([&]() -> bool {
      return ret_void(call_backend(
          "kvstore_set_str_updater",
          pack_steal(PyLong_FromLong(as_id(handle)),
                     PyLong_FromVoidPtr(
                         reinterpret_cast<void *>(str_updater)),
                     PyLong_FromVoidPtr(updater_handle))));
    });
  }
  return MXKVStoreSetUpdater(handle, updater, updater_handle);
}

int MXKVStoreIsWorkerNode(int *ret) {
  return with_backend([&]() -> bool {
    return ret_int(call_backend("kvstore_is_worker_node", PyTuple_New(0)),
                   ret);
  });
}

int MXKVStoreIsServerNode(int *ret) {
  return with_backend([&]() -> bool {
    return ret_int(call_backend("kvstore_is_server_node", PyTuple_New(0)),
                   ret);
  });
}

int MXKVStoreIsSchedulerNode(int *ret) {
  return with_backend([&]() -> bool {
    return ret_int(call_backend("kvstore_is_scheduler_node",
                                PyTuple_New(0)), ret);
  });
}

int MXKVStoreRunServer(KVStoreHandle handle,
                       void (*controller)(int, const char *, void *),
                       void *controller_handle) {
  (void)controller;
  (void)controller_handle;
  return with_backend([&]() -> bool {
    return ret_void(call_backend(
        "kvstore_run_server", pack_steal(PyLong_FromLong(as_id(handle)))));
  });
}

int MXKVStoreSendCommmandToServers(KVStoreHandle handle, int cmd_id,
                                   const char *cmd_body) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend(
        "kvstore_send_command_to_servers",
        pack_steal(PyLong_FromLong(as_id(handle)), PyLong_FromLong(cmd_id),
                   PyUnicode_FromString(cmd_body))));
  });
}

int MXKVStoreSetBarrierBeforeExit(KVStoreHandle handle,
                                  int barrier_before_exit) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend(
        "kvstore_set_barrier_before_exit",
        pack_steal(PyLong_FromLong(as_id(handle)),
                   PyLong_FromLong(barrier_before_exit))));
  });
}

int MXKVStoreGetNumDeadNode(KVStoreHandle handle, int node_id, int *number) {
  return with_backend([&]() -> bool {
    return ret_int(call_backend(
        "kvstore_get_num_dead_node",
        pack_steal(PyLong_FromLong(as_id(handle)),
                   PyLong_FromLong(node_id))), number);
  });
}

int MXKVStoreSetGradientCompression(KVStoreHandle handle, uint32_t num_params,
                                    const char **keys, const char **vals) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend(
        "kvstore_set_gradient_compression",
        pack_steal(PyLong_FromLong(as_id(handle)),
                   string_list(num_params, keys),
                   string_list(num_params, vals))));
  });
}

int MXInitPSEnv(uint32_t num_vars, const char **keys, const char **vals) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend(
        "init_ps_env",
        pack_steal(string_list(num_vars, keys), string_list(num_vars, vals))));
  });
}

/* string-key init/push/pull (Ex): same backend paths — keys are strings
 * already in this ABI */

int MXKVStoreInitEx(KVStoreHandle handle, uint32_t num, const char **keys,
                    NDArrayHandle *vals) {
  return MXKVStoreInit(handle, num, keys, vals);
}

int MXKVStorePushEx(KVStoreHandle handle, uint32_t num, const char **keys,
                    NDArrayHandle *vals, int priority) {
  return MXKVStorePush(handle, num, keys, vals, priority);
}

int MXKVStorePullEx(KVStoreHandle handle, uint32_t num, const char **keys,
                    NDArrayHandle *vals, int priority) {
  return MXKVStorePull(handle, num, keys, vals, priority);
}

/* -- profiler config / objects ----------------------------------------- */

int MXSetProfilerConfig(int num_params, const char *const *keys,
                        const char *const *vals) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend(
        "set_profiler_config",
        pack_steal(string_list(num_params,
                               const_cast<const char **>(keys)),
                   string_list(num_params,
                               const_cast<const char **>(vals)))));
  });
}

int MXSetProcessProfilerConfig(int num_params, const char *const *keys,
                               const char *const *vals,
                               KVStoreHandle kv_handle) {
  (void)kv_handle;
  return MXSetProfilerConfig(num_params, keys, vals);
}

int MXSetProcessProfilerState(int state, int profile_process,
                              KVStoreHandle kv_handle) {
  (void)profile_process;
  (void)kv_handle;
  return MXSetProfilerState(state);
}

int MXDumpProcessProfile(int finished, int profile_process,
                         KVStoreHandle kv_handle) {
  (void)kv_handle;
  return with_backend([&]() -> bool {
    return ret_void(call_backend(
        "profiler_dump_ex", pack_steal(PyLong_FromLong(finished),
                                       PyLong_FromLong(profile_process))));
  });
}

int MXAggregateProfileStatsPrint(const char **out_str, int reset) {
  return with_backend([&]() -> bool {
    return ret_string(call_backend(
        "aggregate_profile_stats",
        pack_steal(PyLong_FromLong(reset), PyLong_FromLong(0),
                   PyLong_FromLong(0), PyLong_FromLong(0))), out_str);
  });
}

int MXAggregateProfileStatsPrintEx(const char **out_str, int reset,
                                   int format, int sort_by, int ascending) {
  return with_backend([&]() -> bool {
    return ret_string(call_backend(
        "aggregate_profile_stats",
        pack_steal(PyLong_FromLong(reset), PyLong_FromLong(format),
                   PyLong_FromLong(sort_by), PyLong_FromLong(ascending))),
        out_str);
  });
}

int MXProfilePause(int paused) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend("profiler_pause",
                                 pack_steal(PyLong_FromLong(paused))));
  });
}

int MXProcessProfilePause(int paused, int profile_process,
                          KVStoreHandle kv_handle) {
  (void)kv_handle;
  return with_backend([&]() -> bool {
    return ret_void(call_backend(
        "profiler_pause", pack_steal(PyLong_FromLong(paused),
                                     PyLong_FromLong(profile_process))));
  });
}

int MXProfileCreateDomain(const char *domain, ProfileHandle *out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend("profile_create_domain",
                                   pack_steal(PyUnicode_FromString(domain))),
                      out);
  });
}

int MXProfileCreateTask(ProfileHandle domain, const char *task_name,
                        ProfileHandle *out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "profile_create_task",
        pack_steal(PyLong_FromLong(as_id(domain)),
                   PyUnicode_FromString(task_name))), out);
  });
}

int MXProfileCreateFrame(ProfileHandle domain, const char *frame_name,
                         ProfileHandle *out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "profile_create_frame",
        pack_steal(PyLong_FromLong(as_id(domain)),
                   PyUnicode_FromString(frame_name))), out);
  });
}

int MXProfileCreateEvent(const char *event_name, ProfileHandle *out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "profile_create_event",
        pack_steal(PyUnicode_FromString(event_name))), out);
  });
}

int MXProfileCreateCounter(ProfileHandle domain, const char *counter_name,
                           ProfileHandle *out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "profile_create_counter",
        pack_steal(PyLong_FromLong(as_id(domain)),
                   PyUnicode_FromString(counter_name))), out);
  });
}

int MXProfileDestroyHandle(ProfileHandle handle) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend(
        "profile_destroy_handle",
        pack_steal(PyLong_FromLong(as_id(handle)))));
  });
}

int MXProfileDurationStart(ProfileHandle handle) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend(
        "profile_duration_start",
        pack_steal(PyLong_FromLong(as_id(handle)))));
  });
}

int MXProfileDurationStop(ProfileHandle handle) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend(
        "profile_duration_stop",
        pack_steal(PyLong_FromLong(as_id(handle)))));
  });
}

int MXProfileSetCounter(ProfileHandle handle, uint64_t value) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend(
        "profile_set_counter",
        pack_steal(PyLong_FromLong(as_id(handle)),
                   PyLong_FromUnsignedLongLong(value))));
  });
}

int MXProfileAdjustCounter(ProfileHandle handle, int64_t value) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend(
        "profile_adjust_counter",
        pack_steal(PyLong_FromLong(as_id(handle)),
                   PyLong_FromLongLong(value))));
  });
}

int MXProfileSetMarker(ProfileHandle domain, const char *instant_marker_name,
                       const char *scope) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend(
        "profile_set_marker",
        pack_steal(PyLong_FromLong(as_id(domain)),
                   PyUnicode_FromString(instant_marker_name),
                   PyUnicode_FromString(scope))));
  });
}

/* -- RecordIO ----------------------------------------------------------- */

int MXRecordIOWriterCreate(const char *uri, RecordIOHandle *out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend("recordio_writer_create",
                                   pack_steal(PyUnicode_FromString(uri))),
                      out);
  });
}

int MXRecordIOWriterFree(RecordIOHandle handle) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend(
        "recordio_free", pack_steal(PyLong_FromLong(as_id(handle)))));
  });
}

int MXRecordIOWriterWriteRecord(RecordIOHandle handle, const char *buf,
                                size_t size) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend(
        "recordio_write_record",
        pack_steal(PyLong_FromLong(as_id(handle)),
                   PyBytes_FromStringAndSize(buf,
                                             static_cast<Py_ssize_t>(size)))));
  });
}

int MXRecordIOWriterTell(RecordIOHandle handle, size_t *pos) {
  return with_backend([&]() -> bool {
    int v = 0;
    if (!ret_int(call_backend("recordio_writer_tell",
                              pack_steal(PyLong_FromLong(as_id(handle)))),
                 &v))
      return false;
    *pos = static_cast<size_t>(v);
    return true;
  });
}

int MXRecordIOReaderCreate(const char *uri, RecordIOHandle *out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend("recordio_reader_create",
                                   pack_steal(PyUnicode_FromString(uri))),
                      out);
  });
}

int MXRecordIOReaderFree(RecordIOHandle handle) {
  return MXRecordIOWriterFree(handle);
}

int MXRecordIOReaderReadRecord(RecordIOHandle handle, char const **buf,
                               size_t *size) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "recordio_read_record", pack_steal(PyLong_FromLong(as_id(handle))));
    if (!ret) return false;
    char *data = nullptr;
    Py_ssize_t n = 0;
    PyBytes_AsStringAndSize(ret, &data, &n);
    g_bytes_buf.assign(data ? data : "", static_cast<size_t>(n));
    Py_DECREF(ret);
    *buf = n ? g_bytes_buf.data() : nullptr;
    *size = static_cast<size_t>(n);
    return true;
  });
}

int MXRecordIOReaderSeek(RecordIOHandle handle, size_t pos) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend(
        "recordio_reader_seek",
        pack_steal(PyLong_FromLong(as_id(handle)),
                   PyLong_FromSize_t(pos))));
  });
}

int MXRecordIOReaderTell(RecordIOHandle handle, size_t *pos) {
  return with_backend([&]() -> bool {
    int v = 0;
    if (!ret_int(call_backend("recordio_reader_tell",
                              pack_steal(PyLong_FromLong(as_id(handle)))),
                 &v))
      return false;
    *pos = static_cast<size_t>(v);
    return true;
  });
}

/* -- legacy Function API (v0.x: functions are the imperative ops) ------- */

int MXListFunctions(uint32_t *out_size, FunctionHandle **out_array) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend("list_functions", PyTuple_New(0));
    if (!ret) return false;
    load_string_list(ret, g_op_names, g_op_name_ptrs);
    Py_DECREF(ret);
    static thread_local std::vector<const void *> fhandles;
    fhandles.resize(g_op_names.size());
    for (size_t i = 0; i < g_op_names.size(); ++i)
      fhandles[i] = g_op_names[i].c_str();
    *out_size = static_cast<uint32_t>(fhandles.size());
    *out_array = fhandles.data();
    return true;
  });
}

int MXGetFunction(const char *name, FunctionHandle *out) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend("func_get_info",
                                 pack_steal(PyUnicode_FromString(name)));
    if (!ret) return false;
    Py_DECREF(ret);
    // INTERN the name: the handle must outlive every later ABI call
    // (g_str_buf is clobbered by any string-returning entry point); a
    // node-based set gives stable c_str addresses for process lifetime
    static std::set<std::string> interned;
    *out = interned.insert(name).first->c_str();
    return true;
  });
}

int MXFuncGetInfo(FunctionHandle fun, const char **name,
                  const char **description, uint32_t *num_args,
                  const char ***arg_names, const char ***arg_type_infos,
                  const char ***arg_descriptions, const char **return_type) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "func_get_info",
        pack_steal(PyUnicode_FromString(static_cast<const char *>(fun))));
    if (!ret) return false;
    static thread_local std::string nm, doc;
    static thread_local std::vector<std::string> an, at, ad;
    static thread_local std::vector<const char *> anp, atp, adp;
    const char *s = PyUnicode_AsUTF8(PyTuple_GetItem(ret, 0));
    nm = s ? s : "";
    s = PyUnicode_AsUTF8(PyTuple_GetItem(ret, 1));
    doc = s ? s : "";
    load_string_list(PyTuple_GetItem(ret, 2), an, anp);
    load_string_list(PyTuple_GetItem(ret, 3), at, atp);
    load_string_list(PyTuple_GetItem(ret, 4), ad, adp);
    Py_DECREF(ret);
    *name = nm.c_str();
    *description = doc.c_str();
    *num_args = static_cast<uint32_t>(an.size());
    *arg_names = anp.data();
    *arg_type_infos = atp.data();
    *arg_descriptions = adp.data();
    if (return_type) *return_type = "";
    return true;
  });
}

int MXFuncDescribe(FunctionHandle fun, uint32_t *num_use_vars,
                   uint32_t *num_scalars, uint32_t *num_mutate_vars,
                   int *type_mask) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "func_get_info",
        pack_steal(PyUnicode_FromString(static_cast<const char *>(fun))));
    if (!ret) return false;
    Py_ssize_t n = PyList_Size(PyTuple_GetItem(ret, 2));
    Py_DECREF(ret);
    *num_use_vars = static_cast<uint32_t>(n);
    *num_scalars = 0;
    *num_mutate_vars = 1;
    *type_mask = 0;
    return true;
  });
}

static int func_invoke_impl(FunctionHandle fun, NDArrayHandle *use_vars,
                            NDArrayHandle *mutate_vars, int num_params,
                            const char **param_keys,
                            const char **param_vals) {
  /* arity comes from the same source MXFuncDescribe reports: the op's
   * declared tensor inputs — the caller sized use_vars from Describe */
  return with_backend([&]() -> bool {
    uint32_t n_use = 0, n_scalar = 0, n_mut = 0;
    int type_mask = 0;
    if (MXFuncDescribe(fun, &n_use, &n_scalar, &n_mut, &type_mask) != 0)
      return false;
    PyObject *ret = call_backend(
        "func_invoke",
        pack_steal(PyUnicode_FromString(static_cast<const char *>(fun)),
                   handle_list(n_use, use_vars),
                   string_list(static_cast<uint32_t>(num_params),
                               param_keys),
                   string_list(static_cast<uint32_t>(num_params),
                               param_vals),
                   handle_list(n_mut, mutate_vars)));
    if (!ret) return false;
    Py_DECREF(ret);
    return true;
  });
}

int MXFuncInvoke(FunctionHandle fun, NDArrayHandle *use_vars, float *scalars,
                 NDArrayHandle *mutate_vars) {
  (void)scalars;  /* num_scalars is reported 0 by MXFuncDescribe */
  return func_invoke_impl(fun, use_vars, mutate_vars, 0, nullptr, nullptr);
}

int MXFuncInvokeEx(FunctionHandle fun, NDArrayHandle *use_vars,
                   float *scalars, NDArrayHandle *mutate_vars,
                   int num_params, char **param_keys, char **param_vals) {
  (void)scalars;
  return func_invoke_impl(fun, use_vars, mutate_vars, num_params,
                          const_cast<const char **>(param_keys),
                          const_cast<const char **>(param_vals));
}

/* -- ndarray extras / 64-bit variants ----------------------------------- */

int MXNDArrayCreateEx(const uint32_t *shape, uint32_t ndim, int dev_type,
                      int dev_id, int delay_alloc, int dtype,
                      NDArrayHandle *out) {
  (void)dev_type;
  (void)dev_id;
  (void)delay_alloc;
  static const char *kDtypes[] = {"float32", "float64", "float16", "uint8",
                                  "int32",   "int8",    "int64",   "bool"};
  const char *dt = (dtype >= 0 && dtype < 8) ? kDtypes[dtype] : "float32";
  return MXNDArrayCreate(shape, ndim, dt, out);
}

int MXNDArrayCreateEx64(const int64_t *shape, int ndim, int dev_type,
                        int dev_id, int delay_alloc, int dtype,
                        NDArrayHandle *out) {
  (void)dev_type;
  (void)dev_id;
  (void)delay_alloc;
  std::vector<uint32_t> s32(static_cast<size_t>(ndim));
  for (int i = 0; i < ndim; ++i) s32[static_cast<size_t>(i)] =
      static_cast<uint32_t>(shape[i]);
  return MXNDArrayCreateEx(s32.data(), static_cast<uint32_t>(ndim), dev_type,
                           dev_id, delay_alloc, dtype, out);
}

int MXNDArrayCreateNone(NDArrayHandle *out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend("ndarray_create_none", PyTuple_New(0)),
                      out);
  });
}

int MXNDArrayGetShapeEx(NDArrayHandle handle, int *out_dim,
                        const int **out_pdata) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "ndarray_get_shape", pack_steal(PyLong_FromLong(as_id(handle))));
    if (!ret) return false;
    static thread_local std::vector<int> dims;
    Py_ssize_t n = PyTuple_Size(ret);
    dims.resize(static_cast<size_t>(n));
    for (Py_ssize_t i = 0; i < n; ++i)
      dims[static_cast<size_t>(i)] =
          static_cast<int>(PyLong_AsLong(PyTuple_GetItem(ret, i)));
    Py_DECREF(ret);
    *out_dim = static_cast<int>(n);
    *out_pdata = dims.data();
    return true;
  });
}

int MXNDArrayGetShape64(NDArrayHandle handle, int *out_dim,
                        const int64_t **out_pdata) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "ndarray_get_shape", pack_steal(PyLong_FromLong(as_id(handle))));
    if (!ret) return false;
    Py_ssize_t n = PyTuple_Size(ret);
    g_shape64_buf.resize(static_cast<size_t>(n));
    for (Py_ssize_t i = 0; i < n; ++i)
      g_shape64_buf[static_cast<size_t>(i)] =
          PyLong_AsLongLong(PyTuple_GetItem(ret, i));
    Py_DECREF(ret);
    *out_dim = static_cast<int>(n);
    *out_pdata = g_shape64_buf.data();
    return true;
  });
}

int MXNDArrayGetShapeEx64(NDArrayHandle handle, int *out_dim,
                          const int64_t **out_pdata) {
  return MXNDArrayGetShape64(handle, out_dim, out_pdata);
}

int MXNDArrayAt64(NDArrayHandle handle, int64_t idx, NDArrayHandle *out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "ndarray_at", pack_steal(PyLong_FromLong(as_id(handle)),
                                 PyLong_FromLongLong(idx))), out);
  });
}

int MXNDArraySlice64(NDArrayHandle handle, int64_t begin, int64_t end,
                     NDArrayHandle *out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "ndarray_slice",
        pack_steal(PyLong_FromLong(as_id(handle)),
                   PyLong_FromLongLong(begin), PyLong_FromLongLong(end))),
        out);
  });
}

int MXNDArrayReshape64(NDArrayHandle handle, int ndim, dim_t *dims,
                       bool reverse, NDArrayHandle *out) {
  (void)reverse;
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "ndarray_reshape",
        pack_steal(PyLong_FromLong(as_id(handle)),
                   shape_list64(reinterpret_cast<const int64_t *>(dims),
                                static_cast<uint32_t>(ndim)))), out);
  });
}

int MXNDArrayGetStorageType(NDArrayHandle handle, int *out_storage_type) {
  return with_backend([&]() -> bool {
    return ret_int(call_backend(
        "ndarray_get_storage_type",
        pack_steal(PyLong_FromLong(as_id(handle)))), out_storage_type);
  });
}

int MXNDArrayWaitToWrite(NDArrayHandle handle) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend(
        "ndarray_wait_to_write",
        pack_steal(PyLong_FromLong(as_id(handle)))));
  });
}

int MXNDArrayDetach(NDArrayHandle handle, NDArrayHandle *out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "ndarray_detach", pack_steal(PyLong_FromLong(as_id(handle)))), out);
  });
}

int MXNDArraySetGradState(NDArrayHandle handle, int state) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend(
        "ndarray_set_grad_state",
        pack_steal(PyLong_FromLong(as_id(handle)),
                   PyLong_FromLong(state))));
  });
}

int MXNDArrayGetGradState(NDArrayHandle handle, int *out) {
  return with_backend([&]() -> bool {
    return ret_int(call_backend(
        "ndarray_get_grad_state",
        pack_steal(PyLong_FromLong(as_id(handle)))), out);
  });
}

int MXNDArraySaveRawBytes(NDArrayHandle handle, size_t *out_size,
                          const char **out_buf) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "ndarray_save_raw_bytes",
        pack_steal(PyLong_FromLong(as_id(handle))));
    if (!ret) return false;
    char *data = nullptr;
    Py_ssize_t n = 0;
    PyBytes_AsStringAndSize(ret, &data, &n);
    g_bytes_buf.assign(data ? data : "", static_cast<size_t>(n));
    Py_DECREF(ret);
    *out_size = static_cast<size_t>(n);
    *out_buf = g_bytes_buf.data();
    return true;
  });
}

int MXNDArrayLoadFromRawBytes(const void *buf, size_t size,
                              NDArrayHandle *out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "ndarray_load_from_raw_bytes",
        pack_steal(PyBytes_FromStringAndSize(
            static_cast<const char *>(buf),
            static_cast<Py_ssize_t>(size)))), out);
  });
}

int MXNDArrayLoadFromBuffer(const void *buf, size_t size, uint32_t *out_size,
                            NDArrayHandle **out_arr, uint32_t *out_name_size,
                            const char ***out_names) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "ndarray_load_from_buffer",
        pack_steal(PyBytes_FromStringAndSize(
            static_cast<const char *>(buf),
            static_cast<Py_ssize_t>(size))));
    if (!ret) return false;
    PyObject *hs = PyTuple_GetItem(ret, 0);
    PyObject *names = PyTuple_GetItem(ret, 1);
    Py_ssize_t n = PyList_Size(hs);
    g_handle_buf.resize(static_cast<size_t>(n));
    for (Py_ssize_t i = 0; i < n; ++i)
      g_handle_buf[static_cast<size_t>(i)] =
          as_handle(PyLong_AsLong(PyList_GetItem(hs, i)));
    load_string_list(names, g_name_buf, g_name_ptr_buf);
    Py_DECREF(ret);
    *out_size = static_cast<uint32_t>(n);
    *out_arr = g_handle_buf.data();
    *out_name_size = static_cast<uint32_t>(g_name_buf.size());
    *out_names = g_name_ptr_buf.data();
    return true;
  });
}

int MXNDArrayLoadFromBuffer64(const void *buf, size_t size,
                              uint32_t *out_size, NDArrayHandle **out_arr,
                              uint32_t *out_name_size,
                              const char ***out_names) {
  return MXNDArrayLoadFromBuffer(buf, size, out_size, out_arr, out_name_size,
                                 out_names);
}

int MXNDArrayLoad64(const char *fname, uint32_t *out_size,
                    NDArrayHandle **out_arr, uint32_t *out_name_size,
                    const char ***out_names) {
  return MXNDArrayLoad(fname, out_size, out_arr, out_name_size, out_names);
}

int MXNDArraySyncCopyFromNDArray(NDArrayHandle handle_dst,
                                 const NDArrayHandle handle_src, int i) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend(
        "ndarray_sync_copy_from_ndarray",
        pack_steal(PyLong_FromLong(as_id(handle_dst)),
                   PyLong_FromLong(as_id(const_cast<void *>(handle_src))),
                   PyLong_FromLong(i))));
  });
}

int MXNDArraySyncCheckFormat(NDArrayHandle handle, const bool full_check) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend(
        "ndarray_sync_check_format",
        pack_steal(PyLong_FromLong(as_id(handle)),
                   PyLong_FromLong(full_check ? 1 : 0))));
  });
}

int MXNDArrayGetData(NDArrayHandle handle, void **out_pdata) {
  /* host copy of the buffer, valid until the next call on this thread */
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "ndarray_sync_copy_to_cpu",
        pack_steal(PyLong_FromLong(as_id(handle))));
    if (!ret) return false;
    char *data = nullptr;
    Py_ssize_t n = 0;
    PyBytes_AsStringAndSize(ret, &data, &n);
    g_bytes_buf.assign(data ? data : "", static_cast<size_t>(n));
    Py_DECREF(ret);
    *out_pdata = const_cast<char *>(g_bytes_buf.data());
    return true;
  });
}

int MXNDArrayGetDataNDArray(NDArrayHandle handle, NDArrayHandle *out) {
  return MXShallowCopyNDArray(handle, out);
}

/* -- engine push: NaiveEngine semantics (execute now, complete now) ----- */

int MXEngineSetBulkSize(int bulk_size, int *prev_bulk_size) {
  return with_backend([&]() -> bool {
    return ret_int(call_backend("engine_set_bulk_size",
                                pack_steal(PyLong_FromLong(bulk_size))),
                   prev_bulk_size);
  });
}

typedef void (*EngineSyncFunc)(void *, void *);
typedef void (*EngineAsyncFunc)(void *, void *, void *);
typedef void (*EngineFuncParamDeleter)(void *);

int MXEnginePushSync(EngineSyncFunc sync_func, void *func_param,
                     EngineFuncParamDeleter deleter, void *ctx_handle,
                     void *const_vars_handle, int num_const_vars,
                     void *mutable_vars_handle, int num_mutable_vars,
                     void *prop_handle, int priority, const char *opr_name) {
  (void)ctx_handle; (void)const_vars_handle; (void)num_const_vars;
  (void)mutable_vars_handle; (void)num_mutable_vars; (void)prop_handle;
  (void)priority; (void)opr_name;
  /* PJRT dispatch is already async; the engine contract collapses to
   * immediate execution (NaiveEngine semantics, SURVEY §1 layer 2) */
  if (sync_func) sync_func(nullptr, func_param);
  if (deleter) deleter(func_param);
  return 0;
}

static void engine_async_complete(void *, void *) {}

int MXEnginePushAsync(EngineAsyncFunc async_func, void *func_param,
                      EngineFuncParamDeleter deleter, void *ctx_handle,
                      void *const_vars_handle, int num_const_vars,
                      void *mutable_vars_handle, int num_mutable_vars,
                      void *prop_handle, int priority, const char *opr_name,
                      bool wait) {
  (void)ctx_handle; (void)const_vars_handle; (void)num_const_vars;
  (void)mutable_vars_handle; (void)num_mutable_vars; (void)prop_handle;
  (void)priority; (void)opr_name; (void)wait;
  if (async_func)
    async_func(nullptr, func_param,
               reinterpret_cast<void *>(&engine_async_complete));
  if (deleter) deleter(func_param);
  return 0;
}

int MXEnginePushSyncND(EngineSyncFunc sync_func, void *func_param,
                       EngineFuncParamDeleter deleter, void *ctx_handle,
                       NDArrayHandle *const_nds, int num_const_nds,
                       NDArrayHandle *mutable_nds, int num_mutable_nds,
                       void *prop_handle, int priority, const char *opr_name) {
  (void)const_nds; (void)mutable_nds;
  return MXEnginePushSync(sync_func, func_param, deleter, ctx_handle,
                          nullptr, num_const_nds, nullptr, num_mutable_nds,
                          prop_handle, priority, opr_name);
}

int MXEnginePushAsyncND(EngineAsyncFunc async_func, void *func_param,
                        EngineFuncParamDeleter deleter, void *ctx_handle,
                        NDArrayHandle *const_nds, int num_const_nds,
                        NDArrayHandle *mutable_nds, int num_mutable_nds,
                        void *prop_handle, int priority,
                        const char *opr_name, bool wait) {
  (void)const_nds; (void)mutable_nds;
  return MXEnginePushAsync(async_func, func_param, deleter, ctx_handle,
                           nullptr, num_const_nds, nullptr, num_mutable_nds,
                           prop_handle, priority, opr_name, wait);
}

/* -- quantization / graph passes ---------------------------------------- */

int MXQuantizeSymbol(SymbolHandle sym_handle, SymbolHandle *ret_sym_handle,
                     const uint32_t num_excluded_symbols,
                     const char **excluded_symbols,
                     const uint32_t num_offline, const char **offline_params,
                     const char *quantized_dtype, const bool calib_quantize) {
  (void)calib_quantize;
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "quantize_symbol",
        pack_steal(PyLong_FromLong(as_id(sym_handle)),
                   string_list(num_excluded_symbols, excluded_symbols),
                   string_list(num_offline, offline_params),
                   PyUnicode_FromString(quantized_dtype))),
        ret_sym_handle);
  });
}

int MXReducePrecisionSymbol(SymbolHandle sym_handle,
                            SymbolHandle *ret_sym_handle, uint32_t num_args,
                            const int *arg_type_data, uint32_t num_ind_ptr,
                            const int *ind_ptr, const int *target_dtype,
                            const int cast_optional_params,
                            const uint32_t num_target_dtype_ops,
                            const char **target_dtype_ops,
                            const uint32_t num_fp32_ops,
                            const char **fp32_ops,
                            const uint32_t num_widest_dtype_ops,
                            const char **widest_dtype_ops,
                            const uint32_t num_conditional_fp32_ops,
                            const char **conditional_fp32_ops,
                            const uint32_t num_excluded_symbols,
                            const char **excluded_symbols,
                            const char **arg_names) {
  (void)num_args; (void)arg_type_data; (void)num_ind_ptr; (void)ind_ptr;
  (void)cast_optional_params; (void)num_target_dtype_ops;
  (void)target_dtype_ops; (void)num_fp32_ops; (void)fp32_ops;
  (void)num_widest_dtype_ops; (void)widest_dtype_ops;
  (void)num_conditional_fp32_ops; (void)conditional_fp32_ops;
  (void)num_excluded_symbols; (void)excluded_symbols; (void)arg_names;
  const char *dt = (target_dtype && *target_dtype == 2) ? "float16"
                                                        : "bfloat16";
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "reduce_precision_symbol",
        pack_steal(PyLong_FromLong(as_id(sym_handle)),
                   PyUnicode_FromString(dt))), ret_sym_handle);
  });
}

int MXSetCalibTableToQuantizedSymbol(SymbolHandle qsym_handle,
                                     const uint32_t num_layers,
                                     const char **layer_names,
                                     const float *low_quantiles,
                                     const float *high_quantiles,
                                     SymbolHandle *ret_sym_handle) {
  return with_backend([&]() -> bool {
    PyObject *lows = PyList_New(num_layers);
    PyObject *highs = PyList_New(num_layers);
    for (uint32_t i = 0; i < num_layers; ++i) {
      PyList_SetItem(lows, i, PyFloat_FromDouble(low_quantiles[i]));
      PyList_SetItem(highs, i, PyFloat_FromDouble(high_quantiles[i]));
    }
    return ret_handle(call_backend(
        "set_calib_table",
        pack_steal(PyLong_FromLong(as_id(qsym_handle)),
                   string_list(num_layers, layer_names), lows, highs)),
        ret_sym_handle);
  });
}

int MXGenBackendSubgraph(SymbolHandle sym_handle, const char *backend,
                         SymbolHandle *ret_sym_handle) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "gen_backend_subgraph",
        pack_steal(PyLong_FromLong(as_id(sym_handle)),
                   PyUnicode_FromString(backend))), ret_sym_handle);
  });
}

int MXOptimizeForBackend(SymbolHandle sym_handle, const char *backend,
                         const int dev_type, SymbolHandle *ret_sym_handle,
                         const uint32_t args_len, NDArrayHandle *in_args,
                         const uint32_t aux_len, NDArrayHandle *in_aux,
                         const uint32_t num_options, const char **keys,
                         const char **vals, int **new_args_cnt,
                         NDArrayHandle **new_args_handle,
                         char ***new_arg_names_handle, int **new_aux_cnt,
                         NDArrayHandle **new_aux_handle,
                         char ***new_aux_names_handle) {
  (void)dev_type; (void)args_len; (void)in_args; (void)aux_len;
  (void)in_aux; (void)num_options; (void)keys; (void)vals;
  if (new_args_cnt) *new_args_cnt = nullptr;
  if (new_args_handle) *new_args_handle = nullptr;
  if (new_arg_names_handle) *new_arg_names_handle = nullptr;
  if (new_aux_cnt) *new_aux_cnt = nullptr;
  if (new_aux_handle) *new_aux_handle = nullptr;
  if (new_aux_names_handle) *new_aux_names_handle = nullptr;
  return MXGenBackendSubgraph(sym_handle, backend, ret_sym_handle);
}

/* -- misc --------------------------------------------------------------- */

int MXIsNumpyShape(int *curr) {
  return with_backend([&]() -> bool {
    return ret_int(call_backend("is_numpy_shape", PyTuple_New(0)), curr);
  });
}

int MXSetIsNumpyShape(int is_np_shape, int *prev) {
  return with_backend([&]() -> bool {
    int unused = 0;
    if (!ret_int(call_backend("is_numpy_shape", PyTuple_New(0)),
                 prev ? prev : &unused))
      return false;
    return ret_void(call_backend(
        "set_is_numpy_shape", pack_steal(PyLong_FromLong(is_np_shape))));
  });
}

int MXSetNumOMPThreads(int thread_num) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend("set_num_omp_threads",
                                 pack_steal(PyLong_FromLong(thread_num))));
  });
}

int MXStorageEmptyCache(int dev_type, int dev_id) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend(
        "storage_empty_cache", pack_steal(PyLong_FromLong(dev_type),
                                          PyLong_FromLong(dev_id))));
  });
}

int MXGetGPUMemoryInformation(int dev, int *free_mem, int *total_mem) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend("get_gpu_memory_information",
                                 pack_steal(PyLong_FromLong(dev)));
    if (!ret) return false;
    *free_mem = static_cast<int>(
        PyLong_AsLongLong(PyTuple_GetItem(ret, 0)) >> 20);
    *total_mem = static_cast<int>(
        PyLong_AsLongLong(PyTuple_GetItem(ret, 1)) >> 20);
    Py_DECREF(ret);
    return true;
  });
}

int MXGetGPUMemoryInformation64(int dev, uint64_t *free_mem,
                                uint64_t *total_mem) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend("get_gpu_memory_information",
                                 pack_steal(PyLong_FromLong(dev)));
    if (!ret) return false;
    *free_mem = static_cast<uint64_t>(
        PyLong_AsLongLong(PyTuple_GetItem(ret, 0)));
    *total_mem = static_cast<uint64_t>(
        PyLong_AsLongLong(PyTuple_GetItem(ret, 1)));
    Py_DECREF(ret);
    return true;
  });
}

int MXLibInfoFeatures(const struct LibFeature **lib_feature, size_t *size) {
  /* the reference returns LibFeature structs; marshal the (name, enabled)
   * pairs into a thread-local array of that layout */
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend("lib_info_features", PyTuple_New(0));
    if (!ret) return false;
    load_string_list(ret, g_attr_buf, g_attr_ptr_buf);
    Py_DECREF(ret);
    static thread_local std::vector<LibFeature> feats;
    size_t n = g_attr_buf.size() / 2;
    feats.resize(n);
    for (size_t i = 0; i < n; ++i) {
      feats[i].name = g_attr_buf[2 * i].c_str();
      feats[i].enabled = g_attr_buf[2 * i + 1] == "1";
    }
    *lib_feature = feats.data();
    *size = n;
    return true;
  });
}

int MXRandomSeedContext(int seed, int dev_type, int dev_id) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend(
        "random_seed_context",
        pack_steal(PyLong_FromLong(seed), PyLong_FromLong(dev_type),
                   PyLong_FromLong(dev_id))));
  });
}

int MXLoadLib(const char *path) {
  return with_backend([&]() -> bool {
    return ret_void(call_backend("load_lib",
                                 pack_steal(PyUnicode_FromString(path))));
  });
}

/* -- DLPack ------------------------------------------------------------- */

int MXNDArrayToDLPack(NDArrayHandle handle, DLManagedTensorHandle *out_dlpack) {
  return with_backend([&]() -> bool {
    PyObject *capsule = call_backend(
        "ndarray_to_dlpack", pack_steal(PyLong_FromLong(as_id(handle))));
    if (!capsule) return false;
    void *ptr = PyCapsule_GetPointer(capsule, "dltensor");
    if (!ptr) {
      PyErr_Clear();
      set_error("invalid DLPack capsule");
      Py_DECREF(capsule);
      return false;
    }
    /* mark consumed so the capsule destructor won't free the tensor the
     * C caller now owns */
    PyCapsule_SetName(capsule, "used_dltensor");
    Py_DECREF(capsule);
    *out_dlpack = ptr;
    return true;
  });
}

int MXNDArrayFromDLPack(DLManagedTensorHandle dlpack, NDArrayHandle *out) {
  return with_backend([&]() -> bool {
    PyObject *capsule = PyCapsule_New(dlpack, "dltensor", nullptr);
    if (!capsule) {
      set_error("failed to wrap DLPack pointer");
      return false;
    }
    return ret_handle(call_backend("ndarray_from_dlpack",
                                   pack_steal(capsule)), out);
  });
}

int MXNDArrayFromDLPackEx(DLManagedTensorHandle dlpack,
                          const bool transient_handle, NDArrayHandle *out) {
  (void)transient_handle;
  return MXNDArrayFromDLPack(dlpack, out);
}

int MXNDArrayCallDLPackDeleter(DLManagedTensorHandle dlpack) {
  /* DLManagedTensor layout: {DLTensor, void* ctx, void (*deleter)()} —
   * invoke the embedded deleter like the reference does */
  struct MiniDLManagedTensor {
    char opaque[sizeof(void *) * 8];  /* DLTensor is larger; deleter is
                                         accessed via real layout below */
  };
  if (dlpack) {
    /* proper layout per dlpack.h */
    struct DLTensorABI {
      void *data;
      int32_t device_type, device_id;
      int32_t ndim;
      uint8_t code, bits;
      uint16_t lanes;
      int64_t *shape, *strides;
      uint64_t byte_offset;
    };
    struct DLManagedTensorABI {
      DLTensorABI dl_tensor;
      void *manager_ctx;
      void (*deleter)(struct DLManagedTensorABI *);
    };
    auto *mt = static_cast<DLManagedTensorABI *>(dlpack);
    if (mt->deleter) mt->deleter(mt);
  }
  return 0;
}

/* -- CUDA-only families: exported, honest unsupported errors ------------ */

int MXRtcCreate(char *name, uint32_t num_input, uint32_t num_output,
                char **input_names, char **output_names,
                NDArrayHandle *inputs, NDArrayHandle *outputs, char *kernel,
                RtcHandle *out) {
  (void)name; (void)num_input; (void)num_output; (void)input_names;
  (void)output_names; (void)inputs; (void)outputs; (void)kernel; (void)out;
  return unsupported("MXRtcCreate", "CUDA RTC compiles .cu source; use "
                     "mxnet_tpu.rtc.PallasModule for runtime TPU kernels");
}

int MXRtcPush(RtcHandle handle, uint32_t num_input, uint32_t num_output,
              NDArrayHandle *inputs, NDArrayHandle *outputs,
              uint32_t gridDimX, uint32_t gridDimY, uint32_t gridDimZ,
              uint32_t blockDimX, uint32_t blockDimY, uint32_t blockDimZ) {
  (void)handle; (void)num_input; (void)num_output; (void)inputs;
  (void)outputs; (void)gridDimX; (void)gridDimY; (void)gridDimZ;
  (void)blockDimX; (void)blockDimY; (void)blockDimZ;
  return unsupported("MXRtcPush", "see MXRtcCreate");
}

int MXRtcFree(RtcHandle handle) {
  (void)handle;
  return unsupported("MXRtcFree", "see MXRtcCreate");
}

int MXRtcCudaModuleCreate(const char *source, int num_options,
                          const char **options, int num_exports,
                          const char **exports, CudaModuleHandle *out) {
  (void)source; (void)num_options; (void)options; (void)num_exports;
  (void)exports; (void)out;
  return unsupported("MXRtcCudaModuleCreate",
                     "CUDA modules do not exist on TPU; use "
                     "mxnet_tpu.rtc.PallasModule");
}

int MXRtcCudaModuleFree(CudaModuleHandle handle) {
  (void)handle;
  return unsupported("MXRtcCudaModuleFree", "see MXRtcCudaModuleCreate");
}

int MXRtcCudaKernelCreate(CudaModuleHandle handle, const char *name,
                          int num_args, int *is_ndarray, int *is_const,
                          int *arg_types, CudaKernelHandle *out) {
  (void)handle; (void)name; (void)num_args; (void)is_ndarray;
  (void)is_const; (void)arg_types; (void)out;
  return unsupported("MXRtcCudaKernelCreate", "see MXRtcCudaModuleCreate");
}

int MXRtcCudaKernelFree(CudaKernelHandle handle) {
  (void)handle;
  return unsupported("MXRtcCudaKernelFree", "see MXRtcCudaModuleCreate");
}

int MXRtcCudaKernelCall(CudaKernelHandle handle, int dev_id, void **args,
                        uint32_t grid_dim_x, uint32_t grid_dim_y,
                        uint32_t grid_dim_z, uint32_t block_dim_x,
                        uint32_t block_dim_y, uint32_t block_dim_z,
                        uint32_t shared_mem) {
  (void)handle; (void)dev_id; (void)args; (void)grid_dim_x;
  (void)grid_dim_y; (void)grid_dim_z; (void)block_dim_x; (void)block_dim_y;
  (void)block_dim_z; (void)shared_mem;
  return unsupported("MXRtcCudaKernelCall", "see MXRtcCudaModuleCreate");
}

int MXLoadTVMOp(const char *libpath) {
  (void)libpath;
  return unsupported("MXLoadTVMOp", "TVM-generated CUDA kernels do not "
                     "apply; XLA compiles the op corpus");
}

int MXCustomOpRegister(const char *op_type, void *creator) {
  (void)op_type; (void)creator;
  return unsupported("MXCustomOpRegister",
                     "C++ CustomOp callbacks are CUDA/C++-runtime specific; "
                     "register python CustomOps (mxnet_tpu.operator) or "
                     "load an op library via MXLoadLib");
}

int MXCustomFunctionRecord(int num_inputs, NDArrayHandle *inputs,
                           int num_outputs, NDArrayHandle *outputs,
                           void *callbacks) {
  (void)num_inputs; (void)inputs; (void)num_outputs; (void)outputs;
  (void)callbacks;
  return unsupported("MXCustomFunctionRecord",
                     "use mxnet_tpu.autograd.Function from python; the C "
                     "callback trampoline is not exposed");
}

/* -- sparse creation (CSR / row-sparse) --------------------------------- */

int MXNDArrayCreateSparseEx(int storage_type, const uint32_t *shape,
                            uint32_t ndim, int dev_type, int dev_id,
                            int delay_alloc, int dtype,
                            uint32_t num_aux, int *aux_type,
                            uint32_t *aux_ndims, const uint32_t *aux_shape,
                            NDArrayHandle *out) {
  (void)dev_type; (void)dev_id; (void)delay_alloc; (void)num_aux;
  (void)aux_type; (void)aux_ndims; (void)aux_shape;
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "ndarray_create_sparse",
        pack_steal(PyLong_FromLong(storage_type), shape_list(shape, ndim),
                   PyLong_FromLong(dtype))), out);
  });
}

int MXNDArrayCreateSparseEx64(int storage_type, const int64_t *shape,
                              int ndim, int dev_type, int dev_id,
                              int delay_alloc, int dtype, uint32_t num_aux,
                              int *aux_type, int *aux_ndims,
                              const int64_t *aux_shape, NDArrayHandle *out) {
  (void)num_aux; (void)aux_type; (void)aux_ndims; (void)aux_shape;
  std::vector<uint32_t> s32(static_cast<size_t>(ndim));
  for (int i = 0; i < ndim; ++i) s32[static_cast<size_t>(i)] =
      static_cast<uint32_t>(shape[i]);
  return MXNDArrayCreateSparseEx(storage_type, s32.data(),
                                 static_cast<uint32_t>(ndim), dev_type,
                                 dev_id, delay_alloc, dtype, 0, nullptr,
                                 nullptr, nullptr, out);
}

int MXNDArrayGetAuxType(NDArrayHandle handle, uint32_t i, int *out_type) {
  (void)handle; (void)i;
  *out_type = 6; /* int64 indices, both CSR and row-sparse aux */
  return 0;
}

int MXNDArrayGetAuxType64(NDArrayHandle handle, int64_t i, int *out_type) {
  return MXNDArrayGetAuxType(handle, static_cast<uint32_t>(i), out_type);
}

int MXNDArrayGetAuxNDArray(NDArrayHandle handle, uint32_t i,
                           NDArrayHandle *out) {
  return with_backend([&]() -> bool {
    return ret_handle(call_backend(
        "ndarray_get_aux", pack_steal(PyLong_FromLong(as_id(handle)),
                                      PyLong_FromUnsignedLong(i))), out);
  });
}

int MXNDArrayGetAuxNDArray64(NDArrayHandle handle, int64_t i,
                             NDArrayHandle *out) {
  return MXNDArrayGetAuxNDArray(handle, static_cast<uint32_t>(i), out);
}

int MXNDArrayGetSharedMemHandle(NDArrayHandle handle, int *shared_pid,
                                int *shared_id) {
  (void)handle; (void)shared_pid; (void)shared_id;
  return unsupported("MXNDArrayGetSharedMemHandle",
                     "cross-process tensors travel via "
                     "multiprocessing.shared_memory in the DataLoader; "
                     "the SysV-style (pid,id) handle pair has no analog");
}

int MXNDArrayCreateFromSharedMem(int shared_pid, int shared_id,
                                 const uint32_t *shape, uint32_t ndim,
                                 int dtype, NDArrayHandle *out) {
  (void)shared_pid; (void)shared_id; (void)shape; (void)ndim; (void)dtype;
  (void)out;
  return unsupported("MXNDArrayCreateFromSharedMem",
                     "see MXNDArrayGetSharedMemHandle");
}

int MXNDArrayCreateFromSharedMemEx(int shared_pid, int shared_id,
                                   const int *shape, int ndim, int dtype,
                                   NDArrayHandle *out) {
  (void)shape; (void)ndim;
  return MXNDArrayCreateFromSharedMem(shared_pid, shared_id, nullptr, 0,
                                      dtype, out);
}

}  // extern "C"

/* ------------------------------------------------------------------------
 * Final delegation tier: Ex/64 spellings + remaining iterator/executor/
 * kvstore/symbol entries (ref: include/mxnet/c_api.h).
 * --------------------------------------------------------------------- */

extern "C" {

int MXDataIterGetIndex(DataIterHandle handle, uint64_t **out_index,
                       uint64_t *out_size) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "data_iter_get_index", pack_steal(PyLong_FromLong(as_id(handle))));
    if (!ret) return false;
    static thread_local std::vector<uint64_t> idx;
    Py_ssize_t n = PyList_Size(ret);
    idx.resize(static_cast<size_t>(n));
    for (Py_ssize_t i = 0; i < n; ++i)
      idx[static_cast<size_t>(i)] = static_cast<uint64_t>(
          PyLong_AsUnsignedLongLong(PyList_GetItem(ret, i)));
    Py_DECREF(ret);
    *out_index = idx.data();
    *out_size = static_cast<uint64_t>(n);
    return true;
  });
}

int MXDataIterGetPadNum(DataIterHandle handle, int *pad) {
  return with_backend([&]() -> bool {
    return ret_int(call_backend("data_iter_get_pad",
                                pack_steal(PyLong_FromLong(as_id(handle)))),
                   pad);
  });
}

int MXDataIterGetIterInfo(DataIterCreator creator, const char **name,
                          const char **description, uint32_t *num_args,
                          const char ***arg_names,
                          const char ***arg_type_infos,
                          const char ***arg_descriptions) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "data_iter_get_info",
        pack_steal(PyUnicode_FromString(
            static_cast<const char *>(creator))));
    if (!ret) return false;
    static thread_local std::string nm, doc;
    static thread_local std::vector<std::string> an, at, ad;
    static thread_local std::vector<const char *> anp, atp, adp;
    const char *s = PyUnicode_AsUTF8(PyTuple_GetItem(ret, 0));
    nm = s ? s : "";
    s = PyUnicode_AsUTF8(PyTuple_GetItem(ret, 1));
    doc = s ? s : "";
    load_string_list(PyTuple_GetItem(ret, 2), an, anp);
    load_string_list(PyTuple_GetItem(ret, 3), at, atp);
    load_string_list(PyTuple_GetItem(ret, 4), ad, adp);
    Py_DECREF(ret);
    *name = nm.c_str();
    *description = doc.c_str();
    *num_args = static_cast<uint32_t>(an.size());
    *arg_names = anp.data();
    *arg_type_infos = atp.data();
    *arg_descriptions = adp.data();
    return true;
  });
}

int MXExecutorBackwardEx(ExecutorHandle handle, uint32_t len,
                         NDArrayHandle *head_grads, int is_train) {
  (void)is_train;
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "executor_backward_ex",
        pack_steal(PyLong_FromLong(as_id(handle)),
                   handle_list(len, head_grads)));
    if (!ret) return false;
    Py_DECREF(ret);
    return true;
  });
}

int MXExecutorBindX(SymbolHandle sym, int dev_type, int dev_id,
                    uint32_t num_map_keys, const char **map_keys,
                    const int *map_dev_types, const int *map_dev_ids,
                    uint32_t len, NDArrayHandle *in_args,
                    NDArrayHandle *arg_grad_store, uint32_t *grad_req_type,
                    uint32_t aux_states_len, NDArrayHandle *aux_states,
                    ExecutorHandle *out) {
  /* group2ctx placement maps are a multi-device GPU concept; GSPMD owns
   * placement here — the map is accepted and ignored. grad_req IS
   * honored: any non-null request binds with gradients (read them back
   * via MXExecutorBackward's returned handles — caller-owned grad
   * stores are not aliased on immutable XLA buffers). */
  (void)num_map_keys; (void)map_keys; (void)map_dev_types;
  (void)map_dev_ids; (void)arg_grad_store;
  (void)aux_states_len; (void)aux_states;
  bool want_grad = false;
  if (grad_req_type)
    for (uint32_t i = 0; i < len; ++i)
      want_grad |= grad_req_type[i] != 0;
  return MXExecutorBind(sym, dev_type, dev_id, len, in_args,
                        want_grad ? "write" : "null", out);
}

int MXExecutorBindEX(SymbolHandle sym, int dev_type, int dev_id,
                     uint32_t num_map_keys, const char **map_keys,
                     const int *map_dev_types, const int *map_dev_ids,
                     uint32_t len, NDArrayHandle *in_args,
                     NDArrayHandle *arg_grad_store, uint32_t *grad_req_type,
                     uint32_t aux_states_len, NDArrayHandle *aux_states,
                     ExecutorHandle shared_exec, ExecutorHandle *out) {
  (void)shared_exec;
  return MXExecutorBindX(sym, dev_type, dev_id, num_map_keys, map_keys,
                         map_dev_types, map_dev_ids, len, in_args,
                         arg_grad_store, grad_req_type, aux_states_len,
                         aux_states, out);
}

int MXExecutorSimpleBindEx(SymbolHandle sym, int dev_type, int dev_id,
                           uint32_t num_args, const char **arg_names,
                           const uint32_t *arg_ind_ptr,
                           const uint32_t *arg_shape_data,
                           const char *grad_req, ExecutorHandle *out,
                           uint32_t *num_arg_arrays,
                           NDArrayHandle **arg_arrays,
                           NDArrayHandle **grad_arrays, uint32_t *num_aux,
                           NDArrayHandle **aux_arrays) {
  return MXExecutorSimpleBind(sym, dev_type, dev_id, num_args, arg_names,
                              arg_ind_ptr, arg_shape_data, grad_req, out,
                              num_arg_arrays, arg_arrays, grad_arrays,
                              num_aux, aux_arrays);
}

int MXExecutorReshapeEx(int partial_shaping, int allow_up_sizing,
                        int dev_type, int dev_id, uint32_t num_args,
                        const char **arg_names, const uint32_t *arg_ind_ptr,
                        const uint32_t *arg_shape_data,
                        ExecutorHandle shared_exec, ExecutorHandle *out,
                        uint32_t *num_arg_arrays, NDArrayHandle **arg_arrays,
                        NDArrayHandle **grad_arrays, uint32_t *num_aux,
                        NDArrayHandle **aux_arrays) {
  return MXExecutorReshape(partial_shaping, allow_up_sizing, dev_type,
                           dev_id, num_args, arg_names, arg_ind_ptr,
                           arg_shape_data, shared_exec, out, num_arg_arrays,
                           arg_arrays, grad_arrays, num_aux, aux_arrays);
}

int MXImperativeInvokeEx(const char *op_name, int num_inputs,
                         NDArrayHandle *inputs, int *num_outputs,
                         NDArrayHandle ***outputs, int num_params,
                         const char **param_keys, const char **param_vals,
                         const int **out_stypes) {
  int rc = MXImperativeInvoke(op_name, num_inputs,
                              reinterpret_cast<void **>(inputs),
                              num_outputs,
                              reinterpret_cast<void ***>(outputs),
                              num_params, param_keys, param_vals);
  if (rc == 0 && out_stypes) {
    static thread_local std::vector<int> stypes;
    stypes.assign(static_cast<size_t>(*num_outputs), 0);
    *out_stypes = stypes.data();
  }
  return rc;
}

int MXKVStorePullRowSparse(KVStoreHandle handle, uint32_t num,
                           const int *keys, NDArrayHandle *vals,
                           const NDArrayHandle *row_ids, int priority) {
  return with_backend([&]() -> bool {
    PyObject *ks = PyList_New(num);
    for (uint32_t i = 0; i < num; ++i)
      PyList_SetItem(ks, i, PyLong_FromLong(keys[i]));
    PyObject *ret = call_backend(
        "kvstore_pull_row_sparse",
        pack_steal(PyLong_FromLong(as_id(handle)), ks,
                   handle_list(num, vals),
                   handle_list(num, const_cast<void **>(row_ids)),
                   PyLong_FromLong(priority)));
    if (!ret) return false;
    Py_DECREF(ret);
    return true;
  });
}

int MXKVStorePullRowSparseEx(KVStoreHandle handle, uint32_t num,
                             const char **keys, NDArrayHandle *vals,
                             const NDArrayHandle *row_ids, int priority) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend(
        "kvstore_pull_row_sparse",
        pack_steal(PyLong_FromLong(as_id(handle)), string_list(num, keys),
                   handle_list(num, vals),
                   handle_list(num, const_cast<void **>(row_ids)),
                   PyLong_FromLong(priority)));
    if (!ret) return false;
    Py_DECREF(ret);
    return true;
  });
}

int MXKVStorePullWithSparse(KVStoreHandle handle, uint32_t num,
                            const int *keys, NDArrayHandle *vals,
                            int priority, bool ignore_sparse) {
  (void)ignore_sparse;
  /* integer keys: stringify, the backend kvstore accepts both */
  std::vector<std::string> skeys(num);
  std::vector<const char *> pkeys(num);
  for (uint32_t i = 0; i < num; ++i) {
    skeys[i] = std::to_string(keys[i]);
    pkeys[i] = skeys[i].c_str();
  }
  return MXKVStorePull(handle, num, pkeys.data(), vals, priority);
}

int MXKVStorePullWithSparseEx(KVStoreHandle handle, uint32_t num,
                              const char **keys, NDArrayHandle *vals,
                              int priority, bool ignore_sparse) {
  (void)ignore_sparse;
  return MXKVStorePull(handle, num, keys, vals, priority);
}

/* atomic symbol creators: creator handles are interned op-name strings,
 * the same convention as FunctionHandle */

int MXSymbolListAtomicSymbolCreators(uint32_t *out_size,
                                     AtomicSymbolCreator **out_array) {
  return with_backend([&]() -> bool {
    PyObject *ret = call_backend("list_op_names", PyTuple_New(0));
    if (!ret) return false;
    load_string_list(ret, g_op_names, g_op_name_ptrs);
    Py_DECREF(ret);
    static thread_local std::vector<const void *> creators;
    creators.resize(g_op_names.size());
    for (size_t i = 0; i < g_op_names.size(); ++i)
      creators[i] = g_op_names[i].c_str();
    *out_size = static_cast<uint32_t>(creators.size());
    *out_array = creators.data();
    return true;
  });
}

int MXSymbolGetAtomicSymbolName(AtomicSymbolCreator creator,
                                const char **name) {
  *name = static_cast<const char *>(creator);
  return 0;
}

int MXSymbolGetAtomicSymbolInfo(
    AtomicSymbolCreator creator, const char **name, const char **description,
    uint32_t *num_args, const char ***arg_names,
    const char ***arg_type_infos, const char ***arg_descriptions,
    const char **key_var_num_args, const char **return_type) {
  if (key_var_num_args) *key_var_num_args = "";
  return MXFuncGetInfo(static_cast<FunctionHandle>(creator), name,
                       description, num_args, arg_names, arg_type_infos,
                       arg_descriptions, return_type);
}

int MXSymbolCutSubgraph(SymbolHandle sym, SymbolHandle **input_symbols,
                        uint32_t *input_size) {
  /* control-flow subgraphs are XLA regions on this backend — there is
   * no mutable graph to cut; report zero cut points (the reference
   * returns the cut inputs only when a subgraph attr matches) */
  (void)sym;
  *input_symbols = nullptr;
  *input_size = 0;
  return 0;
}

int MXSymbolGetInputSymbols(SymbolHandle sym, SymbolHandle **inputs,
                            int *input_size) {
  return with_backend([&]() -> bool {
    int n = 0;
    if (!ret_handle_vec(call_backend(
            "symbol_get_input_symbols",
            pack_steal(PyLong_FromLong(as_id(sym)))), &n,
            reinterpret_cast<void ***>(inputs)))
      return false;
    *input_size = n;
    return true;
  });
}

/* 64-bit / Ex infer-shape spellings: delegate to the uint32 core and
 * widen through thread-local buffers */

static thread_local std::vector<int> g_ndim_i32[3];
static thread_local std::vector<std::vector<int64_t>> g_rows_i64[3];
static thread_local std::vector<const int64_t *> g_ptrs_i64[3];

static void widen_group(int which, uint32_t n, const uint32_t *ndim,
                        const uint32_t **data) {
  g_ndim_i32[which].resize(n);
  g_rows_i64[which].assign(n, {});
  g_ptrs_i64[which].resize(n);
  for (uint32_t i = 0; i < n; ++i) {
    g_ndim_i32[which][i] = static_cast<int>(ndim[i]);
    g_rows_i64[which][i].resize(ndim[i]);
    for (uint32_t j = 0; j < ndim[i]; ++j)
      g_rows_i64[which][i][j] = static_cast<int64_t>(data[i][j]);
    g_ptrs_i64[which][i] = g_rows_i64[which][i].data();
  }
}

int MXSymbolInferShapeEx(SymbolHandle sym, uint32_t num_args,
                         const char **keys, const uint32_t *arg_ind_ptr,
                         const int *arg_shape_data, uint32_t *in_shape_size,
                         const int **in_shape_ndim,
                         const int ***in_shape_data,
                         uint32_t *out_shape_size,
                         const int **out_shape_ndim,
                         const int ***out_shape_data,
                         uint32_t *aux_shape_size,
                         const int **aux_shape_ndim,
                         const int ***aux_shape_data, int *complete) {
  /* int-typed shape spelling: convert in, run the u32 core, and since
   * the u32 core's buffers are >=0 the int reinterpretation is safe */
  std::vector<uint32_t> u32;
  uint32_t total = arg_ind_ptr[num_args];
  u32.resize(total);
  for (uint32_t j = 0; j < total; ++j)
    u32[j] = static_cast<uint32_t>(arg_shape_data[j]);
  const uint32_t *in_nd, *out_nd, *aux_nd;
  const uint32_t **in_d, **out_d, **aux_d;
  int rc = MXSymbolInferShape(sym, num_args, keys, arg_ind_ptr, u32.data(),
                              in_shape_size, &in_nd, &in_d, out_shape_size,
                              &out_nd, &out_d, aux_shape_size, &aux_nd,
                              &aux_d);
  if (rc != 0) return rc;
  if (complete) *complete = 1;
  static thread_local std::vector<int> ndim_i[3];
  static thread_local std::vector<std::vector<int>> rows_i[3];
  static thread_local std::vector<const int *> ptrs_i[3];
  auto widen = [](int w, uint32_t n, const uint32_t *nd,
                  const uint32_t **dt, std::vector<int> *ndim_i,
                  std::vector<std::vector<int>> *rows_i,
                  std::vector<const int *> *ptrs_i) {
    ndim_i[w].resize(n);
    rows_i[w].assign(n, {});
    ptrs_i[w].resize(n);
    for (uint32_t i = 0; i < n; ++i) {
      ndim_i[w][i] = static_cast<int>(nd[i]);
      rows_i[w][i].resize(nd[i]);
      for (uint32_t j = 0; j < nd[i]; ++j)
        rows_i[w][i][j] = static_cast<int>(dt[i][j]);
      ptrs_i[w][i] = rows_i[w][i].data();
    }
  };
  widen(0, *in_shape_size, in_nd, in_d, ndim_i, rows_i, ptrs_i);
  widen(1, *out_shape_size, out_nd, out_d, ndim_i, rows_i, ptrs_i);
  widen(2, *aux_shape_size, aux_nd, aux_d, ndim_i, rows_i, ptrs_i);
  *in_shape_ndim = ndim_i[0].data();
  *in_shape_data = ptrs_i[0].data();
  *out_shape_ndim = ndim_i[1].data();
  *out_shape_data = ptrs_i[1].data();
  *aux_shape_ndim = ndim_i[2].data();
  *aux_shape_data = ptrs_i[2].data();
  return 0;
}

int MXSymbolInferShape64(SymbolHandle sym, uint32_t num_args,
                         const char **keys, const int64_t *arg_ind_ptr,
                         const int64_t *arg_shape_data,
                         size_t *in_shape_size, const int **in_shape_ndim,
                         const int64_t ***in_shape_data,
                         size_t *out_shape_size, const int **out_shape_ndim,
                         const int64_t ***out_shape_data,
                         size_t *aux_shape_size, const int **aux_shape_ndim,
                         const int64_t ***aux_shape_data, int *complete) {
  std::vector<uint32_t> ind(num_args + 1), data;
  for (uint32_t i = 0; i <= num_args; ++i)
    ind[i] = static_cast<uint32_t>(arg_ind_ptr[i]);
  data.resize(ind[num_args]);
  for (uint32_t j = 0; j < ind[num_args]; ++j)
    data[j] = static_cast<uint32_t>(arg_shape_data[j]);
  const uint32_t *in_nd, *out_nd, *aux_nd;
  const uint32_t **in_d, **out_d, **aux_d;
  uint32_t ni, no, na;
  int rc = MXSymbolInferShape(sym, num_args, keys, ind.data(), data.data(),
                              &ni, &in_nd, &in_d, &no, &out_nd, &out_d, &na,
                              &aux_nd, &aux_d);
  if (rc != 0) return rc;
  if (complete) *complete = 1;
  widen_group(0, ni, in_nd, in_d);
  widen_group(1, no, out_nd, out_d);
  widen_group(2, na, aux_nd, aux_d);
  *in_shape_size = ni;
  *in_shape_ndim = g_ndim_i32[0].data();
  *in_shape_data = g_ptrs_i64[0].data();
  *out_shape_size = no;
  *out_shape_ndim = g_ndim_i32[1].data();
  *out_shape_data = g_ptrs_i64[1].data();
  *aux_shape_size = na;
  *aux_shape_ndim = g_ndim_i32[2].data();
  *aux_shape_data = g_ptrs_i64[2].data();
  return 0;
}

int MXSymbolInferShapeEx64(SymbolHandle sym, uint32_t num_args,
                           const char **keys, const int64_t *arg_ind_ptr,
                           const int64_t *arg_shape_data,
                           size_t *in_shape_size, const int **in_shape_ndim,
                           const int64_t ***in_shape_data,
                           size_t *out_shape_size,
                           const int **out_shape_ndim,
                           const int64_t ***out_shape_data,
                           size_t *aux_shape_size,
                           const int **aux_shape_ndim,
                           const int64_t ***aux_shape_data, int *complete) {
  return MXSymbolInferShape64(sym, num_args, keys, arg_ind_ptr,
                              arg_shape_data, in_shape_size, in_shape_ndim,
                              in_shape_data, out_shape_size, out_shape_ndim,
                              out_shape_data, aux_shape_size, aux_shape_ndim,
                              aux_shape_data, complete);
}

int MXSymbolInferShapePartialEx(
    SymbolHandle sym, uint32_t num_args, const char **keys,
    const uint32_t *arg_ind_ptr, const int *arg_shape_data,
    uint32_t *in_shape_size, const int **in_shape_ndim,
    const int ***in_shape_data, uint32_t *out_shape_size,
    const int **out_shape_ndim, const int ***out_shape_data,
    uint32_t *aux_shape_size, const int **aux_shape_ndim,
    const int ***aux_shape_data, int *complete) {
  int rc = MXSymbolInferShapeEx(sym, num_args, keys, arg_ind_ptr,
                                arg_shape_data, in_shape_size, in_shape_ndim,
                                in_shape_data, out_shape_size,
                                out_shape_ndim, out_shape_data,
                                aux_shape_size, aux_shape_ndim,
                                aux_shape_data, complete);
  if (rc != 0) {
    *in_shape_size = *out_shape_size = *aux_shape_size = 0;
    *complete = 0;
    return 0;
  }
  return rc;
}

int MXSymbolInferShapePartial64(
    SymbolHandle sym, uint32_t num_args, const char **keys,
    const int64_t *arg_ind_ptr, const int64_t *arg_shape_data,
    size_t *in_shape_size, const int **in_shape_ndim,
    const int64_t ***in_shape_data, size_t *out_shape_size,
    const int **out_shape_ndim, const int64_t ***out_shape_data,
    size_t *aux_shape_size, const int **aux_shape_ndim,
    const int64_t ***aux_shape_data, int *complete) {
  int rc = MXSymbolInferShape64(sym, num_args, keys, arg_ind_ptr,
                                arg_shape_data, in_shape_size, in_shape_ndim,
                                in_shape_data, out_shape_size,
                                out_shape_ndim, out_shape_data,
                                aux_shape_size, aux_shape_ndim,
                                aux_shape_data, complete);
  if (rc != 0) {
    *in_shape_size = *out_shape_size = *aux_shape_size = 0;
    *complete = 0;
    return 0;
  }
  return rc;
}

int MXSymbolInferShapePartialEx64(
    SymbolHandle sym, uint32_t num_args, const char **keys,
    const int64_t *arg_ind_ptr, const int64_t *arg_shape_data,
    size_t *in_shape_size, const int **in_shape_ndim,
    const int64_t ***in_shape_data, size_t *out_shape_size,
    const int **out_shape_ndim, const int64_t ***out_shape_data,
    size_t *aux_shape_size, const int **aux_shape_ndim,
    const int64_t ***aux_shape_data, int *complete) {
  return MXSymbolInferShapePartial64(
      sym, num_args, keys, arg_ind_ptr, arg_shape_data, in_shape_size,
      in_shape_ndim, in_shape_data, out_shape_size, out_shape_ndim,
      out_shape_data, aux_shape_size, aux_shape_ndim, aux_shape_data,
      complete);
}

}  /* extern "C" */
