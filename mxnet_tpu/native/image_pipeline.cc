// Native JPEG decode + augment + batch pipeline.
//
// TPU-native equivalent of the reference's throughput backbone: the
// threaded C++ parser pipeline of src/io/iter_image_recordio_2.cc
// (:51,708-933) with the default augmenter chain of
// src/io/image_aug_default.cc. Worker threads pull shuffled record
// ranges from the mmap'd RecordIO file (recordio.cc), decode JPEG via
// libjpeg, resize-shorter-side (bilinear), random/center-crop, mirror,
// normalize ((v - mean) / std), and write float32 batches in NCHW or
// NHWC directly — Python only hands the finished buffer to
// jax.device_put (double-buffered by the bounded ready queue).
//
// Image record framing (bit-compatible with the reference
// pack/pack_img, python/mxnet/recordio.py:362-495):
//   IRHeader: u32 flag | f32 label | u64 id | u64 id2
//   if flag > 0: flag * f32 label array
//   then the encoded (JPEG) image bytes.

#include <algorithm>
#include <atomic>
#include <cmath>
#include <condition_variable>
#include <csetjmp>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

#include <jpeglib.h>

extern "C" {
int64_t rio_count(void* reader);
int64_t rio_get(void* reader, int64_t i, const uint8_t** ptr);
}

namespace {

// -- libjpeg decode with longjmp error recovery ------------------------------

struct JpegErr {
  jpeg_error_mgr pub;
  jmp_buf jb;
};

void jpeg_err_exit(j_common_ptr cinfo) {
  JpegErr* err = reinterpret_cast<JpegErr*>(cinfo->err);
  longjmp(err->jb, 1);
}

bool decode_jpeg(const uint8_t* buf, size_t len, int want_channels,
                 std::vector<uint8_t>* out, int* w, int* h) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = jpeg_err_exit;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, buf, static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  cinfo.out_color_space = want_channels == 1 ? JCS_GRAYSCALE : JCS_RGB;
  jpeg_start_decompress(&cinfo);
  *w = static_cast<int>(cinfo.output_width);
  *h = static_cast<int>(cinfo.output_height);
  int c = cinfo.output_components;
  out->resize(static_cast<size_t>(*w) * *h * c);
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = out->data() +
        static_cast<size_t>(cinfo.output_scanline) * *w * c;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

// -- bilinear resize (HWC uint8), the image_aug_default resize role ----------

void resize_bilinear(const std::vector<uint8_t>& src, int sw, int sh, int c,
                     int dw, int dh, std::vector<uint8_t>* dst) {
  dst->resize(static_cast<size_t>(dw) * dh * c);
  const float sx = static_cast<float>(sw) / dw;
  const float sy = static_cast<float>(sh) / dh;
  for (int y = 0; y < dh; ++y) {
    float fy = (y + 0.5f) * sy - 0.5f;
    int y0 = std::max(0, std::min(sh - 1, static_cast<int>(fy)));
    int y1 = std::min(sh - 1, y0 + 1);
    float wy = std::max(0.0f, std::min(1.0f, fy - y0));
    for (int x = 0; x < dw; ++x) {
      float fx = (x + 0.5f) * sx - 0.5f;
      int x0 = std::max(0, std::min(sw - 1, static_cast<int>(fx)));
      int x1 = std::min(sw - 1, x0 + 1);
      float wx = std::max(0.0f, std::min(1.0f, fx - x0));
      for (int ch = 0; ch < c; ++ch) {
        float v00 = src[(static_cast<size_t>(y0) * sw + x0) * c + ch];
        float v01 = src[(static_cast<size_t>(y0) * sw + x1) * c + ch];
        float v10 = src[(static_cast<size_t>(y1) * sw + x0) * c + ch];
        float v11 = src[(static_cast<size_t>(y1) * sw + x1) * c + ch];
        float v = v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
                  v10 * wy * (1 - wx) + v11 * wy * wx;
        (*dst)[(static_cast<size_t>(y) * dw + x) * c + ch] =
            static_cast<uint8_t>(v + 0.5f);
      }
    }
  }
}

// -- the pipeline -------------------------------------------------------------

struct ImgBatch {
  std::vector<float> data;
  std::vector<float> labels;
  int64_t n = 0;
  int64_t pad = 0;      // wrap-padded duplicates in this batch
  uint64_t seq = 0;     // batch index within the epoch (delivery order)
};

struct ImagePipeline {
  void* reader = nullptr;
  int batch = 0, H = 0, W = 0, C = 3, resize = 0, label_width = 1;
  float label_pad_value = 0.0f;
  bool force_resize = false;  // warp to (W,H), no crop (det mode)
  bool rand_crop = false, rand_mirror = false, shuffle = false, nhwc = false;
  float mean[3] = {0, 0, 0}, stdv[3] = {1, 1, 1};
  uint64_t seed = 0;
  int epoch = 0;

  std::vector<uint32_t> order;
  size_t cursor = 0;
  std::mutex cursor_mu;

  // batches are produced by whichever worker finishes first but MUST be
  // consumed in epoch order (reference ImageRecordIter2 is deterministic
  // per seed): workers insert keyed by seq, the consumer pops next_out.
  // Backpressure is a sliding window over seq (not queue size) so the
  // worker holding next_out can never be blocked out by later batches.
  std::map<uint64_t, ImgBatch*> ready;
  uint64_t next_out = 0;
  std::mutex mu;
  std::condition_variable cv_ready, cv_space;
  size_t max_ready = 4;
  std::vector<std::thread> workers;
  std::atomic<bool> stop{false};
  std::atomic<int> active{0};
  std::atomic<int64_t> decode_failures{0};

  ~ImagePipeline() { shutdown(); }

  void reset_order() {
    int64_t n = rio_count(reader);
    order.resize(static_cast<size_t>(n));
    for (uint32_t i = 0; i < order.size(); ++i) order[i] = i;
    if (shuffle) {
      std::mt19937_64 rng(seed + static_cast<uint64_t>(epoch));
      std::shuffle(order.begin(), order.end(), rng);
    }
    cursor = 0;
  }

  bool next_indices(std::vector<uint32_t>* idx, uint64_t* batch_id,
                    int64_t* pad) {
    std::lock_guard<std::mutex> lk(cursor_mu);
    if (cursor >= order.size()) return false;
    *batch_id = cursor / static_cast<size_t>(batch);
    size_t end = std::min(cursor + static_cast<size_t>(batch),
                          order.size());
    idx->assign(order.begin() + cursor, order.begin() + end);
    cursor = end;
    size_t need = batch - idx->size();  // pad final batch by wrapping
    *pad = static_cast<int64_t>(need);
    for (size_t i = 0; i < need; ++i) idx->push_back(order[i % order.size()]);
    return true;
  }

  // one sample: record -> decode -> resize -> crop -> mirror -> normalize
  bool process_one(const uint8_t* rec, int64_t len, float* out_img,
                   float* out_label, std::mt19937_64* rng) {
    if (len < 24) return false;
    uint32_t flag;
    float flabel;
    std::memcpy(&flag, rec, 4);
    std::memcpy(&flabel, rec + 4, 4);
    const uint8_t* p = rec + 24;
    int64_t remain = len - 24;
    if (flag > 0) {
      int64_t lbytes = static_cast<int64_t>(flag) * 4;
      if (remain < lbytes) return false;
      for (int i = 0; i < label_width; ++i) {
        float v = label_pad_value;
        if (i < static_cast<int>(flag)) std::memcpy(&v, p + i * 4, 4);
        out_label[i] = v;
      }
      p += lbytes;
      remain -= lbytes;
    } else {
      out_label[0] = flabel;
      for (int i = 1; i < label_width; ++i) out_label[i] = label_pad_value;
    }

    std::vector<uint8_t> img;
    int w = 0, h = 0;
    if (!decode_jpeg(p, static_cast<size_t>(remain), C, &img, &w, &h))
      return false;

    std::vector<uint8_t> resized;
    if (force_resize) {
      // warp to the exact output size: normalized box labels stay
      // valid (the det augmenter's default, image_det_aug_default.cc)
      if (w != W || h != H) {
        resize_bilinear(img, w, h, C, W, H, &resized);
        img.swap(resized);
        w = W;
        h = H;
      }
    }
    // resize shorter side (image_aug_default.cc resize param)
    if (!force_resize && resize > 0 && std::min(w, h) != resize) {
      int nw, nh;
      if (w < h) {
        nw = resize;
        nh = static_cast<int>(static_cast<int64_t>(h) * resize / w);
      } else {
        nh = resize;
        nw = static_cast<int>(static_cast<int64_t>(w) * resize / h);
      }
      resize_bilinear(img, w, h, C, nw, nh, &resized);
      img.swap(resized);
      w = nw;
      h = nh;
    }
    // if still smaller than the crop, scale up to fit
    if (w < W || h < H) {
      int nw = std::max(w, W), nh = std::max(h, H);
      resize_bilinear(img, w, h, C, nw, nh, &resized);
      img.swap(resized);
      w = nw;
      h = nh;
    }
    // crop
    const float inv_std[3] = {1.0f / stdv[0], 1.0f / stdv[1],
                              1.0f / stdv[2]};
    int x0, y0;
    if (rand_crop) {
      x0 = w == W ? 0 : static_cast<int>((*rng)() % (w - W + 1));
      y0 = h == H ? 0 : static_cast<int>((*rng)() % (h - H + 1));
    } else {
      x0 = (w - W) / 2;
      y0 = (h - H) / 2;
    }
    bool mirror = rand_mirror && ((*rng)() & 1);

    if (nhwc) {
      for (int y = 0; y < H; ++y) {
        const uint8_t* src_row =
            img.data() + (static_cast<size_t>(y0 + y) * w + x0) * C;
        float* dst_row = out_img + (static_cast<size_t>(y) * W) * C;
        for (int x = 0; x < W; ++x) {
          int sx = mirror ? (W - 1 - x) : x;
          for (int ch = 0; ch < C; ++ch)
            dst_row[x * C + ch] =
                (static_cast<float>(src_row[sx * C + ch]) - mean[ch]) *
                inv_std[ch];
        }
      }
    } else {
      // NCHW: write each channel plane contiguously (strided reads are
      // cheaper than strided writes)
      for (int ch = 0; ch < C; ++ch) {
        float* plane = out_img + static_cast<size_t>(ch) * H * W;
        const float m = mean[ch], is = inv_std[ch];
        for (int y = 0; y < H; ++y) {
          const uint8_t* src_row =
              img.data() + (static_cast<size_t>(y0 + y) * w + x0) * C + ch;
          float* dst_row = plane + static_cast<size_t>(y) * W;
          if (mirror) {
            for (int x = 0; x < W; ++x)
              dst_row[x] =
                  (static_cast<float>(src_row[(W - 1 - x) * C]) - m) * is;
          } else {
            for (int x = 0; x < W; ++x)
              dst_row[x] = (static_cast<float>(src_row[x * C]) - m) * is;
          }
        }
      }
    }
    return true;
  }

  void worker_loop() {
    std::vector<uint32_t> idx;
    uint64_t batch_id = 0;
    int64_t pad = 0;
    while (!stop.load()) {
      if (!next_indices(&idx, &batch_id, &pad)) break;
      std::mt19937_64 rng(seed * 1000003u + epoch * 10007u + batch_id);
      ImgBatch* b = new ImgBatch();
      b->pad = pad;
      b->seq = batch_id;
      size_t img_elems = static_cast<size_t>(C) * H * W;
      b->data.resize(static_cast<size_t>(batch) * img_elems);
      b->labels.resize(static_cast<size_t>(batch) * label_width);
      b->n = batch;
      for (size_t k = 0; k < idx.size(); ++k) {
        const uint8_t* rec = nullptr;
        int64_t len = rio_get(reader, idx[k], &rec);
        if (len <= 0 ||
            !process_one(rec, len, b->data.data() + k * img_elems,
                         b->labels.data() + k * label_width, &rng)) {
          decode_failures.fetch_add(1);
          std::memset(b->data.data() + k * img_elems, 0,
                      img_elems * sizeof(float));
          std::memset(b->labels.data() + k * label_width, 0,
                      label_width * sizeof(float));
        }
      }
      std::unique_lock<std::mutex> lk(mu);
      cv_space.wait(lk, [this, b] {
        return b->seq < next_out + max_ready || stop.load();
      });
      if (stop.load()) {
        delete b;
        active.fetch_sub(1);
        cv_ready.notify_all();
        return;
      }
      ready.emplace(b->seq, b);
      cv_ready.notify_all();
    }
    // end-of-epoch is detected by the consumer: active==0 and the
    // reorder map fully drained; just wake it up
    active.fetch_sub(1);
    std::unique_lock<std::mutex> lk(mu);
    cv_ready.notify_all();
  }

  void start(int num_workers) {
    stop.store(false);
    reset_order();
    next_out = 0;
    active.store(num_workers);
    for (int i = 0; i < num_workers; ++i)
      workers.emplace_back([this] { worker_loop(); });
  }

  void shutdown() {
    {
      // set stop under mu: a worker that just evaluated its cv_space
      // predicate (stop still false) but not yet blocked would miss an
      // unsynchronized notify and sleep forever, hanging the join
      std::lock_guard<std::mutex> lk(mu);
      stop.store(true);
    }
    cv_space.notify_all();
    cv_ready.notify_all();
    for (auto& t : workers)
      if (t.joinable()) t.join();
    workers.clear();
    for (auto& kv : ready) delete kv.second;
    ready.clear();
  }
};

}  // namespace

extern "C" {

void* imgpipe_create(void* reader, int batch, int channels, int height,
                     int width, int resize, int label_width, int rand_crop,
                     int rand_mirror, int shuffle, int nhwc,
                     const float* mean3, const float* std3, uint64_t seed,
                     int num_workers, float label_pad_value,
                     int force_resize) {
  if (batch <= 0 || !reader) return nullptr;
  ImagePipeline* p = new ImagePipeline();
  p->reader = reader;
  p->batch = batch;
  p->C = channels;
  p->H = height;
  p->W = width;
  p->resize = resize;
  p->label_width = label_width > 0 ? label_width : 1;
  p->label_pad_value = label_pad_value;
  p->force_resize = force_resize != 0;
  p->rand_crop = rand_crop != 0;
  p->rand_mirror = rand_mirror != 0;
  p->shuffle = shuffle != 0;
  p->nhwc = nhwc != 0;
  if (mean3)
    for (int i = 0; i < 3; ++i) p->mean[i] = mean3[i];
  if (std3)
    for (int i = 0; i < 3; ++i) p->stdv[i] = std3[i] != 0 ? std3[i] : 1.0f;
  p->seed = seed;
  p->start(num_workers > 0 ? num_workers : 2);
  return p;
}

// Returns an ImgBatch* or nullptr at end of epoch. Batches come out in
// epoch order (seq 0, 1, 2, ...) regardless of worker completion order.
void* imgpipe_next(void* pipe) {
  ImagePipeline* p = static_cast<ImagePipeline*>(pipe);
  std::unique_lock<std::mutex> lk(p->mu);
  p->cv_ready.wait(lk, [p] {
    return p->ready.count(p->next_out) || p->stop.load() ||
           (p->active.load() == 0 && p->ready.empty());
  });
  auto it = p->ready.find(p->next_out);
  if (it == p->ready.end()) return nullptr;  // epoch done or stopping
  ImgBatch* b = it->second;
  p->ready.erase(it);
  p->next_out += 1;
  p->cv_space.notify_all();  // window slid: several workers may now fit
  return b;
}

const float* imgpipe_batch_data(void* batch) {
  return static_cast<ImgBatch*>(batch)->data.data();
}

const float* imgpipe_batch_labels(void* batch) {
  return static_cast<ImgBatch*>(batch)->labels.data();
}

int64_t imgpipe_batch_n(void* batch) {
  return static_cast<ImgBatch*>(batch)->n;
}

int64_t imgpipe_batch_pad(void* batch) {
  return static_cast<ImgBatch*>(batch)->pad;
}

void imgpipe_batch_free(void* batch) { delete static_cast<ImgBatch*>(batch); }

void imgpipe_reset(void* pipe) {
  ImagePipeline* p = static_cast<ImagePipeline*>(pipe);
  int workers = static_cast<int>(p->workers.size());
  p->shutdown();
  p->epoch += 1;
  p->start(workers > 0 ? workers : 2);
}

int64_t imgpipe_decode_failures(void* pipe) {
  return static_cast<ImagePipeline*>(pipe)->decode_failures.load();
}

void imgpipe_destroy(void* pipe) { delete static_cast<ImagePipeline*>(pipe); }

}  // extern "C"
