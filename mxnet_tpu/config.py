"""Typed runtime configuration / env-flag system.

The reference exposes ~83 ``MXNET_*`` environment variables read ad hoc
via ``dmlc::GetEnv`` at use sites (ref: docs/faq/env_var.md;
src/engine/threaded_engine_perdevice.cc:84 etc.). Here the flag system
is one typed registry: every flag has a declared type, default, doc
string, and a TPU status — ``active`` flags change behavior in this
framework and are read (through :func:`get`) at a real use site;
``accepted`` flags are recognized for workflow compatibility but are
no-ops on TPU (their job belongs to XLA/PJRT), and reading them warns
once when they are set to a non-default value so users know the knob
has no effect.

Resolution order: :func:`set_flag` runtime override > environment >
declared default.
"""
from __future__ import annotations

import os
import threading
import warnings
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

__all__ = ["Flag", "register_flag", "get", "set_flag", "unset_flag",
           "describe", "flags"]


def _parse_bool(v: str) -> bool:
    return v.lower() in ("1", "true", "yes", "on")


@dataclass
class Flag:
    name: str
    type: type
    default: Any
    doc: str
    active: bool = True           # False: accepted-but-inert on TPU
    tpu_note: str = ""            # why inert / how reinterpreted
    choices: Optional[tuple] = None
    _warned: bool = field(default=False, repr=False)

    def parse(self, raw: str) -> Any:
        if self.type is bool:
            return _parse_bool(raw)
        return self.type(raw)


_FLAGS: Dict[str, Flag] = {}
_OVERRIDES: Dict[str, Any] = {}
_LOCK = threading.Lock()
_GEN = 0  # bumped on every runtime override; hot paths cache against it


def generation() -> int:
    """Monotone counter for flag-cache invalidation (engine.is_sync)."""
    return _GEN


def register_flag(name: str, type: type, default: Any, doc: str,
                  active: bool = True, tpu_note: str = "",
                  choices: Optional[tuple] = None) -> Flag:
    f = Flag(name, type, default, doc, active, tpu_note, choices)
    _FLAGS[name] = f
    return f


def get(name: str, default: Any = None, dtype: Optional[type] = None) -> Any:
    """Resolve a flag: runtime override > env > declared default.

    Unregistered names fall back to a raw env read with ``default``,
    coerced to ``dtype`` (or the default's type) — the dmlc::GetEnv
    escape hatch. For registered names the registry's type/default are
    canonical and ``default``/``dtype`` are ignored."""
    # lock-free read path: dict reads are atomic in CPython, and this is
    # called from the per-op eager dispatch (engine.is_sync)
    f = _FLAGS.get(name)
    if name in _OVERRIDES:
        val = _OVERRIDES.get(name, default)
        if f is not None and not f.active and val != f.default \
                and not f._warned:
            f._warned = True
            warnings.warn(
                f"{name}={val} has no effect on the TPU backend"
                + (f" ({f.tpu_note})" if f.tpu_note else ""),
                stacklevel=2)
        return val
    if f is None:
        raw = os.environ.get(name)
        if raw is None:
            return default
        ty = dtype or (type(default) if default is not None else None)
        if ty is bool or isinstance(default, bool):
            return _parse_bool(raw)
        if ty is not None:
            try:
                return ty(raw)
            except (TypeError, ValueError):
                return raw
        return raw
    raw = os.environ.get(name)
    val = f.default if raw is None else f.parse(raw)
    if not f.active and val != f.default and not f._warned:
        f._warned = True
        warnings.warn(
            f"{name}={val} has no effect on the TPU backend"
            + (f" ({f.tpu_note})" if f.tpu_note else ""), stacklevel=2)
    if f.choices and val not in f.choices:
        raise ValueError(f"{name}={val!r} not in {f.choices}")
    return val


def set_flag(name: str, value: Any) -> None:
    """Runtime override (highest precedence)."""
    global _GEN
    f = _FLAGS.get(name)
    if f is not None:
        if f.type is bool and isinstance(value, str):
            value = _parse_bool(value)
        elif not isinstance(value, f.type):
            value = f.type(value)
        if f.choices and value not in f.choices:
            raise ValueError(f"{name}={value!r} not in {f.choices}")
    with _LOCK:
        _OVERRIDES[name] = value
        _GEN += 1


def unset_flag(name: str) -> None:
    global _GEN
    with _LOCK:
        _OVERRIDES.pop(name, None)
        _GEN += 1


def flags() -> Dict[str, Flag]:
    return dict(_FLAGS)


def flag_rows():
    """One (name, type_name, default_repr, status, doc) tuple per flag —
    the single rendering source for describe() and docs generation
    (tools/gen_env_docs.py). Machine-dependent defaults (home-relative
    paths) are normalized so generated docs are portable."""
    home = os.path.expanduser("~")
    rows = []
    for name in sorted(_FLAGS):
        f = _FLAGS[name]
        status = "active" if f.active else "accepted (no-op on TPU)"
        default = repr(f.default)
        if isinstance(f.default, str) and f.default.startswith(home):
            default = repr("~" + f.default[len(home):])
        doc = " ".join(f.doc.split())
        if f.tpu_note:
            doc += f" TPU: {' '.join(f.tpu_note.split())}"
        rows.append((name, f.type.__name__, default, status, doc))
    return rows


def describe() -> str:
    """Human-readable flag table (the env_var.md analog)."""
    lines = []
    for name, tname, default, status, doc in flag_rows():
        lines.append(f"{name} = {get(name)!r}  [{tname}, "
                     f"default {default}, {status}]")
        lines.append(f"    {doc}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Active flags — each is read via config.get() at the cited use site.
# ---------------------------------------------------------------------------

register_flag(
    "MXNET_ENGINE_TYPE", str, "ThreadedEnginePerDevice",
    "Execution engine. NaiveEngine = fully synchronous dispatch for "
    "debugging (ref: src/engine/engine.cc:32-56).",
    choices=("ThreadedEnginePerDevice", "ThreadedEnginePooled",
             "NaiveEngine"))
register_flag(
    "MXNET_EXEC_BULK_EXEC_TRAIN", bool, True,
    "Bulk (segment) execution of the training graph "
    "(ref: env_var.md:120). TPU: whole-graph jit when on; per-op "
    "dispatch hints when off.")
register_flag(
    "MXNET_EXEC_BULK_EXEC_INFERENCE", bool, True,
    "Bulk execution of inference graphs (ref: env_var.md:123).")
register_flag(
    "MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN", int, 15,
    "Max nodes per bulked segment (ref: env_var.md:129). TPU: advisory "
    "segment size for the engine facade's bulk scope.")
register_flag(
    "MXNET_KVSTORE_BIGARRAY_BOUND", int, 1000000,
    "Arrays above this element count are sharded across kvstore "
    "servers / collective chunks (ref: kvstore_dist.h EncodeDefaultKey).")
register_flag(
    "MXNET_UPDATE_ON_KVSTORE", bool, True,
    "Run the optimizer inside the kvstore (server-side update) when the "
    "kvstore supports it (ref: python/mxnet/model.py _create_kvstore).")
register_flag(
    "MXNET_HOME", str, os.path.join(os.path.expanduser("~"), ".mxnet_tpu"),
    "Data/model cache root (ref: env_var.md MXNET_HOME).")
register_flag(
    "MXNET_GLUON_REPO", str,
    "https://apache-mxnet.s3-accelerate.dualstack.amazonaws.com/",
    "Base URL for gluon model-zoo downloads (ref: env_var.md).",
    active=False,
    tpu_note="no network egress in this build; weights load from local "
             "files")
register_flag(
    "MXNET_USE_SIGNAL_HANDLER", bool, True,
    "Install the SIGSEGV/SIGABRT backtrace handler at import "
    "(ref: src/initialize.cc:62).")
register_flag(
    "MXNET_SAFE_ACCUMULATION", bool, False,
    "Accumulate reductions/softmax in fp32 even for fp16/bf16 inputs "
    "(ref: env_var.md MXNET_SAFE_ACCUMULATION).")
register_flag(
    "MXNET_ENFORCE_DETERMINISM", bool, False,
    "Refuse/avoid non-deterministic kernels. TPU: forces synchronous "
    "NaiveEngine-style dispatch ordering in the engine facade.")
register_flag(
    "MXNET_BACKWARD_DO_MIRROR", bool, False,
    "Trade compute for memory in backward (ref: env_var.md:187, "
    "src/nnvm/gradient.cc mirror). TPU: wraps the forward in "
    "jax.checkpoint (rematerialization) when building grad programs.")
register_flag(
    "MXNET_SUBGRAPH_BACKEND", str, "",
    "Partition graphs with the named subgraph property before "
    "compilation (ref: env_var.md:319 MXNET_SUBGRAPH_BACKEND). "
    "TPU: applies mxnet_tpu.subgraph.build_subgraph in Symbol.bind.")
register_flag(
    "MXNET_STORAGE_FALLBACK_LOG_VERBOSE", bool, True,
    "Warn when a sparse op falls back to the dense implementation "
    "(ref: env_var.md:30).")
register_flag(
    "MXNET_OPTIMIZER_AGGREGATION_SIZE", int, 4,
    "Max tensors fused per multi-tensor optimizer update "
    "(ref: env_var.md MXNET_OPTIMIZER_AGGREGATION_SIZE).")
register_flag(
    "MXNET_GRAD_BUCKET_BYTES", int, 4 << 20,
    "Byte cap per flat gradient-exchange bucket (step.buckets."
    "GradientBuckets, used by gluon Trainer._allreduce_grads): "
    "gradients of like dtype are coalesced into buckets up to this "
    "size so the kvstore data plane does O(buckets) transfers instead "
    "of O(params). Larger buckets amortize transport latency; smaller "
    "ones overlap exchange with the backward earlier "
    "(docs/performance.md).")
register_flag(
    "MXNET_COMPILE_CACHE_DIR", str, "",
    "Directory for JAX's persistent XLA compilation cache "
    "(step.cache.enable_compile_cache, applied at import): compiled "
    "programs — including the fused train step — are written to disk "
    "so warmup survives process restarts. Hits/misses are logged to "
    "the telemetry registry (jax_compile_cache_{hits,misses}_total). "
    "Empty = cache off.")
register_flag(
    "MXNET_EAGER_SYNC", bool, False,
    "Block on device completion after EVERY eager op dispatch "
    "(ndarray.invoke). Default off: PJRT pipelines eager chains "
    "asynchronously. Forced on while the profiler's imperative domain "
    "is recording (accurate per-op timings) and under NaiveEngine / "
    "MXNET_ENFORCE_DETERMINISM.")
register_flag(
    "MXNET_MP_WORKER_NTHREADS", int, 4,
    "Per-worker decode thread cap in multiprocess DataLoader workers "
    "(ref: env_var.md:60).")
register_flag(
    "MXNET_CPU_WORKER_NTHREADS", int, 1,
    "Host-side worker threads for the native IO pipeline "
    "(ref: env_var.md:25). TPU: thread count of the native RecordIO "
    "batch server.")
register_flag(
    "MXNET_PROFILER_AUTOSTART", bool, False,
    "Start the profiler at import (ref: env_var.md MXNET_PROFILER_"
    "AUTOSTART).")
register_flag(
    "MXNET_PROFILER_MODE", int, 0,
    "Default profiler mode bitmask (ref: env_var.md).")
register_flag(
    "MXNET_PROFILER_TOPK", int, 0,
    "Row cap for the profiler's aggregate statistics table and the "
    "tools/mxprof.py default top-K; 0 = unlimited (profiler."
    "get_summary / mxprof summarize).")
register_flag(
    "MXNET_METRICS_EXPORT", str, "",
    "Path of the JSON-lines metrics sink; when set, gluon Trainer.step "
    "and bench.py append one metrics snapshot line per step "
    "(telemetry.record_step). Empty = export off.")
register_flag(
    "MXNET_TELEMETRY_MEMORY_INTERVAL", float, 0.0,
    "Minimum seconds between automatic memory samples at step "
    "boundaries (telemetry.memory.maybe_sample — the jax.live_arrays "
    "census walks every buffer). 0 = sample every step while the "
    "profiler's memory domain is on or a metrics sink is configured.")
register_flag(
    "MXNET_USE_INT64_TENSOR_SIZE", bool, False,
    "Enable tensors with more than 2^31 elements / int64 indexing "
    "(ref: the INT64_TENSOR_SIZE build flag, env_var.md). Read at "
    "import: turns on jax x64 mode, which also widens python-float "
    "weak types — opt-in, like the reference's off-by-default build.")
register_flag(
    "MXNET_USE_OPERATOR_TUNING", str, "1",
    "Measure-and-cache selection between equivalent op implementations "
    "(Pallas flash vs dense attention, ...; operator_tune.autotune — "
    "the TPU reinterpretation of the reference's OMP tuning, "
    "operator_tune.h:165). 0/false/off = always take the default "
    "candidate; any other value (1, float32, ... — the reference's "
    "multi-valued forms) enables tuning.")
register_flag(
    "MXNET_OPTUNE_CHOICE_<NAME>", str, "",
    "Wildcard override: pin a tuned choice by candidate label, "
    "trumping measurement and cache — e.g. "
    "MXNET_OPTUNE_CHOICE_ATTENTION=dense forces XLA dense attention "
    "over the Pallas flash kernel (operator_tune.choose). An unknown "
    "label raises, listing the candidates.")
register_flag(
    "MXNET_GRAPH_OPT", int, 0,
    "Graph-optimizer level for Symbol binds (mxnet_tpu/opt/, "
    "docs/graph_opt.md). 0 = off; 1 = semantics-preserving cleanups "
    "(constant folding, CSE, identity elision, dead-node sweep — "
    "bitwise parity class); 2 = level 1 plus fusion-group "
    "partitioning (conv+bn+relu, matmul+act, elementwise chains, "
    "attention) and NHWC layout selection for TPU/XLA:CPU "
    "(tolerance-tagged parity). Applies at Executor bind, symbol-mode "
    "StepFunction compile, and serve AOT warmup.", choices=(0, 1, 2))
register_flag(
    "MXNET_GRAPH_OPT_VERIFY", bool, False,
    "Bind-time parity gate for the graph optimizer: run the optimized "
    "graph against the unoptimized one on the executor's live buffers "
    "under the pipeline's declared tolerance class, and REVERT to the "
    "unoptimized graph on any mismatch (graph_opt_verify_failures_"
    "total counts reverts). Costs one extra forward per bind; "
    "mxlint --opt turns it on for its self-check.")
register_flag(
    "MXNET_GRAPH_OPT_PALLAS", bool, True,
    "Allow Pallas kernel lowerings for fused patterns (_fused_"
    "attention flash kernel, the fused optimizer+cast mp_sgd step). "
    "Only takes effect on a TPU backend; everywhere else — and when "
    "set to 0 — the automatic XLA fallback composition runs "
    "(bitwise-identical to the unfused graph).")
register_flag(
    "MXSERVE_BUCKETS", str, "1,2,4,8,16,32",
    "Shape-bucket ladder for the serving subsystem (serve.buckets."
    "default_ladder): batch rungs as a comma list, or named axes as "
    "'batch:1,2,4,8;seq:16,32,64' where axis<k> addresses BATCHED-"
    "array axis k, i.e. item axis k-1 (seq = axis1). Requests are "
    "padded up to the next rung so the serving jit cache CLOSES after "
    "warmup (docs/serving.md).")
register_flag(
    "MXSERVE_MAX_LINGER_MS", float, 2.0,
    "Max milliseconds the serving batcher waits for co-batchable "
    "requests before dispatching a partial batch (serve.batcher) — "
    "the cap on batching-added latency; keep ~ one device step time.")
register_flag(
    "MXSERVE_QUEUE_DEPTH", int, 256,
    "Bounded serving-queue capacity (serve.batcher). A submit against "
    "a full queue is rejected immediately with QueueFullError "
    "(HTTP 429 at the endpoint) — load-shed backpressure, never "
    "unbounded blocking.")
register_flag(
    "MXSERVE_MAX_BATCH", int, 0,
    "Row cap per serving dispatch (serve.batcher). 0 (default) = the "
    "ladder's top batch rung.")
register_flag(
    "MXSERVE2_PAGE_SIZE", int, 16,
    "KV-cache page width in tokens for the continuous-batching serving "
    "tier (serve2.kvcache.PagedKVCache): each page is a fixed-size "
    "block of the pooled K/V memory, so admit/finish/preempt never "
    "change a compiled decode program's shapes. Smaller pages waste "
    "less memory on short tails but lengthen the paged-attention scan "
    "(docs/serving.md v2 tuning guide).")
register_flag(
    "MXSERVE2_NUM_PAGES", int, 256,
    "Total pages in the serve2 KV pool (page 0 is reserved as the null "
    "page). Together with MXSERVE2_PAGE_SIZE this fixes the pool's "
    "device footprint at engine construction; running out under load "
    "triggers recompute preemption of the youngest sequence, counted "
    "in mxserve2_preemptions_total.")
register_flag(
    "MXSERVE2_MAX_INFLIGHT", int, 8,
    "Max sequences decoded concurrently by one serve2 DecodeEngine. "
    "The decode bucket ladder is the powers of two up to this cap; "
    "each rung is ONE compiled decode step program, AOT-warmed so the "
    "jit cache closes (zero steady-state recompiles, servelint-"
    "checked).")
register_flag(
    "MXSERVE2_REPLICAS", int, 2,
    "Default replica count per model group in the serve2 Router "
    "(serve2.router): requests spread over N engine replicas with "
    "queue-depth + circuit-breaker aware routing; a tripped replica "
    "is routed around (graceful degradation) until its breaker "
    "half-opens.")
register_flag(
    "MXSERVE2_RELOAD_DRAIN_TIMEOUT_S", float, 30.0,
    "Per-replica drain budget during a rolling model reload "
    "(Router.rolling_reload): the NEW engine is warmed before the "
    "swap, then the old engine gets this many seconds to finish "
    "in-flight work before it is closed; requests still queued after "
    "the budget count as dropped in the reload report (test-enforced "
    "to be zero).")
register_flag(
    "MXSERVE2_DECODE_STEPS", int, 4,
    "Decode iterations folded into ONE compiled serve2 dispatch "
    "(n-step scheduling). The K steps run entirely in-device, so the "
    "pool copy-on-update forced where buffer donation is unavailable "
    "(XLA:CPU) is paid once per K tokens; scheduling granularity "
    "(admit/preempt/finish) coarsens to K tokens. 1 = strict "
    "iteration-level scheduling.")
register_flag(
    "MXSERVE2_PREFILL_BUCKETS", str, "16,32,64",
    "Prompt-length rungs for the serve2 prefill program (comma list). "
    "Prompts are padded up to the next rung so prefill compiles once "
    "per rung — same closed-jit-cache contract as MXSERVE_BUCKETS; "
    "prompts longer than the top rung are rejected at submit.")
register_flag(
    "MXSERVE3_PREFIX_CACHE", bool, False,
    "Prefix caching for serve2 DecodeEngines (serve3 leg a): FULL "
    "pages of each prompt are content-hashed (chain hash over the "
    "whole prefix) so identical prompt prefixes across requests map "
    "to the same refcounted physical pages — prefill runs only over "
    "the uncovered suffix, multiplying effective cache capacity under "
    "templated traffic. Shared pages are read-only; in-place writes "
    "copy-on-write (mxserve3_cow_copies_*). Exact: greedy outputs are "
    "unchanged (the cached K/V is the prefill's own). Off by default "
    "so a flags-off engine is bit-for-bit the PR-8 engine (finished "
    "sequences' pages linger refcounted in the cache when on).")
register_flag(
    "MXSERVE3_PREFIX_CACHE_PAGES", int, 0,
    "Cap on pages the serve2 prefix cache may pin (0 = no explicit "
    "cap; pool pressure still evicts LRU cache pages before the "
    "scheduler resorts to preemption). Tune below the pool size when "
    "templated traffic would otherwise crowd out decode growth.")
register_flag(
    "MXSERVE3_SPEC_TOKENS", int, 0,
    "Draft tokens proposed per speculative-decoding tick (serve3 leg "
    "b) when a DecodeEngine is built with draft_params. Each tick the "
    "draft proposes K tokens in one small dispatch and the target "
    "verifies all K+1 candidates in ONE batched forward; greedy "
    "acceptance is exact (token-for-token the target's own "
    "trajectory), so throughput scales with the draft's acceptance "
    "rate (mxserve3_accept_rate_*). 0 = speculative decoding off.")
register_flag(
    "MXSERVE3_KV_DTYPE", str, "f32",
    "Storage dtype of the serve2 KV page pools (serve3 leg c): 'f32' "
    "(exact), 'bf16' (half the pool bytes, quant_bf16 tolerance "
    "class), or 'int8' (quarter the pool bytes + per-slot f32 dequant "
    "scales, quantize-on-append, quant_int8 class) — int8 roughly "
    "quadruples in-flight sequences per pool byte. Dequantization "
    "happens inside the paged-attention gather.",
    choices=("f32", "bf16", "int8"))
register_flag(
    "MXRESIL_FAULT_PLAN", str, "",
    "Deterministic fault-injection plan (resil.faultplan), e.g. "
    "'step:40=preempt;kvstore.push@3=raise;io=stall:200ms' — "
    "semicolon-separated site[@K|%P|:STEP]=action[:arg] clauses "
    "evaluated at the wired injection sites (kvstore.push/pull, io, "
    "serve.submit, checkpoint.write/restore, step). Empty = injection "
    "off (the hooks are no-ops). See docs/resilience.md.")
register_flag(
    "MXRESIL_SEED", int, 0,
    "Seed for probabilistic fault-plan clauses (site%P): a fixed seed "
    "reproduces the same fault sequence bit-for-bit "
    "(resil.faultplan.Clause).")
register_flag(
    "MXRESIL_RETRY_MAX", int, 3,
    "Max retries per call for the site retry policies "
    "(resil.policy.RetryPolicy) wrapping kvstore push/pull and "
    "checkpoint I/O; only typed RetryableErrors are retried.")
register_flag(
    "MXRESIL_RETRY_BASE_MS", float, 10.0,
    "First-retry backoff in milliseconds; subsequent retries double "
    "it with jitter (resil.policy.BackoffSchedule).")
register_flag(
    "MXRESIL_RETRY_MAX_MS", float, 2000.0,
    "Backoff ceiling in milliseconds (resil.policy.BackoffSchedule).")
register_flag(
    "MXRESIL_BREAKER_FAILURES", int, 5,
    "Consecutive failures that trip a site circuit breaker to OPEN "
    "(fail-fast degraded mode; resil.policy.CircuitBreaker).")
register_flag(
    "MXRESIL_BREAKER_COOLDOWN_S", float, 30.0,
    "Seconds an open circuit breaker waits before admitting one "
    "half-open probe (resil.policy.CircuitBreaker).")
register_flag(
    "MXSHARD_AUTO", bool, False,
    "Shard every gluon Trainer.fuse_step over the local devices when "
    "more than one is present (shard.ShardPlan.from_env over "
    "MXSHARD_AXES/MXSHARD_ZERO): the fused train step compiles with "
    "NamedSharding annotations over a named mesh instead of running "
    "single-device. Explicit shard_plan= arguments always win. See "
    "docs/sharding.md.")
register_flag(
    "MXSHARD_AXES", str, "batch:-1",
    "Mesh axes for MXSHARD_AUTO / ShardPlan.from_env, as "
    "'name:size[,name:size...]' with at most one -1 (inferred from "
    "the device count) — e.g. 'batch:-1' (pure data parallel) or "
    "'batch:4,model:2' (DP x TP composition). The 'batch' axis (or "
    "the first axis named) is the data-parallel axis.")
register_flag(
    "MXSHARD_ZERO", bool, True,
    "ZeRO-style sharding of optimizer state (and thereby the fused "
    "weight-update computation) along the batch axis "
    "(shard.ShardPlan.state_spec): per-replica optimizer memory "
    "scales ~1/N with data-parallel replicas. Off = optimizer state "
    "mirrors its weight's (usually replicated) sharding.")
register_flag(
    "MXELASTIC_HEARTBEAT_S", float, 2.0,
    "Elastic-membership heartbeat interval in seconds (elastic."
    "MembershipTracker): workers beat at every step boundary and "
    "inside every blocked protocol wait; a worker silent for "
    "MXELASTIC_HEARTBEAT_S x MXELASTIC_MISS_LIMIT seconds is declared "
    "lost and the membership generation bumps, fencing in-flight "
    "exchanges with the typed MembershipChanged "
    "(docs/resilience.md elastic section).")
register_flag(
    "MXELASTIC_MISS_LIMIT", int, 3,
    "Missed-heartbeat budget before a worker-lost verdict (elastic."
    "MembershipTracker.check): lost_after = MXELASTIC_HEARTBEAT_S x "
    "this. Lower = faster recovery after a hard kill, higher = more "
    "tolerance for GC pauses / slow steps.")
register_flag(
    "MXELASTIC_MIN_WORLD", int, 1,
    "Smallest world size elastic training may shrink to before the "
    "group HARD-FAILS (elastic.MembershipTracker): below this, every "
    "elastic operation raises GroupFailed so the cluster manager "
    "restarts the job from checkpoint instead of limping on too few "
    "workers.")
register_flag(
    "MXELASTIC_LR_SCALE", bool, True,
    "Linear-scaling rule across membership changes (gluon Trainer."
    "_on_membership_change): after a generation bump the learning "
    "rate is set to base_lr x world/ref_world so per-sample update "
    "magnitude tracks the shrunken/grown global batch. Schedulers are "
    "instead driven through the session's virtual update counter "
    "(samples-based step accounting). Off = LR untouched.")
register_flag(
    "MXELASTIC_LOSS_TOL", float, 0.15,
    "Declared relative tolerance for the elastic loss-trajectory "
    "contract: the final loss of a kill/rejoin drill must match the "
    "uninterrupted run within this fraction (tools/mxresil.py "
    "elastic, bench.py --elastic). The rescaled-batch/LR accounting "
    "exists to keep runs inside it.")
register_flag(
    "MXPIPE_SCHEDULE", str, "1f1b",
    "Microbatch schedule for pipelined training (mxnet_tpu/pipe/"
    "schedule.py, docs/pipeline.md): '1f1b' (non-interleaved one-"
    "forward-one-backward — same tick count and bubble as GPipe but "
    "peak in-flight activations bounded at min(M, S-s) per stage) or "
    "'gpipe' (all forwards then all backwards; peak in-flight = M "
    "everywhere). Both are explicit dependency-validated tick "
    "programs; bubble fraction is (S-1)/(M+S-1) for either.",
    choices=("1f1b", "gpipe"))
register_flag(
    "MXPIPE_MICROBATCH", int, 0,
    "Microbatch count M for the pipeline schedule "
    "(pipe.PipeStepFunction). 0 = auto: M = n_stage, the smallest M "
    "that keeps every stage busy in steady state; raise it to shrink "
    "the bubble fraction (S-1)/(M+S-1) at the cost of more ticks. "
    "The global batch must divide by M — pipelint flags violations "
    "as errors before the runner raises.")
register_flag(
    "MXPIPE_STAGES", int, 0,
    "Pipeline stage count S. 0 = auto: one stage per host in the "
    "elastic/pod membership view (a lost host is a lost stage), or 1 "
    "outside a session. The LM's layer count must divide by S; "
    "checkpoints save the DENSE layout, so the same checkpoint "
    "restores into any valid S (docs/pipeline.md re-stage section).")
register_flag(
    "MXPIPE_BALANCE_TOL", float, 0.25,
    "Stage-balance threshold for passes/pipelint.py: a stage whose "
    "param bytes deviate from the per-stage mean by more than this "
    "fraction draws a warn (the pipeline clocks at the SLOWEST "
    "stage, so imbalance is pure bubble). First/last stages "
    "legitimately carry embed/head extras; size the tolerance to "
    "what your vocab adds.")
register_flag(
    "MXGUARD", bool, False,
    "Silent-corruption integrity taps (mxnet_tpu/guard/, docs/"
    "resilience.md integrity section): per-gradient fingerprints "
    "(checksum, absmax, non-finite count) ride as extra outputs of "
    "the fused train step, cross-replica voting fences a corrupt "
    "replica BEFORE its gradients enter the allreduce, and the EWMA "
    "anomaly probe feeds the watchdog. Part of the fused-step "
    "signature-cache key: flipping it re-keys once, steady state "
    "stays at zero recompiles; taps-on training is bitwise-identical "
    "in weights to taps-off (test-enforced).")
register_flag(
    "MXGUARD_VOTE_TOL", float, 1000.0,
    "Cross-replica vote threshold (guard.fingerprint.vote): a "
    "gradient fingerprint's absmax beyond this factor over the OTHER "
    "replicas' median votes the replica suspect. Legitimate "
    "per-worker batch spread is single-digit; an exponent bit flip "
    "is ~1e30x — the default leaves orders of magnitude of margin "
    "both ways.")
register_flag(
    "MXGUARD_EWMA_FACTOR", float, 100.0,
    "Anomaly factor for the report-only EWMA loss/grad-norm probe "
    "(guard.anomaly.GuardProbe, registered on the resil watchdog): a "
    "step whose loss or gradient absmax exceeds this factor over its "
    "EWMA emits an integrity-anomaly finding naming the replay "
    "window for tools/mxresil.py replay.")
register_flag(
    "MXGUARD_RING", int, 256,
    "Capacity (steps) of the deterministic-replay record ring "
    "(guard.replay.ReplayRecorder): per step one small record of "
    "batch crc32 digests, the raw RNG key, hyper scalars, the loss "
    "digest and the fingerprint matrix — what `tools/mxresil.py "
    "replay` re-executes bitwise to bisect the first corrupted step.")
register_flag(
    "MXGUARD_CKPT_EVERY", int, 25,
    "Known-good checkpoint-ring cadence (steps) of the replay "
    "recorder: a ring checkpoint commits only while no guard verdict "
    "has flagged the run (a snapshot taken after corruption entered "
    "the weights must never become a recovery point — the ring "
    "freezes once tainted).")
register_flag(
    "MXGUARD_STRICT", bool, False,
    "Hard-fail the ONE-PROGRAM fused step on non-finite gradient "
    "fingerprints (GuardCorruption). Off by default: the fused "
    "program has already applied the update when the taps surface, "
    "so there is nothing to retry — the split-phase elastic step "
    "instead classifies by re-execution and retries/quarantines "
    "regardless of this flag.")
register_flag(
    "MXPOD_COORDINATOR", str, "",
    "host:port of the pod control plane (pod.PodContext): rank 0 "
    "binds a kvstore server carrying the elastic membership "
    "coordinator there; every rank's ElasticKVStore reaches it over "
    "the framed-pickle socket transport. Empty = fall back to the "
    "MX_KV_SERVER env exported by tools/launch.py (single process "
    "without either: a loopback server on a free port).")
register_flag(
    "MXPOD_RANK", int, -1,
    "This process's pod rank (pod.PodContext). -1 = fall back to the "
    "launcher env (MX_WORKER_ID / OMPI_COMM_WORLD_RANK / ... via "
    "base.worker_rank). Rank 0 is the coordinator host: it binds "
    "MXPOD_COORDINATOR and owns the membership verdicts.")
register_flag(
    "MXPOD_NPROCS", int, 0,
    "Number of host processes in the pod (pod.PodContext). 0 = fall "
    "back to MX_NUM_WORKERS from the launcher. Group formation waits "
    "for this many registrations before the first exchange.")
register_flag(
    "MXPOD_HEARTBEAT_S", float, 0.0,
    "Pod host-heartbeat interval in seconds: PodContext maps it onto "
    "MXELASTIC_HEARTBEAT_S for both the rank-0 verdict policy and "
    "the worker-side pump, so one flag tunes host-loss detection "
    "end to end. 0 = keep MXELASTIC_HEARTBEAT_S as configured.")
register_flag(
    "MXPOD_JOURNAL_DIR", str, "",
    "Directory of the coordinator's control-plane journal (elastic."
    "ElasticCoordinator): the leader appends one JSON line per "
    "generation bump (generation, workers, devices), and a RESTARTED "
    "rank-0 replays the newest entry to re-form the group — members "
    "restored, generation bumped once more so every survivor fences "
    "with the usual MembershipChanged instead of orphaning "
    "(docs/resilience.md multi-host section). Empty = no journal "
    "(a coordinator restart orphans the group).")
register_flag(
    "MXPOD_COORDINATOR_GRACE_S", float, 30.0,
    "How long a worker's PodGroup keeps retrying the control-plane "
    "socket (bounded jittered backoff, resil.policy.RetryPolicy) "
    "after transport failures before raising the typed "
    "CoordinatorLost. Long enough to cover a coordinator restart + "
    "journal replay; waiters never wedge silently either way.")
register_flag(
    "MXTRACE", bool, True,
    "Correlated cross-subsystem tracing (mxnet_tpu/trace/, docs/"
    "observability.md): spans with trace_id/span_id/parent thread the "
    "serving path (endpoint -> router -> scheduler -> prefill/decode/"
    "verify) and the training path (step -> exchange -> guard vote -> "
    "elastic rebuild), feed the per-phase latency histograms "
    "(mxtrace_phase_*_seconds) and the crash flight recorder. On by "
    "default: a span is two monotonic clock reads and a deque append "
    "(<2% at default sampling, bench.py --trace-overhead enforces); "
    "tracing never touches jit cache keys, so it can never recompile.")
register_flag(
    "MXTRACE_SAMPLE", float, 1.0,
    "Fraction of ROOT traces recorded (trace.span): the decision is "
    "made once where a trace starts (endpoint request, train step) "
    "and inherited by every child span, so a dropped trace pays "
    "~nothing. 1.0 = record everything (default); lower it on "
    "high-QPS serving to bound export volume.")
register_flag(
    "MXTRACE_EXPORT", str, "",
    "Path of the span JSON-lines sink (trace.export): every finished "
    "sampled span appends one line. Read it with `tools/mxprof.py "
    "trace <file>` or convert with trace.write_chrome. Empty = "
    "export off (spans still reach the in-memory flight recorder).")
register_flag(
    "MXTRACE_BUFFER_SPANS", int, 4096,
    "Per-thread finished-span buffer capacity (trace.span.drain "
    "collects + clears them). Oldest spans drop first; the flight "
    "recorder keeps its own per-subsystem rings.")
register_flag(
    "MXTRACE_RECORDER_SPANS", int, 256,
    "Spans retained per subsystem in the crash flight recorder "
    "(trace.recorder): the last-N window a dump freezes on breaker "
    "trip / engine crash / GroupFailed / guard quarantine / watchdog "
    "stall / SIGTERM.")
register_flag(
    "MXTRACE_DUMP_DIR", str, "",
    "Directory for flight-recorder dump files (mxtrace-flight-"
    "<reason>-<ts>.json). Empty = <tempdir>/mxtrace. Dumps are "
    "rate-limited per reason (5 s) so failure storms stay readable.")
register_flag(
    "MXOBS", bool, True,
    "Pod-scale observability plane (mxnet_tpu/obs/, docs/"
    "observability.md multi-host section): control-plane messages "
    "carry the caller's mxtrace context so one train step / rebuild / "
    "guard vote is ONE trace id across every rank, each host's "
    "heartbeat pump pushes a mergeable metrics snapshot to the rank-0 "
    "collector, and a rank-0 dump trigger broadcasts a coordinated "
    "flight-recorder capture over the heartbeat channel. Same "
    "discipline as MXTRACE: structurally zero-cost when off (one "
    "generation-keyed flag-cache read on the hot path, no wire "
    "fields, no collector state), <2% when on (bench.py "
    "--obs-overhead enforces), never touches jit cache keys.")
register_flag(
    "MXOBS_PUSH_INTERVAL_S", float, 2.0,
    "Seconds between a host's metrics-snapshot pushes to the rank-0 "
    "collector (obs.collector, ridden by the elastic heartbeat pump "
    "— no extra thread, no extra connection). Counters/histograms "
    "merge exactly on the collector (count/sum exact, reservoir "
    "merge weighted); lower it in drills that assert on freshness.")
register_flag(
    "MXOBS_EXPORT", str, "",
    "Path of the rank-0 POD-MERGED snapshot JSON-lines sink: the "
    "collector appends one line per export tick with the fleet-"
    "merged metrics plus per-rank sections. Empty = export off "
    "(merged snapshots still queryable via obs_merged / "
    "tools/diagnose.py).")
register_flag(
    "MXOBS_BENCHSTORE", str, "",
    "Benchstore path override (tools/benchstore.py): the append-only "
    "JSONL perf-trajectory DB every bench.py metric line lands in, "
    "keyed by (metric, host fingerprint, mesh, git rev); `mxprof "
    "regress` gates the newest run against the stored trajectory "
    "with median/MAD fences. Empty = tools/benchstore.jsonl; "
    "'0'/'off' = appends disabled (MXTPU_BENCH_STORE=0 is the "
    "bench-side escape hatch).")
register_flag(
    "MXFLEET_HEARTBEAT_S", float, 1.0,
    "Seconds between a fleet engine worker's directory heartbeats to "
    "the coordinator (fleet.worker.EngineHost). The FleetController "
    "treats a worker whose last beat is older than 3x this as dead "
    "and rebuilds the replica group without it; the Router breaker "
    "already sheds it in the meantime.")
register_flag(
    "MXFLEET_AFFINITY", bool, True,
    "Prefix-affinity routing (fleet.routing): hash the first "
    "MXFLEET_AFFINITY_PAGES serve2.prefix.page_keys of each prompt "
    "and prefer the rendezvous-chosen decode worker, so templated "
    "prompts land where their KV pages already live. Off = pure "
    "shallowest-queue across hosts. Only consulted inside fleet/ — "
    "single-host Router behavior is untouched either way.")
register_flag(
    "MXFLEET_AFFINITY_PAGES", int, 4,
    "How many leading page-chain hashes feed the affinity key. "
    "Small = template-level affinity (shared system prompts "
    "colocate); large = whole-prompt affinity (less sharing, better "
    "isolation).")
register_flag(
    "MXFLEET_SPILL_FACTOR", float, 2.0,
    "Affinity spill threshold: the preferred worker is used only "
    "while its queue depth <= this factor x the shallowest worker's "
    "depth (+1). Above it the request spills to shallowest-queue — "
    "cache locality must never buy a convoy. 0 = never spill "
    "(strict affinity).")
register_flag(
    "MXFLEET_PREFILL_DISAGG", bool, True,
    "Prefill/decode disaggregation (fleet.controller): prompts go to "
    "a dedicated prefill worker first, which streams the finished KV "
    "pages to the chosen decode worker over the pagewire before the "
    "decode request lands (CPU host-transfer path; device-to-device "
    "is stubbed pending TPU DMA). Requires at least one registered "
    "prefill-role worker, else requests fall back to direct decode "
    "(the decode worker prefills locally, exactly the single-host "
    "path).")
register_flag(
    "MXFLEET_PAGEWIRE_CHUNK_PAGES", int, 8,
    "Pages per pagewire transfer chunk (fleet.pagewire): the "
    "fixed-shape export/import jit programs move this many KV pages "
    "per dispatch (warmed by DecodeEngine warmup alongside the "
    "decode rungs, so streaming never recompiles). Larger = fewer "
    "dispatches, more padding on the tail chunk.")
register_flag(
    "MXFLEET_AUTOSCALE_WINDOW_S", float, 30.0,
    "Autoscaler observation window (fleet.autoscale.AutoScaler): "
    "grow/shrink decisions read the decode-phase p99 from the merged "
    "obs snapshots over this window, with the same span as cooldown "
    "between actuations (rolling_reload resizes are not free). "
    "0 = autoscaler disabled.")
register_flag(
    "MXFLEET_SLO_P99_MS", float, 0.0,
    "Decode p99 SLO target in milliseconds for the autoscaler: "
    "sustained p99 above it grows the group by one replica, p99 "
    "under half of it (with idle queues) shrinks by one. 0 = no SLO "
    "-> autoscaler holds (observability-only).")
register_flag(
    "MXRESIL_WATCHDOG_STALL_S", float, 0.0,
    "Heartbeat age that counts as a stall (resil.watchdog.Watchdog). "
    "0 = auto: 10x the step-time EWMA (min 1 s; 30 s before any step "
    "has been observed).")
register_flag(
    "MXSAN", bool, False,
    "Runtime lock-order sanitizer (mxnet_tpu/san/, docs/observability"
    ".md MXSAN runbook): the hot subsystems' locks (serve2, pod, "
    "elastic, trace, telemetry) are constructed through san.make_lock/"
    "make_rlock/make_condition — with MXSAN=1 they come back "
    "instrumented, recording the per-thread acquisition-order graph "
    "(cycles = potential deadlocks, reported with BOTH acquisition "
    "stacks), per-lock hold/wait/contention stats (san.export_to_"
    "registry publishes mxsan_lock_* instruments), and a flight-"
    "recorder dump when a waiter blocks past MXSAN_BLOCK_THRESHOLD_MS."
    " Off (default) = the factories return plain threading primitives:"
    " zero wrappers, zero overhead, no recompiles (bench.py "
    "--san-overhead enforces). Read at LOCK CONSTRUCTION time — set "
    "it before building engines/groups (module-level locks capture it "
    "at import).")
register_flag(
    "MXSAN_BLOCK_THRESHOLD_MS", float, 1000.0,
    "MXSAN=1 only: a sanitized-lock waiter blocked longer than this "
    "triggers ONE mxsan-blocked-waiter flight-recorder dump naming "
    "the lock, the holder's acquisition site and the waiter's stack — "
    "then keeps waiting (the sanitizer reports wedges, it never "
    "changes blocking semantics). 0 disables the threshold.")
register_flag(
    "MXNET_KVSTORE_TIMEOUT_MS", float, 0.0,
    "Per-request timeout for kvstore data-plane push/pull over the "
    "dist_async transport: exceeding it raises the typed "
    "KVStoreTimeoutError (retryable by resil policies) instead of "
    "hanging. 0 (default) = fall back to the barrier-timeout-based "
    "socket deadline. An active resil deadline_scope caps it further.")
register_flag(
    "MXNET_KVSTORE_BARRIER_TIMEOUT", float, 300.0,
    "Seconds a worker waits at a dist barrier before declaring the "
    "job failed (failure detection, SURVEY.md §5.3; the reference's "
    "ps-lite van timeouts play this role).")
register_flag(
    "MXTUNE_AUTO", bool, False,
    "Auto-apply tuned configs on bind (mxnet_tpu/tune/, docs/tuning"
    ".md): Trainer.fuse_step, ServingEngine and DecodeEngine consult "
    "the tuning DB at bind time and apply the best measured config "
    "whose key matches this process exactly (model signature, device "
    "kind, mesh shape, knob-space fingerprint) — logging what was "
    "applied with its measured value and provenance. ANY mismatch or "
    "validation failure falls back to defaults (loudly logged, never "
    "raised into the bind). Off (default) = binding is bit-identical "
    "to a build without mxtune (test-enforced).")
register_flag(
    "MXTUNE_DB_DIR", str, "",
    "Tuning-DB directory (tune_db.jsonl lives here). Empty (default) "
    "= ~/.mxnet_tpu/tune. Point search and serving at the same dir "
    "to share tuned configs; the DB is append-crash-safe and "
    "self-compacting (docs/tuning.md, DB format section).")
register_flag(
    "MXTUNE_BUDGET", int, 16,
    "Default measurement budget (trials) for tune.run_search and "
    "`python tools/mxtune.py search` / `bench.py --tune` when no "
    "explicit budget is passed. Trial 0 always measures the DEFAULTS "
    "config, so the best entry is never worse than stock; the "
    "learned cost model starts pruning once ~len(space)+2 legal "
    "measurements exist (docs/tuning.md, budget guidance).")
register_flag(
    "MXTUNE_OBJECTIVE", str, "auto",
    "Objective auto-apply optimizes for, from tune.OBJECTIVES "
    "(fused_step_time_s, serve2_open_qps_slo, serve_open_qps_slo). "
    "'auto' (default) = per bind kind: fuse_step->fused_step_time_s, "
    "DecodeEngine->serve2_open_qps_slo, ServingEngine->"
    "serve_open_qps_slo.")
register_flag(
    "MXNET_TEST_SEED", int, -1,
    "Fixed seed for the test harness; -1 = random per test "
    "(ref: tests/python/unittest/common.py).")
register_flag(
    "MXNET_MODULE_SEED", int, -1,
    "Fixed module-level test seed; -1 = random "
    "(ref: tests/python/unittest/common.py:189).")

# ---------------------------------------------------------------------------
# Accepted-but-inert flags (XLA/PJRT owns the job). Setting them warns.
# ---------------------------------------------------------------------------

for _name, _type, _default, _doc, _note in [
    ("MXNET_GPU_MEM_POOL_TYPE", str, "Naive",
     "GPU memory pool selector (ref: storage.cc:103).",
     "PJRT owns device memory pooling"),
    ("MXNET_GPU_MEM_POOL_RESERVE", int, 5,
     "Percent of GPU memory held back from the pool.",
     "PJRT owns device memory pooling"),
    ("MXNET_EXEC_ENABLE_INPLACE", bool, True,
     "Allow in-place buffer sharing in the memory planner.",
     "XLA's buffer assignment handles aliasing/donation"),
    ("MXNET_EXEC_NUM_TEMP", int, 1,
     "Number of temp-space resources per device.",
     "XLA allocates scratch internally"),
    ("MXNET_CPU_PRIORITY_NTHREADS", int, 4,
     "Priority-queue worker threads of the CPU engine.",
     "PJRT schedules host work"),
    ("MXNET_GPU_WORKER_NTHREADS", int, 2,
     "Per-GPU engine worker threads.",
     "PJRT streams replace engine worker pools"),
    ("MXNET_OMP_MAX_THREADS", int, 0,
     "OpenMP thread cap for CPU kernels.",
     "XLA:CPU threadpool is sized by jax"),
    ("MXNET_CUDNN_AUTOTUNE_DEFAULT", int, 1,
     "cuDNN conv algo autotuning.",
     "XLA autotunes convolutions during compilation"),
    ("MXNET_CUDA_ALLOW_TENSOR_CORE", bool, True,
     "Allow TensorCore math.",
     "use jax.default_matmul_precision / bf16 policies"),
    ("MXNET_ENABLE_OPERATOR_TUNING", int, 1,
     "Enable/disable operator tuning.",
     "superseded by MXNET_USE_OPERATOR_TUNING (active)"),
    ("MXNET_KVSTORE_USETREE", bool, False,
     "Topology-aware tree reduction (ref: comm_tree.h).",
     "ICI collectives are already topology-optimal"),
    ("MXNET_KVSTORE_REDUCTION_NTHREADS", int, 4,
     "CPU threads for kvstore reduction.",
     "psum runs on-device over ICI"),
    ("MXNET_ENABLE_GPU_P2P", bool, True,
     "Peer-to-peer GPU copies in device comm.",
     "ICI replaces P2P copies"),
    ("MXNET_MKLDNN_ENABLED", bool, True,
     "MKL-DNN CPU kernels.", "XLA:CPU generates its own kernels"),
]:
    register_flag(_name, _type, _default, _doc, active=False,
                  tpu_note=_note)
