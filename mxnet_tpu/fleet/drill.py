"""Subprocess fleet drills: the proof layer of mxfleet.

``run_fleet_drill`` spawns REAL host processes (``python -m
mxnet_tpu.fleet.worker`` — own jax runtime, own DecodeEngine, own
socket server), an in-parent coordinator (KVServer + fleet
directory), and a FleetController, then drives templated load through
``controller.predict`` while one scripted fault lands mid-load:

- ``mode="kill_decode"`` — SIGKILL a decode host: its in-flight
  requests surface as ``EngineCrashedError``, breaker-mark, and retry
  on a surviving host — the drill asserts ZERO accepted requests
  drop and that the controller's next sync shrinks the group;
- ``mode="kill_prefill"`` — SIGKILL the prefill host: the
  disaggregation leg fails silently and every prompt falls back to
  local prefill (the single-host path) — zero drops, served count
  unchanged;
- ``mode="controller_restart"`` — stop the coordinator server
  mid-load and bind a fresh one on the SAME port: worker heartbeats
  see ``fleet_heartbeat() -> False`` and re-register, the
  controller's PodGroup rides its bounded-backoff reconnect, and the
  data plane (direct worker sockets) never notices;
- ``mode="baseline"`` — no fault, same load (the comparison run).

Faults are request-count scripted, never timed.  Shared by
tests/test_fleet_drill.py (@slow, 3 modes) and ``bench.py --fleet``.
"""
from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

from ..base import MXNetError, get_logger

__all__ = ["run_fleet_drill", "FleetHarness"]

_log = get_logger("mxnet_tpu.fleet")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


class _Worker:
    """One spawned fleet worker process + its FLEET event stream."""

    def __init__(self, wid: str, role: str, env: Dict[str, str]):
        self.wid = wid
        self.role = role
        self.events: List[Dict] = []
        self.raw: List[str] = []
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "mxnet_tpu.fleet.worker"],
            env=env, cwd=_REPO_ROOT, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        self._reader = threading.Thread(target=self._drain,
                                        daemon=True)
        self._reader.start()

    def _drain(self):
        for ln in self.proc.stdout:
            self.raw.append(ln)
            if ln.startswith("FLEET "):
                try:
                    evt = json.loads(ln[6:])
                except ValueError:
                    continue
                evt["_t"] = time.perf_counter()
                self.events.append(evt)

    def of(self, kind: str) -> List[Dict]:
        return [e for e in self.events if e.get("evt") == kind]

    def address(self) -> Optional[str]:
        ready = self.of("ready")
        return ready[0]["address"] if ready else None

    def kill_now(self):
        try:
            self.proc.kill()
        except OSError:
            pass

    def terminate(self):
        try:
            self.proc.terminate()
        except OSError:
            pass


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class FleetHarness:
    """Coordinator + N workers + controller, reusable by the drill
    and by ``bench.py --fleet``. The parent process plays the
    controller host (binds the KVServer carrying the fleet
    directory)."""

    def __init__(self, *, n_decode: int = 2, n_prefill: int = 1,
                 page_size: int = 8, num_pages: int = 128,
                 max_inflight: int = 4, max_seq: int = 96,
                 max_new: int = 8, heartbeat_s: float = 0.25,
                 grace_s: float = 20.0):
        from .. import config
        from ..kvstore_server import KVServer
        from ..pod.group import PodGroup
        from .controller import FleetController
        self.page_size = int(page_size)
        self.max_new = int(max_new)
        self.heartbeat_s = float(heartbeat_s)
        config.set_flag("MXFLEET_HEARTBEAT_S", self.heartbeat_s)
        self.port = _free_port()
        self.addr = f"127.0.0.1:{self.port}"
        # one "worker" from the kvstore server's point of view: the
        # fleet directory rides the elastic sidecar ops only
        self.server = KVServer(self.addr, 1)
        base_env = dict(os.environ)
        for k in ("MX_COORDINATOR", "MX_KV_SERVER", "MX_WORKER_ID",
                  "MX_NUM_WORKERS", "XLA_FLAGS", "MXRESIL_FAULT_PLAN",
                  "MXPOD_JOIN", "MXFLEET_COORDINATOR"):
            base_env.pop(k, None)
        base_env.update({
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": _REPO_ROOT + os.pathsep
            + base_env.get("PYTHONPATH", ""),
            "MXFLEET_COORDINATOR": self.addr,
            "MXFLEET_HEARTBEAT_S": str(self.heartbeat_s),
            "MXPOD_COORDINATOR_GRACE_S": str(grace_s),
            "FLEET_PAGE": str(page_size),
            "FLEET_PAGES": str(num_pages),
            "FLEET_INFLIGHT": str(max_inflight),
            "FLEET_MAX_SEQ": str(max_seq),
        })
        self.base_env = base_env
        self.workers: List[_Worker] = []
        for i in range(int(n_decode)):
            self.workers.append(self._spawn(f"d{i}", "decode"))
        for i in range(int(n_prefill)):
            self.workers.append(self._spawn(f"p{i}", "prefill"))
        self.group = PodGroup(self.addr, grace_s=grace_s)
        self.controller = FleetController(
            self.group, page_size=page_size,
            heartbeat_s=self.heartbeat_s)

    def _spawn(self, wid: str, role: str) -> _Worker:
        env = dict(self.base_env)
        env["MXFLEET_ROLE"] = role
        env["MXFLEET_WORKER_ID"] = wid
        return _Worker(wid, role, env)

    def decode_workers(self) -> List[_Worker]:
        return [w for w in self.workers if w.role == "decode"]

    def prefill_workers(self) -> List[_Worker]:
        return [w for w in self.workers if w.role == "prefill"]

    def wait_ready(self, timeout_s: float = 180.0):
        """Block until every worker registered and the controller's
        group covers all decode workers (engines warm inside this
        window — the slow part of a host bring-up)."""
        deadline = time.monotonic() + timeout_s
        want = len(self.decode_workers())
        while time.monotonic() < deadline:
            for w in self.workers:
                if w.proc.poll() is not None:
                    raise MXNetError(
                        f"fleet worker {w.wid} died during bring-up "
                        f"(rc={w.proc.returncode}): "
                        f"{''.join(w.raw[-12:])[:1200]}")
            got = self.controller.sync(force=True)
            if got["decode"] == want and \
                    got["prefill"] == len(self.prefill_workers()):
                return
            time.sleep(0.2)
        raise MXNetError(
            f"fleet bring-up timed out after {timeout_s:.0f}s "
            f"(directory: {self.controller.describe()['decode']})")

    def restart_coordinator(self):
        """Kill the control plane and bind a fresh server on the SAME
        port — the coordinator-restart drill. Directory state is
        deliberately lost; workers re-register on their next beat."""
        self.server.stop()
        from ..kvstore_server import KVServer
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            try:
                self.server = KVServer(self.addr, 1)
                break
            except OSError:
                time.sleep(0.1)
        else:
            raise MXNetError("could not rebind coordinator port")
        self.group.reconnect()

    def close(self):
        for w in self.workers:
            w.terminate()
        deadline = time.monotonic() + 15.0
        for w in self.workers:
            while w.proc.poll() is None and \
                    time.monotonic() < deadline:
                time.sleep(0.05)
            if w.proc.poll() is None:
                w.kill_now()
        try:
            self.controller.close()
        except Exception:  # noqa: BLE001
            pass
        try:
            self.group.close()
        except Exception:  # noqa: BLE001
            pass
        self.server.stop()


def _make_payloads(n: int, prompt_len: int, page_size: int,
                   n_templates: int = 4, vocab: int = 64,
                   seed: int = 0) -> List[List[int]]:
    """Templated prompts: a shared leading template (>= 2 pages, so
    the affinity key and the prefix cache both engage) + a unique
    suffix per request."""
    import numpy as onp
    rs = onp.random.RandomState(seed)
    tpl_len = max(2 * page_size, (prompt_len * 2) // 3)
    templates = [rs.randint(0, vocab, size=(tpl_len,)).tolist()
                 for _ in range(n_templates)]
    out = []
    for i in range(n):
        tpl = templates[i % n_templates]
        suffix = rs.randint(0, vocab,
                            size=(max(1, prompt_len - tpl_len),))
        out.append([int(t) for t in tpl] + suffix.tolist())
    return out


def run_fleet_drill(mode: str = "kill_decode", *,
                    n_decode: int = 2, n_prefill: int = 1,
                    n_requests: int = 36, concurrency: int = 4,
                    prompt_len: int = 24, fault_after: int = 8,
                    page_size: int = 8, max_new: int = 8,
                    timeout_s: float = 300.0) -> Dict[str, object]:
    """One scripted fleet drill (module docstring); returns the
    report dict. Every submitted request is an ACCEPTED request —
    the zero-drop assertion is ``completed == n_requests``."""
    if mode not in ("baseline", "kill_decode", "kill_prefill",
                    "controller_restart"):
        raise MXNetError(f"unknown fleet drill mode {mode!r}")
    if mode == "kill_prefill" and n_prefill < 1:
        raise MXNetError("kill_prefill needs a prefill worker")
    t_start = time.perf_counter()
    h = FleetHarness(n_decode=n_decode, n_prefill=n_prefill,
                     page_size=page_size, max_new=max_new)
    fault_fired = threading.Event()
    failures: List[str] = []
    done = {"count": 0}
    from ..san.runtime import make_lock
    lock = make_lock("fleet.drill.counters")
    try:
        h.wait_ready(timeout_s=min(240.0, timeout_s))
        payloads = _make_payloads(n_requests, prompt_len, page_size)
        started = {"count": 0}

        def _fault():
            if mode == "kill_decode":
                h.decode_workers()[0].kill_now()
            elif mode == "kill_prefill":
                h.prefill_workers()[0].kill_now()
            elif mode == "controller_restart":
                h.restart_coordinator()

        def _run(idx: int, tokens: List[int]):
            try:
                out = h.controller.predict(
                    tokens, timeout_ms=60_000.0)
                if not out:
                    raise MXNetError("empty generation")
                with lock:
                    done["count"] += 1
            except Exception as e:  # noqa: BLE001 — the drill's
                # whole point is counting these
                with lock:
                    failures.append(
                        f"req {idx}: {type(e).__name__}: "
                        f"{str(e)[:160]}")

        threads: List[threading.Thread] = []
        sem = threading.Semaphore(int(concurrency))
        for idx, tokens in enumerate(payloads):
            sem.acquire()
            with lock:
                started["count"] += 1
                fire = (mode != "baseline"
                        and not fault_fired.is_set()
                        and started["count"] > int(fault_after))
                if fire:
                    fault_fired.set()
            if fire:
                _fault()

            def _wrapped(i=idx, tk=tokens):
                try:
                    _run(i, tk)
                finally:
                    sem.release()
            t = threading.Thread(target=_wrapped, daemon=True)
            t.start()
            threads.append(t)
        deadline = time.monotonic() + timeout_s
        for t in threads:
            t.join(max(0.0, deadline - time.monotonic()))
            if t.is_alive():
                failures.append("request thread hung past deadline")
                break
        # post-fault convergence: the directory ages the dead host
        # out and the controller's group shrinks to the survivors
        post_sync = {}
        if mode == "kill_decode":
            conv_deadline = time.monotonic() + 10 * h.heartbeat_s
            while time.monotonic() < conv_deadline:
                post_sync = h.controller.sync(force=True)
                if post_sync.get("decode") == n_decode - 1:
                    break
                time.sleep(h.heartbeat_s)
        prefix_stats = {}
        for w in h.workers:
            if w.proc.poll() is not None:
                continue
            addr = w.address()
            if not addr:
                continue
            try:
                from .worker import EngineClient
                cli = EngineClient(addr)
                try:
                    prefix_stats[w.wid] = dict(
                        cli.request("stats")).get(
                            "prefix_cache") or {}
                finally:
                    cli.close()
            except Exception:  # noqa: BLE001
                pass
        return {
            "mode": mode,
            "requests": int(n_requests),
            "completed": int(done["count"]),
            "dropped": int(n_requests - done["count"]),
            "failures": failures[:10],
            "fault_fired": bool(fault_fired.is_set()),
            "post_fault_decode": post_sync.get("decode"),
            "prefix_stats": prefix_stats,
            "controller": h.controller.describe()["depths"],
            "duration_s": round(time.perf_counter() - t_start, 3),
        }
    finally:
        h.close()
