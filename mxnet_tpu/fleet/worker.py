"""Fleet engine host: one serving worker PROCESS (decode or prefill).

:class:`EngineHost` wraps one DecodeEngine in a thread-per-connection
TCP server speaking the same framed-pickle wire as the kvstore
control plane (`kvstore_server._send_msg`/`_recv_msg`) — usable
in-process by the fast tests and as the data plane of the subprocess
drill.  :class:`EngineClient` is the matching blocking client;
remote exceptions come back TYPED (by serve-taxonomy class name) so
the controller's RemoteEngine can hand the Router the exact error
semantics it already understands.

``python -m mxnet_tpu.fleet.worker`` — spawned per host by
fleet/drill.py and ``bench.py --fleet``.  Each process builds the
SAME seeded pipeline-LM params as its siblings (env-seeded, so every
decode replica serves the identical model), warms the engine
(including the pagewire chunk programs), starts an EngineHost,
registers in the coordinator's fleet directory, and heartbeats at
MXFLEET_HEARTBEAT_S with its live queue depth.  One ``FLEET {json}``
line per event on stdout for the harness.  SIGTERM = drain + leave +
exit 0; a coordinator restart surfaces as ``fleet_heartbeat() ->
False`` and the worker simply re-registers (the directory is not
journaled — workers outlive it and re-announce).
"""
from __future__ import annotations

import json
import os
import signal
import socket
import sys
import threading
import time
from typing import Dict, Optional

from ..base import MXNetError, get_logger
from ..san.runtime import make_lock

__all__ = ["EngineHost", "EngineClient", "RemoteEngineError"]

_log = get_logger("mxnet_tpu.fleet")


class RemoteEngineError(MXNetError):
    """A fleet worker reported an exception the serve taxonomy does
    not name — carried across the wire as its type name."""


def _typed_remote_error(etype: str, msg: str) -> BaseException:
    """Rebuild the serve-taxonomy exception the worker raised, so the
    Router's error semantics (client error vs backpressure vs crash)
    survive the wire."""
    from ..serve.batcher import (BatcherStoppedError,
                                 DeadlineExceededError,
                                 InvalidRequestError, QueueFullError,
                                 RequestTooLargeError)
    from ..serve.buckets import BucketOverflowError
    from ..serve2.kvcache import PagePoolExhausted
    from ..serve2.scheduler import EngineCrashedError
    known = {c.__name__: c for c in (
        BatcherStoppedError, DeadlineExceededError, InvalidRequestError,
        QueueFullError, RequestTooLargeError, BucketOverflowError,
        PagePoolExhausted, EngineCrashedError)}
    cls = known.get(etype)
    if cls is not None:
        return cls(msg)
    return RemoteEngineError(f"{etype}: {msg}")


class EngineHost:
    """Serve one engine over the framed-pickle wire.

    Ops: ``ping``, ``predict``, ``depth``, ``stats``, ``drain``,
    ``prefill_push`` (prefill worker: prefill + stream pages to a
    decode host), ``page_probe``/``page_install`` (decode worker:
    pagewire receive side).
    """

    def __init__(self, engine, *, role: str = "decode",
                 name: str = "host", port: int = 0,
                 pagewire_chunk: Optional[int] = None):
        from .. import config
        self.engine = engine
        self.role = str(role)
        self.name = str(name)
        self.pagewire_chunk = int(
            pagewire_chunk if pagewire_chunk is not None
            else config.get("MXFLEET_PAGEWIRE_CHUNK_PAGES"))
        self._lock = make_lock("fleet.worker.host")
        self._threads = []
        self._stopping = False
        self._listener = socket.socket(socket.AF_INET,
                                       socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", int(port)))
        self._listener.listen(64)
        self.address = "%s:%d" % self._listener.getsockname()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"fleet-host-{name}",
            daemon=True)
        self._accept_thread.start()

    # -- server loop ---------------------------------------------------
    def _accept_loop(self):
        while not self._stopping:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _serve(self, conn: socket.socket):
        from ..kvstore_server import _recv_msg, _send_msg
        try:
            while True:
                try:
                    req = _recv_msg(conn)
                except (OSError, EOFError, ConnectionError):
                    return
                try:
                    value = self._dispatch(req.get("op"), req)
                    reply = {"ok": True, "value": value}
                except BaseException as e:  # noqa: BLE001 — every
                    # worker-side failure must reach the caller typed;
                    # the worker process itself stays up
                    reply = {"ok": False,
                             "etype": type(e).__name__,
                             "error": str(e)[:500]}
                try:
                    _send_msg(conn, reply)
                except (OSError, ConnectionError):
                    return
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, op, kw: Dict):
        eng = self.engine
        if op == "ping":
            return {"role": self.role, "name": self.name,
                    "warmed": bool(eng.warmed),
                    "address": self.address}
        if op == "predict":
            return [int(t) for t in eng.predict(
                kw["tokens"], timeout_ms=kw.get("timeout_ms"))]
        if op == "depth":
            return int(eng.queue_depth())
        if op == "stats":
            st = dict(eng.stats())
            st["role"] = self.role
            return st
        if op == "drain":
            return bool(eng.drain(kw.get("timeout")))
        if op == "page_probe":
            # how many leading keys of the chain the local cache holds
            cache = eng.prefix
            if cache is None:
                return 0
            have = 0
            for k in kw["keys"]:
                if cache.find(k) is None:
                    break
                have += 1
            return have
        if op == "page_install":
            from .pagewire import install_chunks
            return install_chunks(eng, kw["keys"], kw["chunks"],
                                  self.pagewire_chunk)
        if op == "prefill_push":
            return self._prefill_push(kw["tokens"], kw.get("dst"))
        raise MXNetError(f"unknown fleet op {op!r}")

    def _prefill_push(self, tokens, dst: Optional[str]) -> Dict:
        """Prefill worker: compute the prompt through the PUBLIC
        engine path (pages land in the local prefix cache), then
        stream the cached pages the destination decode host does not
        already hold."""
        from .pagewire import collect_pages, export_chunks
        eng = self.engine
        h = eng.submit(tokens, max_new_tokens=1)
        h.wait()
        keys, pages = collect_pages(eng, tokens)
        out = {"cached_pages": len(pages), "streamed": 0}
        if not pages or not dst:
            if pages:
                eng.alloc.free(pages)
            return out
        try:
            cli = EngineClient(dst)
            try:
                have = int(cli.request("page_probe", keys=keys))
                send_keys = keys[have:]
                send_pages = pages[have:]
                if send_pages:
                    chunks = export_chunks(eng.lm, send_pages,
                                           self.pagewire_chunk)
                    out["streamed"] = int(cli.request(
                        "page_install", keys=send_keys,
                        chunks=chunks))
            finally:
                cli.close()
        finally:
            eng.alloc.free(pages)
        return out

    def stop(self):
        self._stopping = True
        try:
            self._listener.close()
        except OSError:
            pass


class EngineClient:
    """Blocking framed-pickle client for one EngineHost. One socket,
    serialized by a lock — controller callers that want concurrency
    hold one client per thread (RemoteEngine does)."""

    def __init__(self, address: str, connect_timeout_s: float = 5.0):
        self.address = address
        host, _, port = address.partition(":")
        self._lock = make_lock("fleet.worker.client")
        self._sock = socket.create_connection(
            (host or "127.0.0.1", int(port)),
            timeout=connect_timeout_s)
        # ops block for the remote predict duration — no socket
        # timeout; host death surfaces as ECONNRESET/EOF instead
        self._sock.settimeout(None)

    def request(self, op: str, **kw):
        from ..kvstore_server import _recv_msg, _send_msg
        kw["op"] = op
        with self._lock:
            _send_msg(self._sock, kw)
            reply = _recv_msg(self._sock)
        if reply.get("ok"):
            return reply.get("value")
        raise _typed_remote_error(reply.get("etype", "Exception"),
                                  reply.get("error", ""))

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass


# ----------------------------------------------------------------------
# subprocess entry
# ----------------------------------------------------------------------
def _emit(evt: str, **kw):
    kw["evt"] = evt
    print("FLEET " + json.dumps(kw), flush=True)


def build_engine(*, seed: int, vocab: int, n_layers: int, d_model: int,
                 n_heads: int, page_size: int, num_pages: int,
                 max_inflight: int, max_seq_len: int,
                 pagewire_chunk: int, name: str,
                 prefill_buckets=None):
    """The shared engine recipe: every fleet host builds the SAME
    seeded params (greedy decode is then deterministic fleet-wide —
    the cross-host parity test and the zero-drop retry path both ride
    on it)."""
    from ..parallel.pipeline_lm import init_pipeline_lm
    from ..serve2 import DecodeEngine
    params = init_pipeline_lm(
        int(seed), vocab=vocab, d_model=d_model, n_layers=n_layers,
        n_heads=n_heads, d_head=d_model // n_heads, d_ff=2 * d_model,
        n_experts=2)
    return DecodeEngine(
        params, page_size=page_size, num_pages=num_pages,
        max_inflight=max_inflight, max_seq_len=max_seq_len,
        prefill_buckets=prefill_buckets,
        prefix_cache=True, pagewire_chunk=pagewire_chunk, name=name)


def main(argv=None) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from .. import config
    from ..pod.group import PodGroup

    role = os.environ.get("MXFLEET_ROLE", "decode")
    wid = os.environ.get("MXFLEET_WORKER_ID", f"{role}-{os.getpid()}")
    coord = os.environ.get("MXFLEET_COORDINATOR") \
        or os.environ.get("MXPOD_COORDINATOR") or ""
    beat_s = float(config.get("MXFLEET_HEARTBEAT_S"))
    chunk = int(config.get("MXFLEET_PAGEWIRE_CHUNK_PAGES"))

    stopping = {"flag": False}

    def _on_sigterm(signum, frame):
        stopping["flag"] = True

    signal.signal(signal.SIGTERM, _on_sigterm)

    # per-role pool override (FLEET_PAGES_DECODE / FLEET_PAGES_PREFILL):
    # decode hosts size their pool for batch state + their affinity
    # shard of the template set; a prefill host is a cache host and
    # may be provisioned larger
    pages = int(os.environ.get(f"FLEET_PAGES_{role.upper()}")
                or os.environ.get("FLEET_PAGES", "128"))
    buckets = [int(b) for b in
               os.environ.get("FLEET_BUCKETS", "").split(",")
               if b.strip()] or None
    engine = build_engine(
        seed=int(os.environ.get("FLEET_SEED", "0")),
        vocab=int(os.environ.get("FLEET_VOCAB", "64")),
        n_layers=int(os.environ.get("FLEET_LAYERS", "2")),
        d_model=int(os.environ.get("FLEET_D_MODEL", "32")),
        n_heads=int(os.environ.get("FLEET_HEADS", "2")),
        page_size=int(os.environ.get("FLEET_PAGE", "8")),
        num_pages=pages,
        max_inflight=int(os.environ.get("FLEET_INFLIGHT", "4")),
        max_seq_len=int(os.environ.get("FLEET_MAX_SEQ", "96")),
        pagewire_chunk=chunk, name=f"fleet-{wid}",
        prefill_buckets=buckets)
    engine.warmup()
    host = EngineHost(engine, role=role, name=wid,
                      port=int(os.environ.get("FLEET_PORT", "0")),
                      pagewire_chunk=chunk)
    _emit("ready", worker_id=wid, role=role, address=host.address,
          pid=os.getpid())

    group = PodGroup(coord) if coord else None
    registered = False
    try:
        while not stopping["flag"]:
            if group is not None:
                try:
                    if not registered:
                        group.fleet_register(
                            wid, role, host.address,
                            meta={"pid": os.getpid()})
                        registered = True
                        _emit("registered", worker_id=wid)
                    elif not group.fleet_heartbeat(
                            wid, depth=engine.queue_depth()):
                        # restarted coordinator: empty directory —
                        # announce again
                        registered = False
                        continue
                except Exception as e:  # noqa: BLE001 — keep serving
                    # through control-plane outages; the data plane
                    # is independent
                    _emit("control_plane_error",
                          error=str(e)[:200])
                    registered = False
            time.sleep(beat_s)
        engine.drain(float(os.environ.get("FLEET_DRAIN_S", "10")))
        if group is not None and registered:
            try:
                group.fleet_leave(wid)
            except Exception:
                pass
        _emit("stopped", worker_id=wid)
        return 0
    finally:
        host.stop()
        try:
            engine.close()
        except Exception:
            pass
        if group is not None:
            try:
                group.close()
            except Exception:
                pass


if __name__ == "__main__":
    sys.exit(main())
