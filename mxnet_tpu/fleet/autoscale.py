"""SLO-driven autoscaling over the obs-merged phase histograms.

The decision loop is deliberately small and fully injectable (clock,
metric source, actuator) so the fast tier can drive it with a fake
clock and canned snapshots:

- **signal**: the fleet-merged ``mxtrace_phase_decode_seconds`` p99
  (PR 17's obs collector merges each host's reservoir; PR 10's trace
  phase histograms feed it) — the decode-tick latency users feel;
- **policy**: sustained p99 above MXFLEET_SLO_P99_MS grows the group
  by one replica; p99 under HALF the SLO with idle queues shrinks by
  one — the half-SLO hysteresis band plus a full
  MXFLEET_AUTOSCALE_WINDOW_S cooldown between actuations keeps the
  loop from flapping (a resize is a rolling_reload, not free);
- **actuator**: any ``(n_replicas) -> report`` callable — in the
  fleet that's ``FleetController.resize`` →
  ``Router.rolling_reload(n_replicas=...)``.

SLO unset (MXFLEET_SLO_P99_MS=0, the default) = observability-only:
every tick records a ``hold`` decision with the measured p99, which
tools/diagnose.py surfaces, and nothing actuates.
"""
from __future__ import annotations

import time
from typing import Callable, Dict, Optional

from ..san.runtime import make_lock
from ..telemetry import metrics as _metrics

__all__ = ["AutoScaler", "p99_ms_from_merged"]

DECODE_PHASE_METRIC = "mxtrace_phase_decode_seconds"


def p99_ms_from_merged(doc: Optional[Dict],
                       metric: str = DECODE_PHASE_METRIC
                       ) -> Optional[float]:
    """Pull a phase p99 (milliseconds) out of an obs ``merged()``
    doc; None when the metric has no samples yet."""
    if not doc:
        return None
    ent = (doc.get("merged") or {}).get(metric)
    if not isinstance(ent, dict):
        return None
    p99 = ent.get("p99")
    return float(p99) * 1e3 if p99 is not None else None


class AutoScaler:
    """See module docstring.

    ``source`` returns ``{"p99_ms": float|None, "depth": int,
    "replicas": int}`` per tick (see :meth:`obs_source` for the
    standard obs-collector adapter); ``actuator(n)`` resizes."""

    def __init__(self, source: Callable[[], Dict],
                 actuator: Callable[[int], object], *,
                 slo_p99_ms: Optional[float] = None,
                 window_s: Optional[float] = None,
                 min_replicas: int = 1, max_replicas: int = 64,
                 clock: Callable[[], float] = time.monotonic,
                 note: Optional[Callable[[str, Dict], None]] = None):
        from .. import config
        self.source = source
        self.actuator = actuator
        # optional breadcrumb publisher — wired to the directory's
        # fleet_note so tools/diagnose.py can show the last decision
        # from OUTSIDE the controller process
        self.note = note
        self.slo_p99_ms = float(
            slo_p99_ms if slo_p99_ms is not None
            else config.get("MXFLEET_SLO_P99_MS"))
        self.window_s = float(
            window_s if window_s is not None
            else config.get("MXFLEET_AUTOSCALE_WINDOW_S"))
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self._clock = clock
        self._lock = make_lock("fleet.autoscale")
        self._last_action_mono: Optional[float] = None
        self._last: Dict = {"decision": "hold", "reason": "no ticks",
                            "p99_ms": None, "ts": None}
        self._m_grow = _metrics.counter(
            "mxfleet_autoscale_grow_total",
            "fleet group grow actuations")
        self._m_shrink = _metrics.counter(
            "mxfleet_autoscale_shrink_total",
            "fleet group shrink actuations")

    @staticmethod
    def obs_source(group, router_stats: Callable[[], Dict]):
        """The standard signal adapter: p99 from the coordinator's
        obs-merged doc, depth/replicas from the Router."""
        def _src() -> Dict:
            try:
                doc = group.obs_merged()
            except Exception:  # noqa: BLE001 — no signal = hold
                doc = None
            st = router_stats()
            reps = next(iter(st.get("models", {}).values()),
                        {"replicas": []})["replicas"]
            return {"p99_ms": p99_ms_from_merged(doc),
                    "depth": sum(int(r.get("depth", 0))
                                 for r in reps),
                    "replicas": len(reps)}
        return _src

    def tick(self) -> Dict:
        """One observe-decide-(actuate) cycle. Returns the decision
        record (also kept for :meth:`last_decision`)."""
        obs = self.source() or {}
        p99 = obs.get("p99_ms")
        depth = int(obs.get("depth") or 0)
        replicas = int(obs.get("replicas") or 0)
        now = self._clock()
        decision, reason, target = "hold", "", replicas
        if self.slo_p99_ms <= 0 or self.window_s <= 0:
            reason = "no SLO configured (MXFLEET_SLO_P99_MS=0)"
        elif p99 is None:
            reason = "no decode-phase samples yet"
        elif self._last_action_mono is not None and \
                now - self._last_action_mono < self.window_s:
            reason = (f"cooldown "
                      f"({now - self._last_action_mono:.1f}s of "
                      f"{self.window_s:g}s)")
        elif p99 > self.slo_p99_ms and replicas < self.max_replicas:
            decision, target = "grow", replicas + 1
            reason = (f"p99 {p99:.1f}ms > SLO "
                      f"{self.slo_p99_ms:g}ms")
        elif p99 < 0.5 * self.slo_p99_ms and depth == 0 \
                and replicas > self.min_replicas:
            decision, target = "shrink", replicas - 1
            reason = (f"p99 {p99:.1f}ms < half-SLO with idle queues")
        else:
            reason = f"p99 {p99:.1f}ms within band" if p99 is not None \
                else "steady"
        record = {"decision": decision, "reason": reason,
                  "p99_ms": p99, "depth": depth,
                  "replicas": replicas, "target": target,
                  "ts": time.time()}
        if decision != "hold":
            try:
                self.actuator(target)
                self._last_action_mono = now
                (self._m_grow if decision == "grow"
                 else self._m_shrink).inc()
            except Exception as e:  # noqa: BLE001 — a failed resize
                # must not kill the decision loop
                record["decision"] = "hold"
                record["reason"] = (f"{decision} failed: "
                                    f"{str(e)[:120]}")
        with self._lock:
            self._last = record
        if self.note is not None:
            try:
                self.note("autoscale", record)
            except Exception:  # noqa: BLE001 — breadcrumbs only
                pass
        return record

    def last_decision(self) -> Dict:
        with self._lock:
            return dict(self._last)
