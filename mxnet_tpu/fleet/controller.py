"""FleetController: one serve2 Router group whose replicas live in
OTHER PROCESSES.

:class:`RemoteEngine` is the engine duck type
(``predict/warmup/warmed/queue_depth/stats/drain/close``) over an
:class:`~mxnet_tpu.fleet.worker.EngineClient` — the Router can't tell
it from a local DecodeEngine.  The transport failure contract is the
whole point: a SIGKILLed host surfaces as ``EngineCrashedError``, the
Router breaker-marks the replica and retries the FULL prompt on the
next host (greedy decode is deterministic, so the retry is
bit-identical) — zero in-flight-accepted drops, the same invariant
the single-host rolling-reload soak enforces, now across hosts.

The controller itself is policy glue:

- **membership**: :meth:`sync` reads the coordinator's fleet
  directory (``fleet_view``), drops entries whose heartbeat age
  exceeds 3x MXFLEET_HEARTBEAT_S, and when the live decode set
  changed, resizes/rebuilds the Router group through
  ``rolling_reload(n_replicas=...)`` — replica ``i`` proxies decode
  worker ``i`` in sorted-id order, so the mapping is deterministic;
- **affinity** (:mod:`.routing`): per request, the page-chain
  affinity key rendezvous-picks a decode worker; the Router's
  ``prefer=`` tries it first, capped by the spill threshold computed
  from the directory's advertised depths;
- **disaggregation**: with prefill workers registered and
  MXFLEET_PREFILL_DISAGG on, the prompt goes to a prefill worker
  first (rendezvous by the same key, so ITS cache warms per template
  too), which streams the finished KV pages to the chosen decode
  worker (:mod:`.pagewire`) before the decode request lands.  Any
  failure in that leg just skips it — the decode worker prefills
  locally, which is exactly the single-host path.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

from ..base import get_logger
from ..san.runtime import make_lock
from ..serve2.router import AllReplicasUnavailable, Router
from ..serve2.scheduler import EngineCrashedError
from ..telemetry import metrics as _metrics
from . import routing as _routing
from .worker import EngineClient

__all__ = ["FleetController", "RemoteEngine"]

_log = get_logger("mxnet_tpu.fleet")


class RemoteEngine:
    """Engine duck type over one fleet worker's socket wire.

    A small CONNECTION POOL, not one socket: a remote predict holds
    its connection for the whole generation, and the worker's
    scheduler batches concurrent requests — one shared socket would
    serialize them and throw the engine's continuous batching away.
    A connection that fails is closed, never pooled again."""

    POOL_MAX = 8

    def __init__(self, address: str, name: str = "remote"):
        self.address = address
        self.name = name
        self._lock = make_lock("fleet.controller.remote")
        self._pool: List[EngineClient] = []

    def _acquire(self) -> EngineClient:
        with self._lock:
            if self._pool:
                return self._pool.pop()
        return EngineClient(self.address)

    def _release(self, cli: EngineClient):
        with self._lock:
            if len(self._pool) < self.POOL_MAX:
                self._pool.append(cli)
                return
        cli.close()

    def _request(self, op: str, **kw):
        cli = self._acquire()
        try:
            value = cli.request(op, **kw)
        except BaseException:
            cli.close()
            raise
        self._release(cli)
        return value

    def predict(self, data, timeout_ms: Optional[float] = None):
        tokens = [int(t) for t in _flat(data)]
        try:
            return self._request("predict", tokens=tokens,
                                 timeout_ms=timeout_ms)
        except (OSError, EOFError, ConnectionError) as e:
            # host gone mid-request: the Router treats this exactly
            # like a crashed local scheduler — breaker mark + retry
            # the full prompt on another replica
            raise EngineCrashedError(
                f"fleet worker {self.address} unreachable: {e}") from e

    def queue_depth(self) -> int:
        try:
            return int(self._request("depth"))
        except Exception:  # noqa: BLE001 — a dead host sorts last;
            # the predict attempt will type the failure properly
            return 1 << 20

    @property
    def warmed(self) -> bool:
        return True  # workers warm themselves before registering

    def warmup(self, input_specs=None):
        return []

    def stats(self) -> dict:
        try:
            return dict(self._request("stats"))
        except Exception:  # noqa: BLE001
            return {"name": self.name, "unreachable": True}

    def drain(self, timeout: Optional[float] = None) -> bool:
        # PROXY-local, deliberately: the Router drains a replica
        # before retiring it, but retiring this proxy must NOT stop
        # the remote batcher — the worker outlives group membership
        # (it may be re-proxied under a new replica slot one sync
        # later, and other controllers may be serving through it).
        # In-flight predicts hold their own acquired sockets and the
        # old proxy object, so they complete regardless of when the
        # Router drops its reference.  The wire-level "drain" op
        # stays for the worker's OWN shutdown path (SIGTERM/harness).
        return True

    def close(self):
        # closes the PROXY's sockets only — worker lifecycle belongs
        # to the drill/bench harness, not the router
        with self._lock:
            pool, self._pool = self._pool, []
        for cli in pool:
            cli.close()


def _flat(data):
    import numpy as onp
    arr = onp.asarray(data)
    if arr.ndim == 2 and arr.shape[0] == 1:
        arr = arr[0]
    return arr.reshape(-1)


class FleetController:
    """See module docstring. ``group`` is the coordinator transport
    (PodGroup/RemoteGroup); ``page_size`` must match the workers'."""

    MODEL = "fleet"

    def __init__(self, group, *, page_size: int,
                 heartbeat_s: Optional[float] = None,
                 sync_interval_s: Optional[float] = None):
        from .. import config
        self.group = group
        self.page_size = int(page_size)
        self.heartbeat_s = float(
            heartbeat_s if heartbeat_s is not None
            else config.get("MXFLEET_HEARTBEAT_S"))
        self.sync_interval_s = float(
            sync_interval_s if sync_interval_s is not None
            else self.heartbeat_s)
        self.router = Router(name="fleet")
        self._lock = make_lock("fleet.controller.sync")
        self._decode: List[Dict] = []   # sorted by worker id
        self._prefill: List[Dict] = []
        self._depths: Dict[str, int] = {}
        self._synced_mono = 0.0
        self._m_requests = _metrics.counter(
            "mxfleet_requests_total",
            "requests routed through the fleet controller")
        self._m_affinity = _metrics.counter(
            "mxfleet_affinity_routed_total",
            "requests routed to their prefix-affinity worker")
        self._m_disagg = _metrics.counter(
            "mxfleet_prefill_disagg_total",
            "requests whose prefill ran on a dedicated prefill worker")
        self._m_disagg_miss = _metrics.counter(
            "mxfleet_prefill_fallback_total",
            "requests that fell back to local prefill (no prefill "
            "worker / push failed)")

    # -- membership ----------------------------------------------------
    def sync(self, force: bool = False) -> Dict:
        """Pull the fleet directory and converge the Router group on
        the live decode workers. Cheap when nothing changed."""
        with self._lock:
            now = time.monotonic()
            if not force and self._decode \
                    and now - self._synced_mono < self.sync_interval_s:
                return {"decode": len(self._decode),
                        "prefill": len(self._prefill)}
            view = self.group.fleet_view()
            stale = 3.0 * self.heartbeat_s
            decode, prefill, depths = [], [], {}
            for wid in sorted(view.get("workers", {})):
                ent = view["workers"][wid]
                if float(ent.get("age_s", 0.0)) > stale:
                    continue
                rec = {"wid": wid, "address": ent["address"]}
                depths[wid] = int(ent.get("meta", {})
                                  .get("depth", 0) or 0)
                if ent.get("role") == "prefill":
                    prefill.append(rec)
                else:
                    decode.append(rec)
            self._synced_mono = now
            if decode:
                self._prefill = prefill
                self._depths = depths
                if [d["wid"] for d in decode] != \
                        [d["wid"] for d in self._decode]:
                    self._decode = decode
                    self._rebuild_group()
                else:
                    self._decode = decode
            # no live decode entries = a directory outage or the
            # pre-re-announce window after a coordinator restart:
            # keep the LAST-KNOWN membership picture whole (group,
            # depths, prefill) — the proxies still serve, and
            # describe() must not contradict that
            return {"decode": len(decode), "prefill": len(prefill)}

    def _rebuild_group(self):
        """Converge the Router group on self._decode (under _lock).
        Replica i proxies decode worker i; rolling_reload keeps the
        swap zero-downtime and doubles as the resize actuator."""
        def factory(version, replica):
            ent = self._decode[replica]
            return RemoteEngine(ent["address"],
                                name=f"fleet/{ent['wid']}")
        n = len(self._decode)
        if self.MODEL not in self.router.models():
            self.router.add_group(self.MODEL, factory, n_replicas=n,
                                  warmup=False)
        else:
            grp = self.router._group(self.MODEL)
            grp.factory = factory
            self.router.rolling_reload(self.MODEL, n_replicas=n)
        _log.info("fleet group converged on %d decode workers: %s",
                  n, [d["wid"] for d in self._decode])

    def resize(self, n_replicas: int) -> dict:
        """The autoscale actuator: resize the Router group. The fleet
        can only shrink below its registered worker count (proxies are
        dropped, workers stay up for the next grow) — growing beyond
        it requires more registered hosts, so the target is capped."""
        with self._lock:
            n = max(1, min(int(n_replicas), len(self._decode)))
            report = self.router.rolling_reload(self.MODEL,
                                                n_replicas=n)
        try:
            self.group.fleet_note("last_resize", {
                "target": n, "ts": time.time()})
        except Exception:  # noqa: BLE001 — breadcrumbs only
            pass
        return report

    # retry cadence when every replica refused: re-sync the directory
    # (the refusals may reflect a membership change we haven't
    # converged on yet) and back off briefly before the next pass
    RETRY_BACKOFF_S = 0.2
    DEFAULT_RETRY_BUDGET_S = 15.0

    # -- serving -------------------------------------------------------
    def predict(self, data, timeout_ms: Optional[float] = None):
        """Route one request.  ``AllReplicasUnavailable`` is absorbed
        with bounded retries inside the request's deadline budget: a
        host loss opens a breaker window / membership-rebuild window
        during which one Router pass can find every replica refusing,
        but an ACCEPTED request must ride that out — the zero-drop
        invariant the fleet drill enforces."""
        self._m_requests.inc()
        deadline = time.monotonic() + (
            float(timeout_ms) / 1e3 if timeout_ms is not None
            else self.DEFAULT_RETRY_BUDGET_S)
        while True:
            try:
                return self._predict_once(data, timeout_ms=timeout_ms)
            except AllReplicasUnavailable:
                if time.monotonic() + self.RETRY_BACKOFF_S >= deadline:
                    raise
                time.sleep(self.RETRY_BACKOFF_S)
                try:
                    self.sync(force=True)
                except Exception:  # noqa: BLE001 — directory outage
                    # must not turn a retryable refusal into a crash;
                    # the next Router pass uses the last-known group
                    pass

    def _predict_once(self, data, timeout_ms: Optional[float] = None):
        from .. import config
        self.sync()
        tokens = [int(t) for t in _flat(data)]
        prefer = None
        cap = None
        target = None
        with self._lock:
            decode = list(self._decode)
            prefill = list(self._prefill)
            depths = dict(self._depths)
        key = None
        if bool(config.get("MXFLEET_AFFINITY")) and decode:
            key = _routing.affinity_key(tokens, self.page_size)
        if key is not None:
            wids = [d["wid"] for d in decode]
            pick = _routing.rendezvous_pick(key, wids)
            if pick is not None:
                idx = wids.index(pick)
                target = decode[idx]
                prefer = f"{self.MODEL}/r{idx}"
                shallowest = min(
                    (depths.get(w, 0) for w in wids), default=0)
                cap = _routing.spill_cap(shallowest)
                self._m_affinity.inc()
        if bool(config.get("MXFLEET_PREFILL_DISAGG")) and prefill \
                and len(tokens) >= self.page_size:
            self._push_prefill(tokens, key, prefill,
                               target or (decode[0] if decode
                                          else None))
        return self.router.predict(self.MODEL, tokens,
                                   timeout_ms=timeout_ms,
                                   prefer=prefer,
                                   prefer_max_depth=cap)

    def _push_prefill(self, tokens, key, prefill, target):
        """Disaggregation leg: prefill on a dedicated worker, pages
        streamed to the chosen decode worker. Best-effort — every
        failure path is a silent local-prefill fallback."""
        if target is None:
            self._m_disagg_miss.inc()
            return
        wids = [p["wid"] for p in prefill]
        pick = _routing.rendezvous_pick(key or bytes(8), wids)
        ent = prefill[wids.index(pick)]
        try:
            cli = EngineClient(ent["address"])
            try:
                cli.request("prefill_push", tokens=tokens,
                            dst=target["address"])
            finally:
                cli.close()
            self._m_disagg.inc()
        except Exception:  # noqa: BLE001 — optimization only
            self._m_disagg_miss.inc()

    # -- introspection -------------------------------------------------
    def describe(self) -> dict:
        with self._lock:
            return {
                "decode": [dict(d) for d in self._decode],
                "prefill": [dict(p) for p in self._prefill],
                "depths": dict(self._depths),
                "router": self.router.stats(),
            }

    def heartbeat_note(self):
        """Publish controller liveness into the directory notes (the
        tools/diagnose.py mxfleet section reads it)."""
        try:
            self.group.fleet_note("controller", {
                "ts": time.time(),
                "decode": len(self._decode),
                "prefill": len(self._prefill)})
        except Exception:  # noqa: BLE001
            pass

    def close(self):
        try:
            self.router.close()
        except Exception:  # noqa: BLE001
            pass
