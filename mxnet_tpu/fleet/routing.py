"""Prefix-affinity routing policy (mechanism lives in serve2.Router).

The affinity key of a prompt is one of its ``serve2.prefix.page_keys``
chain hashes: hash ``i`` commits to every token of pages ``0..i``, so
the key of page ``MXFLEET_AFFINITY_PAGES - 1`` identifies the whole
leading template.  Two prompts sharing that template share the key,
rendezvous-hash to the same decode worker, and the second one finds
its KV pages already in that worker's prefix cache — PR 11's
per-engine cache made fleet-wide without any shared state.

Rendezvous (highest-random-weight) hashing rather than a modulo ring:
adding or removing one worker remaps only the keys that pointed AT the
departed worker, so a host loss doesn't shuffle the whole fleet's
cache locality.  Everything here is pure policy over SHA-1 digests —
deterministic across interpreter processes (page_keys never touches
the salted builtin ``hash()``; test_fleet enforces cross-process
stability).
"""
from __future__ import annotations

import hashlib
from typing import List, Optional, Sequence

from ..serve2.prefix import page_keys

__all__ = ["affinity_key", "rendezvous_pick", "spill_cap"]


def affinity_key(tokens: Sequence[int], page_size: int,
                 n_pages: Optional[int] = None) -> Optional[str]:
    """The prompt's affinity key: the chain hash of its
    ``min(n_pages, full_pages)``-th page, or None for prompts shorter
    than one page (no cacheable prefix — route by queue depth
    alone)."""
    if n_pages is None:
        from .. import config
        n_pages = int(config.get("MXFLEET_AFFINITY_PAGES"))
    keys = page_keys(tokens, page_size)
    if not keys:
        return None
    return keys[:max(1, int(n_pages))][-1]


def _hexkey(key) -> str:
    return key.hex() if isinstance(key, (bytes, bytearray)) \
        else str(key)


def rendezvous_pick(key, workers: Sequence[str]) -> Optional[str]:
    """Highest-random-weight pick of one worker id for ``key``
    (bytes digest or str). Deterministic in (key, worker set) and
    independent of the sequence's order."""
    if not workers:
        return None
    k = _hexkey(key)
    return max(sorted(workers), key=lambda w: hashlib.sha1(
        f"{k}|{w}".encode()).digest())


def rendezvous_rank(key, workers: Sequence[str]) -> List[str]:
    """All workers, best-first — the failover order that preserves
    affinity stability when the first choice is saturated."""
    k = _hexkey(key)
    return sorted(sorted(workers), key=lambda w: hashlib.sha1(
        f"{k}|{w}".encode()).digest(), reverse=True)


def spill_cap(shallowest_depth: int,
              factor: Optional[float] = None) -> Optional[int]:
    """Translate MXFLEET_SPILL_FACTOR into the Router's absolute
    ``prefer_max_depth``: the preferred worker keeps the request while
    its depth <= factor * shallowest + 1.  ``factor == 0`` means never
    spill (strict affinity), returned as None — the Router's
    unconditional-prefer value."""
    if factor is None:
        from .. import config
        factor = float(config.get("MXFLEET_SPILL_FACTOR"))
    if factor <= 0:
        return None
    return int(factor * max(0, int(shallowest_depth)) + 1)
