"""mxfleet: pod-scale disaggregated serving (PR 18).

The serving control plane layered over what already exists — it owns
no model math and no transport primitives of its own:

- **replica groups over pod hosts** (:mod:`.controller`): a
  :class:`~mxnet_tpu.fleet.controller.FleetController` rides the
  journaled coordinator's fleet directory (``fleet_register`` /
  ``fleet_view`` ops over the PodGroup typed-fence transport) and
  fronts one serve2 :class:`~mxnet_tpu.serve2.router.Router` group of
  :class:`~mxnet_tpu.fleet.controller.RemoteEngine` proxies, so the
  shallowest-queue + breaker + failover semantics extend across host
  processes unchanged — a SIGKILLed host surfaces as
  ``EngineCrashedError``, breaker-marks, and the request retries on a
  live host (zero in-flight-accepted drops, drill-enforced);
- **prefill/decode disaggregation** (:mod:`.pagewire`,
  :mod:`.worker`): dedicated prefill workers compute prompts and
  stream the finished KV pages (serve3's quantized-page pool planes,
  ``PagedLM.export_pages``/``import_pages``) to the chosen decode
  worker over the framed-pickle socket wire — CPU host-transfer path;
  the TPU device-to-device DMA is stubbed;
- **prefix-affinity routing** (:mod:`.routing`): the
  ``serve2.prefix.page_keys`` chain hash (deterministic across
  processes — test-enforced) keys a rendezvous pick, so templated
  prompts land where their pages already live; the Router's
  ``prefer=`` mechanism applies it with a spill cap
  (MXFLEET_SPILL_FACTOR) so locality never buys a convoy;
- **SLO autoscaling** (:mod:`.autoscale`): grow/shrink decisions from
  the obs-merged ``mxtrace_phase_decode_seconds`` p99 against
  MXFLEET_SLO_P99_MS, actuated through
  ``Router.rolling_reload(n_replicas=...)``.

Flags-off (no ``MXFLEET_*`` set, nothing from this package imported)
the serving path is bit-for-bit the PR 11 single-host router: the
only serve2/ changes are default-``None`` keyword arguments and a
default-0 warmup chunk.  See docs/fleet.md.
"""
from .autoscale import AutoScaler
from .controller import FleetController, RemoteEngine
from .routing import affinity_key, rendezvous_pick, spill_cap
from .worker import EngineClient, EngineHost

__all__ = ["AutoScaler", "FleetController", "RemoteEngine",
           "EngineClient", "EngineHost", "affinity_key",
           "rendezvous_pick", "spill_cap"]
