"""The pagewire: KV-page streaming between engine hosts.

Send side (a prefill worker): the prompt was just prefilled through
the PUBLIC engine path (``submit(prompt, max_new_tokens=1)``), so its
full pages sit in the worker's own prefix cache under their chain
keys.  :func:`collect_pages` pins them (``PrefixCache.lookup`` —
caller-owned refs) and :func:`export_chunks` gathers their pool
planes (K, V, int8 scales) through ``PagedLM.export_pages`` in
fixed-size chunks — one warmed jit program per chunk size, never a
recompile mid-stream.

Receive side (the chosen decode worker): :func:`install_chunks`
allocates pages, scatters the planes in through
``PagedLM.import_pages``, registers the chain keys in the local
prefix cache, and drops its own allocation refs — exactly the
refcount dance of a local admission, so the page-accounting audit
stays clean.  Installation is an OPTIMIZATION: any failure (pool
pressure, size mismatch, a dead sender) installs nothing and the
decode worker simply prefills the prompt locally — correctness never
depends on the wire.

Chunk padding contract (both sides): a short tail repeats the FINAL
real page index, never page 0 — the null page's content is scratch,
and a duplicate index carries a duplicate plane row so whichever
scatter write wins is the same value.

This is the CPU host-transfer path (numpy planes over the
framed-pickle socket wire).  On TPU the planes should move
device-to-device (ICI DMA) without touching the host — stubbed until
a multi-host device mesh exists in CI: :func:`device_transfer_stub`
raises with the design note.
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as onp

from ..base import MXNetError
from ..telemetry import metrics as _metrics

__all__ = ["collect_pages", "export_chunks", "install_chunks",
           "device_transfer_stub"]

_m_pages_sent = _metrics.counter(
    "mxfleet_pagewire_pages_sent_total",
    "KV pages exported onto the pagewire by prefill workers")
_m_pages_installed = _metrics.counter(
    "mxfleet_pagewire_pages_installed_total",
    "KV pages installed from the pagewire into a decode worker's "
    "prefix cache")
_m_install_skips = _metrics.counter(
    "mxfleet_pagewire_install_skips_total",
    "pagewire installs skipped whole (pool pressure / shape "
    "mismatch) — the decode worker prefills locally instead")


def collect_pages(engine, tokens: Sequence[int]
                  ) -> Tuple[List[bytes], List[int]]:
    """Pin the cached pages of ``tokens``' full-page prefix in
    ``engine``'s prefix cache.  Returns ``(keys, pages)`` of equal
    length (the cached-coverage prefix of the chain); the caller owns
    one allocator ref per page and MUST ``engine.alloc.free(pages)``
    after exporting."""
    from ..serve2.prefix import page_keys
    if engine.prefix is None:
        return [], []
    keys = page_keys(tokens, engine.page_size)
    pages = engine.prefix.lookup(keys)
    return keys[:len(pages)], pages


def export_chunks(lm, pages: Sequence[int], chunk: int
                  ) -> List[Tuple[int, Dict[str, onp.ndarray]]]:
    """Gather ``pages``' pool planes in fixed-``chunk`` dispatches.
    Returns ``[(real_count, planes), ...]`` ready for the wire."""
    chunk = int(chunk)
    if chunk < 1:
        raise MXNetError("pagewire chunk must be >= 1")
    out = []
    for s in range(0, len(pages), chunk):
        part = list(pages[s:s + chunk])
        count = len(part)
        padded = part + [part[-1]] * (chunk - count)
        out.append((count, lm.export_pages(padded)))
        _m_pages_sent.inc(count)
    return out


def install_chunks(engine, keys: Sequence[bytes],
                   chunks: Sequence[Tuple[int, Dict[str, onp.ndarray]]],
                   chunk: int) -> int:
    """Install streamed planes under ``keys`` in ``engine``'s prefix
    cache (the receive side).  All-or-nothing: returns the number of
    pages installed, 0 when the install was skipped (no cache, pool
    pressure, or a count mismatch).  Safe against the live scheduler —
    cache, allocator, and pool dispatch all carry their own locks."""
    cache = engine.prefix
    if cache is None or not keys:
        return 0
    n = len(keys)
    if sum(c for c, _ in chunks) != n:
        _m_install_skips.inc()
        return 0
    # the sender probed our coverage before exporting, but a
    # concurrent local admission may have cached some of these keys
    # since; register() would keep the existing entries anyway, so an
    # overlapping install only wastes wire+import work — skip it and
    # let the (rare) race resolve as a local prefill
    if any(cache.find(k) is not None for k in keys):
        _m_install_skips.inc()
        return 0
    alloc = engine.alloc
    if not alloc.can_alloc(n):
        _m_install_skips.inc()
        return 0
    pages = alloc.alloc(n)
    dst = list(pages)
    pos = 0
    try:
        for count, planes in chunks:
            part = dst[pos:pos + count]
            padded = part + [part[-1]] * (int(chunk) - count)
            engine.lm.import_pages(padded, planes)
            pos += count
    except Exception:
        alloc.free(pages)
        _m_install_skips.inc()
        raise
    cache.register(list(keys), dst)
    alloc.free(pages)
    _m_pages_installed.inc(n)
    return n


def device_transfer_stub(*_a, **_k):
    """TPU device-to-device page transfer — NOT implemented.

    On a multi-host TPU mesh the planes should move over ICI via a
    device-resident collective permute (source worker's pool slice ->
    destination worker's pool slice) without a host round-trip; CI has
    a single CPU host, so the pagewire ships numpy planes over the
    socket wire instead.  Raises so a misconfigured TPU deployment
    fails loudly rather than silently staging through host memory."""
    raise NotImplementedError(
        "pagewire device-to-device transfer is stubbed: CPU CI ships "
        "planes over the socket wire; wire up an ICI collective "
        "permute before enabling this path on a TPU pod")
