"""Pod-merged metrics: the rank-0 collector channel.

Each host's elastic heartbeat pump pushes one *mergeable* snapshot
(:func:`telemetry.metrics.mergeable_snapshot`) every
``MXOBS_PUSH_INTERVAL_S`` over the control socket (``obs_push`` — no
extra thread, no extra connection). The coordinator hands the
snapshots to one :class:`MetricsCollector`, which answers the question
per-process registries cannot: *what is the pod-wide step p99?*

Merge semantics (docs/observability.md, benchmarked exact):

- counters and gauges sum across ranks (fleet totals — steps taken,
  live bytes; per-rank values stay available under rank labels for
  the instruments where a sum is meaningless);
- histograms merge EXACTLY on count/sum/min/max and by count-weighted
  reservoir sampling on the quantile window
  (:meth:`~mxnet_tpu.telemetry.metrics.Histogram.merge`) — the merged
  ``count`` equals the sum of the per-rank counts, bit for bit.

Lifecycle follows the PR 12 metriclint owner-token contract: the
collector adopts its pod-scope instruments (host-count gauge, push
counter, one freshness gauge per rank) at construction, retires a
rank's gauge the moment the membership plane drops the host, and
closes the token with :meth:`close` — ``passes/obslint.py`` flags any
collector that skips the retirement declaration.
"""
from __future__ import annotations

import json
import time
import weakref
from typing import Dict, List, Optional

from ..san.runtime import make_lock
from ..telemetry import metrics as _metrics

__all__ = ["MetricsCollector", "live_collectors", "fleet_probe"]

# live-instance ledger for the obslint live path and tools/diagnose.py
# (weak: a dropped collector must not be kept alive by its audit)
_LIVE: "weakref.WeakSet" = weakref.WeakSet()


def live_collectors() -> List["MetricsCollector"]:
    return list(_LIVE)


class _HostState:
    __slots__ = ("rank", "snap", "wall", "mono", "pushes")

    def __init__(self, rank):
        self.rank = rank
        self.snap: Dict[str, dict] = {}
        self.wall = 0.0
        self.mono = 0.0
        self.pushes = 0


class MetricsCollector:
    """See module docstring. One per coordinator; thread-safe."""

    def __init__(self, name: str = "pod"):
        self.name = str(name)
        self._lock = make_lock("obs.collector")
        self._hosts: Dict[str, _HostState] = {}
        self.closed = False
        self._m_hosts = _metrics.gauge(
            "mxobs_collector_hosts",
            "hosts with a live metrics snapshot on the pod collector")
        self._m_pushes = _metrics.counter(
            "mxobs_pushes_total",
            "per-host metrics snapshots received by the collector")
        self.token = _metrics.owner(f"obs.collector.{self.name}")
        self.token.adopt(self._m_hosts, self._m_pushes)
        _LIVE.add(self)

    # -- the push channel ----------------------------------------------
    @staticmethod
    def _age_gauge_name(rank) -> str:
        return f"mxobs_push_age_seconds_r{rank}"

    def push(self, worker_id: str, rank, snap) -> None:
        """Record one host's mergeable snapshot (coordinator-side of
        the ``obs_push`` control-plane op). Never raises — telemetry
        must not take down the control plane."""
        try:
            if self.closed or not isinstance(snap, dict):
                return
            rank = int(rank) if rank is not None else -1
            with self._lock:
                st = self._hosts.get(worker_id)
                if st is None:
                    st = self._hosts[worker_id] = _HostState(rank)
                    # per-rank freshness gauge: registered on first
                    # push, ADOPTED by the collector token, retired on
                    # host departure (the recurring gauge-leak class)
                    self.token.adopt(_metrics.gauge(
                        self._age_gauge_name(rank),
                        f"seconds since rank {rank}'s last metrics "
                        "push reached the pod collector"))
                st.rank = rank
                st.snap = snap
                st.wall = time.time()
                st.mono = time.monotonic()
                st.pushes += 1
                self._m_hosts.set(len(self._hosts))
            self._m_pushes.inc()
            _metrics.gauge(self._age_gauge_name(rank)).set(0.0)
        except Exception:  # noqa: BLE001
            pass

    def retire(self, worker_id: str) -> None:
        """Drop a departed host's snapshot and unregister its per-rank
        gauge (leave / mark_lost call this — a dead host must not keep
        publishing a fresh-looking age in /metrics)."""
        with self._lock:
            st = self._hosts.pop(worker_id, None)
            self._m_hosts.set(len(self._hosts))
        if st is not None:
            _metrics.unregister(self._age_gauge_name(st.rank))

    # -- the merged view -----------------------------------------------
    def merged(self) -> Dict[str, object]:
        """The pod-wide snapshot: fleet-merged values plus per-rank
        sections. Histogram counts are the EXACT sum of the per-rank
        counts (the 2-process smoke asserts this bit-for-bit)."""
        now = time.monotonic()
        with self._lock:
            hosts = {w: (st.rank, st.snap, st.wall, now - st.mono,
                         st.pushes)
                     for w, st in self._hosts.items()}
        merged: Dict[str, object] = {}
        kinds: Dict[str, str] = {}
        hists: Dict[str, _metrics.Histogram] = {}
        per_rank: Dict[str, Dict[str, object]] = {}
        for w in sorted(hosts):
            rank, snap, wall, age, pushes = hosts[w]
            _metrics.gauge(self._age_gauge_name(rank)).set(age)
            rank_vals: Dict[str, object] = {}
            for name, entry in snap.items():
                kind = entry.get("kind", "untyped")
                kinds[name] = kind
                if kind == "histogram":
                    h = hists.get(name)
                    if h is None:
                        # detached instance: merged state must not
                        # pollute the rank-0 process registry
                        h = hists[name] = _metrics.Histogram(name)
                    h.merge(entry)
                    rank_vals[name] = {
                        "count": entry.get("count", 0),
                        "sum": entry.get("sum", 0.0)}
                else:
                    v = entry.get("value", 0)
                    rank_vals[name] = v
                    merged[name] = (merged.get(name) or 0) + v
            per_rank[str(rank)] = {
                "worker": w, "age_s": round(age, 3), "pushes": pushes,
                "wall": wall, "metrics": rank_vals}
        for name, h in hists.items():
            merged[name] = h.value()
        return {"ts": time.time(), "hosts": len(hosts),
                "kinds": kinds, "merged": merged, "ranks": per_rank}

    # -- exporters -----------------------------------------------------
    def export_jsonl(self, path: Optional[str] = None) -> bool:
        """Append one merged-snapshot line to ``path`` (default: the
        ``MXOBS_EXPORT`` flag). Never raises; False when off/failed."""
        if path is None:
            from .. import config
            path = str(config.get("MXOBS_EXPORT") or "")
        if not path:
            return False
        try:
            with open(path, "a") as f:
                f.write(json.dumps(self.merged()) + "\n")
            return True
        except (OSError, TypeError, ValueError):
            return False

    def to_prometheus(self) -> str:
        """Prometheus text form of the merged view, per-rank series
        labeled ``{rank="k"}`` next to each ``_pod``-suffixed fleet
        aggregate."""
        doc = self.merged()
        lines: List[str] = []
        for name in sorted(doc["merged"]):
            kind = doc["kinds"].get(name, "untyped")
            v = doc["merged"][name]
            if isinstance(v, dict):  # histogram
                lines.append(f"# TYPE {name}_pod summary")
                lines.append(f"{name}_pod_count {v.get('count', 0)}")
                lines.append(f"{name}_pod_sum {v.get('sum', 0.0)}")
                if v.get("count"):
                    lines.append(
                        f'{name}_pod{{quantile="0.5"}} {v["p50"]}')
                    lines.append(
                        f'{name}_pod{{quantile="0.99"}} {v["p99"]}')
            else:
                lines.append(f"# TYPE {name}_pod {kind}")
                lines.append(f"{name}_pod {v}")
            for rank in sorted(doc["ranks"]):
                rv = doc["ranks"][rank]["metrics"].get(name)
                if rv is None:
                    continue
                if isinstance(rv, dict):
                    lines.append(f'{name}_count{{rank="{rank}"}} '
                                 f'{rv.get("count", 0)}')
                    lines.append(f'{name}_sum{{rank="{rank}"}} '
                                 f'{rv.get("sum", 0.0)}')
                else:
                    lines.append(f'{name}{{rank="{rank}"}} {rv}')
        return "\n".join(lines) + "\n"

    # -- lifecycle -----------------------------------------------------
    def describe(self) -> Dict[str, object]:
        with self._lock:
            return {"name": self.name, "closed": self.closed,
                    "hosts": {w: {"rank": st.rank, "pushes": st.pushes}
                              for w, st in sorted(self._hosts.items())},
                    "owner": self.token.describe()}

    def ranks(self) -> List[int]:
        with self._lock:
            return sorted(st.rank for st in self._hosts.values())

    def close(self) -> None:
        """Retire every pod-scope instrument and close the owner token
        — the declaration obslint audits."""
        with self._lock:
            if self.closed:
                return
            self.closed = True
            hosts = list(self._hosts.values())
            self._hosts.clear()
        for st in hosts:
            _metrics.unregister(self._age_gauge_name(st.rank))
        _metrics.unregister(self._m_hosts.name)
        _metrics.unregister(self._m_pushes.name)
        self.token.close()

    def __repr__(self):
        return (f"<MetricsCollector {self.name!r} "
                f"{len(self._hosts)} host(s)"
                f"{' closed' if self.closed else ''}>")


def fleet_probe(collector: MetricsCollector, stale_factor: float = 3.0):
    """Watchdog probe reading FLEET state: one ``obs-push-stale``
    finding per host whose last snapshot is older than
    ``stale_factor x MXOBS_PUSH_INTERVAL_S`` — the early signal (a
    wedged pump, a paused host) that fires BEFORE the heartbeat budget
    turns it into a host-loss verdict. Wire via
    ``ElasticCoordinator.attach_watchdog``."""
    from ..passes import Finding

    def probe():
        from .. import config
        budget = max(0.1, float(config.get("MXOBS_PUSH_INTERVAL_S"))
                     * stale_factor)
        now = time.monotonic()
        out = []
        with collector._lock:
            hosts = {w: (st.rank, now - st.mono)
                     for w, st in collector._hosts.items()}
        for w, (rank, age) in sorted(hosts.items()):
            if age > budget:
                out.append(Finding(
                    "watchdog", "obs-push-stale", f"obs.r{rank}",
                    "warn",
                    f"rank {rank} ({w!r}) last pushed metrics "
                    f"{age:.2f}s ago (budget {budget:.2f}s = "
                    f"{stale_factor:g}x MXOBS_PUSH_INTERVAL_S) — "
                    "pump wedged or host paused; fleet snapshots are "
                    "going stale before the heartbeat verdict"))
        return out

    return probe
