"""Coordinated flight-recorder capture: one trigger, every rank dumps.

A pod post-mortem used to mean hand-collecting N uncorrelated flight
dumps — and the ranks that *didn't* crash never dumped at all, losing
exactly the surviving-side timeline that explains a quarantine or a
host loss. Now rank 0 owns a **dump epoch** on the coordinator
(:meth:`ElasticCoordinator.request_dump` — bumped by the watchdog
verdict handler, the host-loss poll, ``GroupFailed``/quarantine at the
leader boundary, or an operator via ``obs_request_dump``): the epoch
rides the heartbeat flags every worker already polls, each worker's
:class:`DumpFollower` notices the advance and freezes its local
recorder (``crash_dump`` — rank-tagged filename, shared
``MXTRACE_DUMP_DIR``), and the post-mortem directory holds every live
rank's last spans + metrics from ONE trigger. See the coordinated-dump
runbook in docs/observability.md.
"""
from __future__ import annotations

from typing import Optional

__all__ = ["DumpFollower"]


class DumpFollower:
    """Worker-side epoch tracker. Feed it every heartbeat's flags
    (:meth:`ElasticSession` does); an epoch advance triggers one local
    flight-recorder dump. Not thread-safe per instance — each session
    owns one and calls it from its beat paths (a raced duplicate
    observe is absorbed by the recorder's per-reason rate limit)."""

    __slots__ = ("epoch", "last_path")

    def __init__(self):
        self.epoch = 0
        self.last_path: Optional[str] = None

    def observe(self, flags) -> Optional[str]:
        """Returns the dump path when this observation triggered one
        (None: no advance, obs off, or rate-limited). A follower that
        first hears of a non-zero epoch dumps too — 'dump-all' must
        include late joiners while the incident is still warm."""
        if not isinstance(flags, dict):
            return None
        ep = flags.get("dump_epoch")
        if not ep:
            return None
        ep = int(ep)
        if ep <= self.epoch:
            return None
        self.epoch = ep
        from . import propagate as _prop
        if not _prop.enabled():
            return None
        from ..trace import crash_dump
        reason = str(flags.get("dump_reason") or "requested")
        path = crash_dump(f"pod-dump-{reason}", site="obs.capture",
                          extra={"dump_epoch": ep})
        if path:
            self.last_path = path
        return path
