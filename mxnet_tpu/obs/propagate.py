"""Cross-host mxtrace context propagation (the mxobs wire layer).

Trace ids are process-local by construction (``spans._new_trace_id``
prefixes a per-process session nonce), so without help every rank of
one logical train step roots its own trace and a pod post-mortem is N
uncorrelated trees. Two mechanisms repair that, both behind the
``MXOBS`` flag with the mxtrace zero-cost-off discipline (one
generation-keyed flag-cache read on the hot path):

- **carried context** — :func:`wire_context` packs the caller's
  ambient :class:`~mxnet_tpu.trace.SpanContext` into a tiny dict that
  rides every control-plane request (``RemoteGroup._req`` attaches it
  as ``_trace``); the rank-0 server :func:`bind`\\ s it back and runs
  the coordinator op under it, so fenced rounds, rebuild barriers and
  guard votes show up as children INSIDE the calling rank's trace;
- **derived identity** — :func:`pod_step_context` computes the SAME
  (trace_id, root span_id) on every rank from control-plane state
  (the coordinator's group uid + generation + step), each rank's
  ``train.step`` parents under it, and the leader retroactively emits
  the shared ``pod.step`` root (:func:`emit_pod_root`) — so the
  per-rank span files stitch into ONE rooted tree under
  ``mxprof trace --dir`` with zero orphans.

Nothing here touches jit cache keys: propagation can never recompile.
"""
from __future__ import annotations

from typing import Dict, Optional

from ..trace import spans as _spans
from ..trace.spans import SpanContext

__all__ = ["enabled", "wire_context", "bind", "pod_step_context",
           "emit_pod_root"]

# (config generation, MXOBS) — same pattern as trace.spans._flags
_FLAG_CACHE = (-1, True)


def _obs_on() -> bool:
    global _FLAG_CACHE
    config = _spans._cfg()
    gen = config.generation()
    cached = _FLAG_CACHE
    if cached[0] == gen:
        return cached[1]
    on = bool(config.get("MXOBS"))
    _FLAG_CACHE = (gen, on)
    return on


def enabled() -> bool:
    """The one hot-path gate: MXOBS and MXTRACE both on. Two cached
    flag reads — MXOBS=0 (or MXTRACE=0) makes every propagation site
    structurally free (no wire fields, no binds, no pod roots)."""
    return _obs_on() and _spans.enabled()


def wire_context() -> Optional[Dict[str, str]]:
    """The caller's ambient span context in wire form (``{"t":
    trace_id, "s": span_id}``), or None when there is nothing to
    carry: obs/tracing off, no ambient span, or the trace was dropped
    by sampling (unsampled contexts stay process-local — the remote
    side could only produce spans that would be discarded here)."""
    if not enabled():
        return None
    ctx = _spans._CURRENT.get()
    if ctx is None or not ctx.sampled:
        return None
    return {"t": ctx.trace_id, "s": ctx.span_id}


def bind(wire) -> Optional[SpanContext]:
    """Rehydrate a :func:`wire_context` dict on the receiving side;
    None when obs is off here or the payload is malformed (a newer
    worker talking to an older server must degrade to local traces,
    never crash the control plane)."""
    if not enabled() or not isinstance(wire, dict):
        return None
    tid = wire.get("t")
    sid = wire.get("s")
    if not tid or not sid:
        return None
    return SpanContext(str(tid), str(sid), True)


def _pod_ids(uid: str, generation: int, step: int):
    tid = f"pod{uid}g{int(generation)}s{int(step)}"
    return tid, f"{tid}.root"


def pod_step_context(uid: Optional[str], generation: int,
                     step: int) -> Optional[SpanContext]:
    """The DERIVED shared identity of one pod-wide train step: every
    rank computes the same (trace_id, root span_id) from the group uid
    the coordinator handed out at registration, so their ``train.step``
    spans land in one trace without any rendezvous. None when obs is
    off or the session has no pod identity (single-process runs keep
    plain per-process traces)."""
    if not uid or not enabled():
        return None
    tid, sid = _pod_ids(uid, generation, step)
    return SpanContext(tid, sid, True)


def emit_pod_root(uid: str, generation: int, step: int,
                  t0_ns: int, t1_ns: int, **attrs):
    """Leader-only: retroactively record the shared ``pod.step`` root
    span (explicit identity via :func:`~mxnet_tpu.trace.emit_root`)
    the other ranks' step trees already parent under. Exactly ONE rank
    must emit it per (generation, step) or the stitched tree grows
    duplicate roots."""
    if not enabled():
        return None
    tid, sid = _pod_ids(uid, generation, step)
    return _spans.emit_root("pod.step", "pod", t0_ns, t1_ns, tid, sid,
                            attrs=attrs or None)
