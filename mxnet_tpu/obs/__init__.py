"""mxobs: the pod-scale observability plane (ISSUE 17).

PR 12 built per-process observability (mxtrace spans, the flight
recorder, the metrics registry); PR 15 moved training into real host
processes. This package closes the gap between them:

- :mod:`~mxnet_tpu.obs.propagate` — cross-host trace propagation:
  control-plane messages carry the caller's span context, and every
  rank derives one shared ``pod.step`` root per (group uid,
  generation, step), so a pod-wide train step / rebuild / guard vote
  is ONE trace id stitched by ``mxprof trace --dir``;
- :mod:`~mxnet_tpu.obs.collector` — pod-merged metrics: hosts push
  mergeable snapshots over the heartbeat channel, rank 0 merges them
  (histogram counts exactly; owner-token lifecycle audited by
  ``passes/obslint.py``) and exports JSON-lines / Prometheus with
  per-rank labels;
- :mod:`~mxnet_tpu.obs.capture` — coordinated flight-recorder
  capture: one rank-0 dump trigger broadcasts over the heartbeat
  flags and every live rank freezes its recorder into the shared,
  rank-named dump directory.

Everything is behind ``MXOBS`` with the mxtrace cost discipline:
structurally zero-cost off, <2% on (``bench.py --obs-overhead``),
never touches jit cache keys. docs/observability.md has the multi-host
section; ``tools/benchstore.py`` + ``mxprof regress`` are the
perf-trajectory half of the plane.
"""
from __future__ import annotations

from . import capture, collector, propagate  # noqa: F401
from .capture import DumpFollower  # noqa: F401
from .collector import MetricsCollector, fleet_probe  # noqa: F401
from .collector import live_collectors  # noqa: F401
from .propagate import (bind, emit_pod_root, enabled,  # noqa: F401
                        pod_step_context, wire_context)

__all__ = ["propagate", "collector", "capture", "enabled",
           "wire_context", "bind", "pod_step_context", "emit_pod_root",
           "MetricsCollector", "live_collectors", "fleet_probe",
           "DumpFollower"]
