"""Autograd: eager tape + jax.vjp backward.

TPU-native re-design of the reference imperative autograd runtime
(ref: src/imperative/imperative.cc — RecordOp :193, Backward :280,
MarkVariables :123; python/mxnet/autograd.py scopes :122-181).

Design: instead of attaching AGInfo to NNVM nodes and running an MXGradient
graph pass (ref: src/nnvm/gradient.cc:275), every recorded op stores its pure
jax function and the concrete input/output jax.Arrays. Backward walks the tape
in reverse and calls `jax.vjp` per node — the FGradient registry, backward
shape inference, and the dependency engine all collapse into jax's tracing.
Gradient buffers live on marked NDArrays (`attach_grad`), mirroring
`grad_req` semantics ('write'/'add'/'null').
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as onp

from .base import MXNetError

__all__ = [
    "record",
    "pause",
    "train_mode",
    "predict_mode",
    "is_recording",
    "is_training",
    "set_recording",
    "set_training",
    "mark_variables",
    "backward",
    "grad",
    "Function",
    "get_symbol",
]


class _AGState(threading.local):
    def __init__(self):
        self.recording = False
        self.training = False
        self.tape: Optional["Tape"] = None


_STATE = _AGState()


def is_recording() -> bool:
    """ref: MXAutogradIsRecording / imperative.cc:26-32 thread-local flags."""
    return _STATE.recording


def is_training() -> bool:
    return _STATE.training


def set_recording(is_rec: bool) -> bool:
    prev = _STATE.recording
    _STATE.recording = is_rec
    if is_rec and _STATE.tape is None:
        _STATE.tape = Tape()
    return prev


def set_training(train: bool) -> bool:
    prev = _STATE.training
    _STATE.training = train
    return prev


class _Scope:
    def __init__(self, recording: Optional[bool], training: Optional[bool]):
        self._rec, self._train = recording, training
        self._prev_rec = self._prev_train = None

    def __enter__(self):
        if self._rec is not None:
            self._prev_rec = set_recording(self._rec)
        if self._train is not None:
            self._prev_train = set_training(self._train)
        return self

    def __exit__(self, *exc):
        if self._rec is not None:
            set_recording(self._prev_rec)
        if self._train is not None:
            set_training(self._prev_train)


def record(train_mode: bool = True) -> _Scope:  # noqa: F811 (name parity)
    """ref: python/mxnet/autograd.py:122 `record`."""
    return _Scope(True, train_mode)


def pause(train_mode: bool = False) -> _Scope:
    """ref: python/mxnet/autograd.py:148 `pause`."""
    return _Scope(False, train_mode)


def train_mode() -> _Scope:
    return _Scope(None, True)


def predict_mode() -> _Scope:
    return _Scope(None, False)


# ---------------------------------------------------------------------------
# Tape
# ---------------------------------------------------------------------------

class TapeNode:
    __slots__ = ("fn", "inputs", "outputs", "input_owners", "differentiable",
                 "custom_backward")

    def __init__(self, fn, inputs, outputs, input_owners, differentiable=True,
                 custom_backward=None):
        self.fn = fn                      # pure: (*jax arrays) -> array or tuple
        self.inputs = inputs              # list[jax.Array]
        self.outputs = outputs            # list[jax.Array]
        self.input_owners = input_owners  # list[Optional[NDArray]]
        self.differentiable = differentiable
        self.custom_backward = custom_backward  # (out_grads)->in_grads, overrides vjp


class Tape:
    """Eager tape (ref: the AGInfo chain built by Imperative::RecordOp)."""

    def __init__(self):
        self.nodes: List[TapeNode] = []
        self.producer: Dict[int, TapeNode] = {}  # id(out array) -> node
        self.marked: Dict[int, Any] = {}          # id(NDArray) -> NDArray

    def record(self, fn, in_arrays, out_arrays, in_owners, differentiable=True,
               custom_backward=None):
        node = TapeNode(fn, list(in_arrays), list(out_arrays), list(in_owners),
                        differentiable, custom_backward)
        self.nodes.append(node)
        for o in out_arrays:
            self.producer[id(o)] = node
        return node


def current_tape() -> Optional[Tape]:
    return _STATE.tape


def _reset_tape():
    _STATE.tape = Tape() if _STATE.recording else None


def mark_variables(variables, gradients, grad_reqs="write"):
    """ref: Imperative::MarkVariables (imperative.cc:123)."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for var, g, req in zip(variables, gradients, grad_reqs):
        var._grad = g if req != "null" else None
        var._grad_req = req


def _is_float(arr) -> bool:
    return jnp.issubdtype(arr.dtype, jnp.floating) or jnp.issubdtype(
        arr.dtype, jnp.complexfloating
    )


def _zero_cotangent(arr):
    if _is_float(arr):
        return jnp.zeros(arr.shape, arr.dtype)
    return onp.zeros(arr.shape, dtype=jax.dtypes.float0)


def backward(heads, head_grads=None, retain_graph=False, train_mode=True,
             _create_graph=False):
    """Run reverse accumulation from `heads`.

    ref: MXAutogradBackwardEx → Imperative::Backward (imperative.cc:280-523).
    Walks the eager tape in reverse creation order (already topological),
    vjp-ing each op; gradients land on marked NDArrays respecting grad_req.

    With `_create_graph` (set by autograd.grad(create_graph=True)), every
    vjp evaluation — and every gradient accumulation — is itself recorded
    as a tape node, so the produced gradients can be differentiated again
    (ref: imperative.cc:512-523 create_graph re-enabling recording).
    """
    from .ndarray.ndarray import NDArray  # cycle-free at call time

    if isinstance(heads, NDArray):
        heads = [heads]
    tape = _STATE.tape
    if tape is None or not tape.nodes:
        raise MXNetError("no computation recorded; call inside autograd.record()")
    if _create_graph and not _STATE.recording:
        raise MXNetError(
            "create_graph=True requires an active autograd.record() "
            "scope: the backward pass records its own nodes, which is "
            "impossible once recording has stopped")
    record_bwd = _create_graph

    if head_grads is None:
        head_grads = [jnp.ones(h.shape, h.dtype) for h in heads]
    else:
        # cast user-provided head gradients to each head's dtype —
        # e.g. fp32 ones against a bf16 AMP output must not poison the
        # vjp cotangent chain with a dtype mismatch
        head_grads = [
            jnp.ones(h.shape, h.dtype) if g is None
            else g._data.astype(h.dtype)
            for h, g in zip(heads, head_grads)
        ]

    # grad accumulator keyed by id of the recorded jax array
    grads: Dict[int, Any] = {}
    for h, hg in zip(heads, head_grads):
        grads[id(h._data)] = hg

    for node in reversed(list(tape.nodes)):
        out_grads = [grads.get(id(o)) for o in node.outputs]
        if all(g is None for g in out_grads):
            continue
        if not node.differentiable:
            continue
        from .ndarray.sparse_ops import SparseCotangent
        if record_bwd and any(isinstance(g, SparseCotangent)
                              for g in out_grads):
            # densify() buffers and sparse accumulation are not recorded;
            # silently wrong second derivatives are worse than an error
            raise MXNetError(
                "create_graph=True through sparse gradients "
                "(row_sparse/SparseCotangent paths) is not supported")
        cotangents = [
            (g.densify() if isinstance(g, SparseCotangent) else g)
            if g is not None else _zero_cotangent(o)
            for g, o in zip(out_grads, node.outputs)
        ]
        # align cotangent dtypes with the primal outputs: a mixed-
        # precision chain (bf16 conv → f32 BatchNorm) hands this node
        # an f32 cotangent for a bf16 output, and the per-op transpose
        # rules require an exact dtype match (whole-graph jax.vjp
        # inserts the same convert at its promotion sites)
        cotangents = [
            c if getattr(c, "dtype", None) == o.dtype
            else jnp.asarray(c, dtype=o.dtype)
            for c, o in zip(cotangents, node.outputs)
        ]
        if node.custom_backward is not None:
            if record_bwd:
                # a host-side custom backward (autograd.Function,
                # CustomOp, sparse scatter) is opaque to the tape: its
                # outputs would be unreachable orphans on the next
                # backward — raise rather than return silent zeros
                raise MXNetError(
                    "create_graph=True through an op with a custom "
                    "backward (autograd.Function / CustomOp) is not "
                    "supported")
            in_grads = node.custom_backward(cotangents)
        else:
            def _fn_tuple(*args, _f=node.fn):
                out = _f(*args)
                return out if isinstance(out, (tuple, list)) else (out,)

            _, vjp_fn = jax.vjp(_fn_tuple, *node.inputs)
            in_grads = vjp_fn(tuple(cotangents))
            if record_bwd:
                # create_graph: the vjp evaluation becomes a tape node
                # over (original inputs, cotangents), so these gradients
                # are themselves differentiable on the next backward
                keep = [i for i, g in enumerate(in_grads)
                        if g is not None and getattr(g, "dtype", None)
                        != jax.dtypes.float0]
                if keep:
                    def _bwd_fn(*args, _f=node.fn,
                                _n=len(node.inputs), _keep=tuple(keep)):
                        ins, cots = args[:_n], args[_n:]

                        def _tup(*xs):
                            o = _f(*xs)
                            return o if isinstance(o, (tuple, list)) \
                                else (o,)

                        _, vjp = jax.vjp(_tup, *ins)
                        igs = vjp(tuple(cots))
                        return tuple(igs[i] for i in _keep)

                    tape.record(_bwd_fn,
                                list(node.inputs) + list(cotangents),
                                [in_grads[i] for i in keep],
                                list(node.input_owners)
                                + [None] * len(cotangents))
        for inp, owner, ig in zip(node.inputs, node.input_owners, in_grads):
            if ig is None or (hasattr(ig, "dtype") and ig.dtype == jax.dtypes.float0):
                continue
            key = id(inp)
            if key in grads:
                prev = grads[key]
                total = prev + ig  # SparseCotangent sums too
                if record_bwd and not isinstance(prev, SparseCotangent) \
                        and not isinstance(ig, SparseCotangent):
                    # accumulation must live on the tape too, or the
                    # summed gradient is an orphan the next backward
                    # cannot reach
                    tape.record(lambda a, b: (a + b,), [prev, ig],
                                [total], [None, None])
                grads[key] = total
            else:
                grads[key] = ig
            if owner is not None and getattr(owner, "_grad", None) is not None:
                owner._pending_grad = grads[key]

    # deposit into marked variables per grad_req
    from .ndarray.sparse import BaseSparseNDArray, RowSparseNDArray
    from .ndarray.sparse_ops import SparseCotangent
    seen = set()
    for node in tape.nodes:
        for owner in node.input_owners:
            if owner is None or id(owner) in seen:
                continue
            seen.add(id(owner))
            pend = getattr(owner, "_pending_grad", None)
            if pend is None:
                continue
            if isinstance(pend, SparseCotangent):
                # row-sparse gradient: deposit without materializing the
                # dense buffer when the grad slot is sparse (ref:
                # Embedding sparse_grad / dot(csr.T, _) grads)
                if isinstance(owner._grad, BaseSparseNDArray) \
                        and owner._grad_req != "add":
                    owner._grad = pend.to_rowsparse()
                elif isinstance(owner._grad, BaseSparseNDArray):
                    prev = owner._grad
                    merged = SparseCotangent(
                        jnp.concatenate([prev._aux["values"], pend.values]),
                        jnp.concatenate([prev._aux["indices"],
                                         pend.indices]), pend.shape) \
                        if prev._aux["values"].size else pend
                    owner._grad = merged.to_rowsparse()
                elif owner._grad_req == "add":
                    owner._grad._data = owner._grad._data + pend.densify()
                else:
                    owner._grad._data = pend.densify()
            elif isinstance(owner, BaseSparseNDArray):
                # leaf stored sparse: cotangent is values-shaped; pair it
                # with the leaf's indices as a row_sparse grad
                new_g = RowSparseNDArray(
                    pend, owner._aux["indices"], owner.shape)
                if owner._grad_req == "add" and \
                        isinstance(owner._grad, RowSparseNDArray) and \
                        owner._grad._aux["values"].size:
                    prev = owner._grad
                    merged = SparseCotangent(
                        jnp.concatenate([prev._aux["values"], pend]),
                        jnp.concatenate([prev._aux["indices"],
                                         new_g._aux["indices"]]),
                        owner.shape)
                    new_g = merged.to_rowsparse()
                owner._grad = new_g
            elif isinstance(owner._grad, BaseSparseNDArray):
                # dense cotangent reached a sparse grad slot (mixed
                # sparse+dense paths): grad degrades to dense honestly
                from .ndarray.ndarray import _wrap as _dense_wrap
                owner._grad = _dense_wrap(
                    owner._grad._data + pend if owner._grad_req == "add"
                    else pend)
            elif owner._grad_req == "add":
                owner._grad._data = owner._grad._data + pend
            else:  # write
                owner._grad._data = pend.astype(owner._grad._data.dtype) \
                    if pend.dtype != owner._grad._data.dtype else pend
            owner._pending_grad = None

    if not retain_graph:
        _reset_tape()


def grad(heads, variables, head_grads=None, retain_graph=None, create_graph=False,
         train_mode=True):
    """ref: python/mxnet/autograd.py:273 `grad` — returns grads instead of
    storing into .grad buffers. With create_graph=True (inside record()),
    the backward pass records its own vjp + accumulation nodes so the
    returned gradients are differentiable again (higher-order grads;
    ref: imperative.cc:512-523, tests/python/unittest/
    test_higher_order_grad.py)."""
    from .ndarray.ndarray import NDArray, array as _nd_array

    if isinstance(heads, NDArray):
        heads = [heads]
    if isinstance(variables, NDArray):
        variables = [variables]
    # temporarily attach scratch grads
    saved = [(v, getattr(v, "_grad", None), getattr(v, "_grad_req", "null"))
             for v in variables]
    for v in variables:
        v._grad = _nd_array(onp.zeros(v.shape, dtype=onp.dtype(v.dtype)), ctx=v.ctx)
        v._grad_req = "write"
    try:
        backward(heads, head_grads, retain_graph=bool(retain_graph) or create_graph,
                 train_mode=train_mode, _create_graph=create_graph)
        out = [v.grad for v in variables]
    finally:
        for v, g, req in saved:
            v._grad, v._grad_req = g, req
    return out


def get_symbol(x):
    raise NotImplementedError(
        "get_symbol: use hybridize/jit tracing instead (tape is value-level)"
    )


# ---------------------------------------------------------------------------
# Custom differentiable functions (ref: python/mxnet/autograd.py:368 Function,
# backed C-side by src/c_api/c_api_function.cc callbacks)
# ---------------------------------------------------------------------------

class Function:
    """User-defined op with custom backward.

    Subclass and implement `forward(self, *inputs)` and
    `backward(self, *output_grads)` operating on NDArrays with autograd
    paused (mirrors the reference contract).
    """

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *args):
        self._saved = args

    @property
    def saved_tensors(self):
        return self._saved

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray.ndarray import NDArray, _wrap

        with pause():
            outputs = self.forward(*inputs)
        single = not isinstance(outputs, (tuple, list))
        outs = [outputs] if single else list(outputs)

        if is_recording():
            # passthrough forwards may return an input NDArray (or alias
            # one buffer across outputs); tape grads are keyed by buffer
            # id, so aliased outputs are re-wrapped around a copied
            # buffer (NOT rebound in place — the output may BE the input
            # object) or the head cotangent double-counts (same guard
            # as invoke())
            import jax.numpy as _jnp
            seen = {id(i._data) for i in inputs}
            for k, o in enumerate(outs):
                if isinstance(o, NDArray):
                    if id(o._data) in seen:
                        o = outs[k] = _wrap(_jnp.copy(o._data))
                    seen.add(id(o._data))
            tape = current_tape()

            def custom_backward(cotangents, _self=self, _inputs=inputs):
                with pause():
                    in_grads = _self.backward(*[_wrap(c) for c in cotangents])
                if not isinstance(in_grads, (tuple, list)):
                    in_grads = (in_grads,)
                return tuple(g._data if isinstance(g, NDArray) else g
                             for g in in_grads)

            tape.record(
                fn=None,
                in_arrays=[i._data for i in inputs],
                out_arrays=[o._data for o in outs],
                in_owners=list(inputs),
                custom_backward=custom_backward,
            )
        return outs[0] if single else outs
