"""StepFunction: one donated XLA computation per training step.

The reference-shaped training loop runs four phases per step — forward,
backward, gradient exchange, optimizer update — as separate dispatch
streams: the gluon ``Trainer`` pushes/pulls one kvstore key per
parameter and calls one ``Optimizer.update`` per parameter, each a
separate un-jitted dispatch (ref: python/mxnet/gluon/trainer.py:305).
``StepFunction`` captures all four into ONE ``jax.jit`` computation —
one dispatch per step instead of O(params):

- forward + backward via ``jax.vjp`` over the same pure trace the
  hybridize/Executor machinery uses (``gluon.block.functional_call``
  for HybridBlocks, ``executor.graph_forward_backward`` for Symbols),
  seeded with a ones cotangent exactly like ``loss.backward()``;
- gradient exchange lowered in-jit: identity for the single-process
  path, ``lax.psum`` over ``psum_axis`` when the step runs inside a
  mesh context (the cross-replica phase is part of the fused program,
  per "Automatic Cross-Replica Sharding of Weight Update");
- the optimizer via the functional multi-tensor
  :meth:`~mxnet_tpu.optimizer.Optimizer.fused_apply` kernels. Per-step
  scalars (lr, wd, Adam bias correction) are computed on the host in
  float64 — the exact arithmetic of the eager per-param loop — and
  passed as weakly-typed f32 scalars so schedulers never retrace;
- weight and optimizer-state buffers **donated** to XLA (buffer
  reuse); the post-step write-back rebinds the gluon Parameters and
  the Updater states in place, so checkpoints, kvstore updaters and
  ``mxresil`` preemption guards observe the post-update values.

The fused step is **bitwise-identical** to the eager loop
(test-enforced for SGD/Adam/AdamW in tests/test_step.py). Two
mechanisms make that hold: the eager per-param path dispatches each
optimizer kernel as one jitted program (optimizer._jk — the same
expression DAG XLA sees inside the fused step, so FMA contraction
applies equally to both), and an ``optimization_barrier`` pins the
gradient/update boundary so fusion cannot clone gradient producers
into the update kernels with different contraction.

Compiled programs are keyed by the input shape signature; hits/misses
feed the telemetry registry (``fused_step_cache_hits_total`` /
``..._misses_total``) and every miss is classified by the recompile
auditor (kind ``fused_step``) — ``tools/mxprof.py step`` renders the
report. See docs/performance.md.
"""
from __future__ import annotations

import time
import warnings
from typing import Dict, Optional

import jax
import jax.numpy as jnp

from .. import optimizer as opt_mod
from ..base import MXNetError
from ..ndarray.ndarray import NDArray, _wrap
from ..optimizer import _state_rebind, _state_values
from .. import random as _random

__all__ = ["StepFunction"]


def _raw(a):
    return a._data if isinstance(a, NDArray) else jnp.asarray(a)


class StepFunction:
    """Fused whole-train-step compiler for a HybridBlock (or Symbol).

    Block mode::

        trainer = gluon.Trainer(net.collect_params(), "sgd", {...})
        fused = StepFunction(net, loss_fn, trainer=trainer)
        for x, y in batches:
            loss = fused.step(x, y)          # ONE dispatch

    is the fused equivalent of::

        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(batch_size)

    and bitwise-equal to it for every optimizer with a functional
    ``fused_apply`` (SGD/NAG/Adam/AdamW/RMSProp). Without a trainer,
    pass ``optimizer=``/``optimizer_params=`` and the StepFunction owns
    its own Updater (state lives in ``self.updater.states`` — the same
    structure ``Trainer.save_states`` snapshots).

    Symbol mode::

        fused = StepFunction(loss_sym, arg_dict=args, aux_dict=auxs,
                             input_names=("data", "label"),
                             optimizer="sgd")

    traces the symbol through the Executor's ``eval_graph`` machinery
    (``executor.graph_forward_backward``); the symbol's first output is
    the per-sample loss.
    """

    def __init__(self, net, loss_fn=None, trainer=None, optimizer="sgd",
                 optimizer_params=None, arg_dict=None, aux_dict=None,
                 input_names=("data", "softmax_label"), grad_names=None,
                 donate=True, psum_axis=None, name=None):
        from ..symbol.symbol import Symbol
        self._net = net
        self._loss_fn = loss_fn
        self._trainer = trainer
        self._psum_axis = psum_axis
        self._symbol_mode = isinstance(net, Symbol)
        self._name = name or (net.name if hasattr(net, "name")
                              else type(net).__name__)
        # donation is a no-op on the CPU backend (and jax warns about
        # it per compile); request it only where PJRT honors it
        self._donate = bool(donate) and jax.default_backend() != "cpu"
        self._cache = {}
        self._last = None  # (jitted fn, key) of the newest compile
        self._opt_report = None  # graph-optimizer report (symbol mode)
        self._opt_level = 0
        # mxguard integrity taps (mxnet_tpu/guard/): fingerprints ride
        # as extra outputs of the SAME compiled program when MXGUARD is
        # on (or a Monitor tic forces them); the flag is part of the
        # signature-cache key so flipping it re-keys visibly and the
        # steady state stays at zero recompiles either way
        self._nstep = 0
        self._guard_probe = None  # per-instance EWMA anomaly probe
        self._recorder = None  # guard.ReplayRecorder (attach_recorder)
        self._monitor_cb = None  # Monitor duck-type (set_monitor_...)
        self._monitor_all = False
        self._last_fps = None  # (2+n_grads, 3) of the last noted step
        self._pending_guard = None  # deferred (fps, loss, step) note
        self._fp_names = ()
        self._last_loss = None
        self.guard_events = []  # vote/self-check verdicts (elastic)

        if trainer is not None:
            if optimizer_params or optimizer != "sgd":
                raise MXNetError("pass either trainer= or optimizer=/"
                                 "optimizer_params=, not both")
            self._optimizer = trainer._optimizer
            self._updater = trainer._updaters[0]
            self._scale = trainer._scale
            if (trainer._kvstore_params.get("update_on_kvstore")
                    or (trainer._kv_initialized
                        and trainer._update_on_kvstore)):
                raise MXNetError(
                    "StepFunction runs the optimizer inside the fused "
                    "step; update_on_kvstore trainers are unsupported — "
                    "create the Trainer with update_on_kvstore=False (or "
                    "no kvstore)")
            kvs = trainer._kvstore_params.get("kvstore")
            kv_type = getattr(kvs, "type",
                              kvs if isinstance(kvs, str) else "")
            if isinstance(kv_type, str) and "dist" in kv_type:
                raise MXNetError(
                    "StepFunction does not drive the kvstore data "
                    "plane; for multi-process training use "
                    "parallel.ParallelTrainer (in-jit psum over a "
                    "mesh) or the eager Trainer loop")
        else:
            self._optimizer = opt_mod.create(optimizer,
                                             **(optimizer_params or {}))
            self._updater = opt_mod.get_updater(self._optimizer)
            self._scale = 1.0

        if self._optimizer.multi_precision:
            raise MXNetError("StepFunction does not support "
                             "multi_precision optimizers; use the eager "
                             "per-param path")
        if not self._optimizer.has_fused_apply:
            raise MXNetError(
                f"optimizer {type(self._optimizer).__name__} has no "
                "functional fused_apply — the fused step would downgrade "
                "to eager; implement fused_apply (see steplint) or use "
                "the eager Trainer loop")
        if trainer is not None:
            # ALL validation passed — only now alter the trainer: the
            # fused step replaces the kvstore data plane, so a later
            # trainer.step() must not double-apply through a
            # server-side optimizer
            trainer._kvstore_params["update_on_kvstore"] = False

        if self._symbol_mode:
            self._init_symbol(net, arg_dict or {}, aux_dict or {},
                              tuple(input_names), grad_names)
        else:
            self._plist = None  # resolved lazily (deferred shapes)

    # ------------------------------------------------------------------
    # parameter resolution
    # ------------------------------------------------------------------
    def _init_symbol(self, sym, arg_dict, aux_dict, input_names,
                     grad_names):
        # bind-time graph optimization (MXNET_GRAPH_OPT): the fused
        # step traces the OPTIMIZED symbol — and because the rewrite
        # pipeline preserves the binding surface, the sharded subclass
        # composes unchanged (same in/out shardings over the optimized
        # graph; the plan never names interior nodes). The report is
        # keyed into _shard_key so flipping the level between
        # constructions can never alias a cached program.
        from ..base import get_env
        self._opt_report = None
        self._opt_level = 0
        if get_env("MXNET_GRAPH_OPT", 0):
            from ..opt import optimize_symbol, opt_level
            self._opt_level = opt_level()
            sym, self._opt_report = optimize_symbol(
                sym, where=f"StepFunction:{self._name}")
            self._net = sym
        self._input_names = tuple(input_names)
        missing = [n for n in sym.list_arguments()
                   if n not in arg_dict and n not in self._input_names]
        if missing:
            raise MXNetError(f"symbol-mode StepFunction: arg_dict is "
                             f"missing {missing}")
        self._param_objs = dict(arg_dict)
        self._aux_objs = {n: aux_dict[n]
                          for n in sym.list_auxiliary_states()}
        self._trainable = tuple(sorted(grad_names if grad_names is not None
                                       else self._param_objs))
        self._indices = list(range(len(self._trainable)))
        self._ensure_states({i: self._param_objs[n]
                             for i, n in zip(self._indices,
                                             self._trainable)})

    def _resolve_block_params(self, sample_x):
        from ..gluon.parameter import DeferredInitializationError
        try:
            plist = sorted(
                self._net._collect_params_with_prefix().items())
            for _, p in plist:
                p.data()
        except DeferredInitializationError:
            from .. import autograd as _ag
            with _ag.pause():
                self._net(_wrap(_raw(sample_x)[:1]))
            plist = sorted(
                self._net._collect_params_with_prefix().items())
        self._plist = plist
        self._param_objs = {n: p for n, p in plist}
        # weight tying: one Parameter under several prefixed names
        # would split its gradient across the aliases (each alias gets
        # a partial vjp cotangent), update each alias from the same
        # pre-step weight, and advance its update count once per alias
        # — silently diverging from the eager loop. Refuse loudly.
        by_id = {}
        for n, p in plist:
            if id(p) in by_id:
                raise MXNetError(
                    f"StepFunction: parameter '{p.name}' is shared "
                    f"between blocks (as '{by_id[id(p)]}' and '{n}'); "
                    "weight-tied models are not supported by the fused "
                    "step — use the eager record/backward/step loop")
            by_id[id(p)] = n
        if self._trainer is not None:
            index_of = self._trainer._param2idx
            trainable = [(n, p) for n, p in plist
                         if p.name in index_of and p.grad_req != "null"]
            self._indices = [index_of[p.name] for _, p in trainable]
        else:
            trainable = [(n, p) for n, p in plist if p.grad_req != "null"]
            self._indices = list(range(len(trainable)))
            self._optimizer.param_dict = {
                i: p for i, (_, p) in zip(self._indices, trainable)}
        self._trainable = tuple(n for n, _ in trainable)
        for n, p in trainable:
            if p.grad_req == "add":
                warnings.warn(
                    f"StepFunction: parameter {p.name} has grad_req="
                    "'add'; the fused step computes fresh per-step "
                    "gradients (accumulation is not folded in)")
        self._ensure_states({i: p for i, (_, p) in zip(self._indices,
                                                       trainable)})
        self._psig = tuple(p.grad_req for _, p in plist)

    def _param_dtypes(self):
        """Parameter dtype signature for the cache key: a mid-run
        Parameter.cast retraces jax's jit internally, and without the
        dtypes in OUR key the retrace would be miscounted as a cache
        hit and stay invisible to the recompile auditor."""
        if self._symbol_mode:
            return tuple(str(v._data.dtype)
                         for _, v in sorted(self._param_objs.items()))
        return tuple(str(p.data()._data.dtype) for _, p in self._plist)

    def _ensure_states(self, by_index):
        upd = self._updater
        for i, p in by_index.items():
            if i not in upd.states:
                w = p.data() if hasattr(p, "data") else p
                upd.states[i] = \
                    self._optimizer.create_state_multi_precision(i, w)
                upd.states_synced[i] = True

    # ------------------------------------------------------------------
    # tracing
    # ------------------------------------------------------------------
    def _exchange(self, grads):
        """Gradient exchange, lowered into the jit: identity for the
        single-process path, psum over a named mesh axis otherwise."""
        if self._psum_axis is None:
            return grads
        return jax.tree.map(
            lambda g: jax.lax.psum(g, self._psum_axis), grads)

    def _apply(self, trainable_vals, grads, svals, lrs, wds):
        """The in-jit update segment: exchange + fused multi-tensor
        optimizer. The barrier pins the gradient/update boundary so
        XLA's producer-consumer fusion cannot clone gradient
        expressions into the update kernels with different FMA
        contraction — the bitwise-parity contract with the eager loop
        (whose per-param kernels jit the same expression DAG)."""
        grads = jax.lax.optimization_barrier(grads)
        grads = self._exchange(grads)
        return self._optimizer.fused_apply(
            self._indices,
            [trainable_vals[n] for n in self._trainable],
            [grads[n] for n in self._trainable], svals, lrs, wds)

    def _build_grads(self, taps=False):
        """Pure ``(pvals, inputs, rng) -> (grads, extras, loss)``
        builder — the forward+backward phase shared by the one-program
        step and the elastic split-phase step (mxnet_tpu/elastic/
        stepfn.py, which exchanges gradients host-side between this
        and the update program). ``extras`` is the non-gradient state
        the step must write back (BN running stats; the symbol graph's
        ``__aux__`` dict).

        ``taps=True`` (mxguard) appends a fourth output: the
        fingerprint matrix — row 0 the fold over the pre-step
        trainable weights (bitwise-replicated across data-parallel
        workers, the exact-majority vote row), rows 1..n one
        (checksum, absmax, nonfinite) triple per gradient in sorted
        trainable order, and a final LOCAL loss row
        ``(mean, absmax, nonfinite)`` so the anomaly probe needs no
        second device fetch (the loss row never enters the
        cross-replica vote — losses legitimately differ per worker).
        The gradients pass through an ``optimization_barrier`` before
        being fingerprinted AND before the update consumes them, so
        the gradient producers see the same single consumer with taps
        on or off — the taps-on step is bitwise-identical in weights
        to taps-off (test-enforced)."""
        base = self._build_grads_base()
        if not taps:
            return base
        trainable = self._trainable
        from ..guard.fingerprint import fingerprint_rows, fold_rows

        def tapped(pvals, inputs, rng):
            grads, extras, lout = base(pvals, inputs, rng)
            grads = jax.lax.optimization_barrier(grads)
            prow = fold_rows(fingerprint_rows(
                pvals[n] for n in trainable))
            grows = fingerprint_rows(grads[n] for n in trainable)
            lflat = jnp.asarray(lout).astype(jnp.float32).reshape(-1)
            lrow = jnp.stack([
                jnp.mean(lflat), jnp.max(jnp.abs(lflat)),
                jnp.sum(~jnp.isfinite(lflat)).astype(jnp.float32)])
            fps = jnp.concatenate(
                [prow[None, :], grows, lrow[None, :]], axis=0)
            return grads, extras, lout, fps

        return tapped

    def _build_grads_base(self):
        if self._symbol_mode:
            sym = self._net
            trainable = self._trainable
            input_names = self._input_names
            from ..executor import graph_forward_backward
            fb = graph_forward_backward(sym, list(trainable))

            def pure_grads(pvals, inputs, rng):
                arg_vals = dict(pvals)
                arg_vals.update(zip(input_names, inputs))
                aux_vals = dict(arg_vals.pop("__aux__", {}))
                outs, aux_updates, grads = fb(
                    arg_vals, aux_vals, rng,
                    tuple([None] * len(sym._outputs)))
                return grads, {"__aux__": dict(aux_updates)}, outs[0]

            return pure_grads

        block, loss_fn = self._net, self._loss_fn
        trainable = self._trainable
        from ..gluon.block import functional_call

        def pure_grads(pvals, inputs, rng):
            def loss_of(tvals):
                allp = dict(pvals)
                allp.update(tvals)
                (out,), aux = functional_call(
                    block, allp, [_wrap(inputs[0])], training=True,
                    rng_raw=rng)
                if loss_fn is None:
                    lout = out
                else:
                    louts, _ = functional_call(
                        loss_fn, {},
                        [_wrap(out)] + [_wrap(v) for v in inputs[1:]],
                        training=True)
                    lout = louts[0]
                return lout, aux

            tvals = {n: pvals[n] for n in trainable}
            lout, vjp_fn, aux = jax.vjp(loss_of, tvals, has_aux=True)
            grads = vjp_fn(jnp.ones_like(lout))[0]
            return grads, aux, lout  # aux: BN running stats

        return pure_grads

    def _build_pure(self, guard=False):
        """The whole-step program: grads + exchange + fused update in
        one trace (the expression DAG is unchanged by the _build_grads
        factoring — bitwise parity with the eager loop holds). With
        ``guard`` the fingerprint matrix rides as a fourth output."""
        grads_fn = self._build_grads(taps=guard)
        trainable = self._trainable

        def pure_step(pvals, svals, lrs, wds, inputs, rng):
            out = grads_fn(pvals, inputs, rng)
            grads, extras, lout = out[:3]
            tvals = {n: pvals[n] for n in trainable}
            new_w, new_s = self._apply(tvals, grads, svals, lrs, wds)
            new_params = dict(pvals)
            new_params.update(zip(trainable, new_w))
            new_params.update(extras)
            if guard:
                return new_params, new_s, lout, out[3]
            return new_params, new_s, lout

        return pure_step

    # ------------------------------------------------------------------
    # execution
    # ------------------------------------------------------------------
    def _make_jit(self, pure, guard=False):
        """Compile hook: the sharded subclass (mxnet_tpu/shard/)
        overrides this to attach NamedSharding in/out annotations over
        its device mesh (``guard`` tells it the program carries the
        extra fingerprint output); the base step is
        single-(logical-)device."""
        return jax.jit(pure,
                       donate_argnums=(0, 1) if self._donate else ())

    def _shard_key(self):
        """Extra cache-key component for subclasses whose compiled
        program depends on more than shapes/dtypes/optimizer scalars
        (the sharded step keys on its plan fingerprint)."""
        return ()

    def _hyper(self):
        """Per-step scalar hyperparameters, host-computed (float64 —
        the eager loop's arithmetic), shipped as weakly-typed f32
        scalars so value changes (schedulers, Adam's t) never
        retrace."""
        lrs, wds = [], []
        for i in self._indices:
            lr, wd = self._optimizer.fused_hyper(i)
            lrs.append(jnp.asarray(lr))
            wds.append(jnp.asarray(wd))
        return tuple(lrs), tuple(wds)

    def _gather(self):
        if self._symbol_mode:
            pvals = {n: v._data for n, v in self._param_objs.items()}
            pvals["__aux__"] = {n: v._data
                                for n, v in self._aux_objs.items()}
        else:
            pvals = {n: p.data()._data for n, p in self._plist}
        svals = [_state_values(self._updater.states[i])
                 for i in self._indices]
        return pvals, svals

    def _writeback(self, new_params, new_states):
        if self._symbol_mode:
            aux = new_params.pop("__aux__", {})
            for n, v in aux.items():
                if n in self._aux_objs:
                    self._aux_objs[n]._rebind(v)
            for n, v in new_params.items():
                self._param_objs[n]._rebind(v)
        else:
            for n, v in new_params.items():
                p = self._param_objs.get(n)
                if p is not None:
                    p.data()._rebind(v)
        for i, ns in zip(self._indices, new_states):
            _state_rebind(self._updater.states[i], ns)

    def _prepare(self, inputs):
        """Resolve parameters (and re-derive the trainable set on a
        grad_req flip) before keying/compiling — shared with the
        elastic split-phase step."""
        if not self._symbol_mode:
            if self._plist is None:
                self._resolve_block_params(inputs[0])
            elif self._psig != tuple(p.grad_req
                                     for _, p in self._plist):
                # grad_req flipped mid-run (freeze/unfreeze): the
                # trainable set — and hence the program — changed;
                # re-derive it (the eager loop picks this up
                # implicitly, so the fused step must too)
                self._resolve_block_params(inputs[0])
                self._cache.clear()

    def _miss_signature_extra(self):
        """Non-shape signature keys for the recompile record —
        subclasses whose cache key carries more than shapes/dtypes
        (the sharded step's plan fingerprint) report them here so the
        auditor classifies their re-keys as ``key-change`` instead of
        cache eviction."""
        return {}

    def _record_miss(self, inputs):
        """Count + classify one signature-cache miss (the recompile
        auditor's fused_step kind)."""
        from ..telemetry import metrics as _metrics
        from ..telemetry import recompile as _recompile
        _metrics.counter(
            "fused_step_cache_misses_total",
            "fused-step signature-cache misses (compiles)").inc()
        sig = _recompile.signature_of([_wrap(v) for v in inputs], True)
        sig.update(self._miss_signature_extra())
        _recompile.record_recompile(
            f"StepFunction:{self._name}", sig, kind="fused_step")

    def step(self, x, *labels, batch_size=None, rng_raw=None):
        """Run one fused training step; returns the loss NDArray.
        ``rng_raw`` overrides the step's RNG key data — the
        deterministic-replay hook (mxnet_tpu/guard/replay.py)."""
        from ..telemetry import metrics as _metrics
        from .. import telemetry as _telemetry
        from .. import trace as _trace
        t0 = time.perf_counter()
        inputs = tuple(_raw(a) for a in (x,) + labels)
        self._prepare(inputs)
        if batch_size is None:
            batch_size = int(inputs[0].shape[0]) if inputs[0].ndim else 1
        self._optimizer.rescale_grad = self._scale / batch_size
        guard = self._guard_enabled()

        # the per-step trace root (serving's serve.request analog):
        # compile/dispatch/writeback decompose as children, keyed by
        # step number so mxprof trace correlates across subsystems
        with _trace.span("train.step", "train", step=self._nstep,
                         fn=self._name, kind=type(self).__name__):
            # key on input signature + parameter dtypes + every scalar
            # the trace bakes in (rescale_grad, clip, momentum, betas,
            # ... — fused_signature), so mid-run hyperparameter
            # mutation and Parameter.cast retrace VISIBLY (counted as
            # misses, recorded by the recompile auditor) instead of
            # silently. The mxguard tap flag re-keys the same way
            # (taps are extra outputs of the program — a different
            # program).
            key = (tuple((tuple(v.shape), str(v.dtype))
                         for v in inputs),
                   self._param_dtypes(), self._opt_level, guard,
                   self._optimizer.fused_signature()) \
                + self._shard_key()
            fn = self._cache.get(key)
            if fn is None:
                self._record_miss(inputs)
                tb0 = time.perf_counter()
                with _trace.span("step.compile", "train"):
                    fn = self._make_jit(self._build_pure(guard), guard)
                self._cache[key] = fn
                self._last = (fn, key)
                _metrics.histogram(
                    "fused_step_compile_seconds",
                    "fused-step trace+compile latency").observe(
                    time.perf_counter() - tb0)
            else:
                _metrics.counter(
                    "fused_step_cache_hits_total",
                    "fused-step signature-cache hits").inc()

            with _trace.span("step.prep", "train"):
                lrs, wds = self._hyper()
                pvals, svals = self._gather()
                rng = jnp.asarray(rng_raw) if rng_raw is not None \
                    else jax.random.key_data(_random.next_key())
            t1 = time.perf_counter()
            with _trace.span("step.dispatch", "train",
                             batch=batch_size):
                out = fn(pvals, svals, lrs, wds, inputs, rng)
            new_params, new_states, loss = out[:3]
            t2 = time.perf_counter()
            with _trace.span("step.writeback", "train"):
                self._writeback(new_params, new_states)
                if guard:
                    if self._recorder is not None or self._monitor_all:
                        # recorder/monitor consumers need THIS step's
                        # values (an earlier deferred note flushes
                        # first — the probe must observe steps in
                        # order)
                        self._flush_pending_guard()
                        self._guard_note(out[3], loss, inputs, rng)
                    else:
                        # telemetry-only mode: defer the host read one
                        # step — by the next boundary the program has
                        # completed, so the fetch copies a finished
                        # buffer instead of stalling the async
                        # pipeline (the measured tap overhead is the
                        # in-program reductions alone)
                        self._flush_pending_guard()
                        self._pending_guard = (out[3], loss,
                                               self._nstep)
            t3 = time.perf_counter()
        _metrics.histogram(
            "fused_step_host_seconds",
            "fused-step host prep (hyper scalars + buffer gather)"
            ).observe(t1 - t0)
        _metrics.histogram(
            "fused_step_dispatch_seconds",
            "fused-step compiled-call dispatch (async; excludes device "
            "wait)").observe(t2 - t1)
        _metrics.histogram(
            "fused_step_writeback_seconds",
            "fused-step parameter/state rebind").observe(t3 - t2)
        _telemetry.record_step(batch_size, time.perf_counter() - t0)
        self._nstep += 1
        return _wrap(loss)

    __call__ = step

    # ------------------------------------------------------------------
    # mxguard integrity taps (mxnet_tpu/guard/; docs/resilience.md)
    # ------------------------------------------------------------------
    def _guard_enabled(self) -> bool:
        """Taps on: the MXGUARD flag, or a Monitor tic for this step
        (``_monitor_all`` — the reference executor's monitor switch,
        set by ``Monitor.tic``)."""
        from .. import config
        return bool(config.get("MXGUARD")) or self._monitor_all

    def attach_recorder(self, recorder):
        """Attach a :class:`~mxnet_tpu.guard.replay.ReplayRecorder`:
        every guarded step records its batch digests, RNG key, hyper
        scalars, loss digest and fingerprints into the bounded ring."""
        self._recorder = recorder
        return recorder

    @property
    def guard_probe(self):
        """This step function's OWN EWMA anomaly probe (lazy): each
        in-process worker keeps its own loss/step stream, so replay
        windows attribute to the right run. Register on a watchdog
        via ``wd.add_probe(fused.guard_probe.check)`` — or
        ``guard.anomaly.check_all`` to cover every probe at once."""
        if self._guard_probe is None:
            from ..guard.anomaly import GuardProbe
            self._guard_probe = GuardProbe(name=self._name)
        return self._guard_probe

    @property
    def last_fingerprints(self):
        """The newest tap matrix ``(params, *grads, loss) x (checksum,
        absmax, nonfinite)`` — materializes a deferred note first, so
        readers always see the LAST COMPLETED step's values."""
        self._flush_pending_guard()
        return self._last_fps

    def flush_guard(self):
        """Process any deferred tap note NOW (telemetry-only mode
        reads the previous step's completed buffers; call this after
        the final step of a run, or before reading guard telemetry
        that must include the newest step)."""
        self._flush_pending_guard()
        return self._last_fps

    def _flush_pending_guard(self):
        if self._pending_guard is None:
            return
        fps, loss, step = self._pending_guard
        self._pending_guard = None
        self._guard_note(fps, loss, None, None, step=step)

    def _guard_note(self, fps, loss_raw, inputs, rng,
                    good: bool = True, strict: bool = True,
                    step: Optional[int] = None):
        """Post-step guard bookkeeping shared with the elastic
        subclass: publish the fingerprints, feed the EWMA anomaly
        probe, run the solo strict check, and record the replay ring
        entry."""
        import numpy as onp
        from .. import config
        if step is None:
            step = self._nstep
        # ONE device fetch: the matrix carries the loss row too, so
        # the probe never forces a second transfer (the recorder —
        # opt-in — is the only consumer that touches the loss buffer)
        fps_host = onp.asarray(fps, dtype=onp.float32)
        self._last_fps = fps_host
        self._fp_names = ("__params__",) + self._trainable \
            + ("__loss__",)
        self._last_loss = loss_raw
        n_grads = len(self._trainable)
        loss_row = fps_host[-1]
        loss_mean = float(loss_row[0]) if not loss_row[2] \
            else float("nan")
        grad_absmax = float(fps_host[1:1 + n_grads, 1].max()) \
            if n_grads else None
        anomaly = self.guard_probe.observe(step, loss_mean,
                                           grad_absmax)
        nonfinite = float(fps_host[1:1 + n_grads, 2].sum()) \
            if n_grads else 0.0
        if nonfinite and strict and config.get("MXGUARD_STRICT"):
            # the one-program fused step already applied the update
            # (grads and weights live in ONE donated program), so a
            # transparent retry is impossible here — hard-fail and
            # point at the replay ring. The split-phase elastic step
            # classifies/retries instead (guard/voting.py).
            from ..guard.voting import GuardCorruption
            raise GuardCorruption(step,
                                  [f"nonfinite:{int(nonfinite)}"])
        if self._recorder is not None and inputs is not None:
            scalars = {"rescale": float(self._optimizer.rescale_grad)}
            self._recorder.record(
                step, inputs, rng, onp.asarray(loss_raw),
                fps_host, scalars=scalars, trainer=self._trainer,
                good=good and anomaly is None and not nonfinite)

    def guard_state(self) -> Dict[str, object]:
        """The guardlint surface: what protection THIS step function
        actually has wired (docs/resilience.md integrity section)."""
        from .. import config
        rec = self._recorder
        return {"kind": type(self).__name__,
                "name": self._name,
                "taps": bool(config.get("MXGUARD")),
                "recorder": rec is not None,
                "ring_checkpoints": bool(
                    rec is not None and rec.has_checkpoint_ring),
                "exchanges_gradients": False,
                "guard_events": len(self.guard_events)}

    # -- Monitor duck-type (the executor monitor surface, so
    # ``Monitor.install(fused)`` works on the fused-step path — the
    # eager executor never runs there and per-op activations do not
    # exist as materialized values inside one XLA program; what the
    # monitor observes are the fingerprint taps + the loss) ------------
    def set_monitor_callback(self, callback, monitor_all=False):
        self._monitor_cb = callback
        self._monitor_all = bool(monitor_all)

    def collect_monitor_stats(self, helper):
        """Feed the last step's tap values to a Monitor stat helper:
        one (3,) fingerprint NDArray per gradient (named
        ``<param>_grad_fp``), the params-digest row, and the loss."""
        if self.last_fingerprints is None:
            return
        for name, row in zip(self._fp_names, self.last_fingerprints):
            tag = "params_fp" if name == "__params__" \
                else "loss_fp" if name == "__loss__" \
                else f"{name}_grad_fp"
            helper(tag, _wrap(jnp.asarray(row)))
        if self._last_loss is not None:
            helper("loss", _wrap(jnp.asarray(self._last_loss)))

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------
    @property
    def opt_report(self):
        """Graph-optimizer report for symbol mode (None when off or in
        block mode — the optimizer works on the Symbol IR)."""
        return self._opt_report

    def cache_info(self) -> Dict[str, int]:
        from ..telemetry import metrics as _metrics
        return {
            "programs": len(self._cache),
            "hits": _metrics.counter(
                "fused_step_cache_hits_total").value(),
            "misses": _metrics.counter(
                "fused_step_cache_misses_total").value(),
        }

    def cost_analysis(self, x, *labels):
        """XLA cost analysis of the compiled step (bench roofline,
        mxtune cost-model features): a stable, JSON-serializable dict —
        sorted keys, plain floats only, always containing ``flops`` and
        ``bytes accessed``. Lowers with the CURRENT buffers (a
        persistent-cache hit when the step already ran); does not
        execute or donate."""
        if self._last is None:
            raise MXNetError("no compiled step yet — call step() first")
        fn, _ = self._last
        inputs = tuple(_raw(a) for a in (x,) + labels)
        lrs = tuple(jnp.asarray(0.0) for _ in self._indices)
        wds = tuple(jnp.asarray(0.0) for _ in self._indices)
        pvals, svals = self._gather()
        rng = jax.random.key_data(jax.random.key(0))
        cost = fn.lower(pvals, svals, lrs, wds, inputs,
                        rng).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        # backend cost dicts leak device objects and odd scalar types;
        # keep only what float() accepts so the result round-trips
        # through json (mxtune persists these as model features)
        out = {}
        for k, v in (cost or {}).items():
            try:
                out[str(k)] = float(v)
            except (TypeError, ValueError):
                continue
        out.setdefault("flops", 0.0)
        out.setdefault("bytes accessed", 0.0)
        return dict(sorted(out.items()))
