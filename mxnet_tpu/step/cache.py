"""Persistent XLA compilation cache (MXNET_COMPILE_CACHE_DIR).

The fused train step's one weakness is its first call: a whole-model
forward+backward+optimizer XLA compile can take minutes. JAX ships a
persistent on-disk compilation cache; enabling it means warmup survives
process restarts (a preempted worker recompiles from disk in seconds —
the mxresil restart path), repeated bench/CI runs skip the multi-minute
first compile, and a fleet sharing a cache directory compiles each
program once.

Enabled by the ``MXNET_COMPILE_CACHE_DIR`` flag at import (config.py);
hits and misses are logged through the telemetry metrics registry via
jax's monitoring events, so ``tools/mxprof.py step`` and the
MXNET_METRICS_EXPORT stream show whether warmup actually came from
disk.
"""
from __future__ import annotations

import warnings

__all__ = ["enable_compile_cache", "maybe_enable_compile_cache"]

_ENABLED_DIR = None
_LISTENER_ON = False

# jax monitoring event names of the persistent-cache path
# (jax/_src/compiler.py + compilation_cache.py)
_EVENT_COUNTERS = {
    "/jax/compilation_cache/cache_hits": (
        "jax_compile_cache_hits_total",
        "persistent-compile-cache hits (programs loaded from disk)"),
    "/jax/compilation_cache/cache_misses": (
        "jax_compile_cache_misses_total",
        "persistent-compile-cache misses (programs compiled anew)"),
}


def _on_event(event: str, **kwargs):
    hit = _EVENT_COUNTERS.get(event)
    if hit is None:
        return
    from ..telemetry import metrics as _metrics
    _metrics.counter(*hit).inc()


def enable_compile_cache(directory: str,
                         min_compile_time_secs: float = 0.5) -> bool:
    """Point jax's persistent compilation cache at ``directory`` and
    wire its hit/miss monitoring events into the telemetry registry.
    Returns True when the cache was enabled. Idempotent."""
    global _ENABLED_DIR, _LISTENER_ON
    if not directory:
        return False
    import jax
    try:
        jax.config.update("jax_compilation_cache_dir", directory)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_time_secs))
    except Exception as e:  # unknown config name on an odd jax build
        warnings.warn(f"MXNET_COMPILE_CACHE_DIR: persistent compile "
                      f"cache unavailable on this jax: {e}")
        return False
    try:
        # cache even tiny programs: CPU test models compile in <0.5 s
        # but the restart win is the same
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass
    if not _LISTENER_ON:
        try:
            jax.monitoring.register_event_listener(_on_event)
            _LISTENER_ON = True
        except Exception:
            pass  # telemetry is best-effort; the cache still works
    _ENABLED_DIR = directory
    return True


def maybe_enable_compile_cache() -> bool:
    """Import-time hook: enable the cache when MXNET_COMPILE_CACHE_DIR
    is set (mxnet_tpu/__init__.py calls this once the flag registry is
    up)."""
    from ..base import get_env
    return enable_compile_cache(get_env("MXNET_COMPILE_CACHE_DIR", ""))
